#!/bin/bash
# Babysit an already-running run_tpu_round.sh series (started outside
# chip_watch.sh because the tunnel happened to be up at round start).
#
# Waits for the series pid, commits whatever artifacts were banked
# (success OR partial -- the round-3 lesson: a window that closes
# mid-run must not leave real TPU data uncommitted), then re-arms
# chip_watch.sh if the series did not complete, so a later window can
# finish the job without a human watching.
#
# Usage: bash ci/series_babysit.sh <pid> [round_tag]
set -u
cd "$(dirname "$0")/.."
PID=$1
TAG=${2:-r4}
RES=benchmarks/results
LOG="$RES/chip_watch_${TAG}.log"

log() { echo "$(date -u +%Y-%m-%dT%H:%M:%SZ) [babysit] $*" >> "$LOG"; }

log "watching series pid=$PID tag=$TAG"
while kill -0 "$PID" 2>/dev/null; do
  sleep 30
done
# series pid is gone; rc is unknowable from here, so infer completion
# from the per-run marker the series writes at its very end.  The
# marker is removed at series start, so a stale one from a PRIOR
# same-tag run cannot fake completion (ADVICE r4 #2); existence alone
# is therefore the right test -- an mtime-vs-babysit-start guard
# would misread a series that finished before the babysitter attached
# (cheap with banking) as incomplete and arm a pointless watcher.
if [ -f "$RES/series_${TAG}.done" ]; then
  rc=0
else
  rc=1
fi
log "series pid=$PID exited (complete=$((1 - rc)))"

if [ -n "$(git status --porcelain -- "$RES")" ]; then
  committed=no
  for _ in 1 2 3 4 5; do
    if { git add -- "$RES" && git commit -q -m \
      "TPU series ${TAG}: artifacts from round-start window" \
      -- "$RES"; } >> "$LOG" 2>&1; then
      log "artifacts committed"
      committed=yes
      break
    fi
    log "git add/commit failed; retrying in 10s"
    sleep 10
  done
  if [ "$committed" = no ]; then
    # unstage so the operator's next unrelated commit cannot silently
    # sweep the artifacts in (ADVICE r4 #3; mirrors chip_watch.sh)
    git restore --staged -- "$RES" >> "$LOG" 2>&1 || true
    log "artifact commit FAILED after 5 attempts -- results are" \
        "UNCOMMITTED in $RES (see git errors above)"
  fi
fi

if [ "$rc" -ne 0 ]; then
  log "series incomplete; arming chip_watch"
  exec bash ci/chip_watch.sh "$TAG" 300 10
fi
log "series complete; no watcher needed"
