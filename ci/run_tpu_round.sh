#!/bin/bash
# One-shot TPU measurement series for an end-of-round artifact drop.
#
# Runs every chip-dependent benchmark exactly once, SERIALIZED (the
# axon tunnel starves concurrent clients -- see
# .claude/skills/verify/SKILL.md), with per-step timeouts so a hung
# backend cannot wedge the whole series.  Results land in
# benchmarks/results/ for commit; bench JSON lines are echoed.
#
# RESUMABLE (round 5; VERDICT r4 weak #4): a step whose .out already
# passes its banked-predicate (good bench JSON row / all-good jsonl /
# green pytest / completion trailer) is SKIPPED, so re-firing the
# series after a mid-run backend death resumes the un-banked
# remainder instead of restarting from scratch.  FORCE=1 reruns
# everything.
#
# Usage: bash ci/run_tpu_round.sh [round_tag]    (default r3)
set -u
cd "$(dirname "$0")/.."
TAG=${1:-r3}
RES=benchmarks/results
mkdir -p "$RES"
# per-run completion marker (ADVICE r4 #2): removed at series start,
# written only when the series reaches its end, so a babysitter can
# test completion without grepping a shared append-mode log.
rm -f "$RES/series_${TAG}.done"

# preflight: one bounded probe so a dead tunnel fails the series in
# ~2 minutes instead of burning every step's own probe window
if ! timeout 120 python -c "
import jax, jax.numpy as jnp
assert jax.default_backend() == 'tpu', (
    'not a TPU backend: %s -- a silent CPU fallback would record '
    'bogus artifacts as TPU data' % jax.default_backend())
y = jax.jit(lambda a: a @ a)(jnp.ones((256, 256), jnp.bfloat16))
jax.device_get(y[:1, :1])
print('preflight ok:', jax.default_backend())
" >&2; then
  echo "preflight FAILED: TPU backend unreachable; aborting series" >&2
  exit 2
fi

# --- banked predicates (each: <outfile> -> 0 if already good) --------
pred_json_row() {  # last line is bench JSON: no error/suspect, value>0
  python - "$1" <<'EOF'
import json, sys
try:
    lines = [ln for ln in open(sys.argv[1]).read().splitlines()
             if ln.strip()]
    row = json.loads(lines[-1])
except Exception:
    sys.exit(1)
ok = (not row.get('error') and not row.get('suspect')
      and float(row.get('value', 0)) > 0)
sys.exit(0 if ok else 1)
EOF
}
pred_jsonl() {  # sweep banked: substantial row count, no error rows,
  # majority non-suspect.  Individual suspect rows are a DESIGNED-FOR
  # outcome on a noisy tunnel (emitted, not retried) -- requiring
  # zero of them would permanently un-bank the step and burn a
  # multi-minute rerun every resume.
  python - "$1" <<'EOF'
import json, sys
rows = []
for ln in open(sys.argv[1]).read().splitlines():
    try:
        rows.append(json.loads(ln))
    except ValueError:
        pass
good = sum(1 for r in rows if not r.get('suspect'))
ok = (len(rows) >= 10 and 2 * good > len(rows)
      and not any(r.get('error') for r in rows))
sys.exit(0 if ok else 1)
EOF
}
pred_best_row() {  # good bench row AND still the config adoption
  # would pick from today's banked sweep -- a resumed sweep step that
  # crowns a new winner must un-bank the best-config artifact so the
  # official row (and the warmed compile cache) track the freshest
  # winner (the banked row itself is a candidate, so a rerun that
  # measures the winner directly re-banks)
  pred_json_row "$1" || return 1
  python - "$1" <<'EOF'
import json, os, sys
sys.path.insert(0, os.getcwd())
import bench
lines = [ln for ln in open(sys.argv[1]).read().splitlines()
         if ln.strip()]
row = json.loads(lines[-1])
argv = bench.adopt_tuned_config([], 'resnet50')
want_batch = (int(argv[argv.index('--batch') + 1])
              if '--batch' in argv else None)
have_batch = row.get('per_device_batch_override') or None
want_s2d = '--s2d' in argv
have_s2d = row.get('stem') == 'space_to_depth'
sys.exit(0 if (have_batch == want_batch and have_s2d == want_s2d)
         else 1)
EOF
}
pred_pytest_green() {  # green summary, no failed/error counts
  grep -q ' passed' "$1" && ! grep -Eq '[0-9]+ (failed|error)' "$1"
}
pred_wrote() {  # completion trailer from sweep/trace scripts
  grep -q '^wrote ' "$1"
}

# Dead-tunnel circuit breaker: when the backend dies mid-window, each
# remaining bench step burns ~13 min of probe retries before writing
# its backend_unavailable row -- a dozen queued steps would waste
# hours of window-less probing at the series' own glacial cadence.
# After TWO consecutive dead-looking steps the series aborts (exit 4);
# chip_watch then resumes its 5-minute probes and re-fires the
# resumable series at the first un-banked step on next contact.
# The projection regen is pure host-side arithmetic over whatever is
# banked; run it on EVERY exit path (including the circuit-breaker
# abort below) so the freshest measured inputs are always reflected.
regen_projection() {
  python benchmarks/scaling_projection.py --tag "$TAG" \
    > "$RES/scaling_projection_${TAG}.log" 2>&1 || true
}
trap regen_projection EXIT

DEAD=0
note_outcome() {  # note_outcome <rc> <outfile>
  local rc=$1 out=$2 err
  if [ "$rc" -eq 0 ]; then
    DEAD=0
    return 0
  fi
  # last-JSON-line error field (same one-JSON-line-last contract as
  # pred_json_row; this extracts the error string, that one judges
  # bankability)
  err=$(python - "$out" <<'EOF'
import json, sys
try:
    lines = [ln for ln in open(sys.argv[1]).read().splitlines()
             if ln.strip()]
    print(json.loads(lines[-1]).get('error', ''))
except Exception:
    print('')
EOF
)
  if [ "$err" = backend_unavailable ] || [ "$err" = bench_timeout ] \
      || { [ "$rc" -eq 124 ] && [ -z "$err" ]; }; then
    DEAD=$((DEAD + 1))
    if [ "$DEAD" -ge 2 ]; then
      echo "=== backend dead for $DEAD consecutive steps; aborting" \
           "series (chip_watch resumes the remainder next contact)" >&2
      exit 4
    fi
  else
    # the step FAILED but not in a dead-tunnel way (the backend
    # answered and produced a real error row): that breaks the
    # consecutive-dead run, otherwise two dead steps separated by a
    # live failure would abort a live window
    DEAD=0
  fi
}

run_with() {  # run_with <pred> <name> <timeout_s> <cmd...>
  local pred=$1 name=$2 tmo=$3; shift 3
  local out="$RES/${name}_${TAG}.out"
  if [ "${FORCE:-0}" != 1 ] && [ -s "$out" ] && "$pred" "$out"; then
    echo "=== [$name] already banked; skipping (FORCE=1 reruns)" >&2
    return 0
  fi
  echo "=== [$name] $*" >&2
  timeout "$tmo" "$@" > "$out" 2> "$RES/${name}_${TAG}.err"
  local rc=$?
  echo "=== [$name] rc=$rc" >&2
  tail -2 "$out" >&2 || true
  note_outcome "$rc" "$out"
  return $rc
}
run() { run_with pred_json_row "$@"; }

# Queue-staleness purge (PERF.md: window 2 closed MID-SWEEP at the
# b128 rung, leaving backend_unavailable/bench_timeout rows banked
# under this round's tag).  Such rows already fail the banked
# predicates -- the rungs WILL rerun -- but their presence makes the
# end-of-series JSON listing and any human skim of $RES read dead
# rows as data; delete them up front so the resumable queue state is
# honest and the interrupted b128/b256/best rungs are visibly
# RE-QUEUED (they run in tier 3, ahead of the serve arms below).
for f in "$RES"/bench_*_"$TAG".out; do
  [ -s "$f" ] || continue
  err=$(python - "$f" <<'EOF'
import json, sys
try:
    lines = [ln for ln in open(sys.argv[1]).read().splitlines()
             if ln.strip()]
    print(json.loads(lines[-1]).get('error', ''))
except Exception:
    print('')
EOF
)
  if [ "$err" = backend_unavailable ] || [ "$err" = bench_timeout ]; then
    echo "=== purging stale dead-window row: $f ($err)" >&2
    rm -f "$f"
  fi
done

# Steps are ordered by VALUE-PER-MINUTE, not by headline order: the
# round-3 tunnel answered for ~10 minutes total, so the series must
# bank SOMETHING real in the first minutes of a window.  Tier 1 takes
# ~2-4 min cold and yields suspect-gated TPU data points (mlp model
# line + allreduce staging sweep); tier 2 is the headline ResNet-50;
# tier 3 widens; tier 4 is the MFU chase.

# Quick-step timeout: bench.py's probe retries can eat ~780s on a
# flaky tunnel before the 1800s-watchdogged child starts, so the
# outer bound must exceed 780+1800 for the child's diagnostic-JSON
# guarantee to hold (ADVICE r4 #1).
QT=2700

# --- tier 1: fast real data ------------------------------------------
run bench_mlp $QT python bench.py --model mlp --quick
run_with pred_jsonl allreduce_tpu 1800 \
    python benchmarks/allreduce_payload_sweep.py

# --- tier 2: the headline (compile ~4-6 min/scan-length uncached) ----
# --no-adopt: this artifact IS the default-config (batch 32) row that
# PERF.md and scaling_projection.py consume, and the incumbent the
# adoption policy compares sweep winners against -- letting a prior
# round's winner steer it would make adoption sticky forever (the
# default could never be re-crowned).  bench_resnet50_best below is
# the adoption consumer.
run bench_resnet50 3900 python bench.py --no-adopt

# --- tier 3: the MFU chase (VERDICT r4 next #2) ----------------------
# Promoted ABOVE the remaining workloads after the first r5 window:
# the big cold compiles (vgg16, googlenetbn) repeatedly KILL the
# tunnel's compile service, and anything ordered after them never
# runs.  ResNet-50 variants reuse a proven-compilable graph family,
# so the MFU sweep is cheap-risk, high-value (VERDICT ranks it #2).
for B in 64 128 256; do
  run "bench_resnet50_b${B}" $QT python bench.py --quick --batch "$B"
done
# MXU-friendly space-to-depth stem (exact equivalent; models/resnet50.py)
run bench_resnet50_s2d $QT python bench.py --quick --s2d
run bench_resnet50_s2d_b128 $QT python bench.py --quick --s2d --batch 128
# mixed-precision A/B: bf16 compute + bf16 gradient reduction with
# f32 master weights (chainermn_tpu/precision.py) against the tier-2
# f32-master headline -- rows carry the policy dtypes, so the pair is
# self-describing in the banked artifacts (docs/mixed_precision.md)
run bench_resnet50_bf16 $QT python bench.py --quick --policy bf16
# fused BN+relu+add Pallas arm (docs/kernels.md): the direct attack
# on the HBM-bandwidth wall the r5 batch sweep diagnosed -- rows
# carry fused_norm/hbm_bytes_per_image/pct_of_hbm_peak, so the A/B
# against bench_resnet50_bf16 is self-describing in the artifacts
run bench_resnet50_fused $QT python bench.py --quick --policy bf16 --fused-norm
# donation + remat headline arm (PERF.md knob #6): the default rows
# replay with donate=False, which understates real training -- this
# row measures with buffers donated into the step and the backward
# rematerializing the forward (rows carry donate/remat)
run bench_resnet50_donate $QT python bench.py --quick --donate

# end-of-sweep headline rerun: a PLAIN bench.py invocation adopts the
# sweep winner just banked above (bench.py:adopt_tuned_config), so the
# official-config artifact reflects THIS round's best measured config
# and the exact compile cache the driver's end-of-round BENCH run will
# hit is warmed here.  Runs non-quick (the driver's scan lengths).
# Short-circuited when adoption crowns nothing: the step would only
# duplicate tier-2's default-config measurement at full non-quick
# cost (the tier-2 run already warmed that cache).  Exit codes keep
# a crashed gate distinct from a legitimate no-winner (a crash falls
# through to MEASURING, the conservative default).
python -c "
import sys
sys.path.insert(0, '.')
import bench
sys.exit(0 if bench.adopt_tuned_config([], 'resnet50') else 3)
"
gate_rc=$?
if [ "$gate_rc" -eq 3 ]; then
  echo "=== [bench_resnet50_best] no tuned winner beats the default;" \
       "tier-2's --no-adopt row IS the best measured config" >&2
  # a best row banked EARLIER in the round under a since-dethroned
  # winner must not survive as the official artifact (it matches the
  # adoption glob and would be committed as if current)
  stale="$RES/bench_resnet50_best_${TAG}.out"
  if [ -s "$stale" ] && ! pred_best_row "$stale"; then
    echo "=== [bench_resnet50_best] removing stale dethroned row" >&2
    rm -f "$stale" "$RES/bench_resnet50_best_${TAG}.err"
  fi
else
  [ "$gate_rc" -ne 0 ] && echo "=== [bench_resnet50_best] adoption" \
    "gate crashed (rc=$gate_rc); measuring anyway" >&2
  run_with pred_best_row bench_resnet50_best 3900 python bench.py
fi

# composed dp x tp transformer (docs/mesh_parallelism.md), queued
# right after the resnet sweep: rows carry tokens/s/chip, analytic
# MFU vs the PERF.md 90-115k tok/s/chip anchor, and per-axis
# collective bytes (data vs model wire traffic)
run bench_transformer_tp $QT python bench.py --model transformer --quick --tp 2
# 3-D dp x pp pipeline arm (ISSUE 14): the stage-sliced transformer
# trained 1F1B through the unified MeshPipelineUpdater; rows add
# pp / n_microbatches / bubble_fraction (banked-sidecar conventions
# apply through outages like every transformer row)
run bench_transformer_pp $QT python bench.py --model transformer --quick --pp 2

# --- streaming input pipeline (docs/data_pipeline.md) ----------------
# streamed-vs-device-resident A/B on the resnet50 step: the value is
# streamed samples/s/chip, with the resident twin, the
# loader_efficiency ratio (1.0 = decode + H2D fully hidden under the
# step), the telemetry-measured h2d_overlap_fraction and the
# queue-depth p50 as sidecars -- every other row in this round feeds
# device-resident arrays; this one prices the production feed path.
run bench_resnet50_loader $QT python bench.py --loader --model resnet50 --quick

# --- serving arms (docs/serving.md) ----------------------------------
# AFTER the training headline + the re-queued b128/b256/best rungs on
# purpose: the training MFU chase is the round's primary unbanked
# claim (window 2 died mid-sweep and those rungs have waited two
# rounds), while the serve arms are a NEW metric family with no
# banked baseline to regress -- first-window minutes go to the data
# the projections already consume.  Rows carry req/s/chip, p50/p99
# latency from telemetry histograms, pad-waste fraction, bucket
# hit-rate and AOT/cache provenance; the int8 arm pairs with the
# bf16 one as a self-describing quantization A/B.
run bench_serve_mlp $QT python bench.py --serve --model mlp --quick
run bench_serve_resnet50 $QT python bench.py --serve --quick
run bench_serve_resnet50_int8 $QT python bench.py --serve --quick --int8
# autoregressive arm (docs/serving.md "Autoregressive generation"):
# tokens/s/chip + TTFT + inter-token p50/p99 through continuous
# batching over the prefill/decode AOT split, anchored against the
# PERF.md ~290k tok/s/chip perfect-MXU number; the --int8-kv arm
# pairs with it as the KV-cache-bandwidth A/B (decode is HBM-bound,
# so halving cache bytes is the knob that should move tokens/s).
# Queued here -- after the training headline and the re-queued
# b128/b256/best MFU rungs -- for the same reason as the serve arms
# above: a new metric family with no banked baseline must not starve
# the round's primary unbanked claim.
run bench_serve_generate $QT python bench.py --serve --generate --quick
run bench_serve_generate_int8kv $QT python bench.py --serve --generate --quick --int8-kv

# paged KV cache + chunked prefill (ISSUE 17): the serving
# memory-economy A/B against the slot-cache rows above -- same
# model, same offered load, but the KV lives in a shared page pool
# behind a radix prefix index.  The rows carry prefix_hit_rate /
# pages_per_request / kv_bytes_per_token sidecars; the slot rows
# carry the same columns (None for the page-economy pair) so the
# diff is column-wise.
run bench_serve_generate_paged $QT python bench.py --serve --generate --quick --paged --prefill-chunk 8
run bench_serve_generate_paged_int8kv $QT python bench.py --serve --generate --quick --paged --prefill-chunk 8 --int8-kv

# speculative decoding (ISSUE 19): the last serving-memory-economy
# lever -- a half-depth draft proposes k tokens, the target verifies
# the window in ONE pass, so accepted tokens amortize the HBM-bound
# cache read.  The row's in-bench probe pins exact greedy equivalence
# vs the non-speculative oracle twin (spec_equivalent=true or the arm
# fails), and accepted_draft_rate / verify_per_token ride as the
# amortization sidecars; the paged twin composes with prefix sharing
# + chunked prefill, pairing column-wise with the arms above.
run bench_serve_generate_spec $QT python bench.py --serve --generate --quick --speculative
run bench_serve_generate_paged_spec $QT python bench.py --serve --generate --quick --speculative --paged --prefill-chunk 8

# continuous deployment (ISSUE 13): how fast weights roll through a
# 2-replica serving fleet under live traffic -- rolls/minute with
# the contract sidecars (dropped_during_swap MUST be 0, per-replica
# out-of-rotation downtime p50/p99, promote/rollback outcomes from
# fleet_ledger.jsonl).  Queued after the generate arms: same
# new-family-never-starves-the-headline reasoning.
run bench_serve_fleet $QT python bench.py --serve --fleet --quick

# serving self-healing (ISSUE 20): MTTR from a hard replica kill
# mid-decode to the first recovered continuation token on a
# survivor, with lost_requests as a HARD rc-1 gate (a journal left
# with open entries breaks the contract whatever the MTTR says);
# detection latency, requeue/respawn counts and degradation-rung
# occupancy ride as sidecars.  Queued right after the fleet arm it
# degrades from.
run bench_serve_fleet_recovery $QT python bench.py --serve --fleet --recovery --quick

# --- tier 4: the remaining BASELINE workloads ------------------------
# seq2seq FIRST: it is the variable-shape allreduce configuration
# (VERDICT #4) -- the datum no other workload stands in for -- and
# must not starve behind the transformer pair when a window closes
# mid-tier.  Then the two tunnel-killers LAST, with a smaller-batch
# vgg16 attempt (smaller program) before the standard one so SOME
# vgg16 datum banks even if the full config kills the compile
# service again (per_device_batch_override is recorded in the row,
# so the config is honest)
run bench_seq2seq $QT python bench.py --model seq2seq --quick
run bench_transformer $QT python bench.py --model transformer --quick
run bench_transformer_check $QT python bench.py --model transformer --quick --check

# flash-attention kernel vs XLA attention + block-size sweep
run_with pred_wrote flash_attn 3000 \
    python benchmarks/flash_attention_bench.py --sweep

# transformer re-bench with the sweep's crowned block sizes (adopts
# the winner automatically; exits un-banked when no sweep row yet)
run bench_transformer_fatuned $QT bash ci/run_fa_tuned.sh

# measured strategy comparison + profiler traces (VERDICT r3 item 9)
run_with pred_wrote strategy_trace $QT \
    python benchmarks/strategy_trace.py

# Mosaic kernel gate (fast when compile cache is warm); conftest
# forces CPU unless told to keep the live platform
run_with pred_pytest_green mosaic_gate 1200 \
    env CHAINERMN_TPU_TEST_PLATFORM=axon \
    python -m pytest tests/test_tpu_mosaic.py -v

# --- tier 5: the tunnel-killer compiles, LAST ------------------------
run bench_googlenetbn $QT python bench.py --model googlenetbn --quick
run bench_vgg16_b16 $QT python bench.py --model vgg16 --quick --batch 16
run bench_vgg16 $QT python bench.py --model vgg16 --quick

# (the 8->256 scaling projection regen runs in the EXIT trap above,
# so it also covers the circuit-breaker abort path)

echo "=== series done; JSON lines:" >&2
for f in "$RES"/bench_*_"$TAG".out; do
  tail -1 "$f"
done
date -u +%Y-%m-%dT%H:%M:%SZ > "$RES/series_${TAG}.done"
