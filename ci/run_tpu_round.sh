#!/bin/bash
# One-shot TPU measurement series for an end-of-round artifact drop.
#
# Runs every chip-dependent benchmark exactly once, SERIALIZED (the
# axon tunnel starves concurrent clients -- see
# .claude/skills/verify/SKILL.md), with per-step timeouts so a hung
# backend cannot wedge the whole series.  Results land in
# benchmarks/results/ for commit; bench JSON lines are echoed.
#
# Usage: bash ci/run_tpu_round.sh [round_tag]    (default r3)
set -u
cd "$(dirname "$0")/.."
TAG=${1:-r3}
RES=benchmarks/results
mkdir -p "$RES"

# preflight: one bounded probe so a dead tunnel fails the series in
# ~2 minutes instead of burning every step's own probe window
if ! timeout 120 python -c "
import jax, jax.numpy as jnp
assert jax.default_backend() == 'tpu', (
    'not a TPU backend: %s -- a silent CPU fallback would record '
    'bogus artifacts as TPU data' % jax.default_backend())
y = jax.jit(lambda a: a @ a)(jnp.ones((256, 256), jnp.bfloat16))
jax.device_get(y[:1, :1])
print('preflight ok:', jax.default_backend())
" >&2; then
  echo "preflight FAILED: TPU backend unreachable; aborting series" >&2
  exit 2
fi

run() {  # run <name> <timeout_s> <cmd...>
  local name=$1 tmo=$2; shift 2
  echo "=== [$name] $*" >&2
  timeout "$tmo" "$@" > "$RES/${name}_${TAG}.out" 2> "$RES/${name}_${TAG}.err"
  local rc=$?
  echo "=== [$name] rc=$rc" >&2
  tail -2 "$RES/${name}_${TAG}.out" >&2 || true
  return $rc
}

# Steps are ordered by VALUE-PER-MINUTE, not by headline order: the
# round-3 tunnel answered for ~10 minutes total, so the series must
# bank SOMETHING real in the first minutes of a window.  Tier 1 takes
# ~2-4 min cold and yields the first-ever suspect-gated TPU data
# points (mlp model line + allreduce datum); tier 2 is the headline
# ResNet-50; tier 3 widens.

# --- tier 1: fast real data ------------------------------------------
# (generous timeout: bench.py's own probe retries can eat ~780s on a
# flaky tunnel before the quick child even starts; the step is fast
# when the tunnel is healthy, the bound only caps the worst case)
run bench_mlp 2400 python bench.py --model mlp --quick
run allreduce_tpu 1200 python benchmarks/allreduce_scaling.py --devices 1

# --- tier 2: the headline (compile ~4-6 min/scan-length uncached) ----
run bench_resnet50 3600 python bench.py

# --- tier 3: the other BASELINE workloads (quick scans) --------------
for m in vgg16 googlenetbn seq2seq transformer; do
  run "bench_${m}" 2400 python bench.py --model "$m" --quick
done

# transformer numerics gate: Pallas kernels vs jnp oracle on-device
run bench_transformer_check 2400 python bench.py --model transformer --quick --check

# flash-attention kernel vs XLA attention + block-size sweep
run flash_attn 3000 python benchmarks/flash_attention_bench.py --sweep

# measured strategy comparison + profiler traces (VERDICT r3 item 9)
run strategy_trace 2400 python benchmarks/strategy_trace.py

# Mosaic kernel gate (fast when compile cache is warm); conftest
# forces CPU unless told to keep the live platform
run mosaic_gate 1200 env CHAINERMN_TPU_TEST_PLATFORM=axon \
    python -m pytest tests/test_tpu_mosaic.py -v

# --- tier 4 (only if the window is still open): the MFU direction ---
# per-device batch sweep on the headline model; each point costs its
# own scan compiles, so this runs LAST (PERF.md knob 1)
for B in 64 128; do
  run "bench_resnet50_b${B}" 2400 python bench.py --quick --batch "$B"
done
# MXU-friendly space-to-depth stem (exact equivalent; models/resnet50.py)
run bench_resnet50_s2d 2400 python bench.py --quick --s2d

echo "=== series done; JSON lines:" >&2
for f in "$RES"/bench_*_"$TAG".out; do
  tail -1 "$f"
done
