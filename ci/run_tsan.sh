#!/usr/bin/env bash
# ThreadSanitizer pass over the native collective engine -- the race
# detection the reference never had (SURVEY 5: "race detection /
# sanitizers: none").
set -euo pipefail
cd "$(dirname "$0")/.."
mkdir -p build

g++ -O1 -g -std=c++17 -fsanitize=thread -pthread \
    csrc/chainermn_core.cpp csrc/test_collectives_stress.cpp \
    -o build/tsan_stress
TSAN_OPTIONS="halt_on_error=1" ./build/tsan_stress 4 200

# plain optimized build as a functional stress pass
g++ -O3 -std=c++17 -pthread \
    csrc/chainermn_core.cpp csrc/test_collectives_stress.cpp \
    -o build/stress
./build/stress 8 500
echo "native stress + tsan OK"
