#!/bin/bash
# Bench the transformer with the flash-attention block sizes the
# on-chip sweep just crowned (PERF.md window playbook, automated):
# reads the sweep rows from benchmarks/results/flash_attention_tpu.jsonl,
# picks the fastest non-suspect (block_q, block_k), and execs bench.py
# with the CHAINERMN_TPU_FA_BLOCK_Q/K overrides.  Exits 3 when no
# usable sweep row exists (step stays un-banked and retries next
# window, after the sweep has run).
set -u
cd "$(dirname "$0")/.."

PICK=$(python - <<'EOF'
import json, os
path = 'benchmarks/results/flash_attention_tpu.jsonl'
best = None
if os.path.exists(path):
    for ln in open(path):
        try:
            r = json.loads(ln)
        except ValueError:
            continue
        if (r.get('sweep') and not r.get('suspect')
                and not r.get('error') and r.get('pallas_ms')):
            if best is None or r['pallas_ms'] < best['pallas_ms']:
                best = r
if best:
    print('%d %d' % (best['block_q'], best['block_k']))
EOF
)
if [ -z "$PICK" ]; then
  echo "no usable sweep row in flash_attention_tpu.jsonl; run the" \
       "flash_attn sweep first" >&2
  exit 3
fi
set -- $PICK
echo "adopting sweep winner: block_q=$1 block_k=$2" >&2
exec env CHAINERMN_TPU_FA_BLOCK_Q="$1" CHAINERMN_TPU_FA_BLOCK_K="$2" \
  python bench.py --model transformer --quick
