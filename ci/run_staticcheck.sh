#!/usr/bin/env bash
# shardlint gate: jaxpr-level static analysis of every registered
# communicator strategy plus the example/updater/zero/pipeline train
# steps (docs/static_analysis.md).  JSON mode on stdout for log
# scraping; exit 1 (-> lint gate failure) on any ERROR-severity
# finding.  CPU-only by construction: tracing runs no collective.
#
# TWO sweeps: the default full-precision pass, then the same targets
# under the bf16 mixed-precision policy (strategies constructed with
# reduce_dtype=bfloat16, updaters with Policy.bf16()) -- the
# clean-sweep guarantee covers both precisions, and SL004's
# declared-reduce-dtype allowance is exercised for real, not just in
# fixtures (docs/mixed_precision.md).
#
# Each sweep also carries the HBM-traffic audit (docs/kernels.md):
# the memtraffic report (bytes-accessed / bytes-per-item / widest
# intermediates) over every step target, and rule SL008 flagging f32
# activation materializations in declared-bf16 graphs.  The check
# below pins the gate's structural claims: both resnet50 variants
# (flax-oracle AND fused batch_norm_act) are audited, and the FUSED
# step materializes zero f32 activation-sized intermediates.
set -euo pipefail
cd "$(dirname "$0")/.."

check_memtraffic() {
  python -c "
import json, sys
report = json.load(open(sys.argv[1]))
rows = {r['target']: r for r in report.get('memtraffic', [])}
for target in ('step:resnet50_example', 'step:resnet50_fused'):
    assert target in rows, 'memtraffic row missing: %s' % target
    assert rows[target].get('bytes_accessed') or \
        rows[target].get('cost_error'), rows[target]
fused = rows['step:resnet50_fused']
assert fused['f32_materialized_count'] == 0, fused
unfused = rows['step:resnet50_example']
assert unfused['f32_materialized_bytes'] > \
    fused['f32_materialized_bytes'], (unfused, fused)
print('memtraffic OK: unfused %.2f MB f32-materialized -> fused %d'
      % (unfused['f32_materialized_bytes'] / 1e6,
         fused['f32_materialized_bytes']))
" "$1"
}

# SL009 overlap gate (ISSUE 6 / ROADMAP item 5): the collective-
# schedulability rule must (a) stay SILENT on the bucketed-overlap
# reference step -- >= 2 fused buckets give every collective an
# independently schedulable sibling -- and (b) FIRE on the fused
# single-buffer mlp step (the xla strategy's monolithic psum is the
# deliberately serialized baseline: the whole backward completes
# before the one collective starts).  Enforced in BOTH precision
# sweeps, so an overlap regression fails CI the way dtype regressions
# (SL004/SL008) already do.
check_sl009() {
  python -c "
import json, sys
report = json.load(open(sys.argv[1]))
sl9 = [f for f in report['findings'] if f['rule'] == 'SL009']
bucketed = [f for f in sl9 if f['target'] == 'step:bucketed_overlap']
assert not bucketed, (
    'bucketed-overlap step must lint clean under SL009: %r' % bucketed)
assert 'step:bucketed_overlap' in report['targets'], report['targets']
serialized = [f for f in sl9 if f['target'] == 'step:mlp_example']
assert serialized, (
    'SL009 no longer fires on the fused single-buffer mlp step -- '
    'either overlap was actually fixed (update this check and the '
    'docs) or the rule went blind')
print('SL009 OK: bucketed_overlap clean, fused mlp flagged (%d '
      'finding(s) total)' % len(sl9))
" "$1"
}

# SL010-family gate (docs/mesh_parallelism.md): the composed dp x tp
# transformer_tp step -- and since ISSUE 14 the 3-D dp x pp
# (transformer_pp) and dp x tp x pp (transformer_tp_pp) unified
# pipeline steps -- must be IN the sweep and lint clean under the
# multi-axis rules (SL010 plan-axis discipline incl. the third axis,
# SL011 cross-axis chains, SL012 tp-aware donation); the pipeline
# steps must additionally carry no SL002 finding (the 1F1B
# stage-handoff ppermute ring is bijective BY the lint, not by
# inspection).  Known-bad shapes are pinned by fixtures in
# tests/test_analysis.py; this check pins the clean state in BOTH
# precision sweeps.
check_sl010() {
  python -c "
import json, sys
report = json.load(open(sys.argv[1]))
plan_targets = ('step:transformer_tp', 'step:transformer_pp',
                'step:transformer_tp_pp')
for t in plan_targets:
    assert t in report['targets'], (t, report['targets'])
multi = [f for f in report['findings']
         if f['target'] in plan_targets
         and f['rule'] in ('SL010', 'SL011', 'SL012')]
assert not multi, (
    'plan targets must lint clean under the SL010 family: %r' % multi)
pperm = [f for f in report['findings']
         if f['target'] in ('step:transformer_pp',
                            'step:transformer_tp_pp')
         and f['rule'] == 'SL002']
assert not pperm, (
    'the 1F1B ppermute handoff must pass SL002: %r' % pperm)
print('SL010 OK: transformer_tp + transformer_pp + transformer_tp_pp '
      'swept and clean under the multi-axis rules (SL002 clean on '
      'the ppermute handoff)')
" "$1"
}

# serve-forward gate (docs/serving.md): the serving engine's
# forward-only apply over the MeshPlan must be IN the sweep (the
# request path gets the same SL001-SL012 machine checks as training
# steps) and clean under the multi-axis family and every
# ERROR-severity rule.  ONE warning is expected and pinned: the
# transformer's lm head deliberately contracts logits in f32
# (models/transformer.py vocab-head numerics), which SL008 flags at
# serve bucket shapes -- any finding beyond that set fails the gate.
check_serve() {
  python -c "
import json, sys
report = json.load(open(sys.argv[1]))
assert 'step:serve_forward' in report['targets'], report['targets']
fs = [f for f in report['findings']
      if f['target'] == 'step:serve_forward']
errors = [f for f in fs if f['severity'] == 'error']
assert not errors, (
    'serve_forward must carry no error findings: %r' % errors)
multi = [f for f in fs if f['rule'] in ('SL010', 'SL011', 'SL012')]
assert not multi, (
    'serve_forward must lint clean under the SL010 family: %r'
    % multi)
unexpected = [f for f in fs if f['rule'] != 'SL008']
assert not unexpected, (
    'serve_forward grew findings beyond the pinned lm-head SL008 '
    'warning: %r' % unexpected)
print('serve OK: serve_forward swept, no errors, SL010 family '
      'clean (%d pinned SL008 warning(s))'
      % len([f for f in fs if f['rule'] == 'SL008']))
" "$1"
}

# decode-forward gate (docs/serving.md "Autoregressive generation"):
# the GenerationEngine's KV-cache decode step over the MeshPlan must
# be IN the sweep and clean under every ERROR-severity rule and the
# SL010 multi-axis family -- the decode regime's per-token psums get
# the same machine checks as the batch request path.  Its make_args
# is iteration-independent, so SL007 here is the static twin of the
# continuous-batching no-recompile pin (slot refills never retrace).
# SL008 is tolerated the way check_serve tolerates the lm-head f32
# contraction; anything else fails the gate.
check_decode() {
  python -c "
import json, sys
report = json.load(open(sys.argv[1]))
assert 'step:decode_forward' in report['targets'], report['targets']
fs = [f for f in report['findings']
      if f['target'] == 'step:decode_forward']
errors = [f for f in fs if f['severity'] == 'error']
assert not errors, (
    'decode_forward must carry no error findings: %r' % errors)
multi = [f for f in fs if f['rule'] in ('SL010', 'SL011', 'SL012')]
assert not multi, (
    'decode_forward must lint clean under the SL010 family: %r'
    % multi)
unexpected = [f for f in fs if f['rule'] != 'SL008']
assert not unexpected, (
    'decode_forward grew findings beyond the tolerated SL008 '
    'set: %r' % unexpected)
print('decode OK: decode_forward swept, no errors, SL010 family '
      'clean (%d SL008 warning(s))'
      % len([f for f in fs if f['rule'] == 'SL008']))
" "$1"
}

# spec-verify gate (docs/serving.md "Speculative decoding"): the
# speculative engine's k-token target-verify executable must be IN
# the sweep and clean under every ERROR-severity rule and the SL010
# multi-axis family -- the verify pass carries the same tp psums as
# decode but at window shapes, and its make_args is iteration- AND
# acceptance-independent, so SL007 here is the static twin of the
# runtime guarantee that rollback / variable per-tick commit counts
# never retrace.  SL008 tolerated as in check_decode (lm-head f32
# contraction, now over k positions); anything else fails the gate.
check_spec() {
  python -c "
import json, sys
report = json.load(open(sys.argv[1]))
assert 'step:spec_verify_forward' in report['targets'], \
    report['targets']
fs = [f for f in report['findings']
      if f['target'] == 'step:spec_verify_forward']
errors = [f for f in fs if f['severity'] == 'error']
assert not errors, (
    'spec_verify_forward must carry no error findings: %r' % errors)
multi = [f for f in fs if f['rule'] in ('SL010', 'SL011', 'SL012')]
assert not multi, (
    'spec_verify_forward must lint clean under the SL010 family: %r'
    % multi)
unexpected = [f for f in fs if f['rule'] != 'SL008']
assert not unexpected, (
    'spec_verify_forward grew findings beyond the tolerated SL008 '
    'set: %r' % unexpected)
print('spec OK: spec_verify_forward swept, no errors, SL010 family '
      'clean (%d SL008 warning(s))'
      % len([f for f in fs if f['rule'] == 'SL008']))
" "$1"
}

# commcheck gate (docs/static_analysis.md "Cross-rank verification"):
# the cross-rank communication verifier must have swept EVERY
# registered strategy and the eager reference protocol at world sizes
# {2,3,4} -- and every target (strategies, step/plan jaxprs, 1F1B
# schedules) must be SL013/SL014-clean.  The second half is the
# firing self-test: the verifier itself is exercised against three
# known-bad protocols (rank-branched collective, unmatched send,
# broken multi-step ppermute chain) and must name the ranks and ops
# -- a commcheck that stops firing passes the clean sweep trivially,
# so the gate pins both directions in BOTH precision sweeps.
check_commcheck() {
  python -c "
import json, sys
report = json.load(open(sys.argv[1]))
cc = report.get('commcheck')
assert cc, 'commcheck section missing from the report'
assert cc['world_sizes'] == [2, 3, 4], cc['world_sizes']
from chainermn_tpu.communicators import _COMMUNICATORS
assert sorted(cc['strategies']) == sorted(_COMMUNICATORS), (
    cc['strategies'])
assert cc['ok'], 'commcheck sweep not clean: %r' % cc
assert not cc['skipped'], 'strategies skipped: %r' % cc['skipped']
assert all(p['ok'] for p in cc['protocols']), cc['protocols']
assert all(s['ok'] for s in cc['pipeline_schedules']), (
    cc['pipeline_schedules'])
bad = [f for f in report['findings']
       if f['rule'] in ('SL013', 'SL014') and f['severity'] == 'error']
assert not bad, 'cross-rank findings on real targets: %r' % bad
print('commcheck OK: %d strategies x ws %s clean, %d stream traces, '
      '%d eager protocols, %d pipeline schedules'
      % (len(cc['strategies']), cc['world_sizes'],
         cc['n_stream_traces'], len(cc['protocols']),
         len(cc['pipeline_schedules'])))
"  "$1"
}

check_commcheck_fires() {
  JAX_PLATFORMS=cpu python -c "
from chainermn_tpu.analysis import commcheck
from chainermn_tpu.communicators.recording import (
    RecordingCommunicator, simulate_protocol)

# 1. rank-branched collective: rank 1 issues an extra allreduce.
def branched(comm):
    comm.allreduce_obj(1.0, op='mean')
    if comm.rank == 1:
        comm.allreduce_obj(2.0, op='sum')
    comm.barrier(tag='sync')
d = commcheck.verify_streams(simulate_protocol(branched, 3))
assert d is not None, 'rank-branched collective not detected'
assert d['position'] == 1 and 1 in d['ranks'], d
assert 'rank 1' in d['summary'] and 'allreduce_obj' in d['summary'], d

# 2. unmatched send: rank 0 sends to a rank that never receives.
def lonely_send(comm):
    if comm.rank == 0:
        comm.send_obj({'x': 1}, dest=1, tag=9)
items = commcheck.match_p2p(simulate_protocol(lonely_send, 2))
kinds = [i['kind'] for i in items]
assert 'unmatched_send' in kinds, items
msg = [i for i in items if i['kind'] == 'unmatched_send'][0]
assert 0 in msg['ranks'] and 'tag' in msg['message'], msg

# 3. broken multi-step ppermute chain: the composed permutation
#    never delivers to rank 3 on a size-4 axis.
d = commcheck.check_ppermute_chain([(0, 1), (1, 2)], size=4, n_steps=3)
assert d is not None and d['unreachable'] == [3], d
assert 'rank(s) [3]' in d['message'], d
assert commcheck.check_ppermute_chain(
    [(i, (i + 1) % 4) for i in range(4)], size=4, n_steps=8) is None
print('commcheck firing self-test OK: rank-branch @pos %d, '
      'unmatched send named, broken chain named' % 1)
"
}

out_f32=$(mktemp)
out_bf16=$(mktemp)
trap 'rm -f "$out_f32" "$out_bf16"' EXIT

JAX_PLATFORMS=cpu python -m chainermn_tpu.analysis --json | tee "$out_f32"
check_memtraffic "$out_f32"
check_sl009 "$out_f32"
check_sl010 "$out_f32"
check_serve "$out_f32"
check_decode "$out_f32"
check_spec "$out_f32"
check_commcheck "$out_f32"
JAX_PLATFORMS=cpu python -m chainermn_tpu.analysis --json --policy bf16 | tee "$out_bf16"
check_memtraffic "$out_bf16"
check_sl009 "$out_bf16"
check_sl010 "$out_bf16"
check_serve "$out_bf16"
check_decode "$out_bf16"
check_spec "$out_bf16"
check_commcheck "$out_bf16"
check_commcheck_fires
