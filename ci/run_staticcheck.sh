#!/usr/bin/env bash
# shardlint gate: jaxpr-level static analysis of every registered
# communicator strategy plus the example/updater/zero/pipeline train
# steps (docs/static_analysis.md).  JSON mode on stdout for log
# scraping; exit 1 (-> lint gate failure) on any ERROR-severity
# finding.  CPU-only by construction: tracing runs no collective.
#
# TWO sweeps: the default full-precision pass, then the same targets
# under the bf16 mixed-precision policy (strategies constructed with
# reduce_dtype=bfloat16, updaters with Policy.bf16()) -- the
# clean-sweep guarantee covers both precisions, and SL004's
# declared-reduce-dtype allowance is exercised for real, not just in
# fixtures (docs/mixed_precision.md).
set -euo pipefail
cd "$(dirname "$0")/.."
JAX_PLATFORMS=cpu python -m chainermn_tpu.analysis --json
JAX_PLATFORMS=cpu python -m chainermn_tpu.analysis --json --policy bf16
