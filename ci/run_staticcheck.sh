#!/usr/bin/env bash
# shardlint gate: jaxpr-level static analysis of every registered
# communicator strategy plus the example/updater/zero/pipeline train
# steps (docs/static_analysis.md).  JSON mode on stdout for log
# scraping; exit 1 (-> lint gate failure) on any ERROR-severity
# finding.  CPU-only by construction: tracing runs no collective.
set -euo pipefail
cd "$(dirname "$0")/.."
JAX_PLATFORMS=cpu python -m chainermn_tpu.analysis --json
