#!/usr/bin/env python
"""Dependency-free lint pass (reference parity: ``.travis.yml:51-54``
runs flake8/autopep8; this image ships no linter, so CI enforces the
core rules with the stdlib and ``setup.cfg`` keeps the real flake8
config for environments that have it).

Checks: syntax (ast), line length <= 79, trailing whitespace, tabs in
indentation, unused ``import x`` / ``from x import y`` bindings at
module scope (noqa-comment aware), missing newline at EOF, bare
``except:`` (E722), mutable default arguments (B006), and -- inside
``chainermn_tpu/`` hot paths only -- ``jax.device_get`` /
``np.asarray`` calls (SHL01: either is a host sync when handed a
traced value; the eager driver-level uses are allow-listed with
``# noqa: shardlint``).
"""

import ast
import os
import sys

MAX_LEN = 79
EXCLUDE = {'.git', '__pycache__', 'build', 'docs', '.jax_compile_cache',
           'result', '.pytest_cache'}
#: directories whose code runs per-iteration (traced or driving the
#: device loop) -- the SHL01 host-sync rule applies only here
HOT_PATHS = ('chainermn_tpu/communicators/', 'chainermn_tpu/training/',
             'chainermn_tpu/parallel/', 'chainermn_tpu/ops/')
#: calls that synchronize with the host when given a traced/device
#: value: (module alias, attribute)
HOST_SYNC_CALLS = {('jax', 'device_get'), ('np', 'asarray'),
                   ('numpy', 'asarray')}


def iter_py(root):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d not in EXCLUDE
                       and not d.startswith('result')]
        for fn in filenames:
            if fn.endswith('.py'):
                yield os.path.join(dirpath, fn)


def unused_imports(tree, src_lines):
    names = {}  # alias -> (lineno, qualname)
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                alias = a.asname or a.name.split('.')[0]
                names[alias] = (node.lineno, a.name)
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name == '*':
                    continue
                alias = a.asname or a.name
                names[alias] = (node.lineno, a.name)
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            pass  # the base Name is what binds
    out = []
    for alias, (lineno, qual) in sorted(names.items()):
        line = src_lines[lineno - 1] if lineno - 1 < len(src_lines) else ''
        if 'noqa' in line:
            continue
        if alias not in used:
            out.append((lineno, 'F401 %r imported but unused' % qual))
    return out


def _line_suppressed(src_lines, lineno, code=None):
    """True when the source line carries a ``noqa`` comment (bare, or
    scoped to ``code`` via ``# noqa: <code>``)."""
    line = src_lines[lineno - 1] if 0 < lineno <= len(src_lines) else ''
    if 'noqa' not in line:
        return False
    if code is None:
        return True
    mark = line[line.index('noqa'):]
    return ':' not in mark or code in mark


def ast_rules(tree, src_lines, hot_path):
    """AST-level rules: bare except, mutable defaults, and (hot paths
    only) host-sync calls."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            if not _line_suppressed(src_lines, node.lineno):
                out.append((node.lineno,
                            "E722 do not use bare 'except:'"))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defaults = (list(node.args.defaults)
                        + [d for d in node.args.kw_defaults
                           if d is not None])
            for d in defaults:
                mutable = isinstance(d, (ast.List, ast.Dict, ast.Set))
                if (isinstance(d, ast.Call)
                        and isinstance(d.func, ast.Name)
                        and d.func.id in ('list', 'dict', 'set')):
                    mutable = True
                if mutable and not _line_suppressed(src_lines,
                                                    d.lineno):
                    out.append((d.lineno,
                                'B006 mutable default argument'))
        elif (hot_path and isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and (node.func.value.id, node.func.attr)
                in HOST_SYNC_CALLS):
            if not _line_suppressed(src_lines, node.lineno,
                                    'shardlint'):
                out.append((
                    node.lineno,
                    'SHL01 %s.%s in a hot path: host sync if handed '
                    'a traced value (allow-list deliberate eager use '
                    'with `# noqa: shardlint`)'
                    % (node.func.value.id, node.func.attr)))
    return out


def lint_file(path):
    problems = []
    with open(path, 'rb') as f:
        raw = f.read()
    if raw and not raw.endswith(b'\n'):
        problems.append((len(raw.splitlines()), 'W292 no newline at EOF'))
    try:
        src = raw.decode('utf-8')
    except UnicodeDecodeError as e:
        return [(0, 'E902 %s' % e)]
    lines = src.splitlines()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [(e.lineno or 0, 'E999 %s' % e.msg)]
    for i, line in enumerate(lines, 1):
        if 'noqa' in line:
            continue
        if len(line) > MAX_LEN:
            problems.append((i, 'E501 line too long (%d > %d)'
                             % (len(line), MAX_LEN)))
        if line != line.rstrip():
            problems.append((i, 'W291 trailing whitespace'))
        stripped = line.lstrip(' ')
        if stripped.startswith('\t') or line.startswith('\t'):
            problems.append((i, 'W191 tab in indentation'))
    problems.extend(unused_imports(tree, lines))
    norm = os.path.abspath(path).replace(os.sep, '/')
    hot = any(hp in norm for hp in HOT_PATHS)
    problems.extend(ast_rules(tree, lines, hot))
    return sorted(problems)


def main(root='.'):
    total = 0
    for path in sorted(iter_py(root)):
        for lineno, msg in lint_file(path):
            print('%s:%d: %s' % (os.path.relpath(path, root), lineno, msg))
            total += 1
    print('%d problem(s)' % total)
    return 1 if total else 0


if __name__ == '__main__':
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else '.'))
