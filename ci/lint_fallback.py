#!/usr/bin/env python
"""Dependency-free lint pass (reference parity: ``.travis.yml:51-54``
runs flake8/autopep8; this image ships no linter, so CI enforces the
core rules with the stdlib and ``setup.cfg`` keeps the real flake8
config for environments that have it).

Checks: syntax (ast), line length <= 79, trailing whitespace, tabs in
indentation, unused ``import x`` / ``from x import y`` bindings at
module scope (noqa-comment aware), missing newline at EOF.
"""

import ast
import os
import sys

MAX_LEN = 79
EXCLUDE = {'.git', '__pycache__', 'build', 'docs', '.jax_compile_cache',
           'result', '.pytest_cache'}


def iter_py(root):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d not in EXCLUDE
                       and not d.startswith('result')]
        for fn in filenames:
            if fn.endswith('.py'):
                yield os.path.join(dirpath, fn)


def unused_imports(tree, src_lines):
    names = {}  # alias -> (lineno, qualname)
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                alias = a.asname or a.name.split('.')[0]
                names[alias] = (node.lineno, a.name)
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name == '*':
                    continue
                alias = a.asname or a.name
                names[alias] = (node.lineno, a.name)
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            pass  # the base Name is what binds
    out = []
    for alias, (lineno, qual) in sorted(names.items()):
        line = src_lines[lineno - 1] if lineno - 1 < len(src_lines) else ''
        if 'noqa' in line:
            continue
        if alias not in used:
            out.append((lineno, 'F401 %r imported but unused' % qual))
    return out


def lint_file(path):
    problems = []
    with open(path, 'rb') as f:
        raw = f.read()
    if raw and not raw.endswith(b'\n'):
        problems.append((len(raw.splitlines()), 'W292 no newline at EOF'))
    try:
        src = raw.decode('utf-8')
    except UnicodeDecodeError as e:
        return [(0, 'E902 %s' % e)]
    lines = src.splitlines()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [(e.lineno or 0, 'E999 %s' % e.msg)]
    for i, line in enumerate(lines, 1):
        if 'noqa' in line:
            continue
        if len(line) > MAX_LEN:
            problems.append((i, 'E501 line too long (%d > %d)'
                             % (len(line), MAX_LEN)))
        if line != line.rstrip():
            problems.append((i, 'W291 trailing whitespace'))
        stripped = line.lstrip(' ')
        if stripped.startswith('\t') or line.startswith('\t'):
            problems.append((i, 'W191 tab in indentation'))
    problems.extend(unused_imports(tree, lines))
    return sorted(problems)


def main(root='.'):
    total = 0
    for path in sorted(iter_py(root)):
        for lineno, msg in lint_file(path):
            print('%s:%d: %s' % (os.path.relpath(path, root), lineno, msg))
            total += 1
    print('%d problem(s)' % total)
    return 1 if total else 0


if __name__ == '__main__':
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else '.'))
