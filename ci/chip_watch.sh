#!/bin/bash
# Chip-watch daemon: probe the axon TPU tunnel on a timer and fire the
# round's measurement series (ci/run_tpu_round.sh) on first contact.
#
# Round 3 lost its only benchmark window because the tunnel answered for
# ~10 minutes in a 12-hour round and nobody was watching
# (VERDICT.md round-3, "Next round" item 2).  This watcher removes the
# human from the loop: it logs every probe, records contact windows, and
# runs the serialized series the moment the chip answers.
#
# Usage: bash ci/chip_watch.sh [round_tag] [interval_s] [max_hours]
#   round_tag   tag passed to run_tpu_round.sh (default r4)
#   interval_s  sleep between probes (default 300)
#   max_hours   give up after this many hours (default 11)
#
# Exit codes: 0 = series completed (rc recorded in log), 3 = timed out
# without ever reaching the chip.
set -u
cd "$(dirname "$0")/.."
TAG=${1:-r4}
INTERVAL=${2:-300}
MAX_HOURS=${3:-11}
RES=benchmarks/results
LOG="$RES/chip_watch_${TAG}.log"
mkdir -p "$RES"

log() { echo "$(date -u +%Y-%m-%dT%H:%M:%SZ) $*" >> "$LOG"; }

probe() {
  # Tiny jit + device_get with a hard bound; the tunnel's usual
  # failure mode is an indefinite hang, so timeout is the real check
  # -- but a FAST failure (import error, wrong backend) is an
  # environment bug, not a closed tunnel, and must be visible in the
  # log instead of burning the whole watch window as "no contact".
  timeout 150 python - > /tmp/chip_probe.$$ 2>&1 <<'EOF'
import jax, jax.numpy as jnp
assert jax.default_backend() == "tpu", jax.default_backend()
y = jax.jit(lambda a: a @ a)(jnp.ones((256, 256), jnp.bfloat16))
jax.device_get(y[:1, :1])
EOF
}

log "armed: tag=$TAG interval=${INTERVAL}s max=${MAX_HOURS}h pid=$$"
deadline=$(( $(date +%s) + MAX_HOURS * 3600 ))
attempt=0
while [ "$(date +%s)" -lt "$deadline" ]; do
  attempt=$((attempt + 1))
  t0=$(date +%s)
  probe
  prc=$?
  if [ "$prc" -eq 0 ]; then
    log "contact: attempt=$attempt probe_s=$(( $(date +%s) - t0 ))"
    rm -f /tmp/chip_probe.$$
    log "firing run_tpu_round.sh $TAG"
    bash ci/run_tpu_round.sh "$TAG" >> "$LOG" 2>&1
    rc=$?
    log "series done rc=$rc"
    # Commit whatever was banked, SUCCESS OR PARTIAL: a window that
    # closes mid-run (the round-3 failure mode) must not leave real
    # TPU data uncommitted for a later partial rerun to clobber.
    # Retry on transient index locks; pathspec-restricted so a
    # concurrently staged unrelated file can never be swept in, and
    # unstaged again on failure so the operator's next commit cannot
    # sweep the artifacts either.
    if [ -n "$(git status --porcelain -- "$RES")" ]; then
      committed=no
      for _ in 1 2 3 4 5; do
        if { git add -- "$RES" && git commit -q -m \
          "TPU series ${TAG}: artifacts from a chip-watch window (series rc=$rc)" \
          -- "$RES"; } >> "$LOG" 2>&1; then
          log "artifacts committed (series rc=$rc)"
          committed=yes
          break
        fi
        log "git add/commit failed; retrying in 10s"
        sleep 10
      done
      if [ "$committed" = no ]; then
        git restore --staged -- "$RES" >> "$LOG" 2>&1 || true
        log "artifact commit FAILED after 5 attempts -- results are" \
            "UNCOMMITTED in $RES (see git errors above)"
      fi
    fi
    if [ "$rc" -eq 0 ]; then
      exit 0
    fi
    # Preflight passed but the series died (window closed mid-run):
    # keep watching -- a later window can rerun; completed steps are
    # cheap to redo with warm compile caches.
    log "series incomplete; resuming watch"
  else
    took=$(( $(date +%s) - t0 ))
    if [ "$prc" -ne 124 ] && [ "$took" -lt 30 ]; then
      # fast non-timeout failure = broken environment, not a dead
      # tunnel; log the error so a human (or the builder) can fix it
      log "probe ERROR (rc=$prc, ${took}s -- env problem, not tunnel): $(tail -c 400 /tmp/chip_probe.$$ | tr '\n' ' ')"
    else
      log "no contact: attempt=$attempt probe_s=$took rc=$prc"
    fi
  fi
  rm -f /tmp/chip_probe.$$
  sleep "$INTERVAL"
done
log "gave up: no completed series within ${MAX_HOURS}h"
exit 3
