#!/usr/bin/env python
"""Build an mnist.npz-style file from sklearn's REAL handwritten
digits so the convergence gate can run on real data without egress
(VERDICT r4 next #8).

The scikit-learn ``digits`` dataset (1,797 genuine 8x8 handwritten
digit scans from UCI) ships inside the baked-in sklearn wheel -- no
download.  This tool upsamples each image to the 28x28 MNIST geometry
(3x nearest-neighbour repeat + 2px zero border, a deterministic,
label-preserving transform), rescales intensities 0..16 -> 0..255,
applies a deterministic stratified-ish split, and writes the
``x_train/y_train/x_test/y_test`` npz the
``CHAINERMN_TPU_MNIST`` hook consumes
(``chainermn_tpu/datasets/mnist.py:79-86``).

Usage::

    python ci/make_digits_npz.py /tmp/digits_mnist.npz
    CHAINERMN_TPU_MNIST=/tmp/digits_mnist.npz \
        python -m pytest "tests/test_mnist.py::test_mnist_convergence" -v
"""

import sys

import numpy as np


def build(seed=0, n_test=360):
    from sklearn.datasets import load_digits

    d = load_digits()
    x = d.images.astype(np.float32)          # (1797, 8, 8), 0..16
    y = d.target.astype(np.int32)
    # 8x8 -> 24x24 nearest-neighbour, then 2px zero border -> 28x28
    x = np.repeat(np.repeat(x, 3, axis=1), 3, axis=2)
    x = np.pad(x, ((0, 0), (2, 2), (2, 2)))
    x = np.clip(x * (255.0 / 16.0), 0, 255).astype(np.uint8)
    rng = np.random.RandomState(seed)
    order = rng.permutation(len(x))
    test_idx, train_idx = order[:n_test], order[n_test:]
    return {'x_train': x[train_idx], 'y_train': y[train_idx],
            'x_test': x[test_idx], 'y_test': y[test_idx]}


def main():
    out = sys.argv[1] if len(sys.argv) > 1 else '/tmp/digits_mnist.npz'
    arrays = build()
    np.savez_compressed(out, **arrays)
    print('wrote %s: train %s test %s (real sklearn digits, '
          'upsampled to 28x28)' % (out, arrays['x_train'].shape,
                                   arrays['x_test'].shape))


if __name__ == '__main__':
    main()
