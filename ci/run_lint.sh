#!/usr/bin/env bash
# Lint pass (reference parity: .travis.yml:51-54).  Uses flake8 when
# installed (config in setup.cfg); otherwise the stdlib fallback
# enforcing the core rule set.
set -euo pipefail
cd "$(dirname "$0")/.."
if python -c 'import flake8' 2>/dev/null; then
    python -m flake8 .
else
    python ci/lint_fallback.py .
fi
