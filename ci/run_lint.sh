#!/usr/bin/env bash
# Lint pass (reference parity: .travis.yml:51-54).  Uses flake8 when
# installed (config in setup.cfg); otherwise the stdlib fallback
# enforcing the core rule set.  Then the shardlint static-analysis
# gate (docs/static_analysis.md): a dirty jaxpr -- wrong collective
# axis, dead donation, recompilation leak -- fails the lint gate
# exactly like a style violation.  SHARDLINT=0 skips it (style-only
# iteration).
set -euo pipefail
cd "$(dirname "$0")/.."
if python -c 'import flake8' 2>/dev/null; then
    python -m flake8 .
else
    python ci/lint_fallback.py .
fi
if [ "${SHARDLINT:-1}" != "0" ]; then
    bash ci/run_staticcheck.sh
fi
