#!/usr/bin/env bash
# Device-count matrix, mirroring the reference CI's
#   for NP in 1 2 3; do mpiexec -n ${NP} nosetests ...; done
# (.travis.yml:55) with XLA's virtual host devices in place of MPI
# processes.  The full suite runs at 8; the device-agnostic
# distributed tests run additionally at 1, 2 and 3.
set -euo pipefail
cd "$(dirname "$0")/.."

for N in 1 2 3; do
  echo "=== device matrix: ${N} virtual device(s) ==="
  XLA_FLAGS="--xla_force_host_platform_device_count=${N}" \
    python -m pytest tests/test_device_matrix.py -q
done

# fast set (default: @pytest.mark.slow excluded) is the edit-test
# loop; the FULL set runs once here so no coverage is lost
echo "=== fast suite: 8 virtual devices ==="
python -m pytest tests/ -q

echo "=== slow tail: 8 virtual devices ==="
python -m pytest tests/ -q --runslow -m slow \
  --ignore=tests/test_multiprocess.py \
  --ignore=tests/test_supervisor_mp.py

# ELASTIC + CORRUPTION LEG (ISSUE 5): 3 real jax.distributed
# processes train ZeRO-1, get SIGTERMed into a manifest-tagged
# regathered npz checkpoint, and RESUME AT 2 PROCESSES with the
# optimizer partitions re-split 6->4 devices, matching the
# fixed-topology oracle trajectory; plus corrupt-newest ->
# fallback-to-previous-valid (bit-rotted snapshot skipped with the
# typed CheckpointSkippedWarning, never loaded silently).  Runs
# here, in the full-coverage pass -- the fast (tier-1) halves of the
# integrity layer live in tests/test_chaos.py, so tier-1 wall time
# stays inside its budget.
echo "=== elastic topology-change + checkpoint-corruption leg ==="
python -m pytest tests/test_multiprocess.py -q --runslow \
  -k 'elastic or corrupt'

# MULTI-CONTROLLER CHAOS LEG (VERDICT r5 items 5-6): 2-3 REAL
# jax.distributed CPU processes (gloo collectives, one coordination
# service) run the multiprocess suite once CLEAN and once UNDER
# INJECTED FAULTS (chainermn_tpu.utils.chaos): dropped p2p publishes
# retried through, a killed peer surfacing as a typed PeerDeadError
# within its deadline, dead-receiver GC + cursor rewind, NaN-burst
# divergence checkpoints, and a SIGTERM mid-step producing a
# collective orbax checkpoint that auto-resumes to the exact
# uninterrupted loss trajectory.  See docs/fault_tolerance.md.
echo "=== multi-controller chaos leg: real jax.distributed CPU processes ==="
python -m pytest tests/test_multiprocess.py -q --runslow \
  -k 'not elastic and not corrupt and not doctor and not protocol'

# TELEMETRY DOCTOR LEG (ISSUE 8 acceptance): the cross-rank
# diagnosis proved end-to-end over real jax.distributed processes.
# (1) chaos-delay variant: a rank-restricted fixed p2p delay
# (rank=1;delay_send=*:0.05) -- `telemetry doctor` must name rank 1
# as the chronic straggler with the lagging phase send_obj;
# (2) chaos-kill post-mortem: rank 1 dies at a kill_recv site and
# the doctor -- from the flight record flushed across os._exit, the
# event-log tail and the heartbeat files, all written BEFORE the
# death -- must report the dead rank, its last completed collective
# seq, and the open recv_obj span the survivor was blocked in.
echo "=== telemetry doctor leg: straggler attribution + crash post-mortem ==="
python -m pytest tests/test_multiprocess.py -q --runslow -k 'doctor'

# PROTOCOL-DIVERGENCE LEG (ISSUE 16 acceptance): the commcheck
# dynamic twin proved over real jax.distributed processes.  Two
# 2-proc runs of an interleaved allreduce_obj/barrier protocol:
# (1) CLEAN -- the doctor's protocol-divergence verdict must be
# silent and the capture healthy; (2) chaos-injected
# (rank=1;extra_collective=@1) -- rank 1 records one phantom
# collective span mid-protocol, and `telemetry doctor` must name the
# first divergent position with each rank's surrounding ops (the
# same commcheck.verify_streams core the static gate runs, fed from
# the replayed per-rank seq streams).  See docs/observability.md.
echo "=== protocol-divergence leg: commcheck replay over real processes ==="
python -m pytest tests/test_multiprocess.py -q --runslow -k 'protocol'

# SUPERVISOR LEG (ISSUE 9): the self-healing loop proved unattended
# over real jax.distributed CPU procs -- one `python -m
# chainermn_tpu.supervisor` invocation per scenario, the ledger's
# machine-readable verdicts asserted.  (1) chaos kill_step mid-train:
# classified 'killed' to the same rank the doctor accuses, elastic
# shrink 3->2, resume from the periodic checkpoint, finished run
# matches the fixed-topology oracle; (2) hang_step wedge (heartbeat
# fresh, iteration frozen): progress-watch detection, SIGTERM-grace-
# SIGKILL escalation, culprit named from the chaos-event history,
# pod shrinks and finishes; (3) checkpoint corrupted on every restart:
# typed EXIT_CKPT_CORRUPT relaunch deaths -> crash-loop abort inside
# the restart budget with a non-zero supervisor exit.  Slow-marked,
# tier-1 budget untouched (fast policy units: tests/test_supervisor.py).
echo "=== supervisor leg: kill->shrink->resume, hang->escalation, crash-loop abort ==="
python -m pytest tests/test_supervisor_mp.py -q --runslow

# SLICE-LOSS GOODPUT LEG (ISSUE 18 acceptance): slice-level failure
# domains + async checkpointing + the unified goodput report, end to
# end over real jax.distributed CPU procs.  4 workers run as 2
# slices of 2 (--slices 2; each rank's CHAINERMN_TPU_SLICE names its
# domain); chaos slice_loss hard-kills EVERY rank of slice 1
# mid-train.  The supervisor must classify the whole-slice death
# (granularity=slice, both member ranks named, counted as ONE
# failure), shrink by the whole slice 4 -> 2 -- never splitting one
# -- resume from the async npz checkpoint, and complete.  Then
# `telemetry goodput` joins the ledger with every attempt's capture:
# the decomposition must sum to the wall clock (+-1%), bank a
# NONZERO restart-downtime bucket, and keep goodput_fraction inside
# (0, 1) and above the chaos floor.  See docs/fault_tolerance.md
# ("Goodput").
echo "=== slice-loss goodput leg: 2x2 slices, whole-slice kill -> shrink -> goodput report ==="
SLICE_DIR=$(mktemp -d /tmp/slice_goodput.XXXXXX)
CHAINERMN_TPU_CHAOS='slice_loss=@2:1' \
  python -m chainermn_tpu.supervisor -n 4 --slices 2 \
  --out "${SLICE_DIR}" --steps 6 --ckpt-every 2 --local-devices 2 \
  --stall-timeout 30 --startup-grace 120 --attempt-timeout 420 \
  --no-oracle
python -m chainermn_tpu.telemetry goodput "${SLICE_DIR}" --floor 0.02
python - "${SLICE_DIR}" <<'PY'
import json, sys
d = sys.argv[1]
ledger = [json.loads(l) for l in open(d + '/supervisor_ledger.jsonl')]
fails = [e for e in ledger if e['event'] == 'failure']
assert len(fails) == 1, [e['event'] for e in ledger]
assert fails[0]['granularity'] == 'slice', fails[0]
assert sorted(fails[0]['dead_ranks']) == [2, 3], fails[0]
dec = [e for e in ledger if e['event'] == 'decision'][0]
assert dec['action'] == 'shrink' and dec['granularity'] == 'slice', dec
assert (dec['world_before'], dec['world_after']) == (4, 2), dec
assert any(e['event'] == 'complete' for e in ledger), \
    [e['event'] for e in ledger]
gp = json.load(open(d + '/goodput_report.json'))
assert 0.0 < gp['goodput_fraction'] < 1.0, gp['goodput_fraction']
assert gp['buckets_s']['restart_downtime'] > 0.0, gp['buckets_s']
total = sum(gp['buckets_s'].values())
assert abs(total - gp['wall_s']) <= 0.01 * gp['wall_s'], \
    (total, gp['wall_s'])
print('slice goodput OK: fraction=%.4f, downtime=%.3fs of %.3fs '
      'wall, slice shrink 4->2'
      % (gp['goodput_fraction'],
         gp['buckets_s']['restart_downtime'], gp['wall_s']))
PY
rm -rf "${SLICE_DIR}"

# TELEMETRY SMOKE LEG (ISSUE 6): capture -> merge -> report on the
# mnist example.  The env var is the ONLY switch (zero-cost-off
# contract): the run records step phases, collective/trace marks and
# metrics per rank; the report CLI merges them, prints the step
# timeline + overlap fraction, exits 2 on an empty capture, and the
# asserts below pin a non-empty timeline and a valid Prometheus
# export.
echo "=== telemetry smoke: mnist capture -> merge -> report ==="
TELEMETRY_DIR=$(mktemp -d /tmp/telemetry_smoke.XXXXXX)
CHAINERMN_TPU_TELEMETRY="${TELEMETRY_DIR}" \
  python examples/mnist/train_mnist.py --quick --cpu -b 96 \
  --out "${TELEMETRY_DIR}/result"
python -m chainermn_tpu.telemetry report "${TELEMETRY_DIR}"
# the doctor must also accept the capture: exit 0 and a parseable
# verdict JSON (single-controller, so skew fields are honest Nones)
python -m chainermn_tpu.telemetry doctor "${TELEMETRY_DIR}"
python - "${TELEMETRY_DIR}" <<'PY'
import json, sys
from chainermn_tpu.telemetry import report as trep
d = sys.argv[1]
rep = json.load(open(d + '/merged_report.json'))
assert rep['n_spans'] > 0, 'empty telemetry timeline'
assert rep['steps'], 'no per-step rows in merged timeline'
assert rep['step_time_ms'].get('p50') is not None, rep['step_time_ms']
ov = rep['overlap']['overlap_fraction']
assert ov is None or 0.0 <= ov <= 1.0, rep['overlap']
prom = open(d + '/metrics.prom').read()
bad = trep.validate_prometheus(prom)
assert not bad, 'malformed Prometheus lines: %r' % bad[:3]
doc = json.load(open(d + '/doctor_report.json'))
assert 'verdict' in doc and 'healthy' in doc['verdict'], doc.keys()
assert doc['verdict']['dead_ranks'] == [], doc['verdict']
print('telemetry smoke OK: %d spans, %d step rows, overlap=%r, '
      '%d prom lines, doctor verdict healthy=%r'
      % (rep['n_spans'], len(rep['steps']), ov,
         len(prom.splitlines()), doc['verdict']['healthy']))
PY
rm -rf "${TELEMETRY_DIR}"

# SERVING SLO SMOKE LEG (ISSUE 12): a short autoregressive serve
# window recorded as a full telemetry capture (per-request trace
# spans + serve metrics + the live monitor's slo_snapshot.json),
# then replayed offline: `telemetry slo` must return a parseable
# ok/warn/breach verdict (exit 0), and `telemetry report` must
# reconstruct at least one request timeline with every stage present
# (queue_wait -> bucket_pack -> prefill -> decode) and stage budgets
# summing to the end-to-end latency (+-1 ms) -- the ISSUE 12
# acceptance observable, end to end over real executables.
echo "=== serving slo smoke: generate capture -> slo verdict + request timeline ==="
# the smoke window runs the PAGED engine with chunked prefill
# (ISSUE 17): the capture must still tile every request's stage
# spans (queue_wait -> bucket_pack -> prefill_chunk* -> prefill ->
# decode) and the paged sidecars must land on the bench row.
SLO_DIR=$(mktemp -d /tmp/slo_smoke.XXXXXX)
python bench.py --serve --generate --quick --cpu --paged \
  --prefill-chunk 8 --serve-requests 24 --capture "${SLO_DIR}" \
  > "${SLO_DIR}/bench_row.json"
python -m chainermn_tpu.telemetry slo "${SLO_DIR}"
python -m chainermn_tpu.telemetry report "${SLO_DIR}" > /dev/null
python - "${SLO_DIR}" <<'PY'
import json, sys
d = sys.argv[1]
slo = json.load(open(d + '/slo_report.json'))
v = slo['verdict']['overall']
assert v in ('ok', 'warn', 'breach'), slo['verdict']
assert slo['n_request_records'] > 0, 'slo replay saw no records'
snap = json.load(open(d + '/slo_snapshot.json'))
assert snap['verdict']['overall'] in ('ok', 'warn', 'breach'), snap
rep = json.load(open(d + '/merged_report.json'))
reqs = rep['requests']
assert reqs and reqs['completed'] > 0, reqs
worst = reqs['worst']
stages = set(worst['stage_ms'])
assert {'queue_wait', 'bucket_pack', 'prefill', 'decode'} <= stages, \
    stages
assert abs(worst['stage_sum_ms'] - worst['e2e_ms']) <= 1.0, worst
row = json.load(open(d + '/bench_row.json'))
assert row.get('slo_verdict') in ('ok', 'warn', 'breach'), \
    row.get('slo_verdict')
assert row.get('paged') is True and row.get('paged_kv'), 'paged row'
assert row['paged_kv']['prefill_chunks'] > 0, row['paged_kv']
assert row.get('kv_bytes_per_token'), 'kv_bytes_per_token sidecar'
assert row.get('pages_per_request') is not None, 'pages sidecar'
print('slo smoke OK: verdict=%s (row %s), %d requests traced, worst '
      '%s e2e %.3f ms (stage sum %.3f ms)'
      % (v, row['slo_verdict'], reqs['count'], worst['request_id'],
         worst['e2e_ms'], worst['stage_sum_ms']))
PY
rm -rf "${SLO_DIR}"

# SPECULATIVE DECODING SMOKE LEG (ISSUE 19): the paged speculative
# engine under a real open-loop window, with the two acceptance
# observables asserted straight off the bench row: (1) the in-bench
# equivalence probe -- the speculative engine's outputs are
# token-for-token identical to a non-speculative oracle twin's
# (spec_equivalent, the exact-greedy pin, not a similarity bound);
# (2) amortization accounting -- draft proposals flowed
# (accepted_draft_rate is a number, possibly 0.0 with an untrained
# draft) and verify_per_token < 1 (strictly fewer target passes than
# tokens whenever anything was accepted; <= 1 always).  The capture
# replay must also carry the serve_draft/serve_verify phases and the
# accepted-draft-rate block in serve_summary.
echo "=== speculative smoke: draft-propose / target-verify equivalence + accepted rate ==="
SPEC_DIR=$(mktemp -d /tmp/spec_smoke.XXXXXX)
python bench.py --serve --generate --speculative --quick --cpu \
  --paged --serve-requests 24 --capture "${SPEC_DIR}" \
  > "${SPEC_DIR}/bench_row.json"
python -m chainermn_tpu.telemetry report "${SPEC_DIR}" > /dev/null
python - "${SPEC_DIR}" <<'PY'
import json, sys
d = sys.argv[1]
row = json.load(open(d + '/bench_row.json'))
assert row.get('spec_equivalent') is True, (
    'speculative output diverged from the oracle: %r'
    % row.get('spec_equivalent'))
spec = row.get('speculative')
assert spec, 'speculative block missing from the generate row'
assert spec['draft_proposed'] > 0, spec
rate = row.get('accepted_draft_rate')
assert rate is not None and 0.0 <= rate <= 1.0, rate
vpt = row.get('verify_per_token')
assert vpt is not None and vpt <= 1.0, vpt
assert spec['verify_steps'] > 0, spec
from chainermn_tpu.telemetry import report as trep
assert 'serve_draft' in trep.SERVE_PHASES
assert 'serve_verify' in trep.SERVE_PHASES
rep = json.load(open(d + '/merged_report.json'))
gen = ((rep.get('serve') or {}).get('generate')) or {}
sb = gen.get('speculative')
assert sb and sb['draft_proposed'] > 0, sb
print('speculative smoke OK: equivalent=EXACT rate=%.3f '
      'verify/token=%.3f (%d drafts proposed)'
      % (rate, vpt, spec['draft_proposed']))
PY
rm -rf "${SPEC_DIR}"

# FLEET LEG (ISSUE 13 acceptance): train-to-serve continuous
# deployment proved end to end over REAL subprocess replicas -- one
# `python -m chainermn_tpu.serving.fleet` invocation per scenario,
# every verdict asserted from fleet_ledger.jsonl.  (1) promote: a
# few real CPU sgd steps -> manifest-tagged snapshot -> a 2-replica
# fleet picks it up and rolls it under open-loop traffic, canary ok,
# promote -- with ZERO requests shed (per-swap shed counters AND the
# traffic totals both zero: the roll is invisible to clients);
# (2) canary breach -> rollback: the replica chaos handout ships a
# serve_slow latency regression that bites only on a hot-swapped
# version, the judge breaches on the inter-token delta vs the
# incumbent's matched window, the canary swaps back, the fleet
# converges on the incumbent; (3) swap_kill mid-roll: the controller
# dies at a swap point with replicas on MIXED versions, and a
# relaunch over the same --out converges every replica to one
# consistent version, recording `converged` with the recovered roll
# named.  Slow-marked; the fast in-process halves run in tier-1
# (tests/test_fleet.py).  See docs/serving.md "Continuous
# deployment".
echo "=== fleet leg: roll->promote, canary breach->rollback, swap_kill convergence ==="
python -m pytest tests/test_fleet_mp.py -q --runslow

# SERVING SELF-HEALING LEG (ISSUE 20 acceptance): a replica worker
# process is chaos hard-killed mid-decode (replica_kill=@2:1 --
# os._exit(46) at replica 1's 2nd decode tick, generations in
# flight) under open-loop traffic with the crash-safe request
# journal armed (--recover).  The ledger must prove: every in-flight
# request requeued onto the survivor as an exact continuation and
# attributed by id in `recovered`; a replacement worker respawned
# FROM THE INCUMBENT snapshot and spliced back into the front; zero
# lost requests, zero client-visible errors.  Then the crash-loop
# twin: replica_kill=* survives the one-shot strip by design, the
# respawned worker dies right back, and the shared restart policy
# aborts rc 1 within the crash window.  See docs/fault_tolerance.md
# ("Serving self-healing").
echo "=== serving self-healing leg: replica kill -> requeue -> respawn; crash-loop abort ==="
HEAL_DIR=$(mktemp -d /tmp/fleet_heal.XXXXXX)
CHAINERMN_TPU_CHAOS= \
  python -m chainermn_tpu.serving.fleet --out "${HEAL_DIR}" \
  --rolls 0 --duration 8 --replicas 2 --rate 20 \
  --max-new-tokens 8 --max-prompt-len 16 --traffic-prompt-max 4 \
  --recover --replica-chaos 'replica_kill=@2:1' \
  > "${HEAL_DIR}/summary.json"
python - "${HEAL_DIR}" <<'PY'
import json, sys
d = sys.argv[1]
ledger = [json.loads(l) for l in open(d + '/fleet_ledger.jsonl')]
dead = [e for e in ledger if e['event'] == 'replica_dead']
assert len(dead) == 1 and dead[0]['replica'] == 'replica-1', dead
assert dead[0]['returncode'] == 46 and dead[0]['exit'] == 'crash', \
    dead[0]
requeues = [e['request_id'] for e in ledger
            if e['event'] == 'requeue']
rec = [e for e in ledger if e['event'] == 'recovered'][0]
assert rec['request_ids'] == requeues, (rec, requeues)
assert rec['shed'] == [], rec
respawn = [e for e in ledger if e['event'] == 'respawn'][0]
assert respawn['replica'] == 'replica-1r1', respawn
summary = json.loads(open(d + '/summary.json').read().strip()
                     .splitlines()[-1])
assert respawn['version'] == summary['version'], \
    (respawn, summary['version'])   # incumbent weights
r = summary['recovery']
assert r['deaths'] == 1 and r['respawns'] == 1, r
assert r['lost_requests'] == 0 and not r['aborted'], r
t = summary['traffic']
assert t['errors'] == 0 and t['served'] == t['offered'] > 0, t
print('self-healing OK: %d requeued (%s), respawned at v%d, '
      '%d/%d served, 0 lost'
      % (len(requeues), ','.join(requeues) or '-',
         respawn['version'], t['served'], t['offered']))
PY
if CHAINERMN_TPU_CHAOS= \
  python -m chainermn_tpu.serving.fleet --out "${HEAL_DIR}/loop" \
  --rolls 0 --duration 60 --replicas 2 --rate 20 \
  --max-new-tokens 8 --max-prompt-len 16 --traffic-prompt-max 4 \
  --recover --replica-chaos 'replica_kill=*' \
  > "${HEAL_DIR}/loop_summary.json"; then
  echo "crash loop did NOT abort rc 1" >&2; exit 1
fi
python - "${HEAL_DIR}" <<'PY'
import json, sys
d = sys.argv[1]
ledger = [json.loads(l) for l in open(d + '/loop/fleet_ledger.jsonl')]
aborts = [e for e in ledger if e['event'] == 'abort']
assert len(aborts) == 1 and 'crash_loop' in aborts[0]['reason'], \
    aborts
deaths = [e for e in ledger if e['event'] == 'replica_dead']
assert len(deaths) == 3, deaths   # threshold, inside the budget
print('crash-loop abort OK: 3 deaths -> %r' % aborts[0]['reason'])
PY
rm -rf "${HEAL_DIR}"

# CONVERGENCE-UNDER-CHAOS LEG (ISSUE 15 acceptance): the streaming
# input pipeline proved end to end over REAL jax.distributed CPU
# processes.  (1) stream_elastic: training on streamed record shards
# at 3 procs is SIGTERMed MID-EPOCH (the npz checkpoint carries the
# exact stream cursor), resumed at 2 procs, and the concatenated
# per-rank sample-id ledgers equal the uninterrupted fixed-topology
# oracle's stream EXACTLY -- every (epoch, position) consumed once
# with the oracle's id, no repeats, no drops -- while the combined
# loss trajectory matches the oracle (atol 1e-4).  (2) the payoff
# scenario: one `python -m chainermn_tpu.supervisor` invocation
# trains the learnable streamed dataset to its target loss while
# chaos hard-kills rank 1; the supervisor classifies, shrinks 3 -> 2
# and resumes, and the union of consumed sample ids over ALL
# attempts is exactly epoch 0's id set, position-consistent with the
# deterministic oracle stream.  Slow-marked; the fast halves
# (determinism pin, typed corruption, cursor edges) run in tier-1
# via tests/test_data.py.  See docs/data_pipeline.md.
echo "=== convergence-under-chaos leg: streamed shards + supervisor healing ==="
python -m pytest tests/test_data_mp.py -q --runslow

# REAL-DATA convergence gate (VERDICT r4 next #8): the same positive
# gate, fed genuine handwritten digits (sklearn's vendored UCI scans,
# no egress) through the CHAINERMN_TPU_MNIST hook -- the reference's
# actual >=0.95 bar on real data, alongside the antipodal synthetic
# run above.  -s so the test's data-source line lands in the CI log.
echo "=== real-data convergence gate ==="
python ci/make_digits_npz.py /tmp/digits_mnist.npz
CHAINERMN_TPU_MNIST=/tmp/digits_mnist.npz \
  python -m pytest "tests/test_mnist.py::test_mnist_convergence" -q -s
