// Native runtime core for chainermn_tpu.
//
// TPU-native counterpart of the reference's native layer:
//   - buffer/arena management  (reference: _memory_utility.py
//     DeviceMemory/HostPinnedMemory -- grow-only assign, fused
//     pack/unpack of many tensors into one contiguous buffer)
//   - data-loader hot path     (reference: Chainer MultiprocessIterator
//     worker processes doing crop/flip/mean-subtract in Python;
//     here a C++ thread pool over contiguous sample memory)
//   - host collective engine   (reference: chainermn/nccl/nccl.pyx --
//     allreduce/reduce/bcast/reduce_scatter/allgather with comm-id
//     handshake and an error taxonomy; here over POSIX shared memory
//     for same-host processes.  On-device collectives belong to XLA;
//     this engine serves the eager/object path, e.g. metric
//     aggregation, mirroring the reference's mpi4py usage.)
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).

#include <atomic>
#include <cerrno>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <functional>
#include <mutex>
#include <new>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

#define CMN_API extern "C" __attribute__((visibility("default")))

// ---------------------------------------------------------------------------
// Error taxonomy (parity: nccl.pyx:60-76 status table)
// ---------------------------------------------------------------------------

enum CmnStatus {
  CMN_OK = 0,
  CMN_UNHANDLED_ERROR = 1,
  CMN_SYSTEM_ERROR = 2,
  CMN_INTERNAL_ERROR = 3,
  CMN_INVALID_ARGUMENT = 4,
  CMN_INVALID_USAGE = 5,
  CMN_BUFFER_OVERFLOW = 6,
  CMN_TIMEOUT = 7,
  CMN_RANK_MISMATCH = 8,
};

static const char* kStatusStrings[] = {
    "success",          "unhandled error",  "system error",
    "internal error",   "invalid argument", "invalid usage",
    "buffer overflow",  "timeout",          "rank mismatch",
};

CMN_API const char* cmn_error_string(int status) {
  if (status < 0 || status > CMN_RANK_MISMATCH) return "unknown error";
  return kStatusStrings[status];
}

// ---------------------------------------------------------------------------
// Arena: grow-only aligned buffer (parity: DeviceMemory.assign,
// _memory_utility.py:43-74)
// ---------------------------------------------------------------------------

struct CmnArena {
  void* ptr = nullptr;
  size_t capacity = 0;
};

CMN_API void* cmn_arena_create() { return new (std::nothrow) CmnArena(); }

CMN_API int cmn_arena_assign(void* handle, size_t nbytes) {
  auto* a = static_cast<CmnArena*>(handle);
  if (!a) return CMN_INVALID_ARGUMENT;
  if (nbytes <= a->capacity) return CMN_OK;
  void* p = nullptr;
  if (posix_memalign(&p, 64, nbytes) != 0) return CMN_SYSTEM_ERROR;
  free(a->ptr);
  a->ptr = p;
  a->capacity = nbytes;
  return CMN_OK;
}

CMN_API void* cmn_arena_ptr(void* handle) {
  auto* a = static_cast<CmnArena*>(handle);
  return a ? a->ptr : nullptr;
}

CMN_API size_t cmn_arena_capacity(void* handle) {
  auto* a = static_cast<CmnArena*>(handle);
  return a ? a->capacity : 0;
}

CMN_API void cmn_arena_destroy(void* handle) {
  auto* a = static_cast<CmnArena*>(handle);
  if (a) {
    free(a->ptr);
    delete a;
  }
}

// Fused pack/unpack (parity: pack_params/unpack_params,
// _memory_utility.py:77-92): gather n segments into dst / scatter back.
// Parallel memcpy for large totals.

static void parallel_for(size_t n, size_t grain,
                         const std::function<void(size_t, size_t)>& fn) {
  unsigned hw = std::thread::hardware_concurrency();
  size_t n_threads = hw ? hw : 4;
  if (n_threads > 16) n_threads = 16;
  if (n < grain * 2 || n_threads <= 1) {
    fn(0, n);
    return;
  }
  if (n_threads > n / grain) n_threads = n / grain;
  std::vector<std::thread> threads;
  size_t chunk = (n + n_threads - 1) / n_threads;
  for (size_t t = 0; t < n_threads; ++t) {
    size_t lo = t * chunk, hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    threads.emplace_back([&fn, lo, hi] { fn(lo, hi); });
  }
  for (auto& th : threads) th.join();
}

CMN_API int cmn_pack(void* dst, void** srcs, const size_t* nbytes, int n) {
  if (!dst || !srcs || !nbytes || n < 0) return CMN_INVALID_ARGUMENT;
  std::vector<size_t> offsets(static_cast<size_t>(n) + 1, 0);
  for (int i = 0; i < n; ++i) offsets[i + 1] = offsets[i] + nbytes[i];
  parallel_for(static_cast<size_t>(n), 1, [&](size_t lo, size_t hi) {
    for (size_t i = lo; i < hi; ++i)
      memcpy(static_cast<char*>(dst) + offsets[i], srcs[i], nbytes[i]);
  });
  return CMN_OK;
}

CMN_API int cmn_unpack(void* src, void** dsts, const size_t* nbytes, int n) {
  if (!src || !dsts || !nbytes || n < 0) return CMN_INVALID_ARGUMENT;
  std::vector<size_t> offsets(static_cast<size_t>(n) + 1, 0);
  for (int i = 0; i < n; ++i) offsets[i + 1] = offsets[i] + nbytes[i];
  parallel_for(static_cast<size_t>(n), 1, [&](size_t lo, size_t hi) {
    for (size_t i = lo; i < hi; ++i)
      memcpy(dsts[i], static_cast<char*>(src) + offsets[i], nbytes[i]);
  });
  return CMN_OK;
}

// ---------------------------------------------------------------------------
// Image augmentation pipeline (the data-loader hot path).
//
// Batched crop + horizontal flip + mean-subtract + scale from a
// contiguous (N, H, W, C) float32 sample store into a packed
// (B, crop, crop, C) float32 batch, parallel over batch items.
// Mean is a full (H, W, C) image; the window subtracted tracks the
// crop window (reference train_imagenet.py:79-80).
// ---------------------------------------------------------------------------

CMN_API int cmn_augment_batch(
    const float* samples, int64_t h, int64_t w, int64_t c,
    const int64_t* sample_indices,  // B source sample ids
    const int32_t* tops, const int32_t* lefts, const uint8_t* flips,
    int64_t b, int64_t crop, const float* mean /* nullable, HWC */,
    float scale, float* out /* B*crop*crop*C */) {
  if (!samples || !sample_indices || !tops || !lefts || !flips || !out)
    return CMN_INVALID_ARGUMENT;
  if (crop > h || crop > w) return CMN_INVALID_ARGUMENT;
  const int64_t sample_stride = h * w * c;
  const int64_t out_stride = crop * crop * c;
  std::atomic<int> status{CMN_OK};
  parallel_for(static_cast<size_t>(b), 1, [&](size_t lo, size_t hi) {
    for (size_t i = lo; i < hi; ++i) {
      const int64_t top = tops[i], left = lefts[i];
      if (top < 0 || left < 0 || top + crop > h || left + crop > w) {
        status.store(CMN_INVALID_ARGUMENT);
        continue;
      }
      const float* src = samples + sample_indices[i] * sample_stride;
      float* dst = out + i * out_stride;
      const bool flip = flips[i] != 0;
      for (int64_t y = 0; y < crop; ++y) {
        const float* srow = src + ((top + y) * w + left) * c;
        const float* mrow =
            mean ? mean + ((top + y) * w + left) * c : nullptr;
        float* drow = dst + y * crop * c;
        if (!flip) {
          if (mrow) {
            for (int64_t xc = 0; xc < crop * c; ++xc)
              drow[xc] = (srow[xc] - mrow[xc]) * scale;
          } else {
            for (int64_t xc = 0; xc < crop * c; ++xc)
              drow[xc] = srow[xc] * scale;
          }
        } else {
          // horizontal flip: output col x reads source col crop-1-x
          // (mean window is subtracted pre-flip, matching
          // "subtract then flip" semantics)
          for (int64_t x = 0; x < crop; ++x) {
            const float* spix = srow + (crop - 1 - x) * c;
            const float* mpix = mrow ? mrow + (crop - 1 - x) * c : nullptr;
            float* dpix = drow + x * c;
            for (int64_t ch = 0; ch < c; ++ch)
              dpix[ch] = ((spix[ch] - (mpix ? mpix[ch] : 0.f)) * scale);
          }
        }
      }
    }
  });
  return status.load();
}

// ---------------------------------------------------------------------------
// Host collective engine over POSIX shared memory.
//
// Parity surface with the reference NCCL binding (nccl.pyx):
//   comm-id handshake  -> shm segment name generated by rank 0
//                         (ncclGetUniqueId, nccl.pyx:107-115)
//   comm init          -> cmn_comm_init(name, n_ranks, rank)
//                         (ncclCommInitRank, nccl.pyx:122-133)
//   allreduce/reduce/bcast/reduce_scatter/allgather
//                         (nccl.pyx:140-199)
// Synchronization: per-collective sequence number + sense-reversing
// double barrier on atomics (processes on one host; fail-stop with
// timeout -> CMN_TIMEOUT, a failure-detection behavior the reference
// lacks entirely).
// ---------------------------------------------------------------------------

static const int kMaxRanks = 64;

struct ShmHeader {
  std::atomic<int32_t> arrived[2];   // double-buffered barrier counters
  std::atomic<int32_t> generation;   // barrier phase
  std::atomic<int32_t> attached;     // rank attach count
  std::atomic<int64_t> slot_bytes;
  std::atomic<int32_t> n_ranks;      // published LAST by rank 0
};

struct CmnComm {
  ShmHeader* hdr = nullptr;
  char* slots = nullptr;  // n_ranks * slot_bytes payload area
  int rank = -1;
  int n_ranks = 0;
  int64_t slot_bytes = 0;
  size_t map_bytes = 0;
  std::string name;
  int barrier_count = 0;
  double timeout_s = 60.0;
};

static int comm_barrier(CmnComm* comm) {
  // sense-reversing barrier; index alternates so a fast rank cannot
  // lap a slow one within a single collective
  ShmHeader* h = comm->hdr;
  const int idx = comm->barrier_count & 1;
  comm->barrier_count++;
  const int32_t gen = h->generation.load(std::memory_order_acquire);
  const int32_t pos = h->arrived[idx].fetch_add(1) + 1;
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::duration<double>(comm->timeout_s);
  if (pos == comm->n_ranks) {
    h->arrived[idx].store(0, std::memory_order_relaxed);
    h->generation.store(gen + 1, std::memory_order_release);
    return CMN_OK;
  }
  while (h->generation.load(std::memory_order_acquire) == gen) {
    if (std::chrono::steady_clock::now() > deadline) return CMN_TIMEOUT;
    std::this_thread::yield();
  }
  return CMN_OK;
}

CMN_API void* cmn_comm_create(const char* name, int n_ranks, int rank,
                              int64_t slot_bytes, double timeout_s) {
  if (!name || n_ranks < 1 || n_ranks > kMaxRanks || rank < 0 ||
      rank >= n_ranks || slot_bytes < 8)
    return nullptr;
  const size_t total = sizeof(ShmHeader) +
                       static_cast<size_t>(n_ranks) * slot_bytes;
  int fd = shm_open(name, O_CREAT | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, static_cast<off_t>(total)) != 0) {
    close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED,
                   fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  auto* comm = new (std::nothrow) CmnComm();
  if (!comm) {
    munmap(mem, total);
    return nullptr;
  }
  comm->hdr = static_cast<ShmHeader*>(mem);
  comm->slots = static_cast<char*>(mem) + sizeof(ShmHeader);
  comm->rank = rank;
  comm->n_ranks = n_ranks;
  comm->slot_bytes = slot_bytes;
  comm->map_bytes = total;
  comm->name = name;
  comm->timeout_s = timeout_s > 0 ? timeout_s : 60.0;
  if (rank == 0) {
    comm->hdr->arrived[0].store(0);
    comm->hdr->arrived[1].store(0);
    comm->hdr->generation.store(0);
    comm->hdr->attached.store(0);
    comm->hdr->slot_bytes.store(slot_bytes);
    comm->hdr->n_ranks.store(n_ranks, std::memory_order_release);
  }
  // attach handshake: everyone waits until all ranks have mapped
  // (rank 0 initialized the header first; non-zero ranks spin on it)
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::duration<double>(comm->timeout_s);
  while (comm->hdr->n_ranks.load(std::memory_order_acquire) != n_ranks ||
         comm->hdr->slot_bytes.load() != slot_bytes) {
    if (std::chrono::steady_clock::now() > deadline) {
      munmap(mem, total);
      delete comm;
      return nullptr;
    }
    std::this_thread::yield();
  }
  comm->hdr->attached.fetch_add(1);
  while (comm->hdr->attached.load() < n_ranks) {
    if (std::chrono::steady_clock::now() > deadline) {
      munmap(mem, total);
      delete comm;
      return nullptr;
    }
    std::this_thread::yield();
  }
  return comm;
}

CMN_API void cmn_comm_destroy(void* handle, int unlink_shm) {
  auto* comm = static_cast<CmnComm*>(handle);
  if (!comm) return;
  if (comm->hdr) munmap(comm->hdr, comm->map_bytes);
  if (unlink_shm) shm_unlink(comm->name.c_str());
  delete comm;
}

CMN_API int cmn_comm_rank(void* handle) {
  auto* c = static_cast<CmnComm*>(handle);
  return c ? c->rank : -1;
}

CMN_API int cmn_comm_size(void* handle) {
  auto* c = static_cast<CmnComm*>(handle);
  return c ? c->n_ranks : 0;
}

enum CmnOp { CMN_SUM = 0, CMN_PROD = 1, CMN_MAX = 2, CMN_MIN = 3 };
// CMN_BF16/CMN_F16 mirror the reference's NCCL_HALF surface
// (nccl.pyx:87); bf16 is the TPU-native dtype.
enum CmnDtype { CMN_F32 = 0, CMN_F64 = 1, CMN_I32 = 2, CMN_I64 = 3,
                CMN_BF16 = 4, CMN_F16 = 5 };

static size_t dtype_size(int dtype) {
  switch (dtype) {
    case CMN_F32: return 4;
    case CMN_F64: return 8;
    case CMN_I32: return 4;
    case CMN_I64: return 8;
    case CMN_BF16: return 2;
    case CMN_F16: return 2;
    default: return 0;
  }
}

// ---- 16-bit float conversions (scalar; host reduction payloads are
// small).  bf16 uses round-to-nearest-even truncation; f16 is IEEE
// binary16 with subnormal handling.
static inline float bf16_to_f32(uint16_t v) {
  uint32_t b = static_cast<uint32_t>(v) << 16;
  float f;
  memcpy(&f, &b, 4);
  return f;
}

static inline uint16_t f32_to_bf16(float f) {
  uint32_t b;
  memcpy(&b, &f, 4);
  if ((b & 0x7f800000u) == 0x7f800000u) {
    // inf stays inf; NaN keeps a quiet bit even when the payload
    // lives only in the truncated low 16 bits (else NaN -> inf)
    uint16_t hi = static_cast<uint16_t>(b >> 16);
    if ((b & 0x007fffffu) != 0) hi |= 0x0040u;
    return hi;
  }
  uint32_t rounding = 0x7fffu + ((b >> 16) & 1u);
  return static_cast<uint16_t>((b + rounding) >> 16);
}

static inline float f16_to_f32(uint16_t h) {
  uint32_t sign = (h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1fu;
  uint32_t mant = h & 0x3ffu;
  uint32_t f;
  if (exp == 0) {
    if (mant == 0) {
      f = sign;
    } else {  // subnormal: renormalize
      exp = 127 - 15 + 1;
      while (!(mant & 0x400u)) {
        mant <<= 1;
        --exp;
      }
      mant &= 0x3ffu;
      f = sign | (exp << 23) | (mant << 13);
    }
  } else if (exp == 31) {
    f = sign | 0x7f800000u | (mant << 13);
  } else {
    f = sign | ((exp - 15 + 127) << 23) | (mant << 13);
  }
  float out;
  memcpy(&out, &f, 4);
  return out;
}

static inline uint16_t f32_to_f16(float x) {
  uint32_t b;
  memcpy(&b, &x, 4);
  uint32_t sign = (b >> 16) & 0x8000u;
  int32_t exp = static_cast<int32_t>((b >> 23) & 0xffu) - 127 + 15;
  uint32_t mant = b & 0x7fffffu;
  if (((b >> 23) & 0xffu) == 0xffu)  // inf/nan
    return static_cast<uint16_t>(sign | 0x7c00u | (mant ? 0x200u : 0));
  if (exp >= 31) return static_cast<uint16_t>(sign | 0x7c00u);  // overflow
  if (exp <= 0) {  // subnormal or underflow
    if (exp < -10) return static_cast<uint16_t>(sign);
    mant |= 0x800000u;
    uint32_t shift = static_cast<uint32_t>(14 - exp);
    uint32_t half = mant >> shift;
    uint32_t rem = mant & ((1u << shift) - 1);
    uint32_t halfway = 1u << (shift - 1);
    if (rem > halfway || (rem == halfway && (half & 1)))
      ++half;  // round to nearest even
    return static_cast<uint16_t>(sign | half);
  }
  uint32_t half = sign | (static_cast<uint32_t>(exp) << 10) | (mant >> 13);
  uint32_t rem = mant & 0x1fffu;
  if (rem > 0x1000u || (rem == 0x1000u && (half & 1))) ++half;
  return static_cast<uint16_t>(half);
}

struct Bf16Cvt {
  static float to(uint16_t v) { return bf16_to_f32(v); }
  static uint16_t from(float f) { return f32_to_bf16(f); }
};
struct F16Cvt {
  static float to(uint16_t v) { return f16_to_f32(v); }
  static uint16_t from(float f) { return f32_to_f16(f); }
};

template <typename Cvt>
static void reduce_typed_16(uint16_t* acc, const uint16_t* src, int64_t n,
                            int op) {
  for (int64_t i = 0; i < n; ++i) {
    float a = Cvt::to(acc[i]);
    float s = Cvt::to(src[i]);
    float r;
    switch (op) {
      case CMN_SUM: r = a + s; break;
      case CMN_PROD: r = a * s; break;
      case CMN_MAX: r = a > s ? a : s; break;
      case CMN_MIN: r = a < s ? a : s; break;
      default: r = a; break;
    }
    acc[i] = Cvt::from(r);
  }
}

template <typename T>
static void reduce_typed(T* acc, const T* src, int64_t n, int op) {
  switch (op) {
    case CMN_SUM:
      for (int64_t i = 0; i < n; ++i) acc[i] += src[i];
      break;
    case CMN_PROD:
      for (int64_t i = 0; i < n; ++i) acc[i] *= src[i];
      break;
    case CMN_MAX:
      for (int64_t i = 0; i < n; ++i)
        acc[i] = acc[i] > src[i] ? acc[i] : src[i];
      break;
    case CMN_MIN:
      for (int64_t i = 0; i < n; ++i)
        acc[i] = acc[i] < src[i] ? acc[i] : src[i];
      break;
  }
}

static void reduce_dispatch(void* acc, const void* src, int64_t count,
                            int dtype, int op) {
  switch (dtype) {
    case CMN_F32:
      reduce_typed(static_cast<float*>(acc),
                   static_cast<const float*>(src), count, op);
      break;
    case CMN_F64:
      reduce_typed(static_cast<double*>(acc),
                   static_cast<const double*>(src), count, op);
      break;
    case CMN_I32:
      reduce_typed(static_cast<int32_t*>(acc),
                   static_cast<const int32_t*>(src), count, op);
      break;
    case CMN_I64:
      reduce_typed(static_cast<int64_t*>(acc),
                   static_cast<const int64_t*>(src), count, op);
      break;
    case CMN_BF16:
      reduce_typed_16<Bf16Cvt>(static_cast<uint16_t*>(acc),
                               static_cast<const uint16_t*>(src), count,
                               op);
      break;
    case CMN_F16:
      reduce_typed_16<F16Cvt>(static_cast<uint16_t*>(acc),
                              static_cast<const uint16_t*>(src), count,
                              op);
      break;
  }
}

// allreduce: all ranks contribute `count` elements; every rank receives
// the elementwise reduction.  (nccl.pyx allreduce)
CMN_API int cmn_allreduce(void* handle, const void* sendbuf, void* recvbuf,
                          int64_t count, int dtype, int op) {
  auto* comm = static_cast<CmnComm*>(handle);
  if (!comm || !sendbuf || !recvbuf) return CMN_INVALID_ARGUMENT;
  const size_t esz = dtype_size(dtype);
  if (!esz) return CMN_INVALID_ARGUMENT;
  const size_t nbytes = count * esz;
  if (static_cast<int64_t>(nbytes) > comm->slot_bytes)
    return CMN_BUFFER_OVERFLOW;
  memcpy(comm->slots + comm->rank * comm->slot_bytes, sendbuf, nbytes);
  int st = comm_barrier(comm);  // all contributions visible
  if (st != CMN_OK) return st;
  // every rank reduces locally (small host payloads; contention-free)
  memcpy(recvbuf, comm->slots, nbytes);
  for (int r = 1; r < comm->n_ranks; ++r)
    reduce_dispatch(recvbuf, comm->slots + r * comm->slot_bytes, count,
                    dtype, op);
  return comm_barrier(comm);  // slots free for reuse
}

// reduce to root (nccl.pyx reduce)
CMN_API int cmn_reduce(void* handle, const void* sendbuf, void* recvbuf,
                       int64_t count, int dtype, int op, int root) {
  auto* comm = static_cast<CmnComm*>(handle);
  if (!comm || !sendbuf) return CMN_INVALID_ARGUMENT;
  if (root < 0 || root >= comm->n_ranks) return CMN_INVALID_ARGUMENT;
  if (comm->rank == root && !recvbuf) return CMN_INVALID_ARGUMENT;
  const size_t esz = dtype_size(dtype);
  if (!esz) return CMN_INVALID_ARGUMENT;
  const size_t nbytes = count * esz;
  if (static_cast<int64_t>(nbytes) > comm->slot_bytes)
    return CMN_BUFFER_OVERFLOW;
  memcpy(comm->slots + comm->rank * comm->slot_bytes, sendbuf, nbytes);
  int st = comm_barrier(comm);
  if (st != CMN_OK) return st;
  if (comm->rank == root) {
    memcpy(recvbuf, comm->slots, nbytes);
    for (int r = 1; r < comm->n_ranks; ++r)
      reduce_dispatch(recvbuf, comm->slots + r * comm->slot_bytes, count,
                      dtype, op);
  }
  return comm_barrier(comm);
}

// bcast from root in-place (nccl.pyx bcast)
CMN_API int cmn_bcast(void* handle, void* buf, int64_t count, int dtype,
                      int root) {
  auto* comm = static_cast<CmnComm*>(handle);
  if (!comm || !buf) return CMN_INVALID_ARGUMENT;
  if (root < 0 || root >= comm->n_ranks) return CMN_INVALID_ARGUMENT;
  const size_t esz = dtype_size(dtype);
  if (!esz) return CMN_INVALID_ARGUMENT;
  const size_t nbytes = count * esz;
  if (static_cast<int64_t>(nbytes) > comm->slot_bytes)
    return CMN_BUFFER_OVERFLOW;
  if (comm->rank == root)
    memcpy(comm->slots + root * comm->slot_bytes, buf, nbytes);
  int st = comm_barrier(comm);
  if (st != CMN_OK) return st;
  if (comm->rank != root)
    memcpy(buf, comm->slots + root * comm->slot_bytes, nbytes);
  return comm_barrier(comm);
}

// reduce_scatter: rank r receives the reduction of everyone's r-th
// `recvcount` chunk (nccl.pyx reduce_scatter)
CMN_API int cmn_reduce_scatter(void* handle, const void* sendbuf,
                               void* recvbuf, int64_t recvcount, int dtype,
                               int op) {
  auto* comm = static_cast<CmnComm*>(handle);
  if (!comm || !sendbuf || !recvbuf) return CMN_INVALID_ARGUMENT;
  const size_t esz = dtype_size(dtype);
  if (!esz) return CMN_INVALID_ARGUMENT;
  const size_t total_bytes = recvcount * esz * comm->n_ranks;
  if (static_cast<int64_t>(total_bytes) > comm->slot_bytes)
    return CMN_BUFFER_OVERFLOW;
  memcpy(comm->slots + comm->rank * comm->slot_bytes, sendbuf, total_bytes);
  int st = comm_barrier(comm);
  if (st != CMN_OK) return st;
  const size_t chunk = recvcount * esz;
  memcpy(recvbuf, comm->slots + comm->rank * chunk, chunk);
  for (int r = 1; r < comm->n_ranks; ++r)
    reduce_dispatch(recvbuf,
                    comm->slots + r * comm->slot_bytes +
                        comm->rank * chunk,
                    recvcount, dtype, op);
  return comm_barrier(comm);
}

// allgather: concatenation of every rank's `sendcount` elements
// (nccl.pyx allgather)
CMN_API int cmn_allgather(void* handle, const void* sendbuf, void* recvbuf,
                          int64_t sendcount, int dtype) {
  auto* comm = static_cast<CmnComm*>(handle);
  if (!comm || !sendbuf || !recvbuf) return CMN_INVALID_ARGUMENT;
  const size_t esz = dtype_size(dtype);
  if (!esz) return CMN_INVALID_ARGUMENT;
  const size_t nbytes = sendcount * esz;
  if (static_cast<int64_t>(nbytes) > comm->slot_bytes)
    return CMN_BUFFER_OVERFLOW;
  memcpy(comm->slots + comm->rank * comm->slot_bytes, sendbuf, nbytes);
  int st = comm_barrier(comm);
  if (st != CMN_OK) return st;
  for (int r = 0; r < comm->n_ranks; ++r)
    memcpy(static_cast<char*>(recvbuf) + r * nbytes,
           comm->slots + r * comm->slot_bytes, nbytes);
  return comm_barrier(comm);
}

// barrier as a standalone primitive
CMN_API int cmn_barrier(void* handle) {
  auto* comm = static_cast<CmnComm*>(handle);
  if (!comm) return CMN_INVALID_ARGUMENT;
  return comm_barrier(comm);
}
