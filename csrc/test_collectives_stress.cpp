// Race-detection stress test for the shared-memory collective engine.
//
// The reference has no race detection at all (SURVEY 5); its only
// concurrency-correctness devices are GIL-released NCCL calls and
// stream syncs.  Here the native engine's barrier/slot protocol is
// validated under ThreadSanitizer: N threads play N ranks against one
// shm segment and hammer every collective; build+run via
// ci/run_tsan.sh.

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <unistd.h>
#include <vector>

extern "C" {
void* cmn_comm_create(const char* name, int n_ranks, int rank,
                      long long slot_bytes, double timeout_s);
void cmn_comm_destroy(void* handle, int unlink_shm);
int cmn_allreduce(void* h, const void* s, void* r, long long c, int dt,
                  int op);
int cmn_bcast(void* h, void* b, long long c, int dt, int root);
int cmn_reduce(void* h, const void* s, void* r, long long c, int dt,
               int op, int root);
int cmn_reduce_scatter(void* h, const void* s, void* r, long long c,
                       int dt, int op);
int cmn_allgather(void* h, const void* s, void* r, long long c, int dt);
int cmn_barrier(void* h);
const char* cmn_error_string(int);
}

static int failures = 0;

#define CHECK(cond, msg)                                   \
  do {                                                     \
    if (!(cond)) {                                         \
      fprintf(stderr, "FAIL rank? %s\n", msg);             \
      __atomic_fetch_add(&failures, 1, __ATOMIC_SEQ_CST);  \
    }                                                      \
  } while (0)

// local bf16 helpers (small exactly-representable integers only, so
// sums compare exactly)
static unsigned short to_bf16(float f) {
  unsigned int b;
  memcpy(&b, &f, 4);
  return static_cast<unsigned short>(b >> 16);
}
static float from_bf16(unsigned short v) {
  unsigned int b = static_cast<unsigned int>(v) << 16;
  float f;
  memcpy(&f, &b, 4);
  return f;
}

static void rank_main(const std::string& name, int n, int rank,
                      int iters) {
  void* comm = cmn_comm_create(name.c_str(), n, rank, 1 << 16, 30.0);
  if (!comm) {
    fprintf(stderr, "rank %d: attach failed\n", rank);
    __atomic_fetch_add(&failures, 1, __ATOMIC_SEQ_CST);
    return;
  }
  const int count = 257;  // deliberately not a lane multiple
  std::vector<float> send(count), recv(count), gather(count * n);
  for (int it = 0; it < iters; ++it) {
    for (int i = 0; i < count; ++i)
      send[i] = static_cast<float>(rank + it + i % 7);
    int st = cmn_allreduce(comm, send.data(), recv.data(), count, 0, 0);
    CHECK(st == 0, cmn_error_string(st));
    for (int i = 0; i < count; ++i) {
      float expect = n * (it + i % 7) + n * (n - 1) / 2.0f;
      CHECK(recv[i] == expect, "allreduce value");
    }
    st = cmn_bcast(comm, send.data(), count, 0, it % n);
    CHECK(st == 0, cmn_error_string(st));
    for (int i = 0; i < count; ++i)
      CHECK(send[i] == static_cast<float>(it % n + it + i % 7),
            "bcast value");
    st = cmn_allgather(comm, send.data(), gather.data(), count, 0);
    CHECK(st == 0, cmn_error_string(st));
    // bf16 allreduce (dtype 4): small ints stay exact in bf16 for
    // n <= 8, it < ~100
    std::vector<unsigned short> hsend(count), hrecv(count);
    for (int i = 0; i < count; ++i)
      hsend[i] = to_bf16(static_cast<float>(rank + i % 5));
    st = cmn_allreduce(comm, hsend.data(), hrecv.data(), count, 4, 0);
    CHECK(st == 0, cmn_error_string(st));
    for (int i = 0; i < count; ++i) {
      float expect = n * (i % 5) + n * (n - 1) / 2.0f;
      CHECK(from_bf16(hrecv[i]) == expect, "bf16 allreduce value");
    }
    st = cmn_barrier(comm);
    CHECK(st == 0, cmn_error_string(st));
  }
  cmn_comm_destroy(comm, rank == 0 ? 1 : 0);
}

int main(int argc, char** argv) {
  int n = argc > 1 ? atoi(argv[1]) : 4;
  int iters = argc > 2 ? atoi(argv[2]) : 200;
  std::string name = "/cmn-tsan-" + std::to_string(getpid());
  std::vector<std::thread> threads;
  for (int r = 0; r < n; ++r)
    threads.emplace_back(rank_main, name, n, r, iters);
  for (auto& t : threads) t.join();
  if (failures) {
    fprintf(stderr, "STRESS FAILED: %d\n", failures);
    return 1;
  }
  printf("collectives stress OK: %d ranks x %d iters\n", n, iters);
  return 0;
}
