#!/usr/bin/env python
"""Labeled 8->256-chip allreduce scaling-efficiency PROJECTION.

BASELINE.json's metric is "allreduce scaling efficiency 8->256 chips"
on a v5e pod.  Real multi-chip hardware is not reachable from this
box (one tunneled chip), so this script does the next honest thing
(VERDICT r4 weak #5): it combines

1. **measured** single-chip pieces from
   ``benchmarks/results/allreduce_tpu_r5.out`` (the payload sweep's
   per-strategy staging cost and the HBM-touch bandwidth roofline) and
   the headline ResNet-50 step time from
   ``benchmarks/results/bench_resnet50_r5.out``,
2. an **analytic ICI model** of a v5e 2-D torus (assumptions printed
   with every run, and marked as such), and
3. the **CPU-mesh relative curves**
   (``allreduce_cpu8_r4.jsonl``) as a transport-scaling shape check
   (host shared-memory, so only the trend is meaningful),

into a per-mesh-size projection of gradient-allreduce time and the
resulting scaling efficiency, plus end-to-end training efficiency
bounds with and without backward/allreduce overlap (the bucketed
communicator's design point, ``bucketed_communicator.py``).

EVERY row carries ``projection: true`` -- nothing here claims to be a
measurement.  Reference anchor: the 128-GPU scaling headline the
reference exists for (``/root/reference/README.md:15-24``).

Model (stated, simple, conservative):

- ring/torus allreduce moves ``2 * P * (N-1)/N`` bytes through each
  chip's ICI egress; with reduce-scatter + all-gather split across
  both torus dimensions the effective per-chip algorithm bandwidth is
  ``ici_links * ici_gbs_per_link * ici_efficiency``.
- total time(N) = staging(P) [measured] + wire(P, N) [analytic];
  scaling efficiency(N) = t(8) / t(N)  (constant per-device payload,
  so perfect scaling = flat time).
- v5e assumptions (public "How to Scale Your Model" numbers): 4 ICI
  links/chip (2-D torus, 2 axes x 2 directions), 45 GB/s one-way per
  link, 80% achievable algorithm efficiency; bf16 gradient wire dtype
  (the multi_node_optimizer's default, bf16 wire = 2 bytes/param).
  8..256 chips stay inside one v5e slice (16x16 torus max), so no
  DCN leg enters the window; the DCN term is still modeled (25 GB/s
  per host, 8 chips/host) and reported for the hypothetical
  multi-slice case.

Usage::

    python benchmarks/scaling_projection.py [--tag r5]
"""

import argparse
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
RES = os.path.join(HERE, 'results')

# --- stated v5e assumptions (analytic; see module docstring) --------
ICI_LINKS = 4
ICI_GBS_PER_LINK = 45.0          # one-way, GB/s
ICI_ALG_EFFICIENCY = 0.8
DCN_GBS_PER_HOST = 25.0          # GB/s, per 8-chip host
RESNET50_PARAMS = 25_600_000
WIRE_BYTES_PER_PARAM = 2         # bf16 wire dtype (multi_node_optimizer)
MESHES = (8, 16, 32, 64, 128, 256)


def _rows(path):
    out = []
    if not os.path.exists(path):
        return out
    for ln in open(path).read().splitlines():
        try:
            out.append(json.loads(ln))
        except ValueError:
            pass
    return out


def measured_inputs(tag):
    """Pull the measured single-chip pieces; mark what was found."""
    got = {'staging_ms': None, 'hbm_gbs': None, 'step_time_ms': None,
           'staging_strategy': None, 'staging_below_noise': False}
    raw_min = None
    for r in _rows(os.path.join(RES, 'allreduce_tpu_%s.out' % tag)):
        if r.get('suspect'):
            continue
        if r.get('metric') == 'hbm_touch_bandwidth':
            got['hbm_gbs'] = r.get('measured_hbm_gbs')
        if (r.get('metric') == 'allreduce_payload_sweep'
                and r.get('payload_mb', 0) > 50
                and r.get('staging_overhead_ms') is not None):
            s = r['staging_overhead_ms']
            # fastest measured strategy's staging = the cost a real
            # deployment would pay per step on each chip; track the
            # RAW minimum (clamping to 0 here would make the first
            # noise-negative row unbeatable and record the wrong
            # strategy) and clamp only at use
            if raw_min is None or s < raw_min:
                raw_min = s
                got['staging_strategy'] = r['strategy']
                got['staging_below_noise'] = bool(
                    r.get('staging_below_noise'))
    if raw_min is not None:
        got['staging_ms'] = max(raw_min, 0.0)
    for r in _rows(os.path.join(RES, 'bench_resnet50_%s.out' % tag)):
        if not r.get('suspect') and not r.get('error') \
                and r.get('step_time_ms'):
            got['step_time_ms'] = r['step_time_ms']
    return got


def cpu_shape_check():
    """Relative transport curve from the 8-virtual-device CPU mesh
    (host shared memory): only the TREND is meaningful, reported as
    corroboration that collective time grows sub-linearly per added
    device on a shared transport."""
    rows = [r for r in _rows(os.path.join(RES, 'allreduce_cpu8_r4.jsonl'))
            if r.get('strategy') == 'xla' and not r.get('suspect')]
    return {str(r['devices']): r['value'] for r in rows}


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument('--tag', default='r5')
    parser.add_argument('--params', type=int, default=RESNET50_PARAMS)
    parser.add_argument('--results-dir', default=RES,
                        help='where the jsonl lands (tests point this '
                             'at a tmp dir; measured inputs are still '
                             'read from the repo results dir)')
    args = parser.parse_args()

    got = measured_inputs(args.tag)
    staging_ms = got['staging_ms'] if got['staging_ms'] is not None \
        else 0.15  # fallback: ~100MB f32 pack+unpack at ~1.3 TB/s HBM
    step_ms = got['step_time_ms'] or 12.2  # round-5 measured fallback

    payload = args.params * WIRE_BYTES_PER_PARAM
    b_alg = ICI_LINKS * ICI_GBS_PER_LINK * ICI_ALG_EFFICIENCY  # GB/s

    assumptions = {
        'projection': True,
        'ici_links': ICI_LINKS,
        'ici_gbs_per_link_oneway': ICI_GBS_PER_LINK,
        'ici_alg_efficiency': ICI_ALG_EFFICIENCY,
        'alg_bandwidth_gbs': b_alg,
        'wire_dtype': 'bf16',
        'payload_mb': round(payload / 1e6, 1),
        'staging_ms_measured': got['staging_ms'] is not None,
        'staging_ms': round(staging_ms, 4),
        'staging_strategy': got['staging_strategy'],
        # True when the sweep could not distinguish the winning
        # strategy's staging from zero (VMEM-resident payload):
        # "measured" then means "measured to be below the noise
        # floor", not a signed cost
        'staging_below_noise': got['staging_below_noise'],
        'hbm_touch_gbs_measured': got['hbm_gbs'],
        'resnet50_step_ms_measured': got['step_time_ms'] is not None,
        'resnet50_step_ms': step_ms,
        'torus': '16x16 v5e slice; 8..256 chips all ride ICI '
                 '(no DCN leg inside the projected window)',
        'cpu_mesh_shape_check_ms': cpu_shape_check(),
    }
    emitted = [{'metric': 'scaling_projection_assumptions',
                **assumptions}]
    print(json.dumps(emitted[0]))

    t8 = None
    for n in MESHES:
        wire_ms = 2.0 * payload * (n - 1) / n / (b_alg * 1e9) * 1e3
        t = staging_ms + wire_ms
        if t8 is None:
            t8 = t
        # end-to-end: allreduce either fully exposed (no overlap) or
        # hidden behind the backward (bucketed overlap design point);
        # the truth lies between the two bounds
        step_exposed = step_ms + t
        step_overlap = max(step_ms, t)
        row = {
            'metric': 'allreduce_scaling_projection',
            'projection': True,
            'devices': n,
            'allreduce_ms': round(t, 3),
            'wire_ms': round(wire_ms, 3),
            'staging_ms': round(staging_ms, 4),
            'scaling_efficiency_vs_8': round(t8 / t, 3),
            'train_step_ms_no_overlap': round(step_exposed, 3),
            'train_step_ms_full_overlap': round(step_overlap, 3),
            'train_efficiency_vs_8_no_overlap': round(
                (step_ms + t8) / step_exposed, 3),
            'train_efficiency_vs_8_full_overlap': round(
                max(step_ms, t8) / step_overlap, 3),
        }
        emitted.append(row)
        print(json.dumps(row))

    # hypothetical multi-slice leg (NOT part of the 8->256 window):
    # the DCN term that would dominate past one slice, for context
    dcn_ms = 2.0 * payload / (DCN_GBS_PER_HOST * 1e9) * 1e3
    emitted.append({
        'metric': 'dcn_leg_context', 'projection': True,
        'note': 'beyond one 256-chip v5e slice the inter-slice leg '
                'rides DCN; per-host wire time for the same payload',
        'dcn_gbs_per_host': DCN_GBS_PER_HOST,
        'dcn_wire_ms': round(dcn_ms, 3)})
    print(json.dumps(emitted[-1]))

    os.makedirs(args.results_dir, exist_ok=True)
    out_path = os.path.join(args.results_dir,
                            'scaling_projection_%s.jsonl' % args.tag)
    with open(out_path, 'w') as f:
        for row in emitted:
            f.write(json.dumps(row) + '\n')
    sys.stdout.flush()
    print('wrote %s' % out_path)


if __name__ == '__main__':
    main()
