#!/usr/bin/env python
"""Measured (not merely lowering-pinned) comparison of allreduce
strategies on one ResNet-50 train step, with profiler traces.

VERDICT r3 weak #6 / next-round item 9: the nine communicator
strategies are proven to LOWER differently (HLO pins in
``tests/test_communicator.py``), but nothing showed they differ -- or
agree -- in *time* on real hardware, and the bucketed communicator's
backward-overlap rationale (``bucketed_communicator.py:10-18``) is a
scheduler hypothesis until a trace shows it.  This script times the
same ResNet-50 step under each strategy with the bench.py marginal
method and captures a ``jax.profiler`` trace of individual jitted
steps (the per-step program, so the backward/allreduce interleaving is
visible on the op timeline), so the overlap story can be read off.

Single chip: collectives are mesh=(1,1) loopbacks, so ABSOLUTE
differences are expected to be small; the artifact this produces is
(a) the real-chip timing row per strategy and (b) the traces, which
show where XLA schedules the fused allreduce relative to the backward
ops.  On a CPU mesh (``--cpu``) it is a plumbing check.

Usage::

    python benchmarks/strategy_trace.py            # real TPU
    python benchmarks/strategy_trace.py --cpu      # 8-dev CPU mesh

Appends rows to ``benchmarks/results/strategy_timing_<platform>.jsonl``
as each strategy completes (a timeout mid-series keeps what was
measured) and writes traces under ``benchmarks/results/traces/``.
"""

import json
import os
import shutil
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from bench import (  # noqa: E402
    LINEARITY_GATE, SIGNAL_MULT, _classifier_setup, _noise_estimate,
    _scan_maker, adaptive_marginal_time, devget_sync)

STRATEGIES = ('xla', 'bucketed', 'hierarchical')


def build_step(strategy, on_cpu):
    import jax
    import jax.numpy as jnp

    import chainermn_tpu

    n_dev = jax.device_count()
    inter = 2 if n_dev % 2 == 0 and n_dev > 1 else 1
    comm = chainermn_tpu.create_communicator(
        strategy, mesh_shape=(inter, n_dev // inter))
    if on_cpu:
        # plumbing only: a 2-block ResNet compiles/runs in seconds on
        # the virtual mesh; the real comparison needs the real chip
        from chainermn_tpu.models import ResNet
        insize, per_dev, n_classes = 16, 2, 10
        model = ResNet(stage_sizes=[1, 1], num_classes=n_classes,
                       dtype=jnp.float32, width=8)
    else:
        from chainermn_tpu.models import ResNet50
        insize, per_dev, n_classes = 128, 16, 1000
        model = ResNet50(num_classes=n_classes)
    batch = per_dev * n_dev
    return _classifier_setup(model, insize, batch, comm=comm,
                             n_classes=n_classes)


def main():
    argv = sys.argv[1:]
    cpu = '--cpu' in argv
    import jax
    if cpu:
        from chainermn_tpu.utils import force_host_devices
        force_host_devices(8, require=True)
    else:
        # host backend for throwaway model.init compiles -- the
        # tunnel's remote-compile service has crashed on giant init
        # programs (bench.py:init_on_host)
        from chainermn_tpu.utils.platform import enable_host_cpu_backend
        enable_host_cpu_backend()

    # same persistent compile cache as bench.py: a tunnel drop and
    # rerun must not pay 9 ResNet-50 scan compiles again
    here = os.path.dirname(os.path.abspath(__file__))
    cache = os.path.join(os.path.dirname(here), '.jax_compile_cache')
    jax.config.update('jax_compilation_cache_dir', cache)
    jax.config.update('jax_persistent_cache_min_compile_time_secs', 1.0)
    jax.config.update('jax_persistent_cache_min_entry_size_bytes', -1)

    platform = jax.default_backend()
    res = os.path.join(here, 'results')
    os.makedirs(res, exist_ok=True)
    out_path = os.path.join(res, 'strategy_timing_%s.jsonl' % platform)
    # fresh file per run, but APPEND per strategy: a timeout on a
    # later strategy keeps the rows already measured
    open(out_path, 'w').close()
    for strategy in STRATEGIES:
        print('[strategy_trace] building %s' % strategy,
              file=sys.stderr, flush=True)
        upd, arrays = build_step(strategy, cpu)
        make = _scan_maker(upd, arrays)
        ks, reps = ((2, 3, 4), 2) if cpu else ((2, 4, 6), 3)
        # adaptive escalation vs tunnel RTT jitter (bench.py); the
        # strategies are COMPARED against each other, so all three
        # must clear the same signal gate or the comparison is noise
        per, ov, times, lin, ks_used, esc = adaptive_marginal_time(
            make, ks, reps, max_rep_s=20.0, max_tries=5)
        noise = _noise_estimate(times, reps)
        row = {'strategy': strategy, 'platform': platform,
               'step_time_ms': round(per * 1e3, 3),
               'overhead_ms': round(ov * 1e3, 1),
               'scan_lengths': list(ks_used),
               'adaptive_escalations': esc,
               'timing_noise_ms': round(noise * 1e3, 2),
               'linearity_rel_err': round(lin, 4),
               'n_devices': jax.device_count()}
        if lin > LINEARITY_GATE:
            row['suspect'] = True
        if per * (ks_used[-1] - ks_used[0]) < SIGNAL_MULT * noise:
            row['suspect'] = True
            row['suspect_reason'] = 'marginal signal below noise floor'
        # trace INDIVIDUAL jitted steps (warmed up first), not one
        # compiled scan: the per-step program is what shows the
        # backward/allreduce interleaving on the op timeline
        # platform-scoped like the jsonl: a TPU run must not overwrite
        # the CPU plumbing traces (or vice versa)
        tdir = os.path.join(res, 'traces', platform, strategy)
        # fresh dir per capture: accumulated profiler sessions would
        # make any whole-dir analysis double-count self-times.  The
        # raw traces are local-only (.gitignore'd -- multi-MB
        # binaries); the durable artifact is trace_report.json, which
        # IS committed with the results
        shutil.rmtree(tdir, ignore_errors=True)
        os.makedirs(tdir, exist_ok=True)
        from chainermn_tpu.utils.profiling import trace
        # the TIMING row above is the primary datum; a profiler that
        # cannot capture on this backend (tunneled device planes are
        # unproven) must not cost it, so the capture is best-effort
        try:
            devget_sync(upd.update_core(arrays))  # compile + warm
            with trace(tdir):
                for _ in range(3):
                    metrics = upd.update_core(arrays)
                devget_sync(metrics)
            row['trace_dir'] = os.path.relpath(tdir, here)
        except Exception as e:
            row['trace_error'] = repr(e)[:300]
            # a partially-exported session must not survive for the
            # end-of-run trace_report pass to publish as a valid
            # breakdown contradicting this row's trace_error
            shutil.rmtree(tdir, ignore_errors=True)
            print('[strategy_trace] %s capture failed: %r'
                  % (strategy, e), file=sys.stderr, flush=True)
        with open(out_path, 'a') as f:
            f.write(json.dumps(row) + '\n')
        print(json.dumps(row), flush=True)
    # auto-render the step-time breakdown from the traces just
    # captured (benchmarks/trace_report.py); best-effort so a
    # converter failure cannot cost the timing rows above
    try:
        sys.path.insert(0, here)
        import trace_report
        trace_report.main(['--latest'])
    except Exception as e:
        print('[strategy_trace] trace_report failed: %r' % e,
              file=sys.stderr, flush=True)
    print('wrote %s' % out_path)


if __name__ == '__main__':
    main()
