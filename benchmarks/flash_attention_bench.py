#!/usr/bin/env python
"""Flash-attention microbench: Pallas kernel vs plain-XLA attention.

Times the repo's fused blockwise attention (``chainermn_tpu.ops``)
against the unfused jnp oracle (``mha_reference``: materializes the
(T, T) score matrix and lets XLA fuse what it can) on the SAME chip,
fwd and fwd+bwd, across sequence lengths -- and sweeps kernel block
sizes at one config to pick the best.  This quantifies the custom
hot-path the reference delegates to hand-written native code
(``/root/reference/chainermn/nccl/nccl.pyx:153-199``); here the
native analogue is the Mosaic-compiled kernel.

Measurement follows ``bench.py``: the tunneled backend adds ~70ms
RTT per dispatch and ``block_until_ready`` cannot be trusted, so each
sample is a ``lax.scan`` chain of attention calls compiled into ONE
program, synced by ``jax.device_get`` of a scalar slice, and the
per-call time is the marginal slope fit over three chain lengths
(median-of-reps; worst segment-slope deviation recorded per row as
``*_linearity_rel_err`` and suspect-gated at ``bench.LINEARITY_GATE``).

Usage::

    python benchmarks/flash_attention_bench.py            # real TPU
    python benchmarks/flash_attention_bench.py --cpu      # plumbing
    python benchmarks/flash_attention_bench.py --sweep    # + block sweep

Writes JSONL to ``benchmarks/results/flash_attention_<platform>.jsonl``
(one line per measurement) and prints a summary table.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from bench import (  # noqa: E402 (needs the sys.path insert above)
    BF16_PEAK_TFLOPS, LINEARITY_GATE, SIGNAL_MULT, _noise_estimate,
    adaptive_marginal_time)


def attn_flops(b, t, h, d, causal, bwd):
    # QK^T + PV: each is t^2*d MACs = 2*t^2*d FLOPs per (batch, head)
    f = 4.0 * b * h * t * t * d
    if causal:
        f *= 0.5
    if bwd:
        f *= 3.5  # fwd + recompute + dq/dk/dv passes
    return f


def bench_config(b, t, h, d, causal, dtype, use_pallas, bwd,
                 block_q=128, block_k=128, quick=False):
    import jax
    import jax.numpy as jnp
    from jax import lax

    from chainermn_tpu import ops
    from chainermn_tpu.ops.flash_attention import mha_reference

    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = (jax.random.normal(kq, (b, t, h, d), jnp.float32) * 0.5
         ).astype(dtype)
    k = (jax.random.normal(kk, (b, t, h, d), jnp.float32) * 0.5
         ).astype(dtype)
    v = (jax.random.normal(kv, (b, t, h, d), jnp.float32) * 0.5
         ).astype(dtype)

    if use_pallas:
        def attn(qq, kk_, vv_):
            return ops.flash_attention(qq, kk_, vv_, causal=causal,
                                       block_q=block_q,
                                       block_k=block_k)
    else:
        def attn(qq, kk_, vv_):
            return mha_reference(qq, kk_, vv_, causal=causal)

    if bwd:
        def one(qq):
            # differentiate wrt ALL of q/k/v: grads over q alone let
            # XLA dead-code the dK/dV matmuls on the unfused arm and
            # skew the comparison against attn_flops's full 3.5x
            # backward accounting
            dq, dk, dv = jax.grad(
                lambda q_, k_, v_: (attn(q_, k_, v_).astype(
                    jnp.float32) ** 2).sum(),
                argnums=(0, 1, 2))(qq, k, v)
            return (dq + dk + dv).astype(qq.dtype)
    else:
        def one(qq):
            return attn(qq, k, v).astype(qq.dtype)

    def make(n):
        @jax.jit
        def run():
            def body(c, _):
                # fold the output back into the carry so the chain is
                # data-dependent (XLA cannot elide steps)
                return one(c), ()
            out, _ = lax.scan(body, q, None, length=n)
            return out[0, 0, 0, :1].astype(jnp.float32)
        return run

    # reuse bench.py's measurement primitive (same contract: make(k)
    # returns a compiled thunk; marginal slope fit over three chain
    # lengths, median-of-reps, devget-synced)
    # no length-1 even in quick mode: XLA special-cases a scan of 1
    # and its time sits off the k>=2 line (see bench.py's cpu path).
    # Adaptive escalation (bench.py SIGNAL_MULT): a ~0.1ms attention
    # step is invisible under the tunnel's tens-of-ms RTT jitter at
    # short scans; the floor (a LOWER bound on per-step time: analytic
    # flops at 2x this chip's table peak) plans the span so the
    # escalated scan is long enough on the first retry
    ks = (2, 3, 4) if quick else (2, 4, 6)
    kind = jax.devices()[0].device_kind
    peak = next((v for kk_n, v in BF16_PEAK_TFLOPS.items()
                 if kk_n in kind.lower()), 500.0)
    floor = attn_flops(b, t, h, d, causal, bwd) / (2 * peak * 1e12)
    per, _overhead, times, lin, ks_used, _esc = adaptive_marginal_time(
        make, ks, reps=3, per_item_floor=floor, max_rep_s=15.0)
    # below-signal result: positive-but-jitter slope must not be
    # published as a real kernel time (same gate as bench.measure)
    weak = (per * (ks_used[-1] - ks_used[0])
            < SIGNAL_MULT * _noise_estimate(times, 3))
    return per, lin, weak


def main():
    argv = sys.argv[1:]
    cpu = '--cpu' in argv
    sweep = '--sweep' in argv
    quick = '--quick' in argv or cpu
    if cpu:
        os.environ.setdefault(
            'XLA_FLAGS', '--xla_force_host_platform_device_count=1')
        import jax
        jax.config.update('jax_platforms', 'cpu')
    import jax
    import jax.numpy as jnp

    platform = jax.default_backend()
    here = os.path.dirname(os.path.abspath(__file__))
    out_path = os.path.join(
        here, 'results', 'flash_attention_%s.jsonl' % platform)
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    # write rows to a temp file, renamed into place at the end AND on
    # any partial failure with >=1 row -- an aborted run neither
    # truncates the previously committed results nor loses what it
    # measured
    tmp_path = out_path + '.tmp'
    out_file = open(tmp_path, 'w')
    n_rows = 0

    def record(row):
        nonlocal n_rows
        out_file.write(json.dumps(row) + '\n')
        out_file.flush()
        n_rows += 1
        print(json.dumps(row), flush=True)

    # CPU: tiny plumbing shapes (interpret-mode Pallas is slow);
    # TPU: the real long-context sweep
    if cpu:
        configs = [(1, 256, 2, 64)]
        seqs_note = 'cpu plumbing check'
    else:
        configs = [(4, 1024, 8, 64), (4, 2048, 8, 64),
                   (2, 4096, 8, 64), (1, 8192, 8, 64)]
        seqs_note = 'tpu'
    dtype = jnp.float32 if cpu else jnp.bfloat16

    done = False
    try:
        _run_all(configs, seqs_note, dtype, cpu, sweep, quick,
                 platform, record)
        done = True
    finally:
        out_file.close()
        if done:
            os.replace(tmp_path, out_path)
            print('wrote %s (%d rows)' % (out_path, n_rows))
        elif n_rows:
            # keep what was measured WITHOUT clobbering a previously
            # complete results file
            os.replace(tmp_path, out_path + '.partial')
            print('aborted; kept %d rows in %s.partial'
                  % (n_rows, out_path))
        else:
            try:
                os.unlink(tmp_path)
            except OSError:
                pass


def _run_all(configs, seqs_note, dtype, cpu, sweep, quick, platform,
             record):
    for b, t, h, d in configs:
        for causal in (False, True):
            for bwd in (False, True):
                row = {'b': b, 't': t, 'h': h, 'd': d,
                       'causal': causal, 'bwd': bwd,
                       'dtype': str(dtype.__name__),
                       'platform': platform, 'note': seqs_note}
                try:
                    for name, use_pallas in (('pallas', True),
                                             ('xla', False)):
                        per, lin, weak = bench_config(
                            b, t, h, d, causal, dtype, use_pallas,
                            bwd, quick=quick)
                        row[name + '_ms'] = per * 1e3
                        row[name + '_tflops'] = attn_flops(
                            b, t, h, d, causal, bwd) / per / 1e12
                        row[name + '_linearity_rel_err'] = round(
                            lin, 4)
                        if lin > LINEARITY_GATE:
                            row['suspect'] = True
                            row['suspect_reason'] = (
                                row.get('suspect_reason', '') +
                                '%s arm timing nonlinear (%.0f%%); '
                                % (name, lin * 100))
                        if weak:
                            row['suspect'] = True
                            row['suspect_reason'] = (
                                row.get('suspect_reason', '') +
                                '%s arm signal below noise floor; '
                                % name)
                    row['speedup'] = row['xla_ms'] / row['pallas_ms']
                except Exception as e:  # keep earlier rows (OOM etc.)
                    row['error'] = str(e)[-300:]
                record(row)

    if sweep and not cpu:
        b, t, h, d = 4, 2048, 8, 64
        for bq in (128, 256, 512):
            for bk in (128, 256, 512):
                try:
                    per, lin, weak = bench_config(
                        b, t, h, d, True, dtype, True, True,
                        block_q=bq, block_k=bk, quick=quick)
                    row = {'sweep': True, 'block_q': bq, 'block_k': bk,
                           'b': b, 't': t, 'h': h, 'd': d,
                           'causal': True, 'bwd': True,
                           'pallas_ms': per * 1e3,
                           'linearity_rel_err': round(lin, 4),
                           'platform': platform}
                    if lin > LINEARITY_GATE:
                        row['suspect'] = True
                        row['suspect_reason'] = (
                            'timing nonlinear (%.0f%%)' % (lin * 100))
                    if weak:
                        row['suspect'] = True
                        row['suspect_reason'] = (
                            row.get('suspect_reason', '') +
                            '; signal below noise floor').lstrip('; ')
                except Exception as e:  # Mosaic lowering limits
                    row = {'sweep': True, 'block_q': bq, 'block_k': bk,
                           'error': str(e)[-300:], 'platform': platform}
                record(row)


if __name__ == '__main__':
    main()
