#!/usr/bin/env python
"""On-chip allreduce payload sweep: the single-chip component of the
collective story, measured (VERDICT r4 next #5).

On the one real chip the collective proper is a mesh=(1,1) loopback --
``psum`` over a size-1 axis is identity and XLA folds
slice-of-concatenate, so a bare ``allreduce_grad`` chain can
legitimately compile to nothing (the round-4 row: value 0.0,
unmeasurable).  What a single chip CAN measure honestly:

1. **HBM bandwidth roofline** -- marginal time of an elementwise
   touch of a large buffer (read + write = 2x bytes), the same
   self-calibration idea as bench.py's matmul roofline.
2. **Per-strategy staging cost** -- each scan step runs
   ``touch(c)`` then ``comm.allreduce_grad(...)``; the touch (a
   multiply by 1+1e-7 on every leaf) cannot be folded away, so every
   row has a real, linearity-checkable slope, and the difference
   ``row - baseline`` is the strategy's pack/unpack/reshard overhead
   (flat's fused big-buffer copy vs naive's per-leaf loopback vs
   hierarchical's scatter/gather staging).  That staging cost is the
   per-chip term of the scaling model in
   ``benchmarks/scaling_projection.py``; the ICI term is analytic.

Prints one JSON row per (strategy, payload); ``strategy='touch'``
rows are the elementwise floor.  Rows are suspect-gated exactly like
bench.py (linearity + signal-vs-noise).  Reference anchor: the
communicator strategy menu at
``/root/reference/chainermn/communicators/__init__.py:12-20``.

Usage::

    python benchmarks/allreduce_payload_sweep.py            # real TPU
    python benchmarks/allreduce_payload_sweep.py --cpu 8    # plumbing
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from bench import (  # noqa: E402
    LINEARITY_GATE, SIGNAL_MULT, _noise_estimate, adaptive_marginal_time)

STRATEGIES = ('xla', 'flat', 'naive', 'hierarchical', 'bucketed')


def resnet_shaped_leaves(n_params):
    """A few large + many small leaves, like a real gradient pytree."""
    leaves = {}
    remaining = n_params
    i = 0
    for size in (2048 * 1000, 512 * 512 * 9, 2048 * 512, 1024 * 256):
        while remaining > size and len(leaves) <= 160:
            leaves['w%d' % i] = size
            remaining -= size
            i += 1
    leaves['tail'] = max(remaining, 1)
    return leaves


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument('--payloads', default='6400000,25600000',
                        help='comma list of payload sizes in params '
                             '(f32; default 6.4M and the '
                             'ResNet-50-sized 25.6M)')
    parser.add_argument('--strategies', default=','.join(STRATEGIES))
    parser.add_argument('--cpu', type=int, default=0, metavar='N',
                        help='force an N-virtual-device CPU platform')
    args = parser.parse_args()

    if args.cpu:
        import chainermn_tpu.utils as u
        u.force_host_devices(args.cpu)

    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    import chainermn_tpu

    here = os.path.dirname(os.path.abspath(__file__))
    cache = os.path.join(os.path.dirname(here), '.jax_compile_cache')
    jax.config.update('jax_compilation_cache_dir', cache)
    jax.config.update('jax_persistent_cache_min_compile_time_secs', 1.0)
    jax.config.update('jax_persistent_cache_min_entry_size_bytes', -1)

    n_dev = jax.device_count()
    inter = 2 if n_dev % 2 == 0 and n_dev > 1 else 1

    def emit(row):
        print(json.dumps(row), flush=True)

    # --- 1. HBM bandwidth roofline: touch 256 MB, marginal slope ----
    cal_words = 64 * 1024 * 1024  # 256 MB f32
    x0 = jnp.ones((cal_words,), jnp.float32)

    def make_cal(k):
        @jax.jit
        def run():
            def body(c, _):
                return c * jnp.float32(1.0 + 1e-7), ()
            out, _ = lax.scan(body, x0, None, length=k)
            return out[:1]
        return run

    # floor: read+write of the buffer at an optimistic 4 TB/s
    cal_floor = 2.0 * cal_words * 4 / 4e12
    per, _ov, times, lin, ks_used, esc = adaptive_marginal_time(
        make_cal, (4, 8, 12), reps=3, per_item_floor=cal_floor,
        max_rep_s=20.0)
    noise = _noise_estimate(times, 3)
    hbm_gbs = 2.0 * cal_words * 4 / per / 1e9
    cal_row = {
        'metric': 'hbm_touch_bandwidth', 'strategy': 'calibration',
        'payload_mb': round(cal_words * 4 / 1e6, 1),
        'value': round(per * 1e3, 4), 'unit': 'ms',
        'measured_hbm_gbs': round(hbm_gbs, 1),
        'scan_lengths': list(ks_used), 'adaptive_escalations': esc,
        'timing_noise_ms': round(noise * 1e3, 3),
        'linearity_rel_err': round(lin, 4),
        'n_devices': n_dev, 'backend': jax.default_backend(),
        'sync_method': 'device_get',
    }
    if lin > LINEARITY_GATE:
        cal_row['suspect'] = True
    if per * (ks_used[-1] - ks_used[0]) < SIGNAL_MULT * noise:
        cal_row['suspect'] = True
        cal_row['suspect_reason'] = 'marginal signal below noise floor'
    emit(cal_row)

    # --- 2. per-(payload, strategy) staging rows --------------------
    for n_params in (int(v) for v in args.payloads.split(',')):
        leaves = resnet_shaped_leaves(n_params)
        grads = {k: jnp.ones((v,), jnp.float32)
                 for k, v in leaves.items()}
        payload_bytes = n_params * 4
        touch_floor = 2.0 * payload_bytes / 4e12
        baseline_per = None
        for name in ('touch',) + tuple(args.strategies.split(',')):
            if name == 'touch':
                comm = None
            else:
                comm = chainermn_tpu.create_communicator(
                    name, mesh_shape=(inter, n_dev // inter),
                    devices=jax.devices()[:n_dev])

            def make(k, comm=comm):
                def body(c, _):
                    # the touch forbids XLA from folding the chain to
                    # identity even when the collective is a size-1
                    # loopback; carry-threading forbids reordering
                    c = {kk: v * jnp.float32(1.0 + 1e-7)
                         for kk, v in c.items()}
                    if comm is not None:
                        c = comm.allreduce_grad(c)
                    return c, ()

                def mapped(g):
                    out, _ = lax.scan(body, g, None, length=k)
                    return out

                if comm is not None:
                    fn = jax.jit(jax.shard_map(
                        mapped, mesh=comm.mesh, in_specs=P(),
                        out_specs=P(), check_vma=False))
                else:
                    fn = jax.jit(mapped)
                return lambda: fn(grads)['tail'][:1]

            per, _ov, times, lin, ks_used, esc = adaptive_marginal_time(
                make, (2, 4, 6), reps=3, per_item_floor=touch_floor,
                max_rep_s=20.0)
            noise = _noise_estimate(times, 3)
            row = {
                'metric': 'allreduce_payload_sweep',
                'strategy': name,
                'payload_mb': round(payload_bytes / 1e6, 1),
                'n_leaves': len(leaves),
                'value': round(per * 1e3, 4), 'unit': 'ms',
                'effective_gbs': round(
                    2.0 * payload_bytes / per / 1e9, 1),
                'scan_lengths': list(ks_used),
                'adaptive_escalations': esc,
                'timing_noise_ms': round(noise * 1e3, 3),
                'linearity_rel_err': round(lin, 4),
                'n_devices': n_dev, 'backend': jax.default_backend(),
                'sync_method': 'device_get',
            }
            if lin > LINEARITY_GATE:
                row['suspect'] = True
            if per * (ks_used[-1] - ks_used[0]) < SIGNAL_MULT * noise:
                row['suspect'] = True
                row['suspect_reason'] = \
                    'marginal signal below noise floor'
            # plausibility vs the run's own HBM calibration: a row
            # "moving" bytes faster than measured HBM means the
            # loop-carried pytree stayed VMEM-RESIDENT (v5e VMEM is
            # 128 MB; both sweep payloads fit, the 256 MB calibration
            # buffer does not) -- real chip behavior, but the row
            # must say its time is NOT an HBM staging cost
            if ('suspect' not in cal_row
                    and row['effective_gbs'] > hbm_gbs):
                row['vmem_resident_likely'] = True
                row['note'] = ('effective rate exceeds the measured '
                               'HBM roofline (%.0f GB/s): payload '
                               'stayed VMEM-resident across scan '
                               'iterations' % hbm_gbs)
            if name == 'touch':
                if 'suspect' not in row:
                    baseline_per = per
                    baseline_noise = noise
            elif baseline_per is not None:
                stage = (per - baseline_per) * 1e3
                row['staging_overhead_ms'] = round(stage, 4)
                # an overhead the instrument cannot distinguish from
                # zero must not be consumed downstream as a signed
                # measurement (negative values are pure rep noise)
                if abs(stage) < (noise + baseline_noise) * 1e3:
                    row['staging_below_noise'] = True
            emit(row)


if __name__ == '__main__':
    main()
