#!/usr/bin/env python
"""Pipeline-schedule memory footprint from XLA's own accounting.

Quantifies the schedule trade-off the PipelineUpdater docstring
claims, directly from ``compiled.memory_analysis()`` of the real
train step (no estimates): differentiating the GPipe scan stores one
carry per tick so temp memory grows with ``n_micro``; ``remat=True``
shrinks the stored carry to the boundary activation but still grows;
the true 1F1B schedule's in-flight ring is bounded by ``2*n_stages``
so its temp stays FLAT as ``n_micro`` scales.

Micro-batch SIZE is held constant while the COUNT grows, so the
per-micro activation footprint is identical across rows -- any growth
is schedule-carried state.

Usage: ``python benchmarks/pipeline_memory.py`` (8-virtual-device CPU
mesh by default; the analysis is backend-agnostic since it reads the
compiled program's buffer assignment).  Writes
``benchmarks/results/pipeline_memory_<platform>.jsonl``.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    if '--tpu' not in sys.argv:
        # appends to any pre-existing XLA_FLAGS (a bare setdefault
        # would silently lose the device forcing)
        from chainermn_tpu.utils import force_host_devices
        force_host_devices(8)
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from chainermn_tpu.parallel.pipeline import stack_stage_params
    from chainermn_tpu.training.pipeline_updater import (
        PipelineUpdater, pipeline_mesh)

    dim = 64
    micro_b = 8  # per-device micro-batch size, constant across rows
    n_stages = 4
    mesh = pipeline_mesh(n_stages)
    n_data = mesh.shape['data']

    def stage_fn(p, x):
        return jnp.tanh(x @ p['w'])

    def loss_on_last(outs, ym):
        ce = optax.softmax_cross_entropy_with_integer_labels(
            outs.reshape(-1, dim), ym.reshape(-1))
        return ce.mean(), {}

    rng = np.random.RandomState(0)
    plist = [{'w': jnp.asarray(rng.randn(dim, dim) * 0.3, jnp.float32)}
             for _ in range(n_stages)]

    here = os.path.dirname(os.path.abspath(__file__))
    platform = jax.default_backend()
    out_path = os.path.join(
        here, 'results', 'pipeline_memory_%s.jsonl' % platform)
    rows = []
    for n_micro in (4, 8, 16, 32):
        batch = n_data * n_micro * micro_b
        x = rng.randn(batch, dim).astype(np.float32)
        y = rng.randint(0, dim, batch).astype(np.int32)
        for remat, sched in ((False, 'gpipe'), (True, 'gpipe'),
                             (False, '1f1b')):
            upd = PipelineUpdater(
                iter([]), optax.sgd(0.1), stage_fn, loss_on_last,
                stack_stage_params(plist), mesh, n_micro=n_micro,
                remat=remat, schedule=sched, donate=False)
            arrays = upd.shard_batch((x, y))  # pre-collated columns
            ma = upd._step.lower(
                upd.params, upd.extra, upd.opt_state,
                *arrays).compile().memory_analysis()
            row = {'n_micro': n_micro, 'micro_b': micro_b,
                   'schedule': sched + ('+remat' if remat else ''),
                   'temp_kb': round(ma.temp_size_in_bytes / 1024, 1),
                   'platform': platform}
            rows.append(row)
            print(json.dumps(row), flush=True)

    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, 'w') as f:
        for row in rows:
            f.write(json.dumps(row) + '\n')
    print('wrote %s (%d rows)' % (out_path, len(rows)))
    # the design claim, asserted from XLA's numbers: 1f1b flat,
    # gpipe growing
    t = {(r['schedule'], r['n_micro']): r['temp_kb'] for r in rows}
    assert t[('1f1b', 32)] < 1.2 * t[('1f1b', 4)], '1f1b not flat'
    assert t[('gpipe', 32)] > 1.5 * t[('gpipe', 4)], \
        'gpipe unexpectedly flat'
    print('claim holds: 1f1b flat (%.1f->%.1fKB), gpipe grows '
          '(%.1f->%.1fKB)' % (t[('1f1b', 4)], t[('1f1b', 32)],
                              t[('gpipe', 4)], t[('gpipe', 32)]))


if __name__ == '__main__':
    main()
