#!/usr/bin/env python
"""Allreduce scaling-efficiency harness.

The BASELINE metric is "images/sec/chip + allreduce scaling efficiency
8 -> 256 chips".  This harness measures the gradient-allreduce step in
isolation over growing mesh sizes: a ResNet-50-sized gradient pytree
(~25.6M params) is mean-reduced with each communicator strategy, and
efficiency is reported relative to the smallest mesh (perfect scaling
== the per-step time stays flat as devices are added, since the
payload per device is constant).

On real TPU slices the mesh sizes come from the slice; on CPU the
virtual-device flag provides the scaling axis for harness validation
(`--devices 1,2,4,8`).  Prints one JSON line per (strategy, mesh).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument('--devices', default=None,
                        help='comma list of mesh sizes (default: all '
                             'visible devices in powers of two)')
    parser.add_argument('--strategies', default='xla,hierarchical,'
                        'two_dimensional,flat,naive')
    parser.add_argument('--params', type=int, default=25_600_000,
                        help='gradient payload size (default: '
                             'ResNet-50-sized)')
    parser.add_argument('--steps', type=int, default=20)
    parser.add_argument('--cpu', type=int, default=0, metavar='N',
                        help='force an N-virtual-device CPU platform')
    args = parser.parse_args()

    if args.cpu:
        import chainermn_tpu.utils as u
        u.force_host_devices(args.cpu)

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    import chainermn_tpu

    n_all = jax.device_count()
    if args.devices:
        sizes = [int(v) for v in args.devices.split(',')]
    else:
        sizes = [s for s in (1, 2, 4, 8, 16, 32, 64, 128, 256)
                 if s <= n_all]

    # ResNet-50-shaped payload: a few large + many small leaves
    leaves = {}
    remaining = args.params
    i = 0
    for size in (2048 * 1000, 512 * 512 * 9, 2048 * 512, 1024 * 256):
        while remaining > size:
            leaves['w%d' % i] = size
            remaining -= size
            i += 1
            if len(leaves) > 160:
                break
    leaves['tail'] = max(remaining, 1)

    baseline = {}
    for name in args.strategies.split(','):
        for n in sizes:
            inter = 2 if n % 2 == 0 and n > 1 else 1
            if name == 'single_node':
                inter = 1
            comm = chainermn_tpu.create_communicator(
                name, mesh_shape=(inter, n // inter),
                devices=jax.devices()[:n])
            grads = {k: jnp.ones((v,), jnp.float32)
                     for k, v in leaves.items()}

            def red(g):
                return comm.allreduce_grad(g)

            fn = jax.jit(jax.shard_map(
                red, mesh=comm.mesh, in_specs=P(),
                out_specs=P(), check_vma=False))
            # sync via device_get of a real output byte:
            # block_until_ready is NOT a reliable sync on the tunneled
            # TPU backend (see bench.py measurement method)
            out = fn(grads)
            jax.device_get(out['tail'][:1])
            t0 = time.perf_counter()
            for _ in range(args.steps):
                out = fn(out)
            jax.device_get(out['tail'][:1])
            dt = (time.perf_counter() - t0) / args.steps
            key = name
            baseline.setdefault(key, dt)
            eff = baseline[key] / dt
            print(json.dumps({
                'metric': 'allreduce_time_ms',
                'strategy': name,
                'devices': n,
                'value': round(dt * 1e3, 3),
                'payload_mb': round(args.params * 4 / 1e6, 1),
                'scaling_efficiency': round(eff, 3),
            }))


if __name__ == '__main__':
    main()
