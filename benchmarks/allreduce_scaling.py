#!/usr/bin/env python
"""Allreduce scaling-efficiency harness.

The BASELINE metric is "images/sec/chip + allreduce scaling efficiency
8 -> 256 chips".  This harness measures the gradient-allreduce step in
isolation over growing mesh sizes: a ResNet-50-sized gradient pytree
(~25.6M params) is mean-reduced with each communicator strategy, and
efficiency is reported relative to the smallest mesh (perfect scaling
== the per-step time stays flat as devices are added, since the
payload per device is constant).

On real TPU slices the mesh sizes come from the slice; on CPU the
virtual-device flag provides the scaling axis for harness validation
(`--devices 1,2,4,8`).  Prints one JSON line per (strategy, mesh).

Timing follows bench.py's hardened method (a per-call Python loop on
the tunneled backend measures RTT, not the collective): K chained
allreduces run inside ONE compiled ``lax.scan`` under the shard_map,
the per-allreduce time is the marginal slope fit over three scan
lengths (median-of-reps, device_get-synced), and the linearity
diagnostic is reported and suspect-gated per row.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument('--devices', default=None,
                        help='comma list of mesh sizes (default: all '
                             'visible devices in powers of two)')
    parser.add_argument('--strategies', default='xla,hierarchical,'
                        'two_dimensional,flat,naive')
    parser.add_argument('--params', type=int, default=25_600_000,
                        help='gradient payload size (default: '
                             'ResNet-50-sized)')
    parser.add_argument('--steps', type=int, default=20,
                        help='(ignored; kept for invocation compat -- '
                             'timing is the marginal slope over scan '
                             'lengths 2/4/6)')
    parser.add_argument('--cpu', type=int, default=0, metavar='N',
                        help='force an N-virtual-device CPU platform')
    args = parser.parse_args()

    if args.cpu:
        import chainermn_tpu.utils as u
        u.force_host_devices(args.cpu)

    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    import chainermn_tpu
    from bench import LINEARITY_GATE, SIGNAL_MULT, _noise_estimate, \
        adaptive_marginal_time

    n_all = jax.device_count()
    if args.devices:
        sizes = [int(v) for v in args.devices.split(',')]
    else:
        sizes = [s for s in (1, 2, 4, 8, 16, 32, 64, 128, 256)
                 if s <= n_all]

    # ResNet-50-shaped payload: a few large + many small leaves
    leaves = {}
    remaining = args.params
    i = 0
    for size in (2048 * 1000, 512 * 512 * 9, 2048 * 512, 1024 * 256):
        while remaining > size:
            leaves['w%d' % i] = size
            remaining -= size
            i += 1
            if len(leaves) > 160:
                break
    leaves['tail'] = max(remaining, 1)

    baseline = {}
    for name in args.strategies.split(','):
        for n in sizes:
            inter = 2 if n % 2 == 0 and n > 1 else 1
            if name == 'single_node':
                inter = 1
            comm = chainermn_tpu.create_communicator(
                name, mesh_shape=(inter, n // inter),
                devices=jax.devices()[:n])
            grads = {k: jnp.ones((v,), jnp.float32)
                     for k, v in leaves.items()}

            def make(k):
                def mapped(g):
                    def body(c, _):
                        # carry-threading makes each reduction depend
                        # on the previous one; XLA cannot collapse the
                        # chain
                        return comm.allreduce_grad(c), ()
                    out, _ = lax.scan(body, g, None, length=k)
                    return out

                fn = jax.jit(jax.shard_map(
                    mapped, mesh=comm.mesh, in_specs=P(),
                    out_specs=P(), check_vma=False))
                # thunk returns a 1-element slice: the devget sync
                # fetches real bytes without hauling a full leaf over
                # the tunnel per measurement
                return lambda: fn(grads)['tail'][:1]

            # planning floor: one allreduce moves >= payload bytes
            # through HBM; no chip beats 2 TB/s, so this bounds the
            # adaptive span when RTT jitter hides short scans (a
            # 1-device "allreduce" can be legitimately ~free -- the
            # signal gate below marks that row unmeasurable instead
            # of publishing jitter)
            floor = args.params * 4 / 2e12
            per, _ov, times, lin, ks_used, esc = adaptive_marginal_time(
                make, (2, 4, 6), reps=3, per_item_floor=floor,
                max_rep_s=20.0, max_tries=3)
            noise = _noise_estimate(times, 3)
            row = {
                'metric': 'allreduce_time_ms',
                'strategy': name,
                'devices': n,
                'value': round(per * 1e3, 3),
                'payload_mb': round(args.params * 4 / 1e6, 1),
                'scan_lengths': list(ks_used),
                'adaptive_escalations': esc,
                'timing_noise_ms': round(noise * 1e3, 2),
                'linearity_rel_err': round(lin, 4),
                'sync_method': 'device_get',
            }
            if lin > LINEARITY_GATE:
                row['suspect'] = True
            if per * (ks_used[-1] - ks_used[0]) < SIGNAL_MULT * noise:
                row['suspect'] = True
                row['unmeasurable'] = (
                    'marginal signal below noise floor (the op may '
                    'be legitimately near-free at this mesh size)')
            # efficiency only against a TRUSTED smallest-mesh row: a
            # suspect baseline would silently poison every later
            # row's ratio (suspect data is never published raw)
            if 'suspect' not in row:
                baseline.setdefault(name, per)
            if name in baseline:
                row['scaling_efficiency'] = round(
                    baseline[name] / per, 3)
            print(json.dumps(row))


if __name__ == '__main__':
    main()
