#!/usr/bin/env python
"""Turn a banked ``jax.profiler`` trace into a step-time breakdown.

VERDICT r4 next #2 asks for "a trace-backed analysis of the specific
bottleneck" behind the ResNet-50 step time.  ``strategy_trace.py``
captures the traces; this tool converts them into evidence a reader
can act on without TensorBoard: per-category self-time totals (convs
vs elementwise/BN vs copies/transposes vs collectives), the top ops
by self time with their achieved GFLOP/s and memory bandwidth, and
DMA-stall percentages -- i.e. *where the 12.4 ms goes*.

The reference has no profiling subsystem at all (SURVEY §5); this is
parity-plus tooling on the TPU side of the ledger.

Implementation: the trace dirs hold ``*.xplane.pb`` XSpace protos;
``xprof.convert.raw_to_tool_data`` (the TensorBoard profile plugin's
own converter, available in this image) renders the ``hlo_stats``
DataTable, which this script aggregates.  Degrades gracefully when a
trace has no device plane (e.g. a tunnel that does not export device
events): the report then says so instead of fabricating zeros.

Usage::

    python benchmarks/trace_report.py DIR [DIR...]   # explicit dirs
    python benchmarks/trace_report.py --latest       # newest trace per
                                                     # strategy under
                                                     # results/traces/

Writes ``benchmarks/results/trace_report.json`` (one object per trace
dir) and prints a readable summary; exits 0 with a "no traces" note
when nothing is found (so CI wiring is safe before the first trace
lands).
"""

import glob
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
RES = os.path.join(HERE, 'results')
TOP_N = 12

# hlo_stats "HLO op category" -> coarse bucket.  Anything unmatched
# falls into 'other' and is reported verbatim in top_ops, so a novel
# category is visible rather than silently mis-bucketed.
BUCKETS = (
    ('convolution', 'conv/matmul'),
    ('dot', 'conv/matmul'),
    ('all-reduce', 'collective'),
    ('all-gather', 'collective'),
    ('reduce-scatter', 'collective'),
    ('collective', 'collective'),
    ('copy', 'copy/transpose'),
    ('transpose', 'copy/transpose'),
    ('reshape', 'copy/transpose'),
    ('fusion', 'fusion/elementwise'),
    ('loop', 'fusion/elementwise'),
    ('elementwise', 'fusion/elementwise'),
    ('reduce', 'reduction'),
    ('rng', 'rng'),
    ('infeed', 'host-io'),
    ('outfeed', 'host-io'),
)


def bucket_of(category):
    cat = (category or '').lower()
    for needle, bucket in BUCKETS:
        if needle in cat:
            return bucket
    return 'other'


def cell_float(v):
    """Tolerant float from an xprof DataTable cell (ADVICE r5 #3).
    DataTables emit plain numbers but ALSO formatted strings --
    thousands separators ('1,234'), percent suffixes ('56.2%') --
    depending on converter version; a strict float() crashed the
    standalone CLI after analysis already succeeded.  Returns None
    for anything unparseable (callers fall back to the raw value)."""
    if v is None:
        return None
    if isinstance(v, (int, float)):
        return float(v)
    try:
        return float(str(v).replace(',', '').replace('%', '').strip())
    except ValueError:
        return None


def datatable_rows(table):
    """Yield dicts from a Google-DataTable-shaped ``hlo_stats`` JSON."""
    cols = [c.get('id') for c in table.get('cols', [])]
    for row in table.get('rows', []):
        cells = row.get('c', [])
        yield {cols[i]: (cells[i] or {}).get('v')
               for i in range(min(len(cols), len(cells)))}


def _tool_json(paths, tool):
    """One xprof tool's output for a list of xplane paths, via
    whichever converter generation this image ships:

    - ``xprof.convert.raw_to_tool_data`` (standalone xprof package);
    - else the TF pybind entry point directly.  tensorboard-plugin-
      profile 2.17's python wrapper binds
      ``_pywrap_profiler.xspace_to_tools_data``, which TF >= 2.18
      moved to ``_pywrap_profiler_plugin`` -- the wrapper import dies
      with AttributeError and its tool table predates ``hlo_stats``
      anyway, which is why this script "never produced a real
      breakdown" (VERDICT r5) on those images.  The pybind call
      itself works and serves hlo_stats/framework_op_stats DataTable
      JSON; overview_page comes back as a proto and goes through the
      plugin's own gviz converter.
    """
    try:
        from xprof.convert import raw_to_tool_data as r
        data, _ = r.xspace_to_tool_data(paths, tool, {})
        return data
    except ImportError:
        pass
    from tensorflow.python.profiler.internal import (  # noqa: E501  pylint: disable=g-direct-tensorflow-import
        _pywrap_profiler_plugin as plugin)
    raw, ok = plugin.xspace_to_tools_data(list(paths), tool)
    if not ok:
        raise RuntimeError('converter rejected tool %r: %r'
                           % (tool, raw[:200]))
    if tool == 'overview_page':
        from tensorboard_plugin_profile.convert import (
            overview_page_proto_to_gviz)
        return overview_page_proto_to_gviz.to_json(raw)
    return raw


def _tool_tables(paths, tool):
    """hlo_stats returns one DataTable; framework_op_stats returns a
    list of them (device table, host table).  Normalize to a list."""
    data = _tool_json(paths, tool)
    obj = json.loads(data) if isinstance(data, (str, bytes)) else data
    return obj if isinstance(obj, list) else [obj]


def _collect_ops(paths, tool):
    """(buckets, ops) aggregated from one xprof tool's tables."""
    buckets, ops = {}, []
    for table in _tool_tables(paths, tool):
        for row in datatable_rows(table):
            self_us = cell_float(row.get('total_self_time')) or 0.0
            if self_us <= 0:
                continue
            cat = row.get('category') or row.get('type') or '?'
            b = buckets.setdefault(bucket_of(cat),
                                   {'self_time_us': 0.0, 'ops': 0})
            b['self_time_us'] += self_us
            b['ops'] += 1
            ops.append({
                'op': (row.get('hlo_op_name')
                       or row.get('operation') or '?'),
                'category': cat,
                'occurrences': row.get('occurrences'),
                'self_time_us': round(self_us, 1),
                'gflops_per_sec': row.get('model_flop_rate'),
                'memory_bw_gibs': row.get('measured_memory_bw'),
                'dma_stall_pct': row.get('dma_stall_percent'),
            })
    return buckets, ops


def _xplane_pb2():
    """The XSpace proto module, wherever this image ships it."""
    try:
        from xprof.protobuf import xplane_pb2
        return xplane_pb2
    except ImportError:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
        return xplane_pb2


def _collect_intervals(paths):
    """``{plane_name: [(start_us, end_us, bucket), ...]}`` from the
    raw XSpace protos -- the timestamped view the overlap computation
    needs (op-stats tables carry self-times only, no concurrency
    information).  Spans from every line of a plane are pooled: a
    collective on one line overlaps compute on another line of the
    same plane (async collective streams / other cores)."""
    pb = _xplane_pb2()
    out = {}
    for path in paths:
        space = pb.XSpace()
        with open(path, 'rb') as f:
            space.ParseFromString(f.read())
        for plane in space.planes:
            meta = plane.event_metadata
            ivs = out.setdefault(plane.name, [])
            for line in plane.lines:
                for ev in line.events:
                    name = meta[ev.metadata_id].name
                    if name.startswith('$'):
                        continue  # python tracing scaffolding
                    start = ev.offset_ps / 1e6
                    ivs.append((start, start + ev.duration_ps / 1e6,
                                bucket_of(name)))
    return out


#: buckets whose spans count as compute a collective can hide behind
OVERLAP_COMPUTE = ('conv/matmul', 'fusion/elementwise', 'reduction')


def overlap_stats_from_paths(paths):
    """Trace-wide overlap statistics: per plane, the ``collective``-
    bucket intervals vs the union of compute-bucket intervals, summed
    across planes.  Uses the SAME interval arithmetic and definition
    as the runtime telemetry layer
    (:mod:`chainermn_tpu.telemetry.report`): ``overlap_fraction =
    1 - exposed/total``, None when the trace has no collective spans
    (absence of evidence is reported as absence)."""
    from chainermn_tpu.telemetry.report import overlap_from_intervals

    total = exposed = 0.0
    seen = False
    for ivs in _collect_intervals(paths).values():
        coll = [(a, b) for a, b, bk in ivs if bk == 'collective']
        if not coll:
            continue
        comp = [(a, b) for a, b, bk in ivs
                if bk in OVERLAP_COMPUTE]
        st = overlap_from_intervals(coll, comp)
        total += st['total_collective_s']   # _us actually; see below
        exposed += st['exposed_collective_s']
        seen = True
    # intervals above are in MICROSECONDS, so the "seconds" fields of
    # overlap_from_intervals come back in us; normalize to ms here
    return {
        'total_collective_ms': round(total / 1e3, 3),
        'exposed_collective_ms': round(exposed / 1e3, 3),
        'overlap_fraction': (
            None if not seen or total <= 0.0
            else round(max(0.0, min(1.0, 1.0 - exposed / total)), 4)),
    }


def _collect_host_events(paths, min_self_us=1.0):
    """(buckets, ops) from the raw XSpace host planes.

    The CPU backend emits no framework/HLO op-stats rows at all (the
    converter returns an IDLE-only table), but the ``/host:CPU``
    plane DOES carry per-executable and per-HLO-op spans
    (``TfrtCpuExecutable::Execute``, ``dot.3``, ``fusion.12``...).
    Walking the proto directly turns a CPU capture into a real
    breakdown -- the plumbing check that proves the whole
    capture->convert->aggregate path off-chip, which is exactly what
    the r3-r5 windows lacked.  Self time = span duration minus the
    duration of spans nested inside it on the same thread line.
    """
    pb = _xplane_pb2()
    agg = {}
    for path in paths:
        space = pb.XSpace()
        with open(path, 'rb') as f:
            space.ParseFromString(f.read())
        for plane in space.planes:
            meta = plane.event_metadata
            for line in plane.lines:
                spans = sorted(
                    ((ev.offset_ps, ev.offset_ps + ev.duration_ps,
                      meta[ev.metadata_id].name)
                     for ev in line.events
                     # '$'-prefixed spans are the python tracing
                     # scaffolding (profiler.py frames), not workload
                     if not meta[ev.metadata_id].name.startswith('$')),
                    key=lambda s: (s[0], -s[1]))
                stack = []  # (end_ps, self_ps accumulator index)
                selfs = []
                for start, end, name in spans:
                    while stack and stack[-1][0] <= start:
                        stack.pop()
                    if stack:  # nested: parent loses this span's time
                        selfs[stack[-1][1]][1] -= (end - start)
                    selfs.append([name, end - start])
                    stack.append((end, len(selfs) - 1))
                for name, self_ps in selfs:
                    a = agg.setdefault(name, [0, 0.0])
                    a[0] += 1
                    a[1] += max(self_ps, 0) / 1e6  # ps -> us
    buckets, ops = {}, []
    for name, (count, self_us) in agg.items():
        if self_us < min_self_us:
            continue
        cat = bucket_of(name)
        b = buckets.setdefault(cat, {'self_time_us': 0.0, 'ops': 0})
        b['self_time_us'] += self_us
        b['ops'] += 1
        ops.append({'op': name, 'category': cat, 'occurrences': count,
                    'self_time_us': round(self_us, 1)})
    return buckets, ops


# overview_page property keys worth surfacing (TPU traces populate
# these; host-only traces report zeros, which analyze_trace's caller
# sees only alongside real op rows anyway)
UTIL_KEYS = (
    'device_duty_cycle_percent',
    'mxu_utilization_percent',
    'hbm_utilization_percent',
    'flop_rate_utilization_relative_to_roofline',
    'device_idle_time_percent',
)


def device_utilization(paths):
    """Device-level utilization summary from the overview_page tool
    (best-effort; {} when unavailable)."""
    try:
        out = {}
        for table in _tool_tables(paths, 'overview_page'):
            props = table.get('p') or {}
            for key in UTIL_KEYS:
                if key in props and key not in out:
                    out[key] = props[key]
        return out
    except Exception:
        return {}


def overlap_by_axis_from_telemetry(outdir):
    """Per-mesh-axis overlap split from a telemetry session dir
    (``events-rank*.jsonl``).  Device xplane profiles carry no mesh
    axis names -- the HLO op name of a lowered all-reduce says
    nothing about WHICH named axis it spans -- so the dp-vs-tp split
    of the overlap column comes from the axis-tagged telemetry spans
    (:func:`chainermn_tpu.telemetry.report.overlap_stats`), captured
    alongside the profile (``CHAINERMN_TPU_TELEMETRY=<dir>``)."""
    from chainermn_tpu.telemetry import report as treport

    _metas, spans, _events, _bad = treport.load_rank_logs(outdir)
    st = treport.overlap_stats(spans)
    return {
        key: {
            'spans': agg['spans'],
            'total_collective_ms': round(
                agg['total_collective_s'] * 1e3, 3),
            'exposed_collective_ms': round(
                agg['exposed_collective_s'] * 1e3, 3),
            'overlap_fraction': agg['overlap_fraction'],
        }
        for key, agg in (st.get('per_axis') or {}).items()}


def analyze_trace(trace_dir, telemetry_dir=None):
    """One report object for one trace dir (or an explanatory stub).

    ``telemetry_dir`` (or a ``telemetry/`` subdir of the trace dir
    holding ``events-rank*.jsonl``) adds the per-axis dp-vs-tp split
    to the overlap object -- see
    :func:`overlap_by_axis_from_telemetry`."""
    paths = sorted(glob.glob(
        os.path.join(trace_dir, '**', '*.xplane.pb'), recursive=True))
    out = {'trace_dir': os.path.relpath(trace_dir, HERE)}
    if not paths:
        out['error'] = 'no .xplane.pb under trace dir'
        return out
    # a trace dir accumulates one timestamped profiler session per
    # capture (plugins/profile/<ts>/); summing them would double-count
    # self-times across rounds, so analyze ONLY the newest session
    sessions = {}
    for p in paths:
        sessions.setdefault(os.path.dirname(p), []).append(p)
    newest = max(sessions)  # session dir names are UTC timestamps
    paths = sessions[newest]
    out['session'] = os.path.relpath(newest, trace_dir)
    if len(sessions) > 1:
        out['older_sessions_ignored'] = len(sessions) - 1
    try:
        buckets, ops = _collect_ops(paths, 'hlo_stats')
        out['source'] = 'hlo_stats'
        if not ops:
            # a CPU/host-only trace has no HLO device plane; the
            # framework-op view still shows where host time went,
            # and exercises this parser off-chip
            buckets, ops = _collect_ops(paths, 'framework_op_stats')
            out['source'] = 'framework_op_stats (no device-op rows; ' \
                'host-only trace)'
        if not ops:
            # the CPU backend emits op-stats rows for NEITHER tool
            # (IDLE-only tables); the raw host plane still carries
            # per-executable / per-HLO-op spans -- aggregate those
            buckets, ops = _collect_host_events(paths)
            out['source'] = 'xplane_host_events (op-stats tools ' \
                'empty; aggregated raw host-plane spans)'
    except Exception as e:  # converter is external; never crash the CI
        out['error'] = 'xprof conversion failed: %r' % e
        return out
    if not ops:
        out['error'] = ('trace has no device-op, framework-op or '
                        'host-plane rows')
        return out
    # overlap column (ISSUE 6 / ROADMAP item 5): collective span time
    # hidden behind compute vs exposed, from the raw xplane intervals
    # (best-effort: op-stats-only traces carry no timestamps)
    try:
        out['overlap'] = overlap_stats_from_paths(paths)
    except Exception as e:
        out['overlap'] = {'total_collective_ms': None,
                          'exposed_collective_ms': None,
                          'overlap_fraction': None,
                          'error': repr(e)}
    # dp-vs-tp axis split of the overlap column, from the axis-tagged
    # telemetry capture when one rode along (never fabricated from
    # the axis-blind device profile)
    tdir = telemetry_dir or os.path.join(trace_dir, 'telemetry')
    if glob.glob(os.path.join(tdir, 'events-rank*.jsonl')):
        try:
            out['overlap']['by_axis'] = \
                overlap_by_axis_from_telemetry(tdir)
            out['overlap']['by_axis_source'] = tdir
        except Exception as e:
            out['overlap']['by_axis_error'] = repr(e)
    util = device_utilization(paths)
    if util:
        out['device_utilization'] = util
    total = sum(b['self_time_us'] for b in buckets.values())
    out['total_self_time_us'] = round(total, 1)
    out['buckets'] = {
        k: {'self_time_us': round(v['self_time_us'], 1),
            'pct': round(100.0 * v['self_time_us'] / total, 1),
            'ops': v['ops']}
        for k, v in sorted(buckets.items(),
                           key=lambda kv: -kv[1]['self_time_us'])}
    ops.sort(key=lambda o: -o['self_time_us'])
    out['top_ops'] = ops[:TOP_N]
    return out


def latest_trace_dirs():
    """All (platform, strategy) trace dirs under results/traces.
    Each dir holds exactly one strategy's captures; session selection
    (newest capture within a dir) happens in analyze_trace."""
    return sorted(p for p in
                  glob.glob(os.path.join(RES, 'traces', '*', '*'))
                  if os.path.isdir(p))


def render(report):
    lines = ['## %s' % report['trace_dir']]
    if report.get('error'):
        lines.append('  (no analysis: %s)' % report['error'])
        return '\n'.join(lines)
    lines.append('  total device self time: %.1f us'
                 % report['total_self_time_us'])
    ov = report.get('overlap') or {}
    if ov.get('overlap_fraction') is not None:
        lines.append(
            '  overlap fraction: %.3f  (collective %.3f ms, '
            '%.3f ms exposed)'
            % (ov['overlap_fraction'], ov['total_collective_ms'],
               ov['exposed_collective_ms']))
    elif ov:
        lines.append('  overlap: no collective spans in trace%s'
                     % (' (%s)' % ov['error'] if ov.get('error')
                        else ''))
    for key, agg in sorted((ov.get('by_axis') or {}).items()):
        frac = agg.get('overlap_fraction')
        lines.append(
            '    axis %-12s %4d spans  %8.3f ms collective  '
            '%8.3f ms exposed  overlap %s'
            % (key, agg['spans'], agg['total_collective_ms'],
               agg['exposed_collective_ms'],
               '-' if frac is None else '%.3f' % frac))
    for key, val in (report.get('device_utilization') or {}).items():
        lines.append('  %s: %s' % (key, val))
    for name, b in report['buckets'].items():
        lines.append('  %-20s %8.1f us  %5.1f%%  (%d ops)'
                     % (name, b['self_time_us'], b['pct'], b['ops']))
    lines.append('  top ops by self time:')
    for o in report['top_ops']:
        extras = []
        # tolerant per-op formatting (ADVICE r5 #3): a cell the
        # converter rendered as a formatted string must not crash the
        # report -- parse through cell_float, fall back to the raw
        # value verbatim
        for field, fmt in (('gflops_per_sec', '%.0f GF/s'),
                           ('memory_bw_gibs', '%.0f GiB/s'),
                           ('dma_stall_pct', '%.0f%% DMA stall')):
            raw = o.get(field)
            if not raw:
                continue
            try:
                f = cell_float(raw)
                extras.append(fmt % f if f is not None
                              else '%s=%r' % (field, raw))
            except (TypeError, ValueError):
                extras.append('%s=%r' % (field, raw))
        lines.append('    %8.1f us  %-28s %-16s %s'
                     % (o['self_time_us'], o['op'][:28], o['category'],
                        ', '.join(extras)))
    return '\n'.join(lines)


def main(argv):
    telemetry_dir = None
    if '--telemetry' in argv:
        i = argv.index('--telemetry')
        telemetry_dir = argv[i + 1] if i + 1 < len(argv) else None
        argv = argv[:i] + argv[i + 2:]
    dirs = [a for a in argv if not a.startswith('--')]
    if '--latest' in argv or not dirs:
        dirs = dirs or latest_trace_dirs()
    out_path = os.path.join(RES, 'trace_report.json')
    if not dirs:
        # ADVICE r5 #4: a previously committed breakdown must not
        # outlive the captures it described (strategy_trace rmtree's
        # failed capture dirs) -- rewrite the artifact with an
        # explanatory stub so it always reflects the LATEST capture
        # state instead of contradicting a jsonl row's trace_error
        # SAME row shape as the banked-artifact path (one JSONL row,
        # 'trace_dir' key always present, errors under 'error'): JSON
        # consumers iterate rows and read row['trace_dir'] / .get(
        # 'error') uniformly -- the old stub omitted trace_dir and
        # diverged from the per-dir schema
        stub = {
            'trace_dir': None,
            'error': 'no trace dirs found',
            'detail': ('no capture dirs under %s at report time; any '
                       'previous per-op breakdown is superseded (its '
                       'captures were removed)'
                       % os.path.relpath(os.path.join(RES, 'traces'),
                                         HERE)),
        }
        os.makedirs(RES, exist_ok=True)
        with open(out_path, 'w') as f:
            f.write(json.dumps(stub) + '\n')
        print('no trace dirs found under %s'
              % os.path.join(RES, 'traces'))
        print('wrote stub %s' % os.path.relpath(out_path,
                                                os.getcwd()))
        return 0
    reports = [analyze_trace(d, telemetry_dir=telemetry_dir)
               for d in dirs]
    with open(out_path, 'w') as f:
        for rep in reports:
            f.write(json.dumps(rep) + '\n')
    for rep in reports:
        print(render(rep))
    print('wrote %s' % os.path.relpath(out_path, os.getcwd()))
    return 0


if __name__ == '__main__':
    sys.exit(main(sys.argv[1:]))
