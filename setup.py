from setuptools import find_packages, setup

setup(
    name='chainermn-tpu',
    version='0.1.0',
    description='TPU-native distributed deep learning framework '
                '(ChainerMN capability surface, rebuilt on JAX/XLA)',
    packages=find_packages(include=['chainermn_tpu*']),
    install_requires=[
        'jax',
        'flax',
        'optax',
        'numpy',
    ],
    extras_require={
        'checkpoint': ['orbax-checkpoint'],
        'test': ['pytest'],
    },
    python_requires='>=3.9',
    license='MIT',
)
