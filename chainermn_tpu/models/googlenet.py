"""GoogLeNet / Inception-v1 (reference
``examples/imagenet/models_v2/googlenet.py``, insize 224; auxiliary
classifier heads included, weighted 0.3 like the reference loss)."""

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class Inception(nn.Module):
    """Inception module: 1x1 / 3x3 / 5x5 / pool-proj branches."""
    n1: int
    n3r: int
    n3: int
    n5r: int
    n5: int
    proj: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        d = self.dtype
        b1 = nn.relu(nn.Conv(self.n1, (1, 1), dtype=d)(x))
        b3 = nn.relu(nn.Conv(self.n3r, (1, 1), dtype=d)(x))
        b3 = nn.relu(nn.Conv(self.n3, (3, 3), padding=1, dtype=d)(b3))
        b5 = nn.relu(nn.Conv(self.n5r, (1, 1), dtype=d)(x))
        b5 = nn.relu(nn.Conv(self.n5, (5, 5), padding=2, dtype=d)(b5))
        bp = nn.max_pool(x, (3, 3), strides=(1, 1), padding='SAME')
        bp = nn.relu(nn.Conv(self.proj, (1, 1), dtype=d)(bp))
        return jnp.concatenate([b1, b3, b5, bp], axis=-1)


class _AuxHead(nn.Module):
    num_classes: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train):
        x = nn.avg_pool(x, (5, 5), strides=(3, 3))
        x = nn.relu(nn.Conv(128, (1, 1), dtype=self.dtype)(x))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(1024, dtype=self.dtype)(x))
        x = nn.Dropout(0.7, deterministic=not train)(x)
        return nn.Dense(self.num_classes,
                        dtype=jnp.float32)(x).astype(jnp.float32)


class GoogLeNet(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    insize: int = 224
    aux_heads: bool = True

    @nn.compact
    def __call__(self, x, train=True):
        d = self.dtype
        x = x.astype(d)
        x = nn.relu(nn.Conv(64, (7, 7), strides=(2, 2), padding=3,
                            dtype=d)(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding='SAME')
        x = nn.relu(nn.Conv(64, (1, 1), dtype=d)(x))
        x = nn.relu(nn.Conv(192, (3, 3), padding=1, dtype=d)(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding='SAME')
        x = Inception(64, 96, 128, 16, 32, 32, dtype=d)(x)
        x = Inception(128, 128, 192, 32, 96, 64, dtype=d)(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding='SAME')
        x = Inception(192, 96, 208, 16, 48, 64, dtype=d)(x)
        aux1 = (_AuxHead(self.num_classes, d)(x, train)
                if self.aux_heads else None)
        x = Inception(160, 112, 224, 24, 64, 64, dtype=d)(x)
        x = Inception(128, 128, 256, 24, 64, 64, dtype=d)(x)
        x = Inception(112, 144, 288, 32, 64, 64, dtype=d)(x)
        aux2 = (_AuxHead(self.num_classes, d)(x, train)
                if self.aux_heads else None)
        x = Inception(256, 160, 320, 32, 128, 128, dtype=d)(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding='SAME')
        x = Inception(256, 160, 320, 32, 128, 128, dtype=d)(x)
        x = Inception(384, 192, 384, 48, 128, 128, dtype=d)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(0.4, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        x = x.astype(jnp.float32)
        if self.aux_heads and train:
            return x, (aux1, aux2)
        return x
