"""Model zoo.

The reference delegates models to Chainer plus an ImageNet zoo under
``examples/imagenet/models_v2/`` (alex, googlenet, googlenetbn, nin,
resnet50) and MLPs in the MNIST examples.  ChainerMN-TPU is standalone,
so the zoo lives in the package: flax.linen modules, NHWC layouts,
bfloat16-friendly, reported metrics matching the reference's
``chainer.report({'loss','accuracy'})`` convention via classifier
loss functions.
"""

from chainermn_tpu.models.mlp import MLP  # noqa
from chainermn_tpu.models.classifier import Classifier, classifier_loss  # noqa
