"""Model zoo.

The reference delegates models to Chainer plus an ImageNet zoo under
``examples/imagenet/models_v2/`` (alex, googlenet, googlenetbn, nin,
resnet50) and MLPs in the MNIST examples.  ChainerMN-TPU is standalone,
so the zoo lives in the package: flax.linen modules, NHWC layouts,
bfloat16-friendly, reported metrics matching the reference's
``chainer.report({'loss','accuracy'})`` convention via classifier
loss functions.
"""

from chainermn_tpu.models.mlp import MLP  # noqa
from chainermn_tpu.models.classifier import (  # noqa
    Classifier, StatefulClassifier, classifier_loss)
from chainermn_tpu.models.alex import Alex  # noqa
from chainermn_tpu.models.nin import NIN  # noqa
from chainermn_tpu.models.vgg import VGG, VGG16  # noqa
from chainermn_tpu.models.googlenet import GoogLeNet  # noqa
from chainermn_tpu.models.googlenetbn import GoogLeNetBN  # noqa
from chainermn_tpu.models.resnet50 import (  # noqa
    ResNet, ResNet50, ResNet101, ResNet152)
from chainermn_tpu.models.seq2seq import Seq2seq, seq2seq_loss  # noqa
from chainermn_tpu.models.transformer import (  # noqa
    TransformerLM, TransformerBlock, decode_step, decode_step_paged,
    init_kv_cache, init_paged_kv_cache, kv_cache_specs, lm_loss,
    lm_loss_sum, pipeline_parts, pipeline_stage_specs, prefill,
    prefill_paged, spec_verify, spec_verify_paged, tp_oracle,
    tp_param_specs)


def get_arch(name, **kwargs):
    """Architecture registry (parity with the reference's arch table at
    ``train_imagenet.py:103-109``)."""
    archs = {
        'alex': Alex,
        'googlenet': GoogLeNet,
        'googlenetbn': GoogLeNetBN,
        'nin': NIN,
        'resnet50': ResNet50,
        # MXU-friendly space-to-depth stem; exact weight-mapped
        # equivalent of resnet50 (models/resnet50.py)
        'resnet50_s2d': (lambda **kw: ResNet50(
            stem='space_to_depth', **kw)),
        'resnet101': ResNet101,
        'resnet152': ResNet152,
        'vgg16': VGG16,
    }
    if name not in archs:
        raise ValueError('unknown architecture %r (choose from %s)'
                         % (name, ', '.join(sorted(archs))))
    return archs[name](**kwargs)
