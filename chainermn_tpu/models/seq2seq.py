"""LSTM encoder-decoder for NMT (BASELINE config 4: "seq2seq / NMT,
dynamic define-by-run graph, variable-shape allreduce").

The reference relies on Chainer's eager graphs to handle ragged
sequences; the TPU-native treatment is static-shape buckets: pad to a
bucket length, mask the loss, and let one compiled step per bucket
serve the whole corpus (`lax.scan` over time steps keeps the program
compiler-friendly).  Gradient shapes are therefore constant -- the
"variable-shape allreduce" stress disappears by design, which is
exactly the right TPU answer to that config.
"""

from typing import Any

import flax.linen as nn
import jax.numpy as jnp
import optax


class Seq2seq(nn.Module):
    n_layers: int = 2
    n_source_vocab: int = 8000
    n_target_vocab: int = 8000
    n_units: int = 512
    dtype: Any = jnp.bfloat16

    def setup(self):
        self.embed_x = nn.Embed(self.n_source_vocab, self.n_units,
                                dtype=self.dtype)
        self.embed_y = nn.Embed(self.n_target_vocab, self.n_units,
                                dtype=self.dtype)
        # nn.RNN lifts lax.scan over the flax module (time axis 1)
        self.encoder = [
            nn.RNN(nn.OptimizedLSTMCell(self.n_units, dtype=self.dtype),
                   return_carry=True)
            for _ in range(self.n_layers)]
        self.decoder = [
            nn.RNN(nn.OptimizedLSTMCell(self.n_units, dtype=self.dtype),
                   return_carry=True)
            for _ in range(self.n_layers)]
        self.out = nn.Dense(self.n_target_vocab, dtype=jnp.float32)

    def __call__(self, xs, ys_in):
        """Teacher-forced training forward.

        xs: (B, Ts) int32 source tokens (0 = pad).
        ys_in: (B, Tt) int32 target input tokens (BOS-shifted).
        Returns logits (B, Tt, n_target_vocab), float32.
        """
        h = self.embed_x(xs)
        carries = []
        for rnn in self.encoder:
            carry, h = rnn(h)
            carries.append(carry)
        h = self.embed_y(ys_in)
        for rnn, carry in zip(self.decoder, carries):
            _, h = rnn(h, initial_carry=carry)
        return self.out(h).astype(jnp.float32)


def seq2seq_loss(apply_fn, pad_id=0):
    """Masked token cross-entropy + perplexity metric, the reference's
    seq2seq loss shape."""

    def loss_fn(params, xs, ys_in, ys_out):
        logits = apply_fn(params, xs, ys_in)
        mask = (ys_out != pad_id).astype(jnp.float32)
        ce = optax.softmax_cross_entropy_with_integer_labels(
            logits, ys_out)
        total = jnp.sum(ce * mask)
        n = jnp.maximum(jnp.sum(mask), 1.0)
        loss = total / n
        return loss, {'perp': jnp.exp(loss)}

    return loss_fn


def bucket_batches(pairs, bucket_widths=(8, 16, 32, 64), pad_id=0):
    """Group (src, tgt) token-id sequences into static-shape buckets.

    Returns ``{width: (xs, ys_in, ys_out)}`` arrays; sequences longer
    than the widest bucket are truncated.  This is the TPU-native
    replacement for the reference's per-batch dynamic shapes.
    """
    import numpy as np
    buckets = {}
    widest = max(bucket_widths)
    for src, tgt in pairs:
        src, tgt = list(src)[:widest], list(tgt)[:widest - 1]
        width = next(w for w in sorted(bucket_widths)
                     if w >= max(len(src), len(tgt) + 1))
        buckets.setdefault(width, []).append((src, tgt))
    out = {}
    for width, items in buckets.items():
        xs = np.full((len(items), width), pad_id, np.int32)
        yin = np.full((len(items), width), pad_id, np.int32)
        yout = np.full((len(items), width), pad_id, np.int32)
        for i, (src, tgt) in enumerate(items):
            xs[i, :len(src)] = src
            yin[i, 0] = 1  # BOS
            yin[i, 1:len(tgt) + 1] = tgt
            yout[i, :len(tgt)] = tgt
            yout[i, len(tgt)] = 2  # EOS
        out[width] = (xs, yin, yout)
    return out
