"""Network-in-Network (reference ``examples/imagenet/models_v2/nin.py``,
insize 227: 4 mlpconv stacks, global average pool head).

Norm-free model: activations route through the zoo's shared
:func:`chainermn_tpu.models._norm.norm_act` helper with
``use_norm=False``, so ``fused_norm`` is accepted for zoo API parity
and is a no-op here."""

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from chainermn_tpu.models._norm import norm_act


class NIN(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    insize: int = 227
    fused_norm: bool = False  # accepted for zoo API parity; no norm

    def _act(self, x, train):
        return norm_act(x, train=train, fused=self.fused_norm,
                        dtype=self.dtype, name=None, use_norm=False)

    def _mlpconv(self, x, features, kernel, stride, pad, train):
        x = self._act(nn.Conv(features, kernel, strides=stride,
                              padding=pad, dtype=self.dtype)(x), train)
        x = self._act(nn.Conv(features, (1, 1), dtype=self.dtype)(x),
                      train)
        x = self._act(nn.Conv(features, (1, 1), dtype=self.dtype)(x),
                      train)
        return x

    @nn.compact
    def __call__(self, x, train=True):
        if x.shape[1] < 68 or x.shape[2] < 68:
            # VALID 11x11/4 conv + three 3x3/2 pools: below ~68px the
            # spatial dims collapse to zero and the global-average head
            # silently yields NaN -- fail at trace time instead
            raise ValueError(
                'NIN needs input >= 68x68 (canonical %d), got %r'
                % (self.insize, x.shape[1:3]))
        x = x.astype(self.dtype)
        x = self._mlpconv(x, 96, (11, 11), (4, 4), 'VALID', train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = self._mlpconv(x, 256, (5, 5), (1, 1), 2, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = self._mlpconv(x, 384, (3, 3), (1, 1), 1, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = self._mlpconv(x, self.num_classes, (3, 3), (1, 1), 1,
                          train)
        x = jnp.mean(x, axis=(1, 2))  # global average pooling head
        return x.astype(jnp.float32)
