"""Decoder-only transformer LM -- the long-context flagship.

Not a reference-parity model (the reference's zoo stops at 2017 CNNs);
this is the workload that exercises the long-context machinery the
reference lacks and SURVEY 5 marks as the design axis: the fused
attention kernel (``ops.flash_attention``) on one chip, ring attention
(``parallel.ring_attention``) when the sequence dim is sharded over a
mesh axis, fused LayerNorm, and fused softmax cross-entropy with a
vocab-sharded-friendly shape.

All matmuls are bfloat16-by-default (MXU-native); accumulation and
softmax bookkeeping stay float32.
"""

from typing import Any, Optional, Tuple

import flax.linen as nn
import jax.numpy as jnp
from jax import lax

from chainermn_tpu import ops


class _TpDense(nn.Module):
    """Explicit-shape kernel/bias holder for the tensor-parallel path.

    The tp-local parameter TREE must mirror the unsharded oracle's
    module names (``block_0/qkv/kernel`` ...) so that the GLOBAL
    arrays -- local shapes times the ``model`` axis, reassembled by
    ``shard_map`` out_specs / :func:`tp_param_specs` -- are exactly
    the oracle's parameter tree: init the oracle once, place with the
    tp shardings, and the two models share ONE checkpoint format.
    ``nn.Dense``/``nn.DenseGeneral`` cannot declare the local shapes
    (they re-derive the kernel shape from the input and reject the
    shard), hence this holder."""

    kernel_shape: Tuple[int, ...]
    bias_shape: Optional[Tuple[int, ...]] = None

    @nn.compact
    def __call__(self):
        k = self.param('kernel', nn.initializers.lecun_normal(),
                       self.kernel_shape)
        b = (self.param('bias', nn.initializers.zeros,
                        self.bias_shape)
             if self.bias_shape is not None else None)
        return k, b


class TransformerBlock(nn.Module):
    d_model: int
    n_heads: int
    d_ff: int
    dtype: Any = jnp.bfloat16
    sequence_axis: Optional[str] = None
    dropout: float = 0.0
    sp_scheme: str = 'ring'  # 'ring' | 'ulysses' (see parallel.sequence)
    tp_axis: Optional[str] = None  # Megatron tensor parallelism

    def _tp_call(self, x):
        """Megatron-sharded block body: heads and MLP columns split
        over ``tp_axis``, one psum per half-block (attention, MLP)
        via the row-parallel exits.  Entries/exits use the
        ``tp_copy``/``tp_reduce`` conjugate pair so gradients taken
        INSIDE ``shard_map`` (the updaters' mode, check_vma=False)
        match the unsharded oracle -- see parallel/tensor.py."""
        from chainermn_tpu.parallel import tensor

        tp = lax.axis_size(self.tp_axis)
        if self.n_heads % tp or self.d_ff % tp:
            raise ValueError(
                'tp_axis=%r of size %d must divide n_heads=%d and '
                'd_ff=%d' % (self.tp_axis, tp, self.n_heads,
                             self.d_ff))
        d_head = self.d_model // self.n_heads
        heads_l = self.n_heads // tp
        d_ff_l = self.d_ff // tp

        ln1_g = self.param('ln1_scale', nn.initializers.ones,
                           (self.d_model,))
        ln1_b = self.param('ln1_bias', nn.initializers.zeros,
                           (self.d_model,))
        h = ops.layer_norm(x, ln1_g, ln1_b).astype(self.dtype)
        h = tensor.tp_copy(h, self.tp_axis)
        wqkv, bqkv = _TpDense((self.d_model, 3, heads_l, d_head),
                              (3, heads_l, d_head), name='qkv')()
        attn = tensor.qkv_attention(
            h, wqkv.astype(self.dtype), causal=True,
            bqkv=bqkv.astype(self.dtype))
        wo, bo = _TpDense((heads_l * d_head, self.d_model),
                          (self.d_model,), name='proj')()
        x = x + tensor.row_parallel_dense(
            attn, wo.astype(self.dtype), self.tp_axis,
            bo.astype(self.dtype), grad_conjugate=True)

        ln2_g = self.param('ln2_scale', nn.initializers.ones,
                           (self.d_model,))
        ln2_b = self.param('ln2_bias', nn.initializers.zeros,
                           (self.d_model,))
        h = ops.layer_norm(x, ln2_g, ln2_b).astype(self.dtype)
        h = tensor.tp_copy(h, self.tp_axis)
        w_in, b_in = _TpDense((self.d_model, d_ff_l), (d_ff_l,),
                              name='ff_in')()
        g = nn.gelu(tensor.column_parallel_dense(
            h, w_in.astype(self.dtype), b_in.astype(self.dtype)))
        w_out, b_out = _TpDense((d_ff_l, self.d_model),
                                (self.d_model,), name='ff_out')()
        return x + tensor.row_parallel_dense(
            g, w_out.astype(self.dtype), self.tp_axis,
            b_out.astype(self.dtype), grad_conjugate=True)

    @nn.compact
    def __call__(self, x, train=False):
        if self.tp_axis is not None:
            if self.sequence_axis is not None:
                raise ValueError('tp_axis and sequence_axis cannot '
                                 'both be set on one block')
            if train and self.dropout > 0:
                raise ValueError('tp_axis blocks run without dropout '
                                 '(per-rank rng divergence would '
                                 'silently break the head groups); '
                                 'build with dropout=0.0')
            return self._tp_call(x)
        d_head = self.d_model // self.n_heads
        ln1_g = self.param('ln1_scale', nn.initializers.ones,
                           (self.d_model,))
        ln1_b = self.param('ln1_bias', nn.initializers.zeros,
                           (self.d_model,))
        h = ops.layer_norm(x, ln1_g, ln1_b).astype(self.dtype)
        qkv = nn.DenseGeneral((3, self.n_heads, d_head), axis=-1,
                              dtype=self.dtype, name='qkv')(h)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if self.sequence_axis is not None:
            # sequence dim sharded over the mesh axis
            from chainermn_tpu.parallel import (ring_attention,
                                                ulysses_attention)
            if self.sp_scheme not in ('ring', 'ulysses'):
                raise ValueError(
                    "sp_scheme must be 'ring' or 'ulysses', got %r"
                    % (self.sp_scheme,))
            sp = (ulysses_attention if self.sp_scheme == 'ulysses'
                  else ring_attention)
            attn = sp(q, k, v, self.sequence_axis, causal=True)
        else:
            attn = ops.flash_attention(q, k, v, causal=True)
        attn = attn.reshape(attn.shape[:2] + (self.d_model,))
        out = nn.Dense(self.d_model, dtype=self.dtype, name='proj')(attn)
        if train and self.dropout > 0:
            out = nn.Dropout(self.dropout, deterministic=False)(out)
        x = x + out

        ln2_g = self.param('ln2_scale', nn.initializers.ones,
                           (self.d_model,))
        ln2_b = self.param('ln2_bias', nn.initializers.zeros,
                           (self.d_model,))
        h = ops.layer_norm(x, ln2_g, ln2_b).astype(self.dtype)
        h = nn.Dense(self.d_ff, dtype=self.dtype, name='ff_in')(h)
        h = nn.gelu(h)
        h = nn.Dense(self.d_model, dtype=self.dtype, name='ff_out')(h)
        if train and self.dropout > 0:
            h = nn.Dropout(self.dropout, deterministic=False)(h)
        return x + h


class _TpEmbed(nn.Module):
    """Vocab-row-sharded embedding table holder (tp-local shape,
    oracle tree name ``embed/embedding``)."""

    shape: Tuple[int, ...]

    @nn.compact
    def __call__(self):
        return self.param('embedding', nn.initializers.normal(0.02),
                          self.shape)


class TransformerLM(nn.Module):
    """Causal LM.  With ``sequence_axis`` set, call inside
    ``shard_map`` with the token dim sharded over that axis; position
    embeddings are offset by the local shard's global start.

    With ``tp_axis`` set (mutually exclusive with ``sequence_axis``),
    call inside ``shard_map`` over a mesh binding that axis (the
    :class:`chainermn_tpu.parallel.MeshPlan` ``model`` axis):
    attention heads and MLP columns/rows split Megatron-style on the
    axis with one psum per half-block, the embedding table is
    vocab-row-sharded (masked local lookup + psum) and the vocab
    projection is row-parallel over ``d_model``.  The parameter tree
    is EXACTLY the unsharded oracle's -- init the ``tp_axis=None``
    twin and place its params with :func:`tp_param_specs`; activations
    stay replicated over the axis, so the batch shards on ``data``
    only.  Numerically pinned against the oracle in
    ``tests/test_transformer.py`` / ``tests/test_meshplan.py``.
    """

    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 6
    d_ff: int = 2048
    max_len: int = 32768
    dtype: Any = jnp.bfloat16
    sequence_axis: Optional[str] = None
    dropout: float = 0.0
    sp_scheme: str = 'ring'  # 'ring' | 'ulysses' (see parallel.sequence)
    tp_axis: Optional[str] = None  # Megatron tensor parallelism

    def _tp_embed(self, tokens):
        """Vocab-row-sharded lookup: each rank owns rows
        ``[r*V/tp, (r+1)*V/tp)``; off-shard tokens contribute zeros
        and ONE psum (``tp_reduce`` -- identity backward, so the local
        table rows receive exactly their own scatter-add gradients)
        completes the lookup."""
        from chainermn_tpu.parallel import tensor

        tp = lax.axis_size(self.tp_axis)
        if self.vocab_size % tp or self.d_model % tp:
            raise ValueError(
                'tp_axis=%r of size %d must divide vocab_size=%d and '
                'd_model=%d' % (self.tp_axis, tp, self.vocab_size,
                                self.d_model))
        v_local = self.vocab_size // tp
        emb = _TpEmbed((v_local, self.d_model), name='embed')()
        local = tokens - lax.axis_index(self.tp_axis) * v_local
        in_shard = (local >= 0) & (local < v_local)
        rows = jnp.take(emb, jnp.clip(local, 0, v_local - 1), axis=0)
        x = jnp.where(in_shard[..., None], rows,
                      jnp.zeros((), rows.dtype)).astype(self.dtype)
        # exact in any dtype: per token exactly one rank is nonzero
        return tensor.tp_reduce(x, self.tp_axis)

    def _tp_head(self, x):
        """Row-parallel vocab projection: ``d_model`` sliced per rank,
        f32 contraction completed by one psum, bias added once after
        (same arithmetic as the oracle's f32 ``lm_head`` Dense up to
        the split-contraction summation order)."""
        from chainermn_tpu.parallel import tensor

        tp = lax.axis_size(self.tp_axis)
        d_local = self.d_model // tp
        kernel, bias = _TpDense((d_local, self.vocab_size),
                                (self.vocab_size,), name='lm_head')()
        xh = tensor.tp_copy(x.astype(self.dtype), self.tp_axis)
        x_local = lax.dynamic_slice_in_dim(
            xh, lax.axis_index(self.tp_axis) * d_local, d_local,
            axis=-1)
        return tensor.row_parallel_dense(
            x_local.astype(jnp.float32), kernel.astype(jnp.float32),
            self.tp_axis, bias, grad_conjugate=True)

    @nn.compact
    def __call__(self, tokens, train=False):
        """tokens (B, T_local) int32 -> logits (B, T_local, V) f32."""
        tp_mode = self.tp_axis is not None
        if tp_mode and self.sequence_axis is not None:
            raise ValueError('tp_axis and sequence_axis cannot both '
                             'be set (compose tp with data/pipeline '
                             'axes via MeshPlan instead)')
        b, t = tokens.shape
        if tp_mode:
            x = self._tp_embed(tokens)
        else:
            x = nn.Embed(self.vocab_size, self.d_model,
                         dtype=self.dtype, name='embed')(tokens)
        pos0 = 0
        if self.sequence_axis is not None:
            pos0 = lax.axis_index(self.sequence_axis) * t
        pos_table = self.param(
            'pos_embed', nn.initializers.normal(0.02),
            (self.max_len, self.d_model))
        pos = lax.dynamic_slice_in_dim(pos_table, pos0, t, 0)
        x = x + pos.astype(self.dtype)
        for i in range(self.n_layers):
            x = TransformerBlock(
                self.d_model, self.n_heads, self.d_ff, self.dtype,
                self.sequence_axis, self.dropout, self.sp_scheme,
                tp_axis=self.tp_axis,
                name=f'block_{i}')(x, train=train)
        gf = self.param('lnf_scale', nn.initializers.ones,
                        (self.d_model,))
        bf = self.param('lnf_bias', nn.initializers.zeros,
                        (self.d_model,))
        x = ops.layer_norm(x, gf, bf)
        if tp_mode:
            return self._tp_head(x)
        logits = nn.Dense(self.vocab_size, dtype=jnp.float32,
                          name='lm_head')(x.astype(self.dtype))
        return logits


def tp_oracle(model):
    """The unsharded twin of a ``tp_axis`` model: same config, same
    parameter tree (init THIS one to get params for either)."""
    return model.clone(tp_axis=None, name=None)


def tp_param_specs(params, axis='model'):
    """``PartitionSpec`` tree for a ``TransformerLM(tp_axis=axis)``
    parameter tree (which IS the unsharded oracle's tree): attention
    heads and MLP columns/rows on ``axis``, embedding rows on the
    vocab dim, ``lm_head`` rows on ``d_model``, everything else
    (layer norms, positional table, post-reduction biases)
    replicated.  Feed to
    :meth:`chainermn_tpu.parallel.MeshPlan.param_shardings` or a
    ``StandardUpdater(param_specs=...)``."""
    from jax.sharding import PartitionSpec as P

    def one(path, leaf):
        names = {str(getattr(k, 'key', k)) for k in path}
        nd = getattr(leaf, 'ndim', 0)
        if 'embedding' in names:
            return P(axis, None)
        if 'qkv' in names:
            return (P(None, None, axis, None) if nd == 4
                    else P(None, axis, None))
        if 'ff_in' in names:
            return P(None, axis) if nd == 2 else P(axis)
        if 'ff_out' in names or 'proj' in names \
                or 'lm_head' in names:
            # row-parallel kernels; their biases ride post-psum,
            # replicated
            return P(axis, None) if nd == 2 else P()
        return P()

    import jax
    return jax.tree_util.tree_map_with_path(one, params)


# ---------------------------------------------------------------------
# incremental decode: slot-addressed KV cache (ISSUE 11)
#
# Autoregressive serving never re-runs the prompt: the PREFILL pass
# computes the full causal forward once and banks every layer's K/V in
# a cache SLOT; each DECODE step then runs one token per live slot,
# appends its K/V at the slot's position, and attends the single query
# row against the cache (ops.flash_attention_decode -- one HBM pass,
# per-slot dynamic lengths).  The cache is a plain pytree of stacked
# per-layer arrays, so it threads through jit/AOT executables, is
# donatable (the serving engine updates it in place across calls), and
# shards over a MeshPlan 'model' axis on its HEAD dim exactly like the
# attention weights (kv_cache_specs).
#
# These are module-level functions doing the SAME arithmetic as
# TransformerLM.__call__ over the SAME parameter tree (the
# pipeline_parts idiom): the flax module stays the single source of
# the parameters, and the parity pins in tests/test_transformer.py
# hold the two paths together (f32 rtol 1e-5, bf16/int8-KV 5e-2).

def init_kv_cache(model, n_slots, max_len=None, dtype=None, tp=1,
                  int8_kv=False):
    """Zeroed slot-addressed KV cache for ``model``.

    Layout: ``{'k'|'v': (n_layers, n_slots, S, H_local, d_head)}``
    with ``S = max_len or model.max_len`` and ``H_local =
    n_heads / tp`` (pass the mesh's model-axis size as ``tp`` when the
    cache lives sharded inside ``shard_map``).  ``int8_kv=True`` adds
    ``'k_scale'``/``'v_scale'`` ``(n_layers, n_slots, S, H_local)``
    f32 trees and stores k/v as int8 (:func:`chainermn_tpu.precision.
    quantize_kv` at write time) -- half the decode-bound HBM bytes of
    bf16.  Slots are REUSED without zeroing: reads mask by the live
    length, so a previous occupant's stale rows are never attended.
    """
    if model.n_heads % tp:
        raise ValueError('tp=%d must divide n_heads=%d'
                         % (tp, model.n_heads))
    n_layers = model.n_layers
    h_local = model.n_heads // tp
    d_head = model.d_model // model.n_heads
    s = int(max_len or model.max_len)
    dtype = dtype or model.dtype
    shape = (n_layers, int(n_slots), s, h_local, d_head)
    if int8_kv:
        return {'k': jnp.zeros(shape, jnp.int8),
                'v': jnp.zeros(shape, jnp.int8),
                'k_scale': jnp.zeros(shape[:-1], jnp.float32),
                'v_scale': jnp.zeros(shape[:-1], jnp.float32)}
    return {'k': jnp.zeros(shape, dtype),
            'v': jnp.zeros(shape, dtype)}


def init_paged_kv_cache(model, n_pages, page_size, dtype=None, tp=1,
                        int8_kv=False):
    """Zeroed PAGED KV cache: a fixed pool of ``n_pages`` pages of
    ``page_size`` token positions each, shared by every sequence.

    Layout: ``{'k'|'v': (n_layers, n_pages, page_size, H_local,
    d_head)}`` (+ ``'k_scale'``/``'v_scale'`` ``(n_layers, n_pages,
    page_size, H_local)`` f32 under ``int8_kv``) -- the slot cache's
    layout with the ``(n_slots, S)`` slab axes re-cut into
    ``(n_pages, page_size)``, so :func:`kv_cache_specs` shards it
    unchanged (head axis over ``tp``).  Sequences address the pool
    through per-sequence page tables (:func:`decode_step_paged` /
    :func:`prefill_paged`); refcounting, prefix sharing and
    copy-on-write live host-side in
    :mod:`chainermn_tpu.serving.paged`.  By convention page 0 is the
    allocator's SCRATCH page: pad rows write there and no live table
    ever points at it, so garbage writes are structurally harmless.
    Pages are reused without zeroing -- reads mask by live length.
    """
    if model.n_heads % tp:
        raise ValueError('tp=%d must divide n_heads=%d'
                         % (tp, model.n_heads))
    h_local = model.n_heads // tp
    d_head = model.d_model // model.n_heads
    dtype = dtype or model.dtype
    shape = (model.n_layers, int(n_pages), int(page_size), h_local,
             d_head)
    if int8_kv:
        return {'k': jnp.zeros(shape, jnp.int8),
                'v': jnp.zeros(shape, jnp.int8),
                'k_scale': jnp.zeros(shape[:-1], jnp.float32),
                'v_scale': jnp.zeros(shape[:-1], jnp.float32)}
    return {'k': jnp.zeros(shape, dtype),
            'v': jnp.zeros(shape, dtype)}


def kv_cache_specs(cache, axis='model'):
    """``PartitionSpec`` tree for a cache under tensor parallelism:
    the head dim shards with the attention heads, everything else
    replicated (slots are NOT data-sharded -- continuous batching
    refills them independently of the mesh)."""
    import jax
    from jax.sharding import PartitionSpec as P

    def one(leaf):
        if leaf.ndim == 5:                      # k / v
            return P(None, None, None, axis, None)
        return P(None, None, None, axis)        # scales
    return jax.tree_util.tree_map(one, cache)


def _cache_int8(cache):
    return 'k_scale' in cache


def _dense(x, p, dtype):
    """``nn.Dense`` twin: promote input/kernel/bias to ``dtype``."""
    return (x.astype(dtype) @ p['kernel'].astype(dtype)
            + p['bias'].astype(dtype))


def _qkv_proj(h, bp, dtype):
    """``nn.DenseGeneral((3, H, d_head), axis=-1)`` twin over (..., d)
    activations: returns (..., 3, H, d_head)."""
    w = bp['qkv']['kernel'].astype(dtype)
    b = bp['qkv']['bias'].astype(dtype)
    return jnp.einsum('...d,dchf->...chf', h.astype(dtype), w) + b


def _write_kv(cache, layer, k_new, v_new, slots, positions):
    """Append one token's K/V per row: ``k_new``/``v_new``
    (N, H_local, d_head) written at ``(layer, slots[i],
    positions[i])``.  ``slots=None`` means row i IS slot i."""
    from chainermn_tpu.precision import quantize_kv
    n = k_new.shape[0]
    idx_slots = (jnp.arange(n) if slots is None
                 else slots.astype(jnp.int32))
    out = dict(cache)
    if _cache_int8(cache):
        for name, val in (('k', k_new), ('v', v_new)):
            q, scale = quantize_kv(val)
            out[name] = cache[name].at[
                layer, idx_slots, positions].set(q)
            out[name + '_scale'] = cache[name + '_scale'].at[
                layer, idx_slots, positions].set(scale)
        return out
    dt = cache['k'].dtype
    out['k'] = cache['k'].at[layer, idx_slots, positions].set(
        k_new.astype(dt))
    out['v'] = cache['v'].at[layer, idx_slots, positions].set(
        v_new.astype(dt))
    return out


def _attend_cache(cache, layer, q, slots, lengths):
    """One decode-attention read: row i's query against its slot's
    cache prefix.  With ``slots=None`` (full-slot decode bucket) the
    cache rows are consumed IN PLACE -- one HBM read, the jaxpr pin in
    tests/test_transformer.py; a compacted bucket gathers its rows
    first (one extra pass -- the cost of running a smaller executable,
    documented in docs/serving.md)."""
    from chainermn_tpu import ops

    def rows(name):
        full = cache[name][layer]
        return full if slots is None else jnp.take(
            full, slots.astype(jnp.int32), axis=0)

    if _cache_int8(cache):
        return ops.flash_attention_decode(
            q, rows('k'), rows('v'), lengths,
            k_scale=rows('k_scale'), v_scale=rows('v_scale'))
    return ops.flash_attention_decode(q, rows('k'), rows('v'),
                                      lengths)


def _tp_embed_rows(params, tokens, vocab_size, d_model, dtype, axis):
    """Forward-only twin of ``TransformerLM._tp_embed`` for a flat
    (N,) token vector: masked local lookup + one psum."""
    tp = lax.axis_size(axis)
    v_local = vocab_size // tp
    emb = params['embed']['embedding']
    local = tokens - lax.axis_index(axis) * v_local
    in_shard = (local >= 0) & (local < v_local)
    rows = jnp.take(emb, jnp.clip(local, 0, v_local - 1), axis=0)
    x = jnp.where(in_shard[..., None], rows,
                  jnp.zeros((), rows.dtype)).astype(dtype)
    return lax.psum(x, axis)


def _head_logits(model, params, x):
    """The lm head on (..., d_model) activations -- non-tp
    ``nn.Dense(vocab, dtype=f32)`` twin or the row-parallel tp form
    (one psum), matching ``TransformerLM._tp_head``."""
    from chainermn_tpu.parallel import tensor

    if model.tp_axis is None:
        return _dense(x.astype(model.dtype), params['lm_head'],
                      jnp.float32)
    tp = lax.axis_size(model.tp_axis)
    d_local = model.d_model // tp
    xh = x.astype(model.dtype)
    x_local = lax.dynamic_slice_in_dim(
        xh, lax.axis_index(model.tp_axis) * d_local, d_local, axis=-1)
    return tensor.row_parallel_dense(
        x_local.astype(jnp.float32),
        params['lm_head']['kernel'].astype(jnp.float32),
        model.tp_axis, params['lm_head']['bias'])


def _decode_core(model, params, cache, tokens, positions, write,
                 attend):
    """Shared single-token decode body: embed + per-layer
    (norm -> qkv -> ``write`` one token's K/V -> ``attend`` the cache
    -> proj residual -> MLP residual) -> final norm -> head.  The
    ``write(cache, layer, k_new, v_new)`` / ``attend(cache, layer,
    q)`` closures are the ONLY difference between the slot-addressed
    (:func:`decode_step`) and paged (:func:`decode_step_paged`)
    caches -- paging is a storage indirection, never a model change.
    """
    from chainermn_tpu import ops
    from chainermn_tpu.parallel import tensor

    dtype = model.dtype
    tp_mode = model.tp_axis is not None
    if tp_mode:
        x = _tp_embed_rows(params, tokens, model.vocab_size,
                           model.d_model, dtype, model.tp_axis)
    else:
        x = jnp.take(params['embed']['embedding'], tokens,
                     axis=0).astype(dtype)
    x = x + jnp.take(params['pos_embed'], positions,
                     axis=0).astype(dtype)
    for i in range(model.n_layers):
        bp = params['block_%d' % i]
        h = ops.layer_norm(x, bp['ln1_scale'],
                           bp['ln1_bias']).astype(dtype)
        qkv = _qkv_proj(h, bp, dtype)               # (N, 3, H, d_head)
        q, k_new, v_new = qkv[:, 0], qkv[:, 1], qkv[:, 2]
        cache = write(cache, i, k_new, v_new)
        attn = attend(cache, i, q)
        attn = attn.reshape(attn.shape[0], -1)
        if tp_mode:
            out = tensor.row_parallel_dense(
                attn, bp['proj']['kernel'].astype(dtype),
                model.tp_axis, bp['proj']['bias'].astype(dtype))
        else:
            out = _dense(attn, bp['proj'], dtype)
        x = x + out
        h = ops.layer_norm(x, bp['ln2_scale'],
                           bp['ln2_bias']).astype(dtype)
        if tp_mode:
            g = nn.gelu(tensor.column_parallel_dense(
                h, bp['ff_in']['kernel'].astype(dtype),
                bp['ff_in']['bias'].astype(dtype)))
            x = x + tensor.row_parallel_dense(
                g, bp['ff_out']['kernel'].astype(dtype),
                model.tp_axis, bp['ff_out']['bias'].astype(dtype))
        else:
            x = x + _dense(nn.gelu(_dense(h, bp['ff_in'], dtype)),
                           bp['ff_out'], dtype)
    x = ops.layer_norm(x, params['lnf_scale'], params['lnf_bias'])
    return _head_logits(model, params, x), cache


def decode_step(model, params, cache, tokens, positions, slots=None):
    """One incremental decode step: ``tokens`` (N,) int32 -- the last
    sampled token per row -- at ``positions`` (N,) int32 (0-based;
    this token's K/V lands there and attention covers
    ``positions + 1`` cache entries).  ``slots`` (N,) int32 maps rows
    to cache slots for a compacted active-slot bucket; ``None`` (the
    full bucket) requires ``N == n_slots`` and reads the cache in
    place.  Returns ``(logits (N, vocab) f32, new_cache)``.

    Works under ``tp_axis`` inside ``shard_map`` exactly like
    ``__call__`` (heads and cache sharded over the axis, one psum per
    half-block); parity vs the full-sequence causal forward is pinned
    in tests/test_transformer.py, including across slot refills.
    """
    if slots is None and tokens.shape[0] != cache['k'].shape[1]:
        raise ValueError(
            'full-bucket decode needs one row per cache slot '
            '(%d rows vs %d slots); pass slots= for a compacted '
            'bucket' % (tokens.shape[0], cache['k'].shape[1]))
    lengths = positions.astype(jnp.int32) + 1

    def write(cache, layer, k_new, v_new):
        return _write_kv(cache, layer, k_new, v_new, slots, positions)

    def attend(cache, layer, q):
        return _attend_cache(cache, layer, q, slots, lengths)

    return _decode_core(model, params, cache, tokens, positions,
                        write, attend)


def decode_step_paged(model, params, cache, tokens, positions,
                      page_tables):
    """One incremental decode step against a PAGED cache
    (:func:`init_paged_kv_cache`): ``tokens``/``positions`` (N,) int32
    as in :func:`decode_step`, plus ``page_tables`` (N, n_max) int32
    mapping each row's token position ``p`` to pool page
    ``page_tables[i, p // page_size]``, offset ``p % page_size``.

    The table entry covering ``positions[i]`` must already be
    allocated (the serving scheduler appends a page BEFORE the tick
    that crosses a page boundary); entries beyond the live prefix are
    never read, so idle rows can point at the allocator's scratch
    page.  Arithmetic is identical to :func:`decode_step` -- parity
    (including under ``tp_axis`` and int8 KV) is pinned in
    tests/test_transformer.py.
    """
    from chainermn_tpu import ops
    from chainermn_tpu.precision import quantize_kv

    ps = cache['k'].shape[2]
    positions = positions.astype(jnp.int32)
    lengths = positions + 1
    n = tokens.shape[0]
    pages = page_tables[jnp.arange(n), positions // ps]
    offsets = positions % ps

    def write(cache, layer, k_new, v_new):
        out = dict(cache)
        if _cache_int8(cache):
            for name, val in (('k', k_new), ('v', v_new)):
                qv, scale = quantize_kv(val)
                out[name] = cache[name].at[
                    layer, pages, offsets].set(qv)
                out[name + '_scale'] = cache[name + '_scale'].at[
                    layer, pages, offsets].set(scale)
            return out
        dt = cache['k'].dtype
        out['k'] = cache['k'].at[layer, pages, offsets].set(
            k_new.astype(dt))
        out['v'] = cache['v'].at[layer, pages, offsets].set(
            v_new.astype(dt))
        return out

    def attend(cache, layer, q):
        if _cache_int8(cache):
            return ops.flash_attention_decode_paged(
                q, cache['k'][layer], cache['v'][layer], page_tables,
                lengths, k_scale=cache['k_scale'][layer],
                v_scale=cache['v_scale'][layer])
        return ops.flash_attention_decode_paged(
            q, cache['k'][layer], cache['v'][layer], page_tables,
            lengths)

    return _decode_core(model, params, cache, tokens, positions,
                        write, attend)


def prefill(model, params, cache, tokens, length, slot):
    """Prefill one prompt into cache slot ``slot``: ``tokens``
    (1, T) int32 padded to a prompt bucket, ``length`` scalar int32
    (valid prefix; positions beyond it are written but never attended
    -- decode lengths start at ``length``).  Runs the full causal
    forward ONCE (the compute-bound regime: whole-prompt matmuls
    through the fused flash kernel), banks every layer's K/V at
    ``cache[:, slot, :T]``, and returns ``(logits (vocab,) f32 at
    position length-1, new_cache)`` -- the distribution the first
    generated token is sampled from."""
    from chainermn_tpu import ops
    from chainermn_tpu.parallel import tensor
    from chainermn_tpu.precision import quantize_kv

    dtype = model.dtype
    tp_mode = model.tp_axis is not None
    b, t = tokens.shape
    if b != 1:
        raise ValueError('prefill takes one prompt per call, got '
                         'batch %d (prompt-length bucketing would be '
                         'meaningless across a batch)' % b)
    if tp_mode:
        x = _tp_embed_rows(params, tokens, model.vocab_size,
                           model.d_model, dtype, model.tp_axis)
    else:
        x = jnp.take(params['embed']['embedding'], tokens,
                     axis=0).astype(dtype)
    x = x + params['pos_embed'][:t].astype(dtype)
    slot = jnp.asarray(slot, jnp.int32)
    int8_kv = _cache_int8(cache)
    cache = dict(cache)
    for i in range(model.n_layers):
        bp = params['block_%d' % i]
        h = ops.layer_norm(x, bp['ln1_scale'],
                           bp['ln1_bias']).astype(dtype)
        qkv = _qkv_proj(h, bp, dtype)           # (1, T, 3, H, d_head)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        attn = ops.flash_attention(q, k, v, causal=True)
        attn = attn.reshape(1, t, -1)
        for name, val in (('k', k[0]), ('v', v[0])):
            if int8_kv:
                qv, scale = quantize_kv(val)
                cache[name] = lax.dynamic_update_slice(
                    cache[name], qv[None, None],
                    (i, slot, 0, 0, 0))
                cache[name + '_scale'] = lax.dynamic_update_slice(
                    cache[name + '_scale'], scale[None, None],
                    (i, slot, 0, 0))
            else:
                cache[name] = lax.dynamic_update_slice(
                    cache[name],
                    val.astype(cache[name].dtype)[None, None],
                    (i, slot, 0, 0, 0))
        if tp_mode:
            out = tensor.row_parallel_dense(
                attn, bp['proj']['kernel'].astype(dtype),
                model.tp_axis, bp['proj']['bias'].astype(dtype))
        else:
            out = _dense(attn, bp['proj'], dtype)
        x = x + out
        h = ops.layer_norm(x, bp['ln2_scale'],
                           bp['ln2_bias']).astype(dtype)
        if tp_mode:
            g = nn.gelu(tensor.column_parallel_dense(
                h, bp['ff_in']['kernel'].astype(dtype),
                bp['ff_in']['bias'].astype(dtype)))
            x = x + tensor.row_parallel_dense(
                g, bp['ff_out']['kernel'].astype(dtype),
                model.tp_axis, bp['ff_out']['bias'].astype(dtype))
        else:
            x = x + _dense(nn.gelu(_dense(h, bp['ff_in'], dtype)),
                           bp['ff_out'], dtype)
    # the head only needs the LAST VALID position's activation --
    # a (1, d) slice instead of a (T, vocab) logits block
    x_last = lax.dynamic_slice_in_dim(
        x[0], jnp.asarray(length, jnp.int32) - 1, 1, axis=0)
    x_last = ops.layer_norm(x_last, params['lnf_scale'],
                            params['lnf_bias'])
    return _head_logits(model, params, x_last)[0], cache


def prefill_paged(model, params, cache, tokens, length, page_table,
                  pos0):
    """Prefill ONE CHUNK of a prompt into a paged cache
    (:func:`init_paged_kv_cache`): ``tokens`` (1, C) int32 -- the
    chunk, padded to a fixed width; ``length`` scalar int32 (valid
    chunk prefix); ``page_table`` (n_max,) int32 -- the sequence's
    pages; ``pos0`` scalar int32 -- the running absolute position
    (tokens already banked by earlier chunks).  Returns
    ``(logits (vocab,) f32 at chunk position length-1, new_cache)``.

    This is the chunked-prefill (SARATHI-style) building block: the
    scheduler interleaves these fixed-cost calls with decode ticks so
    a long prompt never freezes inter-token latency.  Each chunk's
    K/V is scattered into its pages (pad rows land on the scratch
    page 0); attention is :func:`~chainermn_tpu.ops.
    flash_attention_chunk` -- causal within the chunk plus the banked
    context masked at ``pos0`` -- so a whole-prompt call
    (``pos0 == 0``) computes bitwise the same causal forward as the
    slot :func:`prefill`.  int8 KV: the chunk half attends the fresh
    float K/V exactly like the slot prefill; only the banked context
    is dequantized.  Table entries covering ``[pos0, pos0+length)``
    must be allocated; nothing before ``pos0`` is written (shared
    prefix pages stay read-only -- the copy-on-write contract in
    ``docs/serving.md``).
    """
    from chainermn_tpu import ops
    from chainermn_tpu.parallel import tensor
    from chainermn_tpu.precision import quantize_kv

    dtype = model.dtype
    tp_mode = model.tp_axis is not None
    b, c = tokens.shape
    if b != 1:
        raise ValueError('prefill_paged takes one prompt chunk per '
                         'call, got batch %d' % b)
    n_max = page_table.shape[0]
    ps = cache['k'].shape[2]
    pos0 = jnp.asarray(pos0, jnp.int32)
    length = jnp.asarray(length, jnp.int32)
    if tp_mode:
        x = _tp_embed_rows(params, tokens, model.vocab_size,
                           model.d_model, dtype, model.tp_axis)
    else:
        x = jnp.take(params['embed']['embedding'], tokens,
                     axis=0).astype(dtype)
    x = x + lax.dynamic_slice_in_dim(
        params['pos_embed'], pos0, c, axis=0).astype(dtype)

    # chunk-row -> (page, offset): pad rows (t >= length) go to the
    # scratch page so the scatter never touches a live table entry
    t = jnp.arange(c, dtype=jnp.int32)
    p_abs = pos0 + t
    page_idx = jnp.clip(p_abs // ps, 0, n_max - 1)
    pages = jnp.where(t < length, page_table[page_idx].astype(
        jnp.int32), 0)
    offsets = p_abs % ps
    ctx_len = pos0[None]                               # (B=1,)
    int8_kv = _cache_int8(cache)
    cache = dict(cache)
    for i in range(model.n_layers):
        bp = params['block_%d' % i]
        h = ops.layer_norm(x, bp['ln1_scale'],
                           bp['ln1_bias']).astype(dtype)
        qkv = _qkv_proj(h, bp, dtype)           # (1, C, 3, H, d_head)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        for name, val in (('k', k[0]), ('v', v[0])):
            if int8_kv:
                qv, scale = quantize_kv(val)
                cache[name] = cache[name].at[
                    i, pages, offsets].set(qv)
                cache[name + '_scale'] = cache[name + '_scale'].at[
                    i, pages, offsets].set(scale)
            else:
                cache[name] = cache[name].at[i, pages, offsets].set(
                    val.astype(cache[name].dtype))

        def gather(name):
            g = jnp.take(cache[name][i], page_table.astype(jnp.int32),
                         axis=0)
            return g.reshape((1, n_max * ps) + g.shape[2:])

        if int8_kv:
            attn = ops.flash_attention_chunk(
                q, k, v, gather('k'), gather('v'), ctx_len,
                k_scale=gather('k_scale'), v_scale=gather('v_scale'))
        else:
            attn = ops.flash_attention_chunk(q, k, v, gather('k'),
                                             gather('v'), ctx_len)
        attn = attn.reshape(1, c, -1)
        if tp_mode:
            out = tensor.row_parallel_dense(
                attn, bp['proj']['kernel'].astype(dtype),
                model.tp_axis, bp['proj']['bias'].astype(dtype))
        else:
            out = _dense(attn, bp['proj'], dtype)
        x = x + out
        h = ops.layer_norm(x, bp['ln2_scale'],
                           bp['ln2_bias']).astype(dtype)
        if tp_mode:
            g = nn.gelu(tensor.column_parallel_dense(
                h, bp['ff_in']['kernel'].astype(dtype),
                bp['ff_in']['bias'].astype(dtype)))
            x = x + tensor.row_parallel_dense(
                g, bp['ff_out']['kernel'].astype(dtype),
                model.tp_axis, bp['ff_out']['bias'].astype(dtype))
        else:
            x = x + _dense(nn.gelu(_dense(h, bp['ff_in'], dtype)),
                           bp['ff_out'], dtype)
    x_last = lax.dynamic_slice_in_dim(x[0], length - 1, 1, axis=0)
    x_last = ops.layer_norm(x_last, params['lnf_scale'],
                            params['lnf_bias'])
    return _head_logits(model, params, x_last)[0], cache


def _verify_core(model, params, cache, tokens, positions, write,
                 attend):
    """Shared k-token verify body (the windowed twin of
    :func:`_decode_core`): ``tokens`` (N, K) int32 -- row i's window is
    K consecutive tokens starting at absolute position
    ``positions[i]`` -- embed + per-layer (norm -> qkv -> ``write`` the
    window's K/V -> ``attend`` window-causal against the banked prefix
    -> proj residual -> MLP residual) -> final norm -> head at ALL K
    positions.  ``write(cache, layer, k_new, v_new)`` /
    ``attend(cache, layer, q, k_new, v_new)`` close over the cache
    addressing exactly as in :func:`_decode_core`; ``attend``
    additionally receives the fresh window K/V because the chunk
    kernel takes them as operands rather than re-reading the cache.
    Returns ``(logits (N, K, vocab) f32, new_cache)``."""
    from chainermn_tpu import ops
    from chainermn_tpu.parallel import tensor

    dtype = model.dtype
    tp_mode = model.tp_axis is not None
    n, kk = tokens.shape
    window = (positions.astype(jnp.int32)[:, None]
              + jnp.arange(kk, dtype=jnp.int32)[None, :])   # (N, K)
    if tp_mode:
        x = _tp_embed_rows(params, tokens, model.vocab_size,
                           model.d_model, dtype, model.tp_axis)
    else:
        x = jnp.take(params['embed']['embedding'], tokens,
                     axis=0).astype(dtype)
    x = x + jnp.take(params['pos_embed'], window,
                     axis=0).astype(dtype)
    for i in range(model.n_layers):
        bp = params['block_%d' % i]
        h = ops.layer_norm(x, bp['ln1_scale'],
                           bp['ln1_bias']).astype(dtype)
        qkv = _qkv_proj(h, bp, dtype)           # (N, K, 3, H, d_head)
        q, k_new, v_new = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        cache = write(cache, i, k_new, v_new)
        attn = attend(cache, i, q, k_new, v_new)
        attn = attn.reshape(n, kk, -1)
        if tp_mode:
            out = tensor.row_parallel_dense(
                attn, bp['proj']['kernel'].astype(dtype),
                model.tp_axis, bp['proj']['bias'].astype(dtype))
        else:
            out = _dense(attn, bp['proj'], dtype)
        x = x + out
        h = ops.layer_norm(x, bp['ln2_scale'],
                           bp['ln2_bias']).astype(dtype)
        if tp_mode:
            g = nn.gelu(tensor.column_parallel_dense(
                h, bp['ff_in']['kernel'].astype(dtype),
                bp['ff_in']['bias'].astype(dtype)))
            x = x + tensor.row_parallel_dense(
                g, bp['ff_out']['kernel'].astype(dtype),
                model.tp_axis, bp['ff_out']['bias'].astype(dtype))
        else:
            x = x + _dense(nn.gelu(_dense(h, bp['ff_in'], dtype)),
                           bp['ff_out'], dtype)
    x = ops.layer_norm(x, params['lnf_scale'], params['lnf_bias'])
    return _head_logits(model, params, x), cache


def _roundtrip_kv(cache, k_new, v_new):
    """What the oracle's NEXT decode step would read back for the
    window's freshly written K/V: the cache-dtype cast (float caches)
    or the int8 quantize->dequantize roundtrip.  Feeding these -- not
    the raw float values -- as the chunk kernel's fresh half is what
    makes speculative verify argmax-equal to the sequential decode
    loop in every KV mode."""
    from chainermn_tpu.precision import dequantize_kv, quantize_kv
    if _cache_int8(cache):
        return (dequantize_kv(*quantize_kv(k_new)),
                dequantize_kv(*quantize_kv(v_new)))
    dt = cache['k'].dtype
    return k_new.astype(dt), v_new.astype(dt)


def spec_verify(model, params, cache, tokens, positions, slots=None):
    """Speculative-decoding verify pass: score K consecutive proposed
    tokens per row in ONE executable.  ``tokens`` (N, K) int32 -- row
    i's window ``[last committed token, draft_1, ..., draft_{K-1}]``
    written at absolute positions ``positions[i] + [0, K)``;
    ``positions`` (N,) int32; ``slots`` as in :func:`decode_step`
    (``None`` = full bucket, one row per slot).  Returns ``(logits
    (N, K, vocab) f32, new_cache)`` where ``logits[i, j]`` is the
    target's next-token distribution GIVEN the window prefix through
    ``tokens[i, j]`` -- row j's argmax verifies draft j+1 and row
    K-1's argmax is the bonus/correction token.

    Column 0 computes exactly what :func:`decode_step` would for
    ``tokens[:, 0]``, and inductively every accepted column matches
    the sequential decode loop -- attention is
    :func:`~chainermn_tpu.ops.flash_attention_chunk` (the chunked-
    prefill kernel: window-causal fresh half + banked context masked
    at ``positions``), with the fresh half fed the cache-roundtripped
    K/V so int8-KV verify attends the same dequantized values the
    oracle reads back.  Window entries at/beyond the cache depth are
    dropped by the scatter and never committed by the scheduler, so a
    window overhanging ``max_len`` is harmless.  Rollback after the
    accept-prefix decision is a position rewind: rejected columns'
    K/V (and int8 scales) stay as masked garbage, exactly like a
    reused slot."""

    if slots is None and tokens.shape[0] != cache['k'].shape[1]:
        raise ValueError(
            'full-bucket verify needs one row per cache slot '
            '(%d rows vs %d slots); pass slots= for a compacted '
            'bucket' % (tokens.shape[0], cache['k'].shape[1]))
    from chainermn_tpu import ops

    n, kk = tokens.shape
    positions = positions.astype(jnp.int32)
    window = positions[:, None] + jnp.arange(kk, dtype=jnp.int32)
    idx_slots = (jnp.arange(n) if slots is None
                 else slots.astype(jnp.int32))

    def write(cache, layer, k_new, v_new):
        from chainermn_tpu.precision import quantize_kv
        out = dict(cache)
        rows_idx = idx_slots[:, None]
        if _cache_int8(cache):
            for name, val in (('k', k_new), ('v', v_new)):
                qv, scale = quantize_kv(val)
                out[name] = cache[name].at[
                    layer, rows_idx, window].set(qv)
                out[name + '_scale'] = cache[name + '_scale'].at[
                    layer, rows_idx, window].set(scale)
            return out
        dt = cache['k'].dtype
        out['k'] = cache['k'].at[layer, rows_idx, window].set(
            k_new.astype(dt))
        out['v'] = cache['v'].at[layer, rows_idx, window].set(
            v_new.astype(dt))
        return out

    def attend(cache, layer, q, k_new, v_new):
        def rows(name):
            full = cache[name][layer]
            return full if slots is None else jnp.take(
                full, idx_slots, axis=0)
        k_att, v_att = _roundtrip_kv(cache, k_new, v_new)
        if _cache_int8(cache):
            return ops.flash_attention_chunk(
                q, k_att, v_att, rows('k'), rows('v'), positions,
                k_scale=rows('k_scale'), v_scale=rows('v_scale'))
        return ops.flash_attention_chunk(
            q, k_att, v_att, rows('k'), rows('v'), positions)

    return _verify_core(model, params, cache, tokens, positions,
                        write, attend)


def spec_verify_paged(model, params, cache, tokens, positions,
                      page_tables):
    """:func:`spec_verify` against a PAGED cache: ``page_tables``
    (N, n_max) int32 as in :func:`decode_step_paged`; table entries
    covering ``[positions[i], positions[i] + K)`` must be allocated
    by the scheduler (the speculative page-growth step), and window
    rows past the pool's addressable range are routed to the scratch
    page like chunked-prefill pad rows.  Context is gathered through
    the page table (:func:`prefill_paged`'s read pattern) and masked
    at ``positions``; arithmetic is otherwise identical to the slab
    verify -- paging stays a storage indirection."""
    from chainermn_tpu import ops
    from chainermn_tpu.precision import quantize_kv

    n, kk = tokens.shape
    n_max = page_tables.shape[1]
    ps = cache['k'].shape[2]
    positions = positions.astype(jnp.int32)
    window = positions[:, None] + jnp.arange(kk, dtype=jnp.int32)
    page_idx = jnp.clip(window // ps, 0, n_max - 1)
    pages = jnp.where(
        window < n_max * ps,
        jnp.take_along_axis(page_tables.astype(jnp.int32), page_idx,
                            axis=1), 0)                      # (N, K)
    offsets = window % ps

    def write(cache, layer, k_new, v_new):
        out = dict(cache)
        if _cache_int8(cache):
            for name, val in (('k', k_new), ('v', v_new)):
                qv, scale = quantize_kv(val)
                out[name] = cache[name].at[
                    layer, pages, offsets].set(qv)
                out[name + '_scale'] = cache[name + '_scale'].at[
                    layer, pages, offsets].set(scale)
            return out
        dt = cache['k'].dtype
        out['k'] = cache['k'].at[layer, pages, offsets].set(
            k_new.astype(dt))
        out['v'] = cache['v'].at[layer, pages, offsets].set(
            v_new.astype(dt))
        return out

    def attend(cache, layer, q, k_new, v_new):
        def gather(name):
            g = jnp.take(cache[name][layer],
                         page_tables.astype(jnp.int32), axis=0)
            return g.reshape((n, n_max * ps) + g.shape[3:])
        k_att, v_att = _roundtrip_kv(cache, k_new, v_new)
        if _cache_int8(cache):
            return ops.flash_attention_chunk(
                q, k_att, v_att, gather('k'), gather('v'), positions,
                k_scale=gather('k_scale'), v_scale=gather('v_scale'))
        return ops.flash_attention_chunk(
            q, k_att, v_att, gather('k'), gather('v'), positions)

    return _verify_core(model, params, cache, tokens, positions,
                        write, attend)


def pipeline_parts(model, params, n_stages, pad_id=-1, tp_axis=None,
                   local_loss=False):
    """Split a ``TransformerLM`` parameter tree into
    :class:`~chainermn_tpu.training.PipelineUpdater` /
    :class:`~chainermn_tpu.training.MeshPipelineUpdater` pieces.

    Returns ``(stage_fn, prologue, loss_on_last, params_stacked,
    extra)``: the block stack becomes the stage-sharded body
    (``n_layers`` must divide into ``n_stages`` even groups) while
    embedding/positional table/final norm/head become the replicated
    ``extra`` tree.  The pipelined composition computes EXACTLY
    ``model.apply`` + :func:`lm_loss` with the same parameters and the
    same fused kernels -- a model trained unpipelined can be resumed
    pipelined and vice versa
    (``tests/test_pipeline_training.py::test_transformer_pipeline_parts``).

    ``model`` must have ``sequence_axis=None`` (pipeline shards the
    batch, not the sequence), ``tp_axis=None`` (the params tree IS
    the unsharded oracle's) and is used with ``train=False``
    semantics (no dropout).

    ``tp_axis`` (e.g. a 3-D plan's ``model`` axis) makes the STAGE
    BODY tensor-parallel: each stage's blocks run the Megatron
    ``_tp_call`` path (heads / MLP columns+rows split over the axis,
    conjugate custom-vjp psums -- exact under 1F1B's per-device
    backward), while the embedding/head ``extra`` ends stay
    replicated and collective-free.  Shard the stacked stage tree
    with :func:`pipeline_stage_specs`.

    ``local_loss=True`` returns a collective-free ``loss_on_last``
    (the 1F1B requirement: its vjp is taken per device): a LOCAL
    masked mean, exact vs :func:`lm_loss` whenever every data shard
    carries the same valid-token count -- always true at
    ``pad_id=-1`` (no padding); unevenly padded shards need the
    default GLOBAL form, whose data-axis psums require the gpipe
    schedule.
    """
    if model.sequence_axis is not None:
        raise ValueError('pipeline_parts shards the batch dimension; '
                         'build the model with sequence_axis=None')
    if model.tp_axis is not None:
        raise ValueError('pipeline_parts expects the unsharded block '
                         'body; build the model with tp_axis=None '
                         '(stage-internal tensor parallelism is the '
                         'tp_axis= argument HERE, over the oracle '
                         'parameter tree)')
    if model.dropout:
        raise ValueError('pipeline_parts runs the blocks without '
                         'dropout rngs; build the model with '
                         'dropout=0.0 (training would otherwise '
                         'silently drop the regularization the '
                         'unpipelined run applies)')
    if model.n_layers % n_stages:
        raise ValueError('%d layers do not split into %d stages'
                         % (model.n_layers, n_stages))
    import jax
    from chainermn_tpu.parallel.pipeline import stack_stage_params

    n_per = model.n_layers // n_stages
    block = TransformerBlock(model.d_model, model.n_heads, model.d_ff,
                             model.dtype, tp_axis=tp_axis)
    layer_trees = [params['block_%d' % i]
                   for i in range(model.n_layers)]
    per_stage = [stack_stage_params(layer_trees[s * n_per:
                                                (s + 1) * n_per])
                 for s in range(n_stages)]
    params_stacked = stack_stage_params(per_stage)
    extra = {'embedding': params['embed']['embedding'],
             'pos_embed': params['pos_embed'],
             'lnf_scale': params['lnf_scale'],
             'lnf_bias': params['lnf_bias'],
             'lm_head': params['lm_head']}

    def stage_fn(p_stage, x):
        for j in range(n_per):
            bp = jax.tree_util.tree_map(lambda a: a[j], p_stage)
            x = block.apply({'params': bp}, x)
        return x

    def prologue(e, tokens):
        # nn.Embed(dtype=model.dtype) lookup + position slice, as in
        # TransformerLM.__call__ with pos0 = 0
        x = jnp.take(e['embedding'], tokens, axis=0).astype(model.dtype)
        pos = e['pos_embed'][:tokens.shape[1]]
        return x + pos.astype(model.dtype)

    def masked_ce(e, outs, y_micro):
        h = ops.layer_norm(outs, e['lnf_scale'],
                           e['lnf_bias']).astype(model.dtype)
        logits = (h.astype(jnp.float32)
                  @ e['lm_head']['kernel'].astype(jnp.float32)
                  + e['lm_head']['bias'])
        v = logits.shape[-1]
        flat = logits.reshape(-1, v)
        yy = y_micro.reshape(-1).astype(jnp.int32)
        ce = ops.softmax_cross_entropy(flat, yy)
        mask = (yy != pad_id).astype(jnp.float32)
        return jnp.sum(ce * mask), jnp.sum(mask)

    def loss_on_last(e, outs, y_micro):
        from chainermn_tpu.training.pipeline_updater import AXIS_DATA
        total, n = masked_ce(e, outs, y_micro)
        # GLOBAL masked mean: sums psum'd over the data axis BEFORE
        # dividing, so unevenly padded shards weight each token
        # equally -- exactly lm_loss's reduction (a per-shard mean
        # pmean'd by the updater would weight a lightly-padded
        # shard's tokens less)
        total = lax.psum(total, AXIS_DATA)
        n = jnp.maximum(lax.psum(n, AXIS_DATA), 1.0)
        loss = total / n
        return loss, {'perp': jnp.exp(jnp.minimum(loss, 20.0))}

    def local_loss_on_last(e, outs, y_micro):
        # LOCAL masked mean (collective-free; see docstring): the
        # updater's last-stage data-mean completes the global mean
        # when shards hold equal valid-token counts
        total, n = masked_ce(e, outs, y_micro)
        loss = total / jnp.maximum(n, 1.0)
        return loss, {'perp': jnp.exp(jnp.minimum(loss, 20.0))}

    return (stage_fn, prologue,
            local_loss_on_last if local_loss else loss_on_last,
            params_stacked, extra)


def pipeline_stage_specs(params_stacked, pipe_axis='pipe',
                         tp_axis=None):
    """``PartitionSpec`` tree for a :func:`pipeline_parts` stacked
    stage tree: every leaf leads with ``pipe_axis`` (each stage's
    weights live on its pipe coordinate -- the
    :meth:`chainermn_tpu.parallel.MeshPlan.stage_specs` placement),
    and with ``tp_axis`` set the Megatron dims shard exactly as
    :func:`tp_param_specs` does for the unstacked tree -- attention
    heads and MLP columns on the axis, row-parallel kernels on their
    input dim, layer norms and post-psum biases replicated (per
    stage).  Leaves carry TWO leading stacking dims
    ``(n_stages, layers_per_stage)`` ahead of the block dims."""
    from jax.sharding import PartitionSpec as P

    def one(path, leaf):
        names = {str(getattr(k, 'key', k)) for k in path}
        nd = getattr(leaf, 'ndim', 0)
        if tp_axis is None:
            return P(pipe_axis)
        if 'qkv' in names:
            # kernel (S, L, d, 3, H, d_head) / bias (S, L, 3, H, d_head)
            return (P(pipe_axis, None, None, None, tp_axis, None)
                    if nd == 6
                    else P(pipe_axis, None, None, tp_axis, None))
        if 'ff_in' in names:
            # kernel (S, L, d, ff) / bias (S, L, ff): column-parallel
            return (P(pipe_axis, None, None, tp_axis) if nd == 4
                    else P(pipe_axis, None, tp_axis))
        if ('ff_out' in names or 'proj' in names) and nd == 4:
            # row-parallel kernels (S, L, in, d): input dim sharded
            return P(pipe_axis, None, tp_axis, None)
        # layer norms, post-psum biases: stage-stacked, tp-replicated
        return P(pipe_axis)

    import jax
    return jax.tree_util.tree_map_with_path(one, params_stacked)


def lm_loss_sum(apply_fn, pad_id=-1):
    """Next-token loss in sum/count form: returns
    ``((loss_sum, token_count), aux)``.

    For sequence-parallel training with a REAL ``pad_id``: feed this
    to ``mapped_global_loss(..., token_weighted=True)`` so the global
    loss is ``psum(sum)/psum(count)`` -- exact under uneven padding
    across shards, where pmean-of-local-means is Jensen-weighted and
    silently wrong (ADVICE r3).  :func:`lm_loss` is the mean form of
    this same computation."""

    def loss_fn(params, tokens, targets):
        logits = apply_fn(params, tokens)
        b, t, v = logits.shape
        ce = ops.softmax_cross_entropy(
            logits.reshape(b * t, v), targets.reshape(b * t).astype(
                jnp.int32))
        mask = (targets.reshape(b * t) != pad_id).astype(jnp.float32)
        return (jnp.sum(ce * mask), jnp.sum(mask)), {}

    return loss_fn


def lm_loss(apply_fn, pad_id=-1):
    """Next-token loss over (tokens, targets); fused cross-entropy.

    ``pad_id`` target positions are masked out (use -1 when every
    position is real)."""
    sum_fn = lm_loss_sum(apply_fn, pad_id)

    def loss_fn(params, tokens, targets):
        (total, n), _ = sum_fn(params, tokens, targets)
        loss = total / jnp.maximum(n, 1.0)
        return loss, {'perp': jnp.exp(jnp.minimum(loss, 20.0))}

    return loss_fn
