"""Classifier wrapper: softmax cross-entropy + accuracy.

The reference models report ``loss``/``accuracy`` through
``chainer.report`` (e.g. ``models_v2/resnet50.py:106-108``,
``L.Classifier`` at ``train_mnist.py:54``).  Ours is functional: wrap a
model apply function into ``loss_fn(params, x, y) -> (loss, metrics)``
consumable by the updater/evaluator.
"""

import jax.numpy as jnp
import optax


def classifier_loss(apply_fn, label_smoothing=0.0):
    """``loss_fn(params, x, y) -> (loss, {'accuracy': ...})``."""

    def loss_fn(params, x, y, train=True):
        logits = apply_fn(params, x)
        if isinstance(logits, tuple):  # models returning (logits, aux)
            logits = logits[0]
        if label_smoothing:
            n = logits.shape[-1]
            onehot = optax.smooth_labels(
                jnp.eye(n, dtype=logits.dtype)[y], label_smoothing)
            loss = optax.softmax_cross_entropy(logits, onehot).mean()
        else:
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()
        acc = jnp.mean((jnp.argmax(logits, -1) == y).astype(jnp.float32))
        return loss, {'accuracy': acc}

    return loss_fn


class StatefulClassifier:
    """Classifier for models with BatchNorm state / dropout RNG.

    Produces the updater's extended protocol:
    ``loss(params, model_state, rng, x, y) ->
    (loss, (metrics, new_model_state))`` and an eval function reading
    running statistics.  Auxiliary-head outputs (GoogLeNet returns
    ``(logits, (aux1, aux2))`` in train mode) are weighted 0.3 like the
    reference (``models_v2/googlenet.py`` loss composition).
    """

    def __init__(self, model, aux_weight=0.3):
        self.model = model
        self.aux_weight = aux_weight

    def _ce(self, logits, y):
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()

    def loss(self, params, model_state, rng, x, y):
        variables = {'params': params, **model_state}
        out, mutated = self.model.apply(
            variables, x, train=True, mutable=list(model_state.keys()),
            rngs={'dropout': rng})
        if isinstance(out, tuple):
            logits, auxes = out
            loss = self._ce(logits, y)
            for aux in auxes:
                if aux is not None:
                    loss = loss + self.aux_weight * self._ce(aux, y)
        else:
            logits = out
            loss = self._ce(logits, y)
        acc = jnp.mean((jnp.argmax(logits, -1) == y).astype(jnp.float32))
        return loss, ({'accuracy': acc}, mutated)

    def eval_metrics(self, params_and_state, x, y):
        """Per-example metrics; ``params_and_state`` is the full
        variables dict (pass ``{'params': p, **state}``)."""
        out = self.model.apply(params_and_state, x, train=False)
        logits = out[0] if isinstance(out, tuple) else out
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, y)
        acc = (jnp.argmax(logits, -1) == y).astype(jnp.float32)
        return {'loss': loss, 'accuracy': acc}


class Classifier:
    """Object flavor for symmetry with ``L.Classifier``; callable as a
    loss function."""

    def __init__(self, apply_fn, label_smoothing=0.0):
        self.apply_fn = apply_fn
        self._loss = classifier_loss(apply_fn, label_smoothing)

    def __call__(self, params, x, y):
        return self._loss(params, x, y)

    def eval_metrics(self, params, x, y):
        """Per-example metrics for the masked evaluator: returns arrays
        of shape (batch,)."""
        logits = self.apply_fn(params, x)
        if isinstance(logits, tuple):
            logits = logits[0]
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, y)
        acc = (jnp.argmax(logits, -1) == y).astype(jnp.float32)
        return {'loss': loss, 'accuracy': acc}
