"""AlexNet (reference ``examples/imagenet/models_v2/alex.py``,
insize 227).  NHWC, bfloat16 compute."""

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class Alex(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    insize: int = 227

    @nn.compact
    def __call__(self, x, train=True):
        if x.shape[1] < 68 or x.shape[2] < 68:
            # VALID 11x11/4 conv + three 3x3/2 pools: below ~68px the
            # final pool window exceeds its input and the flatten feeds
            # an empty tensor -- fail at trace time instead
            raise ValueError(
                'Alex needs input >= 68x68 (canonical %d), got %r'
                % (self.insize, x.shape[1:3]))
        x = x.astype(self.dtype)
        x = nn.relu(nn.Conv(96, (11, 11), strides=(4, 4), padding='VALID',
                            dtype=self.dtype)(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.relu(nn.Conv(256, (5, 5), padding=2, dtype=self.dtype)(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.relu(nn.Conv(384, (3, 3), padding=1, dtype=self.dtype)(x))
        x = nn.relu(nn.Conv(384, (3, 3), padding=1, dtype=self.dtype)(x))
        x = nn.relu(nn.Conv(256, (3, 3), padding=1, dtype=self.dtype)(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)
