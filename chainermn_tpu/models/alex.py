"""AlexNet (reference ``examples/imagenet/models_v2/alex.py``,
insize 227).  NHWC, bfloat16 compute."""

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class Alex(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    insize: int = 227

    @nn.compact
    def __call__(self, x, train=True):
        x = x.astype(self.dtype)
        x = nn.relu(nn.Conv(96, (11, 11), strides=(4, 4), padding='VALID',
                            dtype=self.dtype)(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.relu(nn.Conv(256, (5, 5), padding=2, dtype=self.dtype)(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.relu(nn.Conv(384, (3, 3), padding=1, dtype=self.dtype)(x))
        x = nn.relu(nn.Conv(384, (3, 3), padding=1, dtype=self.dtype)(x))
        x = nn.relu(nn.Conv(256, (3, 3), padding=1, dtype=self.dtype)(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)
