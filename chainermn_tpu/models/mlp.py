"""Three-layer MLP (the reference MNIST model,
``examples/mnist/train_mnist.py:20-31``: 784 -> units -> units -> 10
with ReLU)."""

from functools import partial
from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    """``dtype`` is the COMPUTE dtype (policy-aware construction:
    pass ``policy.compute_dtype``); parameters are always initialized
    in float32 so the updater's master weights start wide regardless
    of the compute precision.  ``None`` computes at input/param
    promotion (full precision for f32 inputs)."""
    n_units: int = 100
    n_out: int = 10
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        dense = partial(nn.Dense, dtype=self.dtype,
                        param_dtype=jnp.float32)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(dense(self.n_units)(x))
        x = nn.relu(dense(self.n_units)(x))
        return dense(self.n_out)(x)
