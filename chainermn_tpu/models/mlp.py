"""Three-layer MLP (the reference MNIST model,
``examples/mnist/train_mnist.py:20-31``: 784 -> units -> units -> 10
with ReLU)."""

import flax.linen as nn


class MLP(nn.Module):
    n_units: int = 100
    n_out: int = 10

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.n_units)(x))
        x = nn.relu(nn.Dense(self.n_units)(x))
        return nn.Dense(self.n_out)(x)
