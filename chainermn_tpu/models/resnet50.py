"""ResNet-50 (the flagship benchmark workload).

Capability parity with reference ``examples/imagenet/models_v2/resnet50.py``
(insize 224, bottleneck ``Block``s of [3,4,6,3], reporting
loss/accuracy).  TPU-native choices: NHWC layout (TPU conv native),
bfloat16 compute with float32 BatchNorm statistics and parameters,
stride on the 3x3 (the v1.5 variant -- better accuracy at equal FLOPs
on MXU), and an init/apply surface that composes with the sharded
updater.
"""

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


class Bottleneck(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck (reference ``BottleNeckA``/``B``)."""
    features: int
    stride: int = 1
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train=True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                       param_dtype=jnp.float32)
        residual = x
        y = conv(self.features, (1, 1))(x)
        y = nn.relu(norm()(y))
        y = conv(self.features, (3, 3), strides=(self.stride,
                                                 self.stride))(y)
        y = nn.relu(norm()(y))
        y = conv(self.features * 4, (1, 1))(y)
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.features * 4, (1, 1),
                            strides=(self.stride, self.stride),
                            name='proj')(residual)
            residual = norm(name='proj_bn')(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    width: int = 64
    dtype: Any = jnp.bfloat16
    insize: int = 224  # reference resnet50.py insize=224

    @nn.compact
    def __call__(self, x, train=True):
        x = x.astype(self.dtype)
        x = nn.Conv(self.width, (7, 7), strides=(2, 2), use_bias=False,
                    dtype=self.dtype, name='conv_init')(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5, dtype=self.dtype,
                         param_dtype=jnp.float32, name='bn_init')(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding='SAME')
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                stride = 2 if i > 0 and j == 0 else 1
                x = Bottleneck(self.width * 2 ** i, stride=stride,
                               dtype=self.dtype)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=jnp.float32, name='fc')(x)
        return x.astype(jnp.float32)


def ResNet50(num_classes=1000, dtype=jnp.bfloat16):
    return ResNet(stage_sizes=[3, 4, 6, 3], num_classes=num_classes,
                  dtype=dtype)


def ResNet101(num_classes=1000, dtype=jnp.bfloat16):
    return ResNet(stage_sizes=[3, 4, 23, 3], num_classes=num_classes,
                  dtype=dtype)


def ResNet152(num_classes=1000, dtype=jnp.bfloat16):
    return ResNet(stage_sizes=[3, 8, 36, 3], num_classes=num_classes,
                  dtype=dtype)
