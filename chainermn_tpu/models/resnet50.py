"""ResNet-50 (the flagship benchmark workload).

Capability parity with reference ``examples/imagenet/models_v2/resnet50.py``
(insize 224, bottleneck ``Block``s of [3,4,6,3], reporting
loss/accuracy).  TPU-native choices: NHWC layout (TPU conv native),
bfloat16 compute with float32 BatchNorm statistics and parameters,
stride on the 3x3 (the v1.5 variant -- better accuracy at equal FLOPs
on MXU), and an init/apply surface that composes with the sharded
updater.
"""

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

from chainermn_tpu.models._norm import norm_act


class Bottleneck(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck (reference ``BottleNeckA``/``B``).

    ``fused_norm=True`` routes every BN+relu (and the final
    BN+add+relu) through the fused ``batch_norm_act`` kernel via
    :func:`chainermn_tpu.models._norm.norm_act`; module names match
    flax's auto-numbering, so variables are interchangeable between
    the two paths."""
    features: int
    stride: int = 1
    dtype: Any = jnp.bfloat16
    fused_norm: bool = False

    @nn.compact
    def __call__(self, x, train=True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(norm_act, train=train, fused=self.fused_norm,
                       dtype=self.dtype)
        residual = x
        y = conv(self.features, (1, 1))(x)
        y = norm(y, name='BatchNorm_0')
        y = conv(self.features, (3, 3), strides=(self.stride,
                                                 self.stride))(y)
        y = norm(y, name='BatchNorm_1')
        y = conv(self.features * 4, (1, 1))(y)
        if residual.shape != y.shape:
            residual = conv(self.features * 4, (1, 1),
                            strides=(self.stride, self.stride),
                            name='proj')(residual)
            residual = norm(residual, name='proj_bn', relu=False)
        # BN (zero-init scale) + shortcut add + relu: ONE fused pass
        return norm(y, name='BatchNorm_2', residual=residual,
                    scale_init=nn.initializers.zeros)


class ResNet(nn.Module):
    """``stem='space_to_depth'`` replaces the 7x7/stride-2 stem conv
    with a mathematically equivalent 4x4/stride-1 conv over the 2x2
    space-to-depth rearrangement of the input (the MLPerf TPU ResNet
    trick): 3-channel 7x7 convs waste the MXU's 128-deep reduction
    axis and the strided first conv is layout-hostile; the s2d form
    feeds the MXU 12 input channels at stride 1.  Exact equivalence
    (a weight mapping turns one stem into the other bit-for-bit in
    f32) is pinned in ``tests/test_models.py``."""

    stage_sizes: Sequence[int]
    num_classes: int = 1000
    width: int = 64
    dtype: Any = jnp.bfloat16
    insize: int = 224  # reference resnet50.py insize=224
    stem: str = 'standard'
    # fused BN+relu(+add) Pallas path (chainermn_tpu/ops/
    # batch_norm_act.py); False keeps the flax nn.BatchNorm oracle
    fused_norm: bool = False

    @nn.compact
    def __call__(self, x, train=True):
        x = x.astype(self.dtype)
        if self.stem == 'space_to_depth':
            b, h, w, c = x.shape
            if h % 2 or w % 2:
                raise ValueError('space_to_depth stem needs even '
                                 'spatial dims, got %s' % ((h, w),))
            x = x.reshape(b, h // 2, 2, w // 2, 2, c)
            x = x.transpose(0, 1, 3, 2, 4, 5)
            x = x.reshape(b, h // 2, w // 2, 4 * c)
            # pad (1,2): the 4 stride-1 taps cover source q in
            # [p-1, p+2], matching the 7x7/s2 conv's SAME pad (2,3)
            x = jnp.pad(x, ((0, 0), (1, 2), (1, 2), (0, 0)))
            x = nn.Conv(self.width, (4, 4), strides=(1, 1),
                        padding='VALID', use_bias=False,
                        dtype=self.dtype, name='conv_init_s2d')(x)
        elif self.stem == 'standard':
            x = nn.Conv(self.width, (7, 7), strides=(2, 2),
                        use_bias=False, dtype=self.dtype,
                        name='conv_init')(x)
        else:
            raise ValueError("stem must be 'standard' or "
                             "'space_to_depth', got %r" % (self.stem,))
        x = norm_act(x, train=train, fused=self.fused_norm,
                     dtype=self.dtype, name='bn_init')
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding='SAME')
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                stride = 2 if i > 0 and j == 0 else 1
                x = Bottleneck(self.width * 2 ** i, stride=stride,
                               dtype=self.dtype,
                               fused_norm=self.fused_norm)(
                                   x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=jnp.float32, name='fc')(x)
        return x.astype(jnp.float32)


def ResNet50(num_classes=1000, dtype=jnp.bfloat16, stem='standard',
             fused_norm=False):
    return ResNet(stage_sizes=[3, 4, 6, 3], num_classes=num_classes,
                  dtype=dtype, stem=stem, fused_norm=fused_norm)


def convert_stem_variables(variables):
    """Convert a standard-stem ResNet variable tree to the
    space-to-depth-stem layout (losslessly: :func:`s2d_stem_kernel`
    maps the one differing kernel; everything else is shared).  The
    equivalence tests pin that the converted model computes the same
    function."""
    import jax

    params = dict(jax.device_get(variables['params']))
    w7 = params.pop('conv_init')['kernel']
    params['conv_init_s2d'] = {
        'kernel': jnp.asarray(s2d_stem_kernel(w7))}
    return {'params': params,
            **{k: v for k, v in variables.items() if k != 'params'}}


def s2d_stem_kernel(w7):
    """Map a standard (7, 7, C, F) stem kernel to the equivalent
    (4, 4, 4C, F) space-to-depth kernel: tap ``t = 2a + phi`` of the
    strided 7x7 window lands on s2d tap ``a``, phase channel ``phi``
    (taps with t == 7 do not exist and stay zero).  With this mapping
    the two stems compute the SAME function -- the equivalence test
    pins it, and pretrained standard-stem checkpoints convert
    losslessly."""
    import numpy as np

    w7 = np.asarray(w7)
    c, f = w7.shape[2], w7.shape[3]
    w4 = np.zeros((4, 4, 4 * c, f), w7.dtype)
    for ah in range(4):
        for ph in range(2):
            th = 2 * ah + ph
            if th > 6:
                continue
            for aw in range(4):
                for pw in range(2):
                    tw = 2 * aw + pw
                    if tw > 6:
                        continue
                    ch = (ph * 2 + pw) * c
                    w4[ah, aw, ch:ch + c, :] = w7[th, tw]
    return w4


def ResNet101(num_classes=1000, dtype=jnp.bfloat16, fused_norm=False):
    return ResNet(stage_sizes=[3, 4, 23, 3], num_classes=num_classes,
                  dtype=dtype, fused_norm=fused_norm)


def ResNet152(num_classes=1000, dtype=jnp.bfloat16, fused_norm=False):
    return ResNet(stage_sizes=[3, 8, 36, 3], num_classes=num_classes,
                  dtype=dtype, fused_norm=fused_norm)
