"""Shared norm+activation layer for the conv zoo.

Every zoo model routes its ``BatchNorm -> relu (-> +residual)``
interludes (and, for the norm-free models, its bare activations)
through :func:`norm_act`, so ONE switch -- the models'
``fused_norm=`` flag -- selects between:

- the stock ``flax.linen.BatchNorm`` + ``jax.nn.relu`` composition
  (the numerics ORACLE: this path is what the fused kernel is pinned
  against, and what ``CHAINERMN_TPU_PALLAS=0`` A/B runs measure); and
- :class:`NormAct`, which drives the fused
  :func:`chainermn_tpu.ops.batch_norm_act` Pallas kernel -- one HBM
  pass for normalize + affine + residual add + relu, f32 statistics
  over bf16 activations, and a backward that recomputes the
  normalized value instead of materializing it (PERF.md's
  "conv+BN+relu Pallas fusion" knob).

Both paths register IDENTICAL variable trees (``scale``/``bias``
params, ``batch_stats`` ``mean``/``var``, under the same module
name), so checkpoints and init are interchangeable: init once,
apply under either flag.
"""

from typing import Any, Callable

import flax.linen as nn
import jax.numpy as jnp

from chainermn_tpu.ops.batch_norm_act import (
    batch_norm_act, batch_norm_act_inference)


class NormAct(nn.Module):
    """Fused-kernel twin of ``nn.BatchNorm`` (+ relu + residual add).

    Same variable layout as ``flax.linen.BatchNorm`` (``scale`` /
    ``bias`` params in ``param_dtype``, f32 ``batch_stats``
    ``mean`` / ``var``, same ``momentum`` running-average update), so
    a module named like the BatchNorm it replaces is checkpoint- and
    init-compatible with it.
    """

    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Any = None
    param_dtype: Any = jnp.float32
    relu: bool = True
    scale_init: Callable = nn.initializers.ones
    bias_init: Callable = nn.initializers.zeros

    @nn.compact
    def __call__(self, x, residual=None):
        features = x.shape[-1]
        scale = self.param('scale', self.scale_init, (features,),
                           self.param_dtype)
        bias = self.param('bias', self.bias_init, (features,),
                          self.param_dtype)
        ra_mean = self.variable(
            'batch_stats', 'mean',
            lambda s: jnp.zeros(s, jnp.float32), (features,))
        ra_var = self.variable(
            'batch_stats', 'var',
            lambda s: jnp.ones(s, jnp.float32), (features,))
        if self.use_running_average:
            return batch_norm_act_inference(
                x, scale, bias, ra_mean.value, ra_var.value,
                eps=self.epsilon, residual=residual, relu=self.relu)
        out, mean, var = batch_norm_act(
            x, scale, bias, eps=self.epsilon, residual=residual,
            relu=self.relu)
        if not self.is_initializing():
            m = self.momentum
            ra_mean.value = m * ra_mean.value + (1.0 - m) * mean
            ra_var.value = m * ra_var.value + (1.0 - m) * var
        return out


def norm_act(x, *, train, fused, dtype, name, residual=None,
             relu=True, use_norm=True, momentum=0.9, epsilon=1e-5,
             scale_init=nn.initializers.ones):
    """The zoo models' one norm+activation entry point.

    Must be called from inside a parent module's ``@nn.compact``
    ``__call__``.  ``name`` is REQUIRED for normed layers so the
    fused and unfused paths register the same module name (pass the
    name flax auto-numbering would have chosen, e.g.
    ``'BatchNorm_0'``, to keep existing checkpoints loadable).

    ``use_norm=False`` (VGG/NIN: activation-only models) skips the
    norm entirely -- the residual add and relu still run here so the
    call sites stay uniform; ``fused`` is a no-op without a norm
    (XLA already fuses a bare add+relu).
    """
    if not use_norm:
        y = x if residual is None else x + residual
        return nn.relu(y) if relu else y
    if fused:
        return NormAct(use_running_average=not train,
                       momentum=momentum, epsilon=epsilon,
                       dtype=dtype, param_dtype=jnp.float32,
                       relu=relu, scale_init=scale_init,
                       name=name)(x, residual)
    y = nn.BatchNorm(use_running_average=not train, momentum=momentum,
                     epsilon=epsilon, dtype=dtype,
                     param_dtype=jnp.float32, scale_init=scale_init,
                     name=name)(x)
    if residual is not None:
        y = y + residual
    return nn.relu(y) if relu else y
