"""VGG-16 (BASELINE config 3: the tensor-fusion stress workload --
~138M parameters in a handful of huge tensors).

No BatchNorm in the canonical VGG-16: conv activations still route
through the zoo's shared :func:`chainermn_tpu.models._norm.norm_act`
helper (``use_norm=False``) so the ``fused_norm`` constructor flag is
uniform across the conv zoo -- here it is accepted and a no-op (XLA
already fuses a bare relu into the conv)."""

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

from chainermn_tpu.models._norm import norm_act

_VGG16 = (2, 2, 3, 3, 3)
_WIDTHS = (64, 128, 256, 512, 512)


class VGG(nn.Module):
    stage_sizes: Sequence[int] = _VGG16
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    insize: int = 224
    fused_norm: bool = False  # accepted for zoo API parity; no norm

    @nn.compact
    def __call__(self, x, train=True):
        x = x.astype(self.dtype)
        for n, width in zip(self.stage_sizes, _WIDTHS):
            for _ in range(n):
                x = norm_act(nn.Conv(width, (3, 3), padding=1,
                                     dtype=self.dtype)(x),
                             train=train, fused=self.fused_norm,
                             dtype=self.dtype, name=None,
                             use_norm=False)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


def VGG16(num_classes=1000, dtype=jnp.bfloat16, fused_norm=False):
    return VGG(num_classes=num_classes, dtype=dtype,
               fused_norm=fused_norm)
