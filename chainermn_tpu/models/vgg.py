"""VGG-16 (BASELINE config 3: the tensor-fusion stress workload --
~138M parameters in a handful of huge tensors)."""

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

_VGG16 = (2, 2, 3, 3, 3)
_WIDTHS = (64, 128, 256, 512, 512)


class VGG(nn.Module):
    stage_sizes: Sequence[int] = _VGG16
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    insize: int = 224

    @nn.compact
    def __call__(self, x, train=True):
        x = x.astype(self.dtype)
        for n, width in zip(self.stage_sizes, _WIDTHS):
            for _ in range(n):
                x = nn.relu(nn.Conv(width, (3, 3), padding=1,
                                    dtype=self.dtype)(x))
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


def VGG16(num_classes=1000, dtype=jnp.bfloat16):
    return VGG(num_classes=num_classes, dtype=dtype)
