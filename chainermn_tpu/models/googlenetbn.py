"""GoogLeNet-BN / BN-Inception (reference
``examples/imagenet/models_v2/googlenetbn.py``, BASELINE config 5:
multi-branch gradients stressing node-aware reduction).  Inception
branches use 3x3 factorization + BatchNorm as in the reference's
``InceptionBN``.

Every conv->BN->relu triple routes through
:func:`chainermn_tpu.models._norm.norm_act`; ``fused_norm=True``
selects the fused ``batch_norm_act`` Pallas pass (explicit
``BatchNorm_N`` module names reproduce flax's auto-numbering, so both
paths share one variable tree)."""

from functools import partial
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from chainermn_tpu.models._norm import norm_act


class InceptionBN(nn.Module):
    """BN-Inception module: 1x1 / 3x3 / double-3x3 / pool-proj, each
    conv followed by BatchNorm (reference InceptionBN)."""
    n1: int
    n3r: int
    n3: int
    d3r: int
    d3: int
    proj: int
    pool: str = 'avg'  # 'avg' | 'max'
    stride: int = 1
    dtype: Any = jnp.bfloat16
    fused_norm: bool = False

    @nn.compact
    def __call__(self, x, train=True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        # explicit names replay flax's auto-numbering (norm creation
        # order == cbr call order), keeping fused/unfused trees equal
        counter = iter(range(16))

        def cbr(y, feats, kernel, stride=1, pad='SAME'):
            y = conv(feats, kernel, strides=(stride, stride),
                     padding=pad)(y)
            return norm_act(y, train=train, fused=self.fused_norm,
                            dtype=self.dtype,
                            name='BatchNorm_%d' % next(counter))

        s = self.stride
        branches = []
        if self.n1:
            branches.append(cbr(x, self.n1, (1, 1)))
        b3 = cbr(x, self.n3r, (1, 1))
        branches.append(cbr(b3, self.n3, (3, 3), stride=s))
        bd = cbr(x, self.d3r, (1, 1))
        bd = cbr(bd, self.d3, (3, 3))
        branches.append(cbr(bd, self.d3, (3, 3), stride=s))
        pool_fn = nn.avg_pool if self.pool == 'avg' else nn.max_pool
        bp = pool_fn(x, (3, 3), strides=(s, s), padding='SAME')
        if self.proj:
            bp = cbr(bp, self.proj, (1, 1))
        branches.append(bp)
        return jnp.concatenate(branches, axis=-1)


class GoogLeNetBN(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    insize: int = 224
    fused_norm: bool = False

    @nn.compact
    def __call__(self, x, train=True):
        d = self.dtype
        conv = partial(nn.Conv, use_bias=False, dtype=d)
        na = partial(norm_act, train=train, fused=self.fused_norm,
                     dtype=d)
        inception = partial(InceptionBN, dtype=d,
                            fused_norm=self.fused_norm)
        x = x.astype(d)
        x = na(conv(64, (7, 7), strides=(2, 2), padding=3)(x),
               name='BatchNorm_0')
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding='SAME')
        x = na(conv(192, (3, 3), padding=1)(x), name='BatchNorm_1')
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding='SAME')
        x = inception(64, 64, 64, 64, 96, 32)(x, train)
        x = inception(64, 64, 96, 64, 96, 64)(x, train)
        x = inception(0, 128, 160, 64, 96, 0, pool='max', stride=2)(
            x, train)
        x = inception(224, 64, 96, 96, 128, 128)(x, train)
        x = inception(192, 96, 128, 96, 128, 128)(x, train)
        x = inception(160, 128, 160, 128, 160, 128)(x, train)
        x = inception(96, 128, 192, 160, 192, 128)(x, train)
        x = inception(0, 128, 192, 192, 256, 0, pool='max', stride=2)(
            x, train)
        x = inception(352, 192, 320, 160, 224, 128)(x, train)
        x = inception(352, 192, 320, 192, 224, 128, pool='max')(
            x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)
