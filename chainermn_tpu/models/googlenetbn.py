"""GoogLeNet-BN / BN-Inception (reference
``examples/imagenet/models_v2/googlenetbn.py``, BASELINE config 5:
multi-branch gradients stressing node-aware reduction).  Inception
branches use 3x3 factorization + BatchNorm as in the reference's
``InceptionBN``."""

from functools import partial
from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class InceptionBN(nn.Module):
    """BN-Inception module: 1x1 / 3x3 / double-3x3 / pool-proj, each
    conv followed by BatchNorm (reference InceptionBN)."""
    n1: int
    n3r: int
    n3: int
    d3r: int
    d3: int
    proj: int
    pool: str = 'avg'  # 'avg' | 'max'
    stride: int = 1
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train=True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                       param_dtype=jnp.float32)

        def cbr(y, feats, kernel, stride=1, pad='SAME'):
            y = conv(feats, kernel, strides=(stride, stride),
                     padding=pad)(y)
            return nn.relu(norm()(y))

        s = self.stride
        branches = []
        if self.n1:
            branches.append(cbr(x, self.n1, (1, 1)))
        b3 = cbr(x, self.n3r, (1, 1))
        branches.append(cbr(b3, self.n3, (3, 3), stride=s))
        bd = cbr(x, self.d3r, (1, 1))
        bd = cbr(bd, self.d3, (3, 3))
        branches.append(cbr(bd, self.d3, (3, 3), stride=s))
        pool_fn = nn.avg_pool if self.pool == 'avg' else nn.max_pool
        bp = pool_fn(x, (3, 3), strides=(s, s), padding='SAME')
        if self.proj:
            bp = cbr(bp, self.proj, (1, 1))
        branches.append(bp)
        return jnp.concatenate(branches, axis=-1)


class GoogLeNetBN(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    insize: int = 224

    @nn.compact
    def __call__(self, x, train=True):
        d = self.dtype
        conv = partial(nn.Conv, use_bias=False, dtype=d)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=d,
                       param_dtype=jnp.float32)
        x = x.astype(d)
        x = nn.relu(norm()(conv(64, (7, 7), strides=(2, 2),
                                padding=3)(x)))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding='SAME')
        x = nn.relu(norm()(conv(192, (3, 3), padding=1)(x)))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding='SAME')
        x = InceptionBN(64, 64, 64, 64, 96, 32, dtype=d)(x, train)
        x = InceptionBN(64, 64, 96, 64, 96, 64, dtype=d)(x, train)
        x = InceptionBN(0, 128, 160, 64, 96, 0, pool='max', stride=2,
                        dtype=d)(x, train)
        x = InceptionBN(224, 64, 96, 96, 128, 128, dtype=d)(x, train)
        x = InceptionBN(192, 96, 128, 96, 128, 128, dtype=d)(x, train)
        x = InceptionBN(160, 128, 160, 128, 160, 128, dtype=d)(x, train)
        x = InceptionBN(96, 128, 192, 160, 192, 128, dtype=d)(x, train)
        x = InceptionBN(0, 128, 192, 192, 256, 0, pool='max', stride=2,
                        dtype=d)(x, train)
        x = InceptionBN(352, 192, 320, 160, 224, 128, dtype=d)(x, train)
        x = InceptionBN(352, 192, 320, 192, 224, 128, pool='max',
                        dtype=d)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)
