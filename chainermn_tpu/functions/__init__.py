from chainermn_tpu.functions.point_to_point_communication import (  # noqa
    send, recv)
from chainermn_tpu.functions.pseudo_connect import pseudo_connect  # noqa
