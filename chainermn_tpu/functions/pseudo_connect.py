"""Graph-splicing no-op.

Rebuild of ``chainermn/functions/pseudo_connect.py``.  The reference
needs ``PseudoConnect`` because Chainer's eager backward only walks
connected graphs: it forwards actual variables unchanged while carrying
a "delegate variable" whose gradient is zero (``pseudo_connect.py:6-24``),
forcing cross-process send/recv pairs to be visited in order.

Under JAX tracing every dependency is explicit, so the operational
content reduces to "make ``actual`` depend on ``delegate`` without
changing its value".  We keep it as a real primitive-level identity
(zero-weighted add) so schedules that rely on ordering edges -- e.g.
forcing a collective to complete before a stage runs -- can still
express them, exactly the role the reference assigns it.
"""

import jax
import jax.numpy as jnp


def pseudo_connect(delegate_variable, *actual_variables):
    """Tie ``actual_variables`` to ``delegate_variable``'s completion.

    Gradient semantics match the reference: actuals get passthrough
    gradients, the delegate gets zeros (``pseudo_connect.py:14-24``).
    """
    if delegate_variable is None:
        return (actual_variables[0] if len(actual_variables) == 1
                else actual_variables)
    anchor = jnp.zeros((), dtype=jnp.float32)
    for leaf in jax.tree_util.tree_leaves(delegate_variable):
        anchor = anchor + jax.lax.stop_gradient(
            jnp.asarray(leaf, jnp.float32).ravel()[:1].sum()) * 0.0
    out = tuple(x + anchor.astype(x.dtype) for x in actual_variables)
    return out[0] if len(out) == 1 else out
