"""Differentiable point-to-point communication.

Rebuild of ``chainermn/functions/point_to_point_communication.py``.
The reference wraps eager MPI send/recv in ``chainer.Function``s whose
backwards run the opposite transfer (``Send.backward = recv`` at
``:23-33``, ``Recv.backward = send`` at ``:76-81``), plus a "delegate
variable" hack to keep the autograd graph connected.

The TPU-native primitive is ``lax.ppermute`` inside an SPMD
(``shard_map``) region: its transpose *is* the reverse permutation, so
JAX autodiff reproduces the reference's backward pairing with no
delegate machinery.  ``send``/``recv`` here are thin, symmetric views
of one collective-permute: every device participates; a device that is
not a declared destination receives (and should ignore) zeros.
"""

from jax import lax

from chainermn_tpu.communicators.mesh_utility import AXES


def send(x, comm=None, rank=None, src=None, axis=AXES, perm=None):
    """Ship ``x`` from device ``src`` to device ``rank``; differentiable.

    Parity with ``chainermn.functions.send(x, comm, rank)``
    (``point_to_point_communication.py:84-116``).  The reference infers
    the source from the calling process; in SPMD form the program is
    identical on every device, so the pair must be explicit: either
    ``(src, rank)`` or a full ``perm`` schedule of disjoint pairs.
    Ranks are *global* device ranks (``comm.axis_rank()`` numbering)
    under the default ``axis`` (both mesh axes); pass one axis name for
    axis-local numbering.
    Returns what *this* device received under the permutation (zeros
    when it is not a destination) -- the reference's separate delegate
    return value is unnecessary because the data dependency itself
    keeps the graph alive, and the transpose rule of ``ppermute``
    reproduces ``Send.backward = recv`` (reference ``:23-33``) exactly.
    """
    if perm is None:
        if rank is None or src is None:
            raise ValueError('provide (src, rank) or an explicit perm')
        perm = [(src, rank)]
    return lax.ppermute(x, axis, perm)


def recv(comm=None, rank=None, dst=None, axis=AXES, x=None, perm=None):
    """Receive on device ``dst`` from device ``rank``; mirror of
    :func:`send`.

    Parity with ``chainermn.functions.recv`` (``:119-150``).  ``x`` is
    each device's contribution template (``zeros_like`` of the
    transported value) since every SPMD participant supplies an
    operand; the value received on non-destination devices is zero.
    """
    if x is None:
        raise ValueError('recv needs a template operand x (zeros_like of '
                         'the transported value)')
    if perm is None:
        if rank is None or dst is None:
            raise ValueError('provide (rank, dst) or an explicit perm')
        perm = [(rank, dst)]
    return lax.ppermute(x, axis, perm)
