from chainermn_tpu.datasets.empty_dataset import create_empty_dataset  # noqa
