"""MNIST access (the workload of reference ``examples/mnist`` and the
convergence gate ``tests/test_mnist.py:33-80``).

This environment has no network egress and no cached MNIST, so
:func:`get_mnist` loads real data when available (``CHAINERMN_TPU_MNIST``
pointing at an ``mnist.npz``-style file) and otherwise generates a
deterministic *learnable stand-in*: 10 anisotropic Gaussian clusters in
784-d with small intra-class structure.  An MLP reaches the same >=0.95
accuracy bar the reference CI enforces, which is what the convergence
test actually measures.
"""

import os

import numpy as np


def _synthetic_mnist(n_train=6000, n_test=1000, dim=784, n_classes=10,
                     seed=1234):
    rng = np.random.RandomState(seed)
    # class prototypes kept well-separated but overlapping enough that
    # a linear model is not trivially perfect
    prototypes = rng.randn(n_classes, dim).astype(np.float32) * 1.2
    # low-rank intra-class variation + isotropic noise
    basis = rng.randn(n_classes, 16, dim).astype(np.float32)

    def make(n, seed2):
        r = np.random.RandomState(seed2)
        labels = r.randint(0, n_classes, size=n).astype(np.int32)
        coeff = r.randn(n, 16).astype(np.float32)
        x = prototypes[labels] + 0.35 * np.einsum(
            'nk,nkd->nd', coeff, basis[labels]) / np.sqrt(16)
        x += 0.45 * r.randn(n, dim).astype(np.float32)
        # squash to [0, 1] like pixel intensities
        x = 1.0 / (1.0 + np.exp(-x))
        return x.astype(np.float32), labels

    return make(n_train, seed + 1), make(n_test, seed + 2)


def _synthetic_mnist_hard(n_train=6000, n_test=1000, dim=784,
                          n_classes=10, seed=4321):
    """Antipodal-cluster task: class ``c`` is the UNION of the two
    antipodal clusters around ``+mu_c`` and ``-mu_c``.

    No linear classifier can exceed chance-ish accuracy (a hyperplane
    assigns opposite signs to a cluster and its mirror), so unlike the
    'classic' stand-in this bar requires real model capacity AND a
    healthy optimization trajectory -- a crippled model or a broken
    gradient mean demonstrably fails it (``tests/test_mnist.py``
    negative tests, VERDICT r3 item 6).  Inputs are NOT squashed to
    [0, 1]: the sigmoid would destroy the antipodal structure.
    """
    rng = np.random.RandomState(seed)
    mu = rng.randn(n_classes, dim).astype(np.float32)
    mu *= 2.0 / np.linalg.norm(mu, axis=1, keepdims=True)

    def make(n, seed2):
        r = np.random.RandomState(seed2)
        labels = r.randint(0, n_classes, size=n).astype(np.int32)
        sign = r.choice([-1.0, 1.0], size=(n, 1)).astype(np.float32)
        x = sign * mu[labels] + 0.28 * r.randn(n, dim).astype(
            np.float32)
        return x.astype(np.float32), labels

    return make(n_train, seed + 1), make(n_test, seed + 2)


def get_mnist(withlabel=True, ndim=1, variant='classic'):
    """Return ``(train, test)`` datasets of ``(x, label)`` tuples.

    Mirrors ``chainer.datasets.get_mnist`` used at
    ``examples/mnist/train_mnist.py:92`` closely enough for the
    examples and tests; see module docstring for the data source.
    ``variant='hard'`` selects the antipodal-cluster stand-in the
    convergence gate uses (ignored when ``CHAINERMN_TPU_MNIST``
    provides real data).
    """
    path = os.environ.get('CHAINERMN_TPU_MNIST')
    if path and os.path.exists(path):
        with np.load(path) as d:
            train_x = d['x_train'].reshape(len(d['x_train']), -1) / 255.0
            test_x = d['x_test'].reshape(len(d['x_test']), -1) / 255.0
            train = (train_x.astype(np.float32), d['y_train'].astype(
                np.int32))
            test = (test_x.astype(np.float32), d['y_test'].astype(np.int32))
    elif variant == 'hard':
        train, test = _synthetic_mnist_hard()
    elif variant == 'classic':
        train, test = _synthetic_mnist()
    else:
        # a typo'd variant silently serving the easy clusters would
        # make the convergence gate pass vacuously -- fail loudly
        raise ValueError("variant must be 'classic' or 'hard', got %r"
                         % (variant,))

    def build(pair):
        x, y = pair
        if ndim == 3:
            x = x.reshape(-1, 1, 28, 28)
        if not withlabel:
            return [xi for xi in x]
        return TupleDataset(x, y)

    return build(train), build(test)


class TupleDataset:
    """Zip of arrays -> tuple examples (chainer.datasets.TupleDataset
    equivalent)."""

    def __init__(self, *arrays):
        n = len(arrays[0])
        if any(len(a) != n for a in arrays):
            raise ValueError('arrays must share length')
        self._arrays = arrays

    def __len__(self):
        return len(self._arrays[0])

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        return tuple(a[i] for a in self._arrays)
