"""ImageNet-style dataset pipeline.

Parity with the reference's ``PreprocessedDataset``
(``examples/imagenet/train_imagenet.py:55-82``): mean subtraction,
random crop to the model insize + horizontal flip for training, center
crop for eval, pixel scaling.  Real data comes from a directory of
``.npy``/``.npz`` shards or a label file (``CHAINERMN_TPU_IMAGENET``);
without it (this environment has no egress) a deterministic synthetic
set with class-dependent structure stands in, which is sufficient for
throughput benchmarking (the BASELINE metric is images/sec/chip, not
final top-1).
"""

import os

import numpy as np


class PreprocessedDataset:
    """(image HWC float32, label) tuples with reference-style
    augmentation."""

    def __init__(self, base, mean, crop_size, random=True):
        self.base = base
        self.mean = mean.astype(np.float32) if mean is not None else None
        self.crop_size = crop_size
        self.random = random
        self._rng = np.random.RandomState(0x5EED)

    def __len__(self):
        return len(self.base)

    def __getitem__(self, i):
        image, label = self.base[i]
        image = np.asarray(image, np.float32)
        crop = self.crop_size
        h, w = image.shape[:2]
        if self.random:
            top = self._rng.randint(0, h - crop + 1)
            left = self._rng.randint(0, w - crop + 1)
            if self._rng.rand() > 0.5:
                image = image[:, ::-1, :]
        else:
            top = (h - crop) // 2
            left = (w - crop) // 2
        image = image[top:top + crop, left:left + crop, :]
        if self.mean is not None:
            image = image - self.mean[:crop, :crop, :]
        image = image * (1.0 / 255.0)
        return image.astype(np.float32), np.int32(label)


class SyntheticImageNet:
    """Deterministic class-structured images, generated on demand (no
    6TB on disk): class-colored low-frequency pattern + noise."""

    def __init__(self, n=1280, size=256, n_classes=1000, seed=7):
        self.n = n
        self.size = size
        self.n_classes = n_classes
        self.seed = seed
        rng = np.random.RandomState(seed)
        self._palette = rng.rand(n_classes, 1, 1, 3).astype(np.float32)

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        rng = np.random.RandomState(self.seed * 1000003 + i)
        label = i % self.n_classes
        s = self.size
        yy, xx = np.mgrid[0:s, 0:s].astype(np.float32) / s
        freq = 1 + (label % 7)
        pattern = np.sin(2 * np.pi * freq * yy)[..., None] * \
            np.cos(2 * np.pi * freq * xx)[..., None]
        img = 127.5 + 80.0 * pattern * self._palette[label] + \
            25.0 * rng.randn(s, s, 3).astype(np.float32)
        return np.clip(img, 0, 255).astype(np.float32), np.int32(label)


def load_labeled_pairs(root, listfile):
    """Reference-style (path, label) list file loader
    (``train_imagenet.py:141-151``); images must be prepared as .npy
    HWC uint8/float arrays."""
    pairs = []
    with open(listfile) as f:
        for line in f:
            path, label = line.split()
            pairs.append((os.path.join(root, path), int(label)))

    class _Loader:
        def __len__(self):
            return len(pairs)

        def __getitem__(self, i):
            path, label = pairs[i]
            return np.load(path), label

    return _Loader()


def get_imagenet(train_size=1280, val_size=128, size=256):
    """(train, val) raw datasets; real data when
    ``CHAINERMN_TPU_IMAGENET`` points at prepared npy lists, synthetic
    otherwise."""
    root = os.environ.get('CHAINERMN_TPU_IMAGENET')
    if root and os.path.isdir(root):
        train = load_labeled_pairs(root, os.path.join(root, 'train.txt'))
        val = load_labeled_pairs(root, os.path.join(root, 'val.txt'))
        return train, val
    return (SyntheticImageNet(train_size, size=size),
            SyntheticImageNet(val_size, size=size, seed=99))


def compute_mean(dataset, limit=256):
    """Mean image over (up to ``limit``) samples -- the reference ships
    this as ``examples/imagenet/compute_mean.py``."""
    acc = None
    n = min(len(dataset), limit)
    for i in range(n):
        img, _ = dataset[i]
        acc = img if acc is None else acc + img
    return (acc / n).astype(np.float32)
