"""ImageNet-style dataset pipeline.

Parity with the reference's ``PreprocessedDataset``
(``examples/imagenet/train_imagenet.py:55-82``): mean subtraction,
random crop to the model insize + horizontal flip for training, center
crop for eval, pixel scaling.  Real data comes from a directory of
``.npy``/``.npz`` shards or a label file (``CHAINERMN_TPU_IMAGENET``);
without it (this environment has no egress) a deterministic synthetic
set with class-dependent structure stands in, which is sufficient for
throughput benchmarking (the BASELINE metric is images/sec/chip, not
final top-1).
"""

import os

import numpy as np


class PreprocessedDataset:
    """(image HWC float32, label) tuples with reference-style
    augmentation."""

    def __init__(self, base, mean, crop_size, random=True):
        self.base = base
        self.mean = mean.astype(np.float32) if mean is not None else None
        self.crop_size = crop_size
        self.random = random
        self._rng = np.random.RandomState(0x5EED)

    def __len__(self):
        return len(self.base)

    def __getitem__(self, i):
        image, label = self.base[i]
        image = np.asarray(image, np.float32)
        crop = self.crop_size
        h, w = image.shape[:2]
        if self.random:
            top = self._rng.randint(0, h - crop + 1)
            left = self._rng.randint(0, w - crop + 1)
            if self._rng.rand() > 0.5:
                image = image[:, ::-1, :]
        else:
            top = (h - crop) // 2
            left = (w - crop) // 2
        image = image[top:top + crop, left:left + crop, :]
        if self.mean is not None:
            # mean window tracks the crop window (reference
            # `train_imagenet.py:79-80`: mean[:, top:bottom, left:right])
            image = image - self.mean[top:top + crop,
                                      left:left + crop, :]
        image = image * (1.0 / 255.0)
        return image.astype(np.float32), np.int32(label)


class SyntheticImageNet:
    """Deterministic class-structured images, generated on demand (no
    6TB on disk): class-colored low-frequency pattern + noise."""

    def __init__(self, n=1280, size=256, n_classes=1000, seed=7):
        self.n = n
        self.size = size
        self.n_classes = n_classes
        self.seed = seed
        rng = np.random.RandomState(seed)
        self._palette = rng.rand(n_classes, 1, 1, 3).astype(np.float32)

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        rng = np.random.RandomState(self.seed * 1000003 + i)
        label = i % self.n_classes
        s = self.size
        yy, xx = np.mgrid[0:s, 0:s].astype(np.float32) / s
        freq = 1 + (label % 7)
        pattern = np.sin(2 * np.pi * freq * yy)[..., None] * \
            np.cos(2 * np.pi * freq * xx)[..., None]
        img = 127.5 + 80.0 * pattern * self._palette[label] + \
            25.0 * rng.randn(s, s, 3).astype(np.float32)
        return np.clip(img, 0, 255).astype(np.float32), np.int32(label)


def load_labeled_pairs(root, listfile):
    """Reference-style (path, label) list file loader
    (``train_imagenet.py:141-151``); images must be prepared as .npy
    HWC uint8/float arrays."""
    pairs = []
    with open(listfile) as f:
        for line in f:
            path, label = line.split()
            pairs.append((os.path.join(root, path), int(label)))

    class _Loader:
        def __len__(self):
            return len(pairs)

        def __getitem__(self, i):
            path, label = pairs[i]
            return np.load(path), label

    return _Loader()


def get_imagenet(train_size=1280, val_size=128, size=256):
    """(train, val) raw datasets; real data when
    ``CHAINERMN_TPU_IMAGENET`` points at prepared npy lists, synthetic
    otherwise."""
    root = os.environ.get('CHAINERMN_TPU_IMAGENET')
    if root and os.path.isdir(root):
        train = load_labeled_pairs(root, os.path.join(root, 'train.txt'))
        val = load_labeled_pairs(root, os.path.join(root, 'val.txt'))
        return train, val
    return (SyntheticImageNet(train_size, size=size),
            SyntheticImageNet(val_size, size=size, seed=99))


class BatchAugmentPipeline:
    """Batch-level augmentation over a contiguous preloaded sample
    store, using the native C++ thread-pool kernel when built
    (``csrc/chainermn_core.cpp`` ``cmn_augment_batch``) and numpy
    otherwise.

    The native path replaces the reference's worker *processes*
    (``train_imagenet.py:174-182`` MultiprocessIterator + forkserver):
    same crop/flip/mean-subtract math, but parallel C threads over
    shared memory instead of pickled IPC.
    """

    def __init__(self, dataset, crop_size, mean=None, random=True,
                 scale=1.0 / 255.0, seed=0):
        first, _ = dataset[0]
        first = np.asarray(first)
        # keep INTEGER datasets in their native dtype (uint8-backed
        # real data stays uint8, 4x smaller) but normalize floats to
        # float32 (a float64-yielding dataset must not double RAM);
        # the per-batch float32 staging below is bounded by the batch
        # size.  The whole-store preload still bounds this pipeline to
        # datasets that fit in host RAM -- for bigger corpora use
        # MultiprocessIterator over PreprocessedDataset.
        store_dtype = (first.dtype if first.dtype.kind in 'iu'
                       else np.float32)
        self._store = np.empty((len(dataset),) + first.shape,
                               store_dtype)
        self._labels = np.empty(len(dataset), np.int32)
        for i in range(len(dataset)):
            img, label = dataset[i]
            self._store[i] = img
            self._labels[i] = label
        self.crop_size = crop_size
        self.mean = (np.ascontiguousarray(mean, np.float32)
                     if mean is not None else None)
        self.random = random
        self.scale = scale
        self._rng = np.random.RandomState(seed)

    def __len__(self):
        return len(self._store)

    def batch(self, indices):
        """(images (B, crop, crop, C) float32, labels (B,) int32)."""
        b = len(indices)
        h, w = self._store.shape[1:3]
        crop = self.crop_size
        if self.random:
            tops = self._rng.randint(0, h - crop + 1, b).astype(np.int32)
            lefts = self._rng.randint(0, w - crop + 1, b).astype(np.int32)
            flips = (self._rng.rand(b) > 0.5).astype(np.uint8)
        else:
            tops = np.full(b, (h - crop) // 2, np.int32)
            lefts = np.full(b, (w - crop) // 2, np.int32)
            flips = np.zeros(b, np.uint8)
        idx64 = np.asarray(indices, np.int64)
        # validate once for BOTH the native and the numpy path (numpy
        # negative indexing would otherwise silently wrap)
        if b and (idx64.min() < 0 or idx64.max() >= len(self._store)):
            raise ValueError('batch indices out of range [0, %d)'
                             % len(self._store))
        labels = self._labels[idx64]
        from chainermn_tpu import native
        if native.available:
            if self._store.dtype == np.float32:
                src, src_idx = self._store, idx64
            else:
                # stage only this batch's source samples as float32
                # (the C kernel consumes float32); B*H*W*C*4 bytes,
                # not N*H*W*C*4
                src = self._store[idx64].astype(np.float32)
                src_idx = np.arange(b, dtype=np.int64)
            images = native.augment_batch(
                src, src_idx, tops, lefts, flips, crop,
                mean=self.mean, scale=self.scale)
            return images, labels
        images = np.empty((b, crop, crop, self._store.shape[3]),
                          np.float32)
        for i, idx in enumerate(idx64):
            t, l = tops[i], lefts[i]
            win = self._store[idx][t:t + crop, l:l + crop].astype(
                np.float32)
            if self.mean is not None:
                win = win - self.mean[t:t + crop, l:l + crop]
            win = win * self.scale
            images[i] = win[:, ::-1] if flips[i] else win
        return images, labels


def compute_mean(dataset, limit=256):
    """Mean image over (up to ``limit``) samples -- the reference ships
    this as ``examples/imagenet/compute_mean.py``."""
    acc = None
    n = min(len(dataset), limit)
    for i in range(n):
        img, _ = dataset[i]
        acc = img if acc is None else acc + img
    return (acc / n).astype(np.float32)
