"""Empty dataset (reference ``chainermn/datasets/empty_dataset.py``).

Placeholder dataset for pure model-parallel workers whose forward pass
begins with a ``recv`` -- same trick as the reference
(``empty_dataset.py:1-18``): keep the training loop's iterator cadence
without feeding real data.
"""


def create_empty_dataset(dataset):
    """A dataset of ``len(dataset)`` empty tuples."""
    return [()] * len(dataset)
