"""Platform/backend setup helpers.

The multi-device CPU simulation the test harness and examples use
(the TPU-native analogue of the reference's ``mpiexec -n N`` CPU
matrix, ``.travis.yml:55``).
"""

import os

import jax


def force_host_devices(n=8):
    """Switch this process to the CPU backend with ``n`` virtual
    devices.  Must run before first backend use; safe to call when the
    flag is already present."""
    flags = os.environ.get('XLA_FLAGS', '')
    if '--xla_force_host_platform_device_count' not in flags:
        os.environ['XLA_FLAGS'] = (
            flags + ' --xla_force_host_platform_device_count=%d' % n
        ).strip()
    jax.config.update('jax_platforms', 'cpu')
