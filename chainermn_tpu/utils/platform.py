"""Platform/backend setup helpers.

The multi-device CPU simulation the test harness and examples use
(the TPU-native analogue of the reference's ``mpiexec -n N`` CPU
matrix, ``.travis.yml:55``).
"""

import os
import re

import jax


def ensure_host_device_flag(n=8):
    """Append ``--xla_force_host_platform_device_count=n`` to
    ``XLA_FLAGS`` unless some value for it is already present.  Safe
    on any platform (only affects the host backend); must run before
    first backend use to have an effect."""
    flags = os.environ.get('XLA_FLAGS', '')
    m = re.search(r'--xla_force_host_platform_device_count=(\d+)', flags)
    if m is None:
        os.environ['XLA_FLAGS'] = (
            flags + ' --xla_force_host_platform_device_count=%d' % n
        ).strip()
    return m


def enable_host_cpu_backend():
    """Expose the host CPU backend ALONGSIDE a pinned accelerator
    platform (e.g. ``JAX_PLATFORMS=axon``), keeping the accelerator
    first -- and therefore the default backend.

    Lets throwaway work (parameter init) run locally instead of
    stressing a tunneled remote-compile service with giant programs
    it has crashed on (``bench.py:init_on_host``).  Must run before
    first backend use; a no-op when no platform pin is set or cpu is
    already listed.  Every tunnel-facing entry point that builds
    models should call this, not just ``bench.py``."""
    plats = os.environ.get('JAX_PLATFORMS', '')
    names = [p.strip() for p in plats.split(',') if p.strip()]
    if names and 'cpu' not in names:
        jax.config.update('jax_platforms', ','.join(names + ['cpu']))


def force_host_devices(n=8, require=False):
    """Switch this process to the CPU backend with ``n`` virtual
    devices and return the live CPU device count.

    Must run before first backend use.  An already-present
    ``--xla_force_host_platform_device_count`` flag is respected (it
    may be a deliberate smaller CI-matrix setting).  With
    ``require=True`` a RuntimeError is raised when fewer than ``n``
    devices actually materialize -- either the pre-existing flag asked
    for fewer, or the backend was initialized before this call could
    take effect.
    """
    m = ensure_host_device_flag(n)
    jax.config.update('jax_platforms', 'cpu')
    devices = jax.devices()
    if devices[0].platform != 'cpu':
        # config update is a no-op once backends are live: the one job
        # of this function failed, never continue silently on real
        # hardware
        raise RuntimeError(
            'could not force the CPU backend: jax already initialized '
            'platform %r before force_host_devices ran'
            % devices[0].platform)
    count = len(devices)
    if require and count < n:
        raise RuntimeError(
            'asked for %d virtual CPU devices but the backend exposes '
            '%d (pre-existing flag: %s); set XLA_FLAGS='
            '--xla_force_host_platform_device_count=%d before first '
            'jax use' % (n, count, m.group(1) if m else 'unset', n))
    return count
