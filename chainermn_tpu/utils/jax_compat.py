"""JAX API compatibility layer.

The codebase is written against the modern surface (``jax.shard_map``
with ``check_vma=...``); older installed runtimes (jax <= 0.4.x) only
ship ``jax.experimental.shard_map.shard_map`` whose equivalent knob is
named ``check_rep``.  :func:`ensure` installs a thin adapter at
``jax.shard_map`` so every call site -- and the static analyzer, which
must trace the exact production functions -- runs unchanged on either
runtime.  On a runtime that already provides ``jax.shard_map`` this is
a no-op.

Called once from ``chainermn_tpu/__init__.py``; importing any
``chainermn_tpu`` submodule triggers it (Python imports the parent
package first).
"""

import functools

import jax


def _adapt_legacy_shard_map(legacy):
    @functools.wraps(legacy)
    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None,
                  check_rep=None, **kwargs):
        if check_rep is None:
            check_rep = True if check_vma is None else check_vma
        return legacy(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_rep,
                      **kwargs)
    return shard_map


def _axis_size(axis_name):
    """``lax.axis_size`` for runtimes that predate it.  ``psum`` of
    the literal 1 is constant-folded to the static axis size at trace
    time (no run-time collective)."""
    from jax import lax
    return lax.psum(1, axis_name)


def ensure():
    """Install missing modern-API aliases on ``jax``.  Idempotent."""
    if not hasattr(jax, 'shard_map'):
        from jax.experimental.shard_map import shard_map as legacy
        jax.shard_map = _adapt_legacy_shard_map(legacy)
    if not hasattr(jax.lax, 'axis_size'):
        jax.lax.axis_size = _axis_size
    return jax


# ----------------------------------------------------------------------
# AOT compilation + persistent compilation cache (the serving engine's
# cold-start surface, ``chainermn_tpu/serving/engine.py``).  Same shim
# discipline as ``jax.shard_map`` above: the engine is written against
# the modern ``jax.jit(...).lower(...).compile()`` AOT API and the
# ``jax_compilation_cache_dir`` config knob; on a runtime that lacks
# either, these helpers DEGRADE (return None / False) instead of
# raising, and the engine falls back to plain ``jit`` -- slower cold
# start, identical results.
# ----------------------------------------------------------------------

def aot_compile(jitted, *args, **kwargs):
    """``jitted.lower(*args).compile()`` guarded across jax versions:
    the compiled executable, or ``None`` when this runtime's jit
    wrapper has no usable AOT surface (missing ``lower``/``compile``,
    or a lowering that rejects these arguments).  Genuine COMPILE
    errors (the function itself is broken) still propagate: only the
    absence of the AOT API degrades."""
    lower = getattr(jitted, 'lower', None)
    if lower is None:
        return None
    try:
        lowered = lower(*args, **kwargs)
        compile_ = getattr(lowered, 'compile', None)
        if compile_ is None:
            return None
        return compile_()
    except (AttributeError, NotImplementedError, TypeError):
        return None


def enable_compilation_cache(cache_dir, min_compile_time_secs=0.0):
    """Point jax's persistent compilation cache at ``cache_dir`` so
    AOT executables survive process restarts (cold start becomes a
    file read).  Returns True when the cache knobs exist and were set,
    False when this runtime has no persistent-cache surface -- the
    caller keeps working, just without persistence.

    ``min_compile_time_secs=0`` persists even fast compiles: a
    serving engine's bucket set is small and every avoided retrace is
    a cold-start win (the default threshold of ~1s would skip exactly
    the small-model executables the CPU tier exercises)."""
    ok = False
    for knob, value in (
            ('jax_compilation_cache_dir', cache_dir),
            ('jax_persistent_cache_min_compile_time_secs',
             min_compile_time_secs),
            ('jax_persistent_cache_min_entry_size_bytes', -1)):
        try:
            jax.config.update(knob, value)
            if knob == 'jax_compilation_cache_dir':
                ok = True
        except (AttributeError, ValueError):
            if knob == 'jax_compilation_cache_dir':
                # older surface: the experimental module's setter
                try:
                    from jax.experimental.compilation_cache import (
                        compilation_cache as cc)
                    cc.set_cache_dir(cache_dir)
                    ok = True
                except Exception:
                    return False
    if ok:
        # the cache object is created lazily ONCE at the first
        # compile; a dir configured after that (any jit ran before
        # the engine was built) would silently never persist --
        # reset so the new dir takes effect.  Private-module probe
        # by necessity; failure degrades to in-process-only caching.
        try:
            from jax._src import compilation_cache as _cc
            _cc.reset_cache()
        except Exception:
            pass
    return ok
