"""JAX API compatibility layer.

The codebase is written against the modern surface (``jax.shard_map``
with ``check_vma=...``); older installed runtimes (jax <= 0.4.x) only
ship ``jax.experimental.shard_map.shard_map`` whose equivalent knob is
named ``check_rep``.  :func:`ensure` installs a thin adapter at
``jax.shard_map`` so every call site -- and the static analyzer, which
must trace the exact production functions -- runs unchanged on either
runtime.  On a runtime that already provides ``jax.shard_map`` this is
a no-op.

Called once from ``chainermn_tpu/__init__.py``; importing any
``chainermn_tpu`` submodule triggers it (Python imports the parent
package first).
"""

import functools

import jax


def _adapt_legacy_shard_map(legacy):
    @functools.wraps(legacy)
    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None,
                  check_rep=None, **kwargs):
        if check_rep is None:
            check_rep = True if check_vma is None else check_vma
        return legacy(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_rep,
                      **kwargs)
    return shard_map


def _axis_size(axis_name):
    """``lax.axis_size`` for runtimes that predate it.  ``psum`` of
    the literal 1 is constant-folded to the static axis size at trace
    time (no run-time collective)."""
    from jax import lax
    return lax.psum(1, axis_name)


def ensure():
    """Install missing modern-API aliases on ``jax``.  Idempotent."""
    if not hasattr(jax, 'shard_map'):
        from jax.experimental.shard_map import shard_map as legacy
        jax.shard_map = _adapt_legacy_shard_map(legacy)
    if not hasattr(jax.lax, 'axis_size'):
        jax.lax.axis_size = _axis_size
    return jax
