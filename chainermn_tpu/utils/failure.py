"""Failure detection and the recovery-side failure taxonomy.

The reference has NONE (SURVEY 5: MPI fail-stop only -- a hung or
diverged rank is discovered by the human).  This module supplies the
detectors a distributed run actually needs, plus the typed errors and
bounded-wait arithmetic the recovery layer acts on:

- numeric: :func:`check_finite` / :class:`NanGuard` -- divergence
  (NaN/Inf in loss, metrics, or params) stops the run with the first
  offending pytree paths named (optionally snapshotting state for
  post-mortem, see ``checkpoint_on_divergence``).
- liveness: :class:`Heartbeat` / :func:`detect_stall` -- each process
  writes a heartbeat file; any watcher (another rank, the launcher, a
  cron) can flag a stalled process without MPI-style global failure.
- timeout: the native collective engine returns CMN_TIMEOUT from a
  barrier whose peers never arrive (``csrc/chainermn_core.cpp``),
  surfacing single-rank death to the surviving ranks.  The eager
  Python channel mirrors that taxonomy: :class:`ChannelTimeout` (the
  wait expired, the peer MAY still be alive) vs :class:`PeerDeadError`
  (the peer is positively detected dead via its stalled heartbeat).
- bounded waits: :class:`Deadline` (absolute budget arithmetic) and
  :class:`Backoff` (deterministic exponential retry schedule) shared
  by every blocking path in ``communicators/base.py`` -- no wait in
  the eager stack is unbounded.

Acted on by :mod:`chainermn_tpu.utils.chaos` (deterministic fault
injection) and :mod:`chainermn_tpu.training.recovery` (preemption
checkpoint + auto-resume); see ``docs/fault_tolerance.md``.
"""

import json
import os
import signal as _signal
import threading
import time

import jax
import numpy as np


# ----------------------------------------------------------------------
# Typed failure taxonomy (eager-channel mirror of the native engine's
# CMN_* status codes, ``csrc/chainermn_core.cpp`` / ``native/core.py``)
# ----------------------------------------------------------------------

def _flight_dump(reason, **attrs):
    """Drop the telemetry flight record at a typed-failure raise
    site.  The typed constructors call this so EVERY raise path --
    present and future -- leaves the black box behind without each
    call site remembering to; a no-op when telemetry is off or
    in-memory, and never raises (a failing dump must not mask the
    typed verdict)."""
    try:
        from chainermn_tpu import telemetry
        if telemetry._active is not None:
            telemetry.dump_flight(reason, **attrs)
    except Exception:
        pass


class CommFailure(RuntimeError):
    """Base of the eager-channel failure taxonomy (Python twin of the
    native engine's :class:`~chainermn_tpu.native.core.CommError`)."""

    status_name = 'CMN_ERROR'


class ChannelTimeout(CommFailure, TimeoutError):
    """A bounded wait expired without evidence the peer is dead --
    mirrors the native barrier's ``CMN_TIMEOUT``.  Retryable: the
    sequence cursor of the waiting stream is never advanced on
    timeout, so the same call can simply be issued again."""

    status_name = 'CMN_TIMEOUT'

    def __init__(self, *args):
        super().__init__(*args)
        _flight_dump('ChannelTimeout',
                     message=str(args[0]) if args else '')


class PeerDeadError(CommFailure):
    """A peer process is POSITIVELY detected dead (its heartbeat file
    went stale past the liveness window, or it is known to have
    exited).  Unlike :class:`ChannelTimeout` this verdict is terminal
    for the conversation: retrying the same wait cannot succeed.

    ``process_index`` names the dead peer."""

    status_name = 'CMN_PEER_DEAD'

    def __init__(self, message, process_index=None):
        super().__init__(message)
        self.process_index = process_index
        _flight_dump('PeerDeadError', message=str(message),
                     process_index=process_index)


class ReplicaDeadError(CommFailure):
    """A serving replica is POSITIVELY detected dead (its stdout
    stream hit EOF, its process exited, or a typed RPC found the
    connection closed).  The serving-fleet sibling of
    :class:`PeerDeadError`: terminal for every request the replica
    was carrying, but -- unlike a training peer -- the fleet front
    can *recover* those requests by replaying their journaled
    ``prompt + emitted`` prefix on a survivor (exact-greedy
    continuation, ``docs/fault_tolerance.md`` "Serving
    self-healing").

    ``replica`` names the dead replica; ``request_ids`` lists the
    in-flight request ids it was carrying when it died (the requeue
    worklist)."""

    status_name = 'CMN_REPLICA_DEAD'

    def __init__(self, message, replica=None, request_ids=()):
        super().__init__(message)
        self.replica = replica
        self.request_ids = tuple(request_ids)
        _flight_dump('ReplicaDeadError', message=str(message),
                     replica=replica,
                     request_ids=list(self.request_ids))


class CheckpointCorruptError(ValueError):
    """A checkpoint failed integrity verification and must NOT be
    restored: truncated/unreadable file, per-leaf crc32 mismatch,
    missing write-complete sentinel, a leaf missing from the snapshot,
    or a shape/dtype mismatch against the restore template.

    The checkpoint-trust member of the failure taxonomy (see
    ``docs/fault_tolerance.md``): where :class:`ChannelTimeout` /
    :class:`PeerDeadError` make *communication* failure typed, this
    makes *state* failure typed -- ``auto_resume`` catches it to walk
    the snapshot chain to the newest VALID snapshot instead of
    silently loading poison or dying inside npz/zipfile internals.

    ``path`` names the snapshot, ``leaf`` the offending tree path
    (when one is identifiable), and ``kind`` classifies the defect:
    ``'unreadable'`` | ``'incomplete'`` | ``'crc'`` | ``'missing'`` |
    ``'shape'`` | ``'dtype'`` | ``'topology'``.  Subclasses
    ``ValueError`` so pre-taxonomy callers that caught the old bare
    errors keep working.
    """

    status_name = 'CMN_CKPT_CORRUPT'

    def __init__(self, message, path=None, leaf=None, kind=None):
        super().__init__(message)
        self.path = path
        self.leaf = leaf
        self.kind = kind
        _flight_dump('CheckpointCorruptError', message=str(message),
                     path=path, leaf=leaf, corruption_kind=kind)


class DataCorruptError(ValueError):
    """An input record failed integrity verification and must NOT be
    consumed: a flipped byte caught by the record crc32, a record
    extending past the shard's EOF (torn file), or a missing/
    unparseable index sidecar.

    The input-data member of the failure taxonomy (see
    ``docs/data_pipeline.md``): where
    :class:`CheckpointCorruptError` makes *state* failure typed, this
    makes *data* failure typed -- the streaming loader catches it to
    SKIP AND COUNT the sample (``corrupt_skipped`` +
    ``data_corrupt_skipped`` telemetry events) instead of silently
    training on poison or dying inside zipfile internals.

    ``shard`` names the file, ``offset`` the byte offset and
    ``record`` the in-shard record index (when identifiable);
    ``kind`` classifies the defect: ``'crc'`` | ``'truncated'`` |
    ``'unreadable'``.  Subclasses ``ValueError`` to mirror
    :class:`CheckpointCorruptError`'s compatibility contract."""

    status_name = 'CMN_DATA_CORRUPT'

    def __init__(self, message, shard=None, offset=None, record=None,
                 kind=None):
        super().__init__(message)
        self.shard = shard
        self.offset = offset
        self.record = record
        self.kind = kind
        _flight_dump('DataCorruptError', message=str(message),
                     shard=shard, offset=offset, record=record,
                     corruption_kind=kind)


class OverloadError(CommFailure):
    """The serving admission layer REFUSED work instead of wedging:
    the bounded request queue is full, or a request's deadline expired
    before (or while) it could be batched/executed.  The load-shedding
    member of the failure taxonomy -- under sustained overload the
    engine keeps serving what it admitted at a bounded latency and
    answers the rest with this typed verdict, which a client can back
    off on (``docs/serving.md``).

    ``reason`` classifies the shed: ``'queue_full'`` |
    ``'deadline'`` | ``'shutdown'``.  ``queue_depth`` records the
    depth observed at the decision.

    Unlike the other typed constructors this one does NOT drop a
    telemetry flight record: sheds fire at request rate when
    saturated (thousands/s), and a black-box dump per shed would
    thrash the disk the flight recorder exists to protect.  The
    batcher counts sheds in the ``serve_shed_total`` metric instead.
    """

    status_name = 'CMN_OVERLOAD'

    def __init__(self, message, reason='queue_full', queue_depth=None):
        super().__init__(message)
        self.reason = reason
        self.queue_depth = queue_depth


class WeightSwapError(RuntimeError):
    """A live weight hot-swap was REFUSED or failed validation before
    cutover: the engine still holds (and keeps serving) its previous
    parameter version.  Raised by ``swap_params`` when the new tree
    produces non-finite outputs on the validation forward, or when a
    generation engine is asked to swap with sequences still in flight
    (mid-sequence weight changes would corrupt the KV cache the
    in-flight sequences already banked).  The fleet records the
    refusal in ``fleet_ledger.jsonl`` and keeps routing to the
    incumbent -- a failed swap never takes a replica down."""

    def __init__(self, message, version=None):
        _flight_dump('weight_swap_failed', version=version)
        super().__init__(message)
        self.version = version


class CheckpointSkippedWarning(UserWarning):
    """Emitted (via ``warnings.warn``) each time ``auto_resume`` skips
    a corrupt or incomplete snapshot while walking the chain
    newest-to-oldest -- the typed, greppable record that a fallback
    happened and why."""


class Deadline:
    """Absolute time budget for a (possibly multi-step) blocking
    operation.  ``timeout=None`` means unbounded (every query reports
    time remaining as ``inf``); all arithmetic is monotonic-clock.

    The one place deadline arithmetic lives (ADVICE r4's timeout-
    arithmetic bug class: nested timeouts that do not add up): slices
    handed to sub-waits are ``min(want, remaining)``, so the sum of
    slices can never exceed the budget.
    """

    def __init__(self, timeout, clock=time.monotonic):
        self._clock = clock
        self.timeout = timeout
        self._t0 = clock()

    def elapsed(self):
        return self._clock() - self._t0

    def remaining(self):
        if self.timeout is None:
            return float('inf')
        return self.timeout - self.elapsed()

    def expired(self):
        return self.remaining() <= 0.0

    def slice(self, want, floor=1e-3):
        """Clamp a sub-wait to the remaining budget (never below
        ``floor`` so a wait API that rejects non-positive timeouts
        still gets a valid value; the caller checks :meth:`expired`
        before trusting the slice)."""
        return max(min(want, self.remaining()), floor)


class Backoff:
    """Deterministic exponential backoff schedule:
    ``initial * factor**k`` capped at ``max_delay``, with optional
    decorrelation jitter drawn from a SEEDED rng so two processes (or
    two runs) given the same seed replay the identical schedule --
    the property the chaos harness's determinism tests pin.

    Use :meth:`next` for the next delay (advances the schedule),
    :meth:`sleep` to also sleep it, :meth:`reset` after a success.
    """

    def __init__(self, initial=0.05, factor=2.0, max_delay=2.0,
                 jitter=0.0, seed=0):
        if initial <= 0 or factor < 1.0 or max_delay < initial:
            raise ValueError(
                'need initial > 0, factor >= 1, max_delay >= initial')
        self.initial = initial
        self.factor = factor
        self.max_delay = max_delay
        self.jitter = jitter
        self._seed = seed
        self.reset()

    def reset(self):
        import random
        self.attempt = 0
        self._rng = random.Random(self._seed)

    def peek(self):
        """The delay :meth:`next` would return, without advancing
        (jitter excluded -- it is drawn only when the step is
        consumed)."""
        return min(self.initial * self.factor ** self.attempt,
                   self.max_delay)

    def next(self):
        base = self.peek()
        self.attempt += 1
        if self.jitter:
            base += base * self.jitter * self._rng.random()
        return min(base, self.max_delay * (1.0 + self.jitter))

    def sleep(self, deadline=None):
        """Sleep the next delay (clamped to ``deadline.remaining()``
        when given); returns the time actually slept."""
        d = self.next()
        if deadline is not None:
            d = max(min(d, deadline.remaining()), 0.0)
        if d > 0:
            time.sleep(d)
        return d

    def delays(self, n):
        """Preview of the first ``n`` un-jittered delays (schedule
        introspection for tests/docs; does not advance state)."""
        return [min(self.initial * self.factor ** k, self.max_delay)
                for k in range(n)]


def check_finite(tree, prefix=''):
    """Return the paths of non-finite leaves (empty list == healthy)."""
    bad = []
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in flat:
        arr = np.asarray(leaf)
        if arr.dtype.kind in 'fc' and not np.all(np.isfinite(arr)):
            key = prefix + '/'.join(
                str(getattr(p, 'key', getattr(p, 'idx', p)))
                for p in path)
            bad.append(key)
    return bad


class DivergenceError(RuntimeError):
    """Raised by NanGuard when training produces non-finite values."""


# ----------------------------------------------------------------------
# Exit-code taxonomy: the typed failures, flattened to the one channel
# that survives a process death -- its exit status.  The supervisor
# (:mod:`chainermn_tpu.training.supervisor`) classifies a dead worker
# from this code first and cross-checks the telemetry doctor's verdict
# second; ``worker_main`` maps the exceptions on the way out.  Codes
# live in the 70-79 band (EX_SOFTWARE neighborhood) so they cannot
# collide with shells (126/127), signals (128+N) or the chaos
# injector's hard-kill defaults (42/43).
# ----------------------------------------------------------------------

EXIT_OK = 0
EXIT_UNCAUGHT = 70         # untyped exception escaped worker_main
EXIT_PREEMPTED = 71        # clean SIGTERM evacuation, checkpoint written
EXIT_DIVERGENCE = 72       # NanGuard verdict (DivergenceError)
EXIT_CHANNEL_TIMEOUT = 73  # bounded wait expired (ChannelTimeout)
EXIT_PEER_DEAD = 74        # typed peer death observed (PeerDeadError)
EXIT_CKPT_CORRUPT = 75     # checkpoint trust failure (CheckpointCorruptError)

#: exit status -> taxonomy name (the supervisor's first classifier)
EXIT_NAMES = {
    EXIT_OK: 'clean',
    EXIT_UNCAUGHT: 'uncaught',
    EXIT_PREEMPTED: 'preempted',
    EXIT_DIVERGENCE: 'divergence',
    EXIT_CHANNEL_TIMEOUT: 'channel_timeout',
    EXIT_PEER_DEAD: 'peer_dead',
    EXIT_CKPT_CORRUPT: 'checkpoint_corrupt',
}


def exit_code_for(exc):
    """The taxonomy exit code for an exception instance -- typed
    failures map to their own code, anything else to
    :data:`EXIT_UNCAUGHT`.  Subclass checks are ordered most-specific
    first (``PeerDeadError`` is a ``CommFailure``; ``ChannelTimeout``
    is also a ``TimeoutError``)."""
    if isinstance(exc, PeerDeadError):
        return EXIT_PEER_DEAD
    if isinstance(exc, ChannelTimeout):
        return EXIT_CHANNEL_TIMEOUT
    if isinstance(exc, CheckpointCorruptError):
        return EXIT_CKPT_CORRUPT
    if isinstance(exc, DivergenceError):
        return EXIT_DIVERGENCE
    return EXIT_UNCAUGHT


def classify_exit(returncode):
    """Taxonomy name for a worker's exit status: ``'clean'`` /
    ``'running'`` (still alive, status None), a typed name from
    :data:`EXIT_NAMES`, ``'signal:NAME'`` for signal deaths (Popen
    reports them as negative), or ``'crash'`` for any other nonzero
    code (the chaos injector's hard-kill defaults 42/43 land here --
    deliberately: an ``os._exit`` mid-step looks exactly like a
    machine loss, and the doctor's flight records are what refine
    it)."""
    if returncode is None:
        return 'running'
    if returncode == 0:
        return 'clean'
    if returncode < 0:
        try:
            return 'signal:' + _signal.Signals(-returncode).name
        except ValueError:
            return 'signal:%d' % -returncode
    return EXIT_NAMES.get(returncode, 'crash')


class NanGuard:
    """Trainer extension: stop on non-finite metrics (every iteration)
    and, every ``param_interval`` iterations, audit the parameters
    themselves (catches silent corruption that metrics lag behind).

    ``checkpoint_on_divergence``: a directory (or ``True`` for
    ``{trainer.out}/divergence``) receiving a forensic npz snapshot of
    the FULL updater state (params, optimizer state, loss-scale state,
    counters) plus a ``divergence.json`` naming the iteration and the
    offending keys, written BEFORE the raise.  The poisoned state is
    preserved for post-mortem while
    :func:`chainermn_tpu.training.recovery.auto_resume` restarts from
    the last healthy periodic snapshot -- divergence becomes a
    checkpoint-and-restart event instead of a lost run (see
    ``docs/fault_tolerance.md``).
    """

    trigger = (1, 'iteration')
    priority = 250  # before LogReport records garbage
    name = 'nan_guard'

    def __init__(self, param_interval=100, raise_on_divergence=True,
                 checkpoint_on_divergence=None):
        self.param_interval = param_interval
        self.raise_on_divergence = raise_on_divergence
        self.checkpoint_on_divergence = checkpoint_on_divergence
        self.divergence_checkpoint = None  # path once written

    def _snapshot_divergence(self, trainer, bad):
        out = self.checkpoint_on_divergence
        if out is True:
            out = os.path.join(trainer.out or '.', 'divergence')
        try:
            from chainermn_tpu import serializers
            os.makedirs(out, exist_ok=True)
            it = trainer.updater.iteration
            path = serializers.save_npz(
                os.path.join(out, 'divergence_iter_%d' % it),
                serializers.updater_state(trainer.updater))
            with open(os.path.join(out, 'divergence.json'), 'w') as f:
                json.dump({'iteration': it, 'bad': bad,
                           'checkpoint': path,
                           'process_index': jax.process_index()}, f)
            self.divergence_checkpoint = path
        except Exception as e:  # forensics must not mask the verdict
            import sys
            sys.stderr.write(
                'NanGuard: divergence checkpoint failed: %r\n' % e)

    def __call__(self, trainer):
        obs = trainer.observation
        bad = [k for k, v in obs.items()
               if isinstance(v, float) and not np.isfinite(v)]
        audit = (self.param_interval and
                 trainer.updater.iteration % self.param_interval == 0)
        if not bad and audit:
            # device-resident metrics (Trainer async_metrics=True) are
            # deliberately NOT fetched per iteration -- that would
            # reintroduce the per-step host sync async mode removes --
            # but the periodic audit is a sync point anyway, so check
            # them here alongside the parameters
            for k, v in obs.items():
                if getattr(v, 'ndim', None) == 0 and not np.isfinite(
                        np.asarray(v)):
                    bad.append(k)
            if not bad:
                bad = check_finite(trainer.updater.params, 'params/')
        if bad:
            msg = ('non-finite values at iteration %d: %s'
                   % (trainer.updater.iteration, ', '.join(bad)))
            if self.checkpoint_on_divergence:
                self._snapshot_divergence(trainer, bad)
            if self.raise_on_divergence:
                raise DivergenceError(msg)
            import sys
            sys.stderr.write('NanGuard: %s\n' % msg)


class Heartbeat:
    """Per-process liveness file, updated from a daemon thread.

    ``{path}`` gets JSON ``{pid, process_index, time, iteration}``
    every ``interval`` seconds; pair with :func:`detect_stall` on any
    observer."""

    def __init__(self, path, interval=10.0):
        self.path = path
        self.interval = interval
        self.iteration = 0
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                    exist_ok=True)
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def _write(self, stopped=False):
        tmp = self.path + '.tmp'
        with open(tmp, 'w') as f:
            json.dump({'pid': os.getpid(),
                       'process_index': jax.process_index(),
                       'time': time.time(),
                       'iteration': self.iteration,
                       'stopped': stopped}, f)
        os.replace(tmp, self.path)

    def _run(self):
        while not self._stop.is_set():
            try:
                self._write()
            except OSError:
                pass
            self._stop.wait(self.interval)

    def beat(self, iteration=None):
        """Optionally called from the training loop to stamp progress."""
        if iteration is not None:
            self.iteration = iteration

    def stop(self):
        """Stop the beat thread and stamp a final ``stopped: true``
        beat, so any observer can distinguish a clean exit from a
        stall instead of reading one last fresh "alive" timestamp.
        The final write is guarded like ``_run``'s: teardown on a
        removed or read-only out dir must not crash the process it
        was supposed to be cleaning up."""
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
            try:
                self._write(stopped=True)
            except OSError:
                pass


def read_heartbeat(path):
    """The parsed heartbeat dict at ``path``, or None when the file
    is missing or torn (a beat mid-``os.replace`` can never be torn,
    but the destination may not exist yet)."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def detect_stall(path, timeout=60.0, now=None, missing='stalled'):
    """True if the heartbeat at ``path`` is older than ``timeout``
    seconds -- the liveness check the reference's MPI stack cannot
    express short of a hang.

    ``missing`` decides the never-started case (no file, or an
    unreadable one): ``'stalled'`` (default; back-compatible --
    absence of a beat is treated as a stall) or ``'alive'`` (absence
    is NOT a stall -- the startup-grace mode the supervisor uses
    while a freshly spawned worker is still booting, so never-started
    and stalled stop being conflated without call-site
    special-casing)."""
    if missing not in ('stalled', 'alive'):
        raise ValueError(
            "detect_stall: missing= must be 'stalled' or 'alive', "
            'got %r' % (missing,))
    beat = read_heartbeat(path)
    if beat is None:
        return missing == 'stalled'
    now = time.time() if now is None else now
    return (now - beat.get('time', 0)) > timeout


def heartbeat_extension(out_dir, interval=10.0):
    """Trainer extension wiring: one heartbeat file per process under
    ``out_dir`` (``heartbeat-{process_index}.json``), iteration stamped
    each call."""
    hb = Heartbeat(os.path.join(
        out_dir, 'heartbeat-%d.json' % jax.process_index()),
        interval=interval)
    hb.start()

    def ext(trainer):
        hb.beat(trainer.updater.iteration)
    ext.trigger = (1, 'iteration')
    ext.priority = 20
    ext.name = 'heartbeat'
    ext.heartbeat = hb
    # the Trainer calls extension finalizers when the run ends:
    # without this the daemon thread keeps beating "alive" forever in
    # a long-lived process -- false liveness to any watcher
    ext.finalize = hb.stop
    return ext
