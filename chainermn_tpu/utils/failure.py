"""Failure detection.

The reference has NONE (SURVEY 5: MPI fail-stop only -- a hung or
diverged rank is discovered by the human).  This module supplies the
three detectors a distributed run actually needs:

- numeric: :func:`check_finite` / :class:`NanGuard` -- divergence
  (NaN/Inf in loss, metrics, or params) stops the run with the first
  offending pytree paths named.
- liveness: :class:`Heartbeat` / :func:`detect_stall` -- each process
  writes a heartbeat file; any watcher (another rank, the launcher, a
  cron) can flag a stalled process without MPI-style global failure.
- timeout: the native collective engine returns CMN_TIMEOUT from a
  barrier whose peers never arrive (``csrc/chainermn_core.cpp``),
  surfacing single-rank death to the surviving ranks.
"""

import json
import os
import threading
import time

import jax
import numpy as np


def check_finite(tree, prefix=''):
    """Return the paths of non-finite leaves (empty list == healthy)."""
    bad = []
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in flat:
        arr = np.asarray(leaf)
        if arr.dtype.kind in 'fc' and not np.all(np.isfinite(arr)):
            key = prefix + '/'.join(
                str(getattr(p, 'key', getattr(p, 'idx', p)))
                for p in path)
            bad.append(key)
    return bad


class DivergenceError(RuntimeError):
    """Raised by NanGuard when training produces non-finite values."""


class NanGuard:
    """Trainer extension: stop on non-finite metrics (every iteration)
    and, every ``param_interval`` iterations, audit the parameters
    themselves (catches silent corruption that metrics lag behind)."""

    trigger = (1, 'iteration')
    priority = 250  # before LogReport records garbage
    name = 'nan_guard'

    def __init__(self, param_interval=100, raise_on_divergence=True):
        self.param_interval = param_interval
        self.raise_on_divergence = raise_on_divergence

    def __call__(self, trainer):
        obs = trainer.observation
        bad = [k for k, v in obs.items()
               if isinstance(v, float) and not np.isfinite(v)]
        audit = (self.param_interval and
                 trainer.updater.iteration % self.param_interval == 0)
        if not bad and audit:
            # device-resident metrics (Trainer async_metrics=True) are
            # deliberately NOT fetched per iteration -- that would
            # reintroduce the per-step host sync async mode removes --
            # but the periodic audit is a sync point anyway, so check
            # them here alongside the parameters
            for k, v in obs.items():
                if getattr(v, 'ndim', None) == 0 and not np.isfinite(
                        np.asarray(v)):
                    bad.append(k)
            if not bad:
                bad = check_finite(trainer.updater.params, 'params/')
        if bad:
            msg = ('non-finite values at iteration %d: %s'
                   % (trainer.updater.iteration, ', '.join(bad)))
            if self.raise_on_divergence:
                raise DivergenceError(msg)
            import sys
            sys.stderr.write('NanGuard: %s\n' % msg)


class Heartbeat:
    """Per-process liveness file, updated from a daemon thread.

    ``{path}`` gets JSON ``{pid, process_index, time, iteration}``
    every ``interval`` seconds; pair with :func:`detect_stall` on any
    observer."""

    def __init__(self, path, interval=10.0):
        self.path = path
        self.interval = interval
        self.iteration = 0
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                    exist_ok=True)
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def _write(self):
        tmp = self.path + '.tmp'
        with open(tmp, 'w') as f:
            json.dump({'pid': os.getpid(),
                       'process_index': jax.process_index(),
                       'time': time.time(),
                       'iteration': self.iteration}, f)
        os.replace(tmp, self.path)

    def _run(self):
        while not self._stop.is_set():
            try:
                self._write()
            except OSError:
                pass
            self._stop.wait(self.interval)

    def beat(self, iteration=None):
        """Optionally called from the training loop to stamp progress."""
        if iteration is not None:
            self.iteration = iteration

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
            self._write()


def detect_stall(path, timeout=60.0, now=None):
    """True if the heartbeat at ``path`` is older than ``timeout``
    seconds (or missing) -- the liveness check the reference's MPI
    stack cannot express short of a hang."""
    try:
        with open(path) as f:
            beat = json.load(f)
    except (OSError, ValueError):
        return True
    now = time.time() if now is None else now
    return (now - beat.get('time', 0)) > timeout


def heartbeat_extension(out_dir, interval=10.0):
    """Trainer extension wiring: one heartbeat file per process under
    ``out_dir`` (``heartbeat-{process_index}.json``), iteration stamped
    each call."""
    hb = Heartbeat(os.path.join(
        out_dir, 'heartbeat-%d.json' % jax.process_index()),
        interval=interval)
    hb.start()

    def ext(trainer):
        hb.beat(trainer.updater.iteration)
    ext.trigger = (1, 'iteration')
    ext.priority = 20
    ext.name = 'heartbeat'
    ext.heartbeat = hb
    return ext
