"""Append-only fsynced JSONL ledgers -- the machine-readable record a
dead control loop leaves behind.

Two control planes write one: the training supervisor
(``supervisor_ledger.jsonl``, PR 9 -- spawn/watch/classify/decide/
resume events) and the serving fleet (``fleet_ledger.jsonl``,
ISSUE 13 -- version_seen/roll_start/replica_swap/canary_verdict/
promote/rollback/converged events).  Both need the identical
contract, so it lives here once:

- **Append-only, fsynced.**  One JSON object per line; every append
  flushes AND fsyncs before returning, so an entry that was written
  survives the writer dying the next instant (``os._exit`` from a
  chaos kill site included).  The entry order IS the event order.
- **Tolerant read.**  :meth:`Ledger.read` returns every parseable
  line and silently skips a torn tail -- the footprint of a writer
  killed mid-append.  A reader never crashes on the artifact of the
  exact failure the ledger exists to document.
- **Self-describing entries.**  Every entry carries ``event`` (the
  type) and ``t`` (wall-clock seconds, for humans and MTTR
  arithmetic); everything else is the writer's schema.

The schemas themselves are documented where they are written:
``docs/fault_tolerance.md`` (supervisor) and ``docs/serving.md``
(fleet, "Continuous deployment").
"""

import json
import os
import time


class Ledger:
    """Append-only JSONL event log: one JSON object per line,
    fsynced per append (see module docstring)."""

    def __init__(self, path):
        self.path = path
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)

    def append(self, event, **fields):
        rec = dict(fields, event=event, t=round(time.time(), 3))
        with open(self.path, 'a') as f:
            f.write(json.dumps(rec, default=repr, sort_keys=True)
                    + '\n')
            f.flush()
            os.fsync(f.fileno())
        return rec

    @staticmethod
    def read(path):
        """Every parseable entry (torn tails from a killed writer
        are skipped, not fatal; a missing file reads as empty)."""
        out = []
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        continue
        except OSError:
            pass
        return out


def events(entries, kind):
    """The entries of one event type, in ledger order -- the shared
    assertion helper the supervisor and fleet test suites both use."""
    return [e for e in entries if e.get('event') == kind]
