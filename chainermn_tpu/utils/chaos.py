"""Seeded, deterministic fault injection for the distributed stack.

Elastic-training systems earn trust by BREAKING themselves on purpose:
inject a fault deterministically, watch the recovery layer absorb it,
assert the run still converges.  This module is that injector for
chainermn_tpu -- the counterpart of the detectors in
:mod:`chainermn_tpu.utils.failure` and the recovery layer in
:mod:`chainermn_tpu.training.recovery` (see
``docs/fault_tolerance.md`` for the full detectors -> injector ->
recovery loop and ``ci/run_matrix.sh`` for the multi-controller chaos
leg that runs the multiprocess suite clean AND under these faults).

Design constraints:

- **Zero cost when off.**  Every hook first checks the module-global
  ``_active`` against ``None`` (one attribute load + identity check);
  no spec parsing, no rng, no environment reads happen on the hot
  path of a chaos-free run.
- **Deterministic under a fixed seed.**  Each site owns a
  ``random.Random`` seeded from ``(seed, crc32(site))`` -- NOT
  Python's per-process salted ``hash`` -- so two processes (or two
  runs) given the same spec replay the identical fault sequence.
  This is what lets the SIGTERM-mid-step scenario fire on every rank
  at the same iteration, making the collective orbax checkpoint
  coherent.
- **Env/flag activated.**  ``CHAINERMN_TPU_CHAOS`` holds a spec
  string; :func:`maybe_install_from_env` (called from communicator
  and updater construction) installs it once per process.

Spec grammar (items separated by ``;``)::

    seed=INT                 rng seed (default 0)
    rank=INT                 restrict the whole spec to this
                             jax.process_index() (default: all)
    SITE=WHEN[:ARG]          one fault rule

    WHEN := '@' i,j,...      fire at these 0-based occurrences of SITE
          | 'p' FLOAT        fire with this probability per occurrence
          | '*'              fire every occurrence
    ARG  := FLOAT            site-specific (delay seconds, burst
                             length, exit code)

Sites (the action is part of the site name):

==================  ====================================================
``drop_send``       the eager-p2p publish attempt fails (transient
                    store error); ``send_obj``'s bounded backoff loop
                    must retry it through
``delay_send``      sleep ARG (default 0.05 s) before the publish
``dup_send``        publish the message key twice (at-least-once
                    delivery duplicate)
``stall_kv``        sleep ARG (default 0.2 s) before each KV-store
                    wait slice (slow/contended coordination service)
``nan_batch``       poison the first ARG (default 1) elements of the
                    next host batch's first floating array with NaN --
                    the gradients of that step become NaN (divergence
                    burst for NanGuard integration)
``sigterm_step``    deliver SIGTERM to this process at the start of
                    update_core occurrence N (preemption mid-step)
``kill_step``       hard-kill (``os._exit(ARG or 42)``) at the start
                    of update_core occurrence N
``hang_step``       hang this process at the start of update_core
                    occurrence N: sleep ARG (default 3600) seconds
                    with the main thread wedged -- heartbeat files
                    keep getting fresh timestamps but the iteration
                    freezes, exactly the livelock a supervisor's
                    progress watch (not a time-based stall probe)
                    must catch and escalate
``kill_recv``       hard-kill at recv_obj call occurrence N (receiver
                    death mid-conversation)
``ckpt_kill``       hard-kill (``os._exit(ARG or 43)``) BETWEEN a
                    checkpoint's temp-file write and its atomic
                    rename -- the crash-mid-write case; the final
                    file must never appear and the previous snapshot
                    must survive intact
``ckpt_stall``      sleep ARG (default 0.5 s) BETWEEN a checkpoint's
                    temp-file fsync and its atomic rename -- a slow
                    or contended disk mid-commit.  Under the async
                    checkpoint writer the stall lands on the
                    BACKGROUND committer thread, so the training step
                    path must stay flat (p99 pinned) while the commit
                    completes late; under a synchronous handler the
                    same stall lands squarely in the step time --
                    exactly the cadence-vs-step-cost trade async
                    checkpointing removes
``slice_loss``      hard-kill (``os._exit(45)``) every process whose
                    failure-domain slice (``CHAINERMN_TPU_SLICE``
                    env; the supervisor's per-rank handout for
                    ``MeshPlan.create(slices=)`` topologies) equals
                    the rule ARG (default slice 0) at the start of
                    update_core occurrence N -- a whole ICI slice
                    dropping off the DCN at once.  Processes outside
                    the target slice never consult the occurrence
                    counter, so survivors record no chaos event and
                    the supervisor must classify the correlated
                    deaths as ONE slice-granularity failure and
                    shrink by whole slices, never splitting one
``ckpt_truncate``   truncate the just-committed checkpoint file to
                    ARG (default 0.5) of its size -- torn write /
                    filesystem loss; verification must reject it
``ckpt_flip``       XOR-flip ARG (default 8) evenly-spaced bytes of
                    the just-committed checkpoint -- silent bit rot;
                    crc verification must reject it
``serve_burst``     amplify a serving-queue submission: enqueue ARG
                    (default 4) extra synthetic copies of the
                    request -- a traffic spike the bounded queue must
                    absorb or SHED with a typed ``OverloadError``,
                    never wedge on (``chainermn_tpu/serving``)
``serve_cancel``    expire ARG (default 1) in-flight generation
                    requests' deadlines at a decode step -- the
                    mid-generation cancellation path: the request is
                    answered with a typed ``OverloadError``
                    (reason=deadline) and its cache slot is freed for
                    refill at the NEXT decode step, never leaked
                    (``chainermn_tpu/serving/generate.py``)
``swap_kill``       hard-kill (``os._exit(ARG or 44)``) the fleet
                    controller at a weight-swap point of a rolling
                    deployment -- occurrence 0 is the canary swap,
                    occurrence k the k-th replica swap of the roll --
                    leaving the fleet MID-ROLL with replicas on mixed
                    parameter versions; a restarted fleet must
                    converge every replica to one consistent version
                    and record it in ``fleet_ledger.jsonl``
                    (``chainermn_tpu/serving/fleet.py``)
``serve_slow``      sleep ARG (default 0.05) seconds before each
                    serve execution on an engine whose parameters
                    were HOT-SWAPPED to a version other than the one
                    it booted with -- models a latency regression
                    shipped by a roll: in an A/B fleet only the
                    canary replica slows down, the incumbents (still
                    at their boot version) never consult the rule,
                    and a rollback (swap back to the boot version)
                    restores full speed.  The canary gate's
                    breach-then-rollback scenario is driven by
                    exactly this site
``serve_longprompt``  inject ARG (default 3) EXTRA max-length prompts
                    into the open-loop generation arrival stream at
                    one arrival point -- a burst of worst-case
                    prefill work landing mid-window: a monolithic
                    prefill engine stalls every live sequence's next
                    token behind the long prompts' compute (windowed
                    inter-token SLO burn), while chunked prefill
                    (``prefill_chunk``) interleaves the same work
                    with decode ticks and holds the SLO
                    (``chainermn_tpu/serving/loadgen.py``)
``replica_kill``    hard-kill (``os._exit(46)``) the engine-replica
                    WORKER process whose replica index
                    (``CHAINERMN_TPU_REPLICA`` env, or the index the
                    caller passes to ``on_replica_kill``) equals the
                    rule ARG (default replica 0) at the start of
                    DECODE tick N (live slots only, so the victim
                    always dies with generations in flight) -- an
                    UNPLANNED replica death mid-decode.  Processes
                    outside the target replica never consult the
                    occurrence counter (the ``slice_loss`` idiom), so
                    survivors record no chaos event; the fleet front
                    must detect the death typed
                    (``failure.ReplicaDeadError``), requeue every
                    journaled in-flight generation as an exact-greedy
                    continuation on a survivor, and respawn the
                    worker (``chainermn_tpu/serving/fleet.py``,
                    ``docs/fault_tolerance.md`` "Serving
                    self-healing")
``data_stall``      sleep ARG (default 0.05) seconds before a shard
                    record read (``chainermn_tpu/data/recordio.py``)
                    -- a slow/contended filesystem; the loader's
                    prefetch depth must hide it, and the telemetry
                    report's input-bound line must surface it when
                    it cannot
``data_corrupt``    XOR-flip ARG (default 4) spread bytes of a just-
                    read record payload BEFORE its crc check -- bit
                    rot on the data path; the reader must reject it
                    with a typed ``failure.DataCorruptError``
                    (kind=crc, shard+offset named) and the loader
                    must skip-and-count it, never silently consume
``extra_collective``  record ARG (default 1) PHANTOM eager collective
                    span(s) after an ``allreduce_obj`` rendezvous:
                    the per-rank eager ``seq`` counter advances and
                    the span lands in the telemetry capture, but no
                    peer participates -- this rank's recorded
                    collective stream diverges while the run itself
                    completes.  Combine with ``rank=N`` to model the
                    classic SPMD bug (a Python branch on rank issuing
                    an extra collective); the doctor's
                    protocol-divergence verdict must replay the
                    capture and name the divergence point
==================  ====================================================

Example -- drop the first publish, delay half the rest, stall the
store, SIGTERM at step 3::

    CHAINERMN_TPU_CHAOS='seed=7;drop_send=@0;delay_send=p0.5:0.02;
                         stall_kv=p0.5:0.05;sigterm_step=@3'

(one line in a real environment; wrapped here for width)
"""

import os
import signal
import time
import zlib

ENV_VAR = 'CHAINERMN_TPU_CHAOS'

SITES = ('drop_send', 'delay_send', 'dup_send', 'stall_kv',
         'nan_batch', 'sigterm_step', 'kill_step', 'hang_step',
         'kill_recv', 'ckpt_kill', 'ckpt_truncate', 'ckpt_flip',
         'ckpt_stall', 'slice_loss',
         'serve_burst', 'serve_cancel', 'swap_kill', 'serve_slow',
         'data_stall', 'data_corrupt', 'extra_collective',
         'serve_longprompt', 'replica_kill')

#: environment variable naming this process's failure-domain slice
#: (the supervisor's per-rank handout; MeshPlan.create(slices=)
#: builds the matching mesh axis).  ``slice_loss`` consults it.
SLICE_ENV_VAR = 'CHAINERMN_TPU_SLICE'

#: environment variable naming this process's serving-replica index
#: (the fleet controller's per-worker handout).  ``replica_kill``
#: consults it (or the index passed to :func:`on_replica_kill`).
REPLICA_ENV_VAR = 'CHAINERMN_TPU_REPLICA'


def slice_id():
    """This process's slice index from :data:`SLICE_ENV_VAR`, or
    None when the run declares no slice topology."""
    v = os.environ.get(SLICE_ENV_VAR)
    if v in (None, ''):
        return None
    return int(v)


class InjectedFault(RuntimeError):
    """Raised by the injector to model a transient failure (e.g. a
    dropped publish).  The message carries 'UNAVAILABLE' so generic
    transient-error classifiers treat it as retryable, which is the
    point: recovery code must survive it without special-casing."""

    def __init__(self, site, occurrence):
        super().__init__(
            'UNAVAILABLE (chaos: injected %s at occurrence %d)'
            % (site, occurrence))
        self.site = site
        self.occurrence = occurrence


class Rule:
    __slots__ = ('site', 'prob', 'at', 'always', 'arg')

    def __init__(self, site, prob=None, at=None, always=False, arg=None):
        self.site = site
        self.prob = prob
        self.at = at
        self.always = always
        self.arg = arg


def parse_spec(spec):
    """``(seed, rank, {site: Rule})`` from a spec string (grammar in
    the module docstring).  Raises ValueError on malformed items so a
    typo'd env var fails loudly at install, not silently mid-run."""
    seed, rank, rules = 0, None, {}
    for item in filter(None, (s.strip() for s in spec.split(';'))):
        name, _, rhs = item.partition('=')
        name = name.strip()
        if name == 'seed':
            seed = int(rhs)
            continue
        if name == 'rank':
            rank = int(rhs)
            continue
        if name not in SITES:
            raise ValueError('chaos spec: unknown site %r (one of %s)'
                             % (name, '/'.join(SITES)))
        when, _, argtxt = rhs.partition(':')
        when = when.strip()
        rule = Rule(name, arg=float(argtxt) if argtxt else None)
        if when.startswith('@'):
            rule.at = frozenset(int(x) for x in when[1:].split(','))
        elif when.startswith('p'):
            rule.prob = float(when[1:])
            if not 0.0 <= rule.prob <= 1.0:
                raise ValueError('chaos spec: probability %r out of '
                                 '[0,1]' % when)
        elif when == '*':
            rule.always = True
        else:
            raise ValueError(
                'chaos spec: bad WHEN %r for %s (use @i,j / pFLOAT / *)'
                % (when, name))
        rules[name] = rule
    return seed, rank, rules


class FaultInjector:
    """Deterministic per-site fault scheduler.

    ``fires(site)`` advances that site's occurrence counter and
    returns the matching :class:`Rule` when the fault fires (else
    ``None``).  ``log`` records every decision as
    ``(site, occurrence, fired)`` -- the determinism tests replay two
    injectors and assert identical logs.
    """

    def __init__(self, spec='', seed=None):
        import random
        pseed, self.rank, self.rules = parse_spec(spec)
        self.seed = pseed if seed is None else seed
        self.spec = spec
        self._counts = {}
        self._rngs = {
            site: random.Random(
                (self.seed & 0xffffffff) * 1000003
                + zlib.crc32(site.encode()))
            for site in self.rules}
        self.log = []

    def fires(self, site):
        rule = self.rules.get(site)
        if rule is None:
            return None
        idx = self._counts.get(site, 0)
        self._counts[site] = idx + 1
        if rule.prob is not None:
            hit = self._rngs[site].random() < rule.prob
        elif rule.at is not None:
            hit = idx in rule.at
        else:
            hit = rule.always
        self.log.append((site, idx, hit))
        if hit:
            # emit the injection into the telemetry timeline so a
            # fault and its latency consequences (retry spans, typed
            # timeouts, checkpoint writes) correlate in one place.
            # Lazy import: chaos must stay importable standalone, and
            # the kill/exit sites flush below before the process dies.
            from chainermn_tpu import telemetry
            if telemetry._active is not None:
                telemetry.event('chaos:' + site, kind='chaos',
                                occurrence=idx, arg=rule.arg)
                if site in ('kill_step', 'kill_recv', 'ckpt_kill',
                            'hang_step', 'swap_kill', 'slice_loss',
                            'replica_kill'):
                    # os._exit skips atexit: flush the timeline AND
                    # drop the crash-safe flight record NOW, or the
                    # fatal injection is invisible post-mortem
                    # (dump_flight flushes internally and never
                    # raises).  hang_step dumps too: the hung process
                    # usually ends SIGKILLed by the supervisor, and
                    # the flight record is what lets the post-mortem
                    # name the wedged rank among the frozen ones.
                    telemetry.dump_flight('chaos:' + site,
                                          occurrence=idx)
        return rule if hit else None

    def counts(self):
        return dict(self._counts)


# ----------------------------------------------------------------------
# Module-level activation (the zero-cost-when-off switch)
# ----------------------------------------------------------------------

_active = None
_env_checked = False


def active():
    """The installed :class:`FaultInjector`, or None."""
    return _active


def install(injector):
    global _active
    _active = injector
    return injector


def uninstall():
    global _active, _env_checked
    _active, _env_checked = None, False


def strip_sites(spec, sites):
    """``spec`` minus the rules for ``sites`` (``seed=``/``rank=``
    and every other rule preserved textually; unknown site names in
    ``sites`` are ignored).

    The supervisor's already-delivered-fault accounting: a
    deterministic one-shot fault (``kill_step=@3``) that a dead
    attempt consumed must NOT be re-delivered to the relaunched pod
    -- per-process occurrence counters restart from zero in a new
    process, so without stripping, every restart replays the same
    death and no restart policy can converge.  The supervisor learns
    *which* site fired from the victim's flight record
    (``chaos:<site>``) and hands the remaining spec to the next
    attempt: the environment replays WITHOUT the fault that was
    already delivered, exactly like a real one-off preemption."""
    sites = set(sites)
    kept = []
    for item in filter(None, (s.strip() for s in spec.split(';'))):
        if item.partition('=')[0].strip() in sites:
            continue
        kept.append(item)
    return ';'.join(kept)


def maybe_install_from_env(env_var=ENV_VAR):
    """Install an injector from ``CHAINERMN_TPU_CHAOS`` once per
    process (no-op when unset, already checked, or the spec's
    ``rank=`` does not match this process)."""
    global _env_checked
    if _active is not None or _env_checked:
        return _active
    _env_checked = True
    spec = os.environ.get(env_var)
    if not spec:
        return None
    inj = FaultInjector(spec)
    if inj.rank is not None:
        import jax
        if jax.process_index() != inj.rank:
            return None
    return install(inj)


# ----------------------------------------------------------------------
# Hook points (called from communicators/base.py and training/updater)
# Every hook is a no-op returning instantly when no injector is
# installed; call sites additionally guard on ``chaos._active is not
# None`` so the off path costs one attribute load.
# ----------------------------------------------------------------------

def before_send():
    """p2p publish hooks: ``delay_send`` sleeps, ``drop_send`` raises
    :class:`InjectedFault` (the bounded-retry loop in ``send_obj``
    must absorb it)."""
    inj = _active
    if inj is None:
        return
    r = inj.fires('delay_send')
    if r is not None:
        time.sleep(r.arg if r.arg is not None else 0.05)
    r = inj.fires('drop_send')
    if r is not None:
        raise InjectedFault('drop_send', inj._counts['drop_send'] - 1)


def duplicate_send():
    """True when the just-published message should be published again
    (at-least-once duplicate)."""
    inj = _active
    return inj is not None and inj.fires('dup_send') is not None


def before_kv_wait():
    """``stall_kv``: sleep before a KV-store wait slice."""
    inj = _active
    if inj is None:
        return
    r = inj.fires('stall_kv')
    if r is not None:
        time.sleep(r.arg if r.arg is not None else 0.2)


def on_recv():
    """``kill_recv``: hard-kill this process at a recv_obj call."""
    inj = _active
    if inj is None:
        return
    r = inj.fires('kill_recv')
    if r is not None:
        os._exit(int(r.arg) if r.arg is not None else 42)


def on_step(iteration):
    """Per-train-step hooks: ``sigterm_step`` (graceful preemption --
    the handler checkpoints and stops), ``kill_step`` (hard kill) and
    ``hang_step`` (wedge the main thread; the heartbeat daemon keeps
    the liveness file fresh while the iteration freezes -- only a
    progress-based watcher catches it)."""
    inj = _active
    if inj is None:
        return
    r = inj.fires('sigterm_step')
    if r is not None:
        os.kill(os.getpid(), signal.SIGTERM)
    r = inj.fires('kill_step')
    if r is not None:
        os._exit(int(r.arg) if r.arg is not None else 42)
    r = inj.fires('hang_step')
    if r is not None:
        time.sleep(r.arg if r.arg is not None else 3600.0)
    # slice_loss: membership gate BEFORE the occurrence counter --
    # survivors outside the target slice must not advance it (their
    # step cadence may differ post-shrink) and must record no chaos
    # event, so the post-mortem sees correlated deaths only on the
    # lost slice.
    rule = inj.rules.get('slice_loss')
    if rule is not None:
        target = int(rule.arg) if rule.arg is not None else 0
        if slice_id() == target and inj.fires('slice_loss') is not None:
            os._exit(45)


def on_checkpoint_write(tmp_path):
    """``ckpt_kill``: hard-kill this process BETWEEN writing a
    checkpoint's temp file and the atomic rename -- the mid-write
    crash.  With tmp+rename discipline the final filename never
    appears, so the previous snapshot must remain the resume point
    (``tests/test_chaos.py`` pins exactly that)."""
    inj = _active
    if inj is None:
        return
    r = inj.fires('ckpt_kill')
    if r is not None:
        os._exit(int(r.arg) if r.arg is not None else 43)
    # ckpt_stall: a slow/contended disk mid-commit.  Landing between
    # fsync and rename means the stalled snapshot is invisible to
    # chain_heads()/CheckpointWatcher for the whole stall -- and under
    # the async writer the sleep is on the background committer, so
    # the step path must not feel it.
    r = inj.fires('ckpt_stall')
    if r is not None:
        time.sleep(r.arg if r.arg is not None else 0.5)
    del tmp_path  # reserved for future partial-write faults


def corrupt_checkpoint(path):
    """``ckpt_truncate`` / ``ckpt_flip``: damage the just-committed
    checkpoint file in place (AFTER the atomic rename -- the file is
    "complete" on disk, so only content verification can reject it).

    ``ckpt_truncate``: keep only ARG (default 0.5) of the bytes.
    ``ckpt_flip``: XOR ARG (default 8) bytes spread evenly across
    the file -- deterministic, so tests replay the identical bit
    rot, and dense enough that at least one flip always lands in a
    checked region (a single flip can disappear into npz alignment
    padding).
    """
    inj = _active
    if inj is None:
        return
    r = inj.fires('ckpt_truncate')
    if r is not None:
        frac = r.arg if r.arg is not None else 0.5
        size = os.path.getsize(path)
        with open(path, 'r+b') as f:
            f.truncate(max(0, int(size * frac)))
        return
    r = inj.fires('ckpt_flip')
    if r is not None:
        n = max(1, int(r.arg) if r.arg is not None else 8)
        size = os.path.getsize(path)
        if size == 0:
            return
        with open(path, 'r+b') as f:
            for i in range(n):
                off = min(size - 1, (size * (i + 1)) // (n + 1))
                f.seek(off)
                byte = f.read(1)
                f.seek(off)
                f.write(bytes([byte[0] ^ 0xFF]))


def on_serve_submit():
    """``serve_burst``: the number of EXTRA synthetic copies of the
    incoming request the serving queue should enqueue (0 = no burst).
    The queue enqueues them through its normal bounded admission path,
    so a burst past capacity exercises the typed-shed contract, not a
    special case."""
    inj = _active
    if inj is None:
        return 0
    r = inj.fires('serve_burst')
    if r is None:
        return 0
    return max(1, int(r.arg) if r.arg is not None else 4)


def extra_collectives():
    """``extra_collective``: the number of PHANTOM eager collective
    spans ``allreduce_obj`` should record after the real rendezvous
    (0 = none).  The phantom advances this rank's per-(name, tag)
    eager ``seq`` counter and is recorded like a real collective, but
    no cross-process rendezvous happens -- the run completes while
    this rank's captured protocol stream gains ops its peers never
    issued, which is exactly the divergence ``telemetry doctor``'s
    protocol-divergence replay (``commcheck.verify_streams``) must
    name."""
    inj = _active
    if inj is None:
        return 0
    r = inj.fires('extra_collective')
    if r is None:
        return 0
    return max(1, int(r.arg) if r.arg is not None else 1)


def on_swap(phase=None):
    """``swap_kill``: hard-kill THIS process at a fleet weight-swap
    point.  The fleet controller calls this immediately before each
    replica swap of a roll (occurrence 0 = the canary swap), so a
    fired site leaves the fleet mid-roll with replicas on MIXED
    parameter versions -- the exact wreckage the restart-convergence
    contract (one consistent version, recorded in the ledger) must
    clean up.  ``phase`` is advisory (span labeling by the caller);
    the occurrence counter, not the phase, decides firing."""
    inj = _active
    if inj is None:
        return
    r = inj.fires('swap_kill')
    if r is not None:
        os._exit(int(r.arg) if r.arg is not None else 44)
    del phase


def on_serve_slow(swapped):
    """``serve_slow``: sleep before one serve execution, but ONLY on
    an engine serving a hot-swapped parameter version (``swapped``
    True: ``param_version != `` the version the engine booted with).
    Engines at their boot version never consult the rule -- which is
    what lets one process-wide spec slow exactly the canary replica
    of an in-process A/B fleet, and lets a rollback restore speed."""
    inj = _active
    if inj is None or not swapped:
        return
    r = inj.fires('serve_slow')
    if r is not None:
        time.sleep(r.arg if r.arg is not None else 0.05)


def replica_index():
    """This process's serving-replica index from
    :data:`REPLICA_ENV_VAR`, or None when the process serves no
    replica role."""
    v = os.environ.get(REPLICA_ENV_VAR)
    if v in (None, ''):
        return None
    return int(v)


def on_replica_kill(index=None):
    """``replica_kill``: hard-kill (``os._exit(46)``) THIS process at
    the start of a generation-engine DECODE tick, but ONLY when
    its replica index equals the rule ARG (default replica 0).  The
    ``slice_loss`` idiom: the membership gate runs BEFORE the
    occurrence counter, so non-target replicas never advance it (their
    tick cadence differs) and record no chaos event -- the post-mortem
    sees exactly one unplanned death, and the fleet front must requeue
    the victim's journaled in-flight generations on the survivors.

    ``index`` overrides :data:`REPLICA_ENV_VAR` (in-process fleets
    have no per-process env to consult)."""
    inj = _active
    if inj is None:
        return
    rule = inj.rules.get('replica_kill')
    if rule is None:
        return
    target = int(rule.arg) if rule.arg is not None else 0
    me = replica_index() if index is None else index
    if me == target and inj.fires('replica_kill') is not None:
        os._exit(46)


def on_serve_longprompt():
    """``serve_longprompt``: the number of EXTRA max-length synthetic
    prompts the open-loop generator should inject at this arrival
    point (0 = none).  The burst arrives through the queue's normal
    bounded admission, so what it really tests is the ENGINE's
    prefill scheduling: monolithic prefill serializes the long
    prompts' compute ahead of every live sequence's next token
    (inter-token SLO burn), chunked prefill interleaves it."""
    inj = _active
    if inj is None:
        return 0
    r = inj.fires('serve_longprompt')
    if r is None:
        return 0
    return max(1, int(r.arg) if r.arg is not None else 3)


def on_serve_cancel():
    """``serve_cancel``: the number of in-flight generation requests
    whose deadlines the generation engine should force-expire at this
    decode step (0 = none).  The engine routes the cancellation
    through its NORMAL deadline-expiry path -- typed
    ``OverloadError(reason='deadline')`` to the client, slot freed for
    refill at the next step -- so the chaos site exercises the real
    cancellation machinery, not a special case."""
    inj = _active
    if inj is None:
        return 0
    r = inj.fires('serve_cancel')
    if r is None:
        return 0
    return max(1, int(r.arg) if r.arg is not None else 1)


def on_data_read():
    """``data_stall``: sleep before one shard record read (a slow or
    contended filesystem on the input path)."""
    inj = _active
    if inj is None:
        return
    r = inj.fires('data_stall')
    if r is not None:
        time.sleep(r.arg if r.arg is not None else 0.05)


def corrupt_record(payload):
    """``data_corrupt``: XOR-flip ARG (default 4) evenly-spaced bytes
    of a just-read record payload BEFORE the reader's crc check --
    silent bit rot on the data path, which the crc must catch and
    type as ``DataCorruptError(kind='crc')``.  Returns the (possibly
    new) payload; never mutates the caller's bytes."""
    inj = _active
    if inj is None:
        return payload
    r = inj.fires('data_corrupt')
    if r is None or not payload:
        return payload
    n = max(1, int(r.arg) if r.arg is not None else 4)
    blob = bytearray(payload)
    size = len(blob)
    for i in range(n):
        off = min(size - 1, (size * (i + 1)) // (n + 1))
        blob[off] ^= 0xFF
    return bytes(blob)


def corrupt_batch(arrays):
    """``nan_batch``: poison the first ARG elements of the first
    floating array of a host batch (tuple/list of numpy arrays) --
    the resulting gradients are a NaN burst.  Returns the (possibly
    new) batch; never mutates the caller's arrays."""
    inj = _active
    if inj is None:
        return arrays
    r = inj.fires('nan_batch')
    if r is None:
        return arrays
    import numpy as np
    out, poisoned = [], False
    for a in arrays:
        arr = np.asarray(a)
        if not poisoned and arr.dtype.kind == 'f':
            arr = np.array(arr, copy=True)
            n = max(1, int(r.arg) if r.arg is not None else 1)
            arr.reshape(-1)[:n] = np.nan
            poisoned = True
        out.append(arr)
    return tuple(out) if isinstance(arrays, tuple) else out
