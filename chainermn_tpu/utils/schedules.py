"""Distributed learning-rate recipes.

The reference's headline result (the 128-GPU ResNet-50 run pointed at
by ``/root/reference/README.md:19``) depends on the large-batch
training recipe popularized alongside it: scale the learning rate
linearly with the global batch and ramp it up over the first epochs so
the early large-batch updates do not diverge.  The reference leaves
the recipe to its example flags; here it is a first-class utility so
every example and user script applies the same math when the mesh
grows.

All helpers return plain ``optax`` schedules (step -> lr) and compose
with any optimizer; ``steps`` means optimizer steps (one per global
batch).
"""

import optax

__all__ = ['linear_scaled_lr', 'gradual_warmup',
           'distributed_sgd_schedule']


def linear_scaled_lr(base_lr, global_batch, base_batch=256):
    """Linear scaling rule: ``lr = base_lr * global_batch/base_batch``.

    ``base_lr`` is the single-device recipe's rate at ``base_batch``;
    growing the mesh grows the global batch and the rate with it.
    """
    if global_batch <= 0 or base_batch <= 0:
        raise ValueError('batch sizes must be positive')
    return base_lr * (global_batch / float(base_batch))


def gradual_warmup(target_lr, warmup_steps, after=None, init_factor=0.1):
    """Ramp from ``init_factor * target_lr`` to ``target_lr`` over
    ``warmup_steps``, then follow ``after`` (an optax schedule taking
    post-warmup steps; default: constant ``target_lr``).

    The gradual-warmup trick that makes the linear scaling rule stable
    for large meshes; with ``warmup_steps=0`` it is just ``after``.
    """
    if after is None:
        after = optax.constant_schedule(target_lr)
    if warmup_steps <= 0:
        return after
    ramp = optax.linear_schedule(
        init_value=init_factor * target_lr, end_value=target_lr,
        transition_steps=warmup_steps)
    return optax.join_schedules([ramp, after], [warmup_steps])


def distributed_sgd_schedule(global_batch, steps_per_epoch,
                             base_lr=0.1, base_batch=256,
                             warmup_epochs=5, total_epochs=90,
                             decay='cosine'):
    """The full large-batch recipe in one call: linear-scaled peak rate,
    ``warmup_epochs`` of gradual warmup, then cosine decay to 0 (or
    ``decay='step'`` for the classic /10 at 30/60/80 epochs).
    """
    peak = linear_scaled_lr(base_lr, global_batch, base_batch)
    warmup_steps = warmup_epochs * steps_per_epoch
    rest = max(1, (total_epochs - warmup_epochs) * steps_per_epoch)
    if decay == 'cosine':
        after = optax.cosine_decay_schedule(peak, decay_steps=rest)
    elif decay == 'step':
        after = optax.piecewise_constant_schedule(
            peak, {(e - warmup_epochs) * steps_per_epoch: 0.1
                   for e in (30, 60, 80) if e > warmup_epochs})
    else:
        raise ValueError("decay must be 'cosine' or 'step'")
    return gradual_warmup(peak, warmup_steps, after)
