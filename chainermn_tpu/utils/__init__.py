"""Runtime utilities: platform setup, profiling, failure detection,
distributed LR recipes."""

from chainermn_tpu.utils.platform import enable_host_cpu_backend  # noqa
from chainermn_tpu.utils.platform import force_host_devices  # noqa
from chainermn_tpu.utils import profiling  # noqa
from chainermn_tpu.utils.failure import (  # noqa
    NanGuard, DivergenceError, Heartbeat, check_finite, detect_stall,
    heartbeat_extension)
from chainermn_tpu.utils.schedules import (  # noqa
    linear_scaled_lr, gradual_warmup, distributed_sgd_schedule)
