"""Runtime utilities: platform setup, profiling, failure detection."""

from chainermn_tpu.utils.platform import force_host_devices  # noqa
