"""Runtime utilities: platform setup, profiling, failure detection
and recovery primitives, chaos (fault) injection, distributed LR
recipes."""

from chainermn_tpu.utils.platform import enable_host_cpu_backend  # noqa
from chainermn_tpu.utils.platform import force_host_devices  # noqa
from chainermn_tpu.utils import profiling  # noqa
from chainermn_tpu.utils import chaos  # noqa
from chainermn_tpu.utils.chaos import FaultInjector  # noqa
from chainermn_tpu.utils.failure import (  # noqa
    NanGuard, DivergenceError, Heartbeat, check_finite, detect_stall,
    read_heartbeat, heartbeat_extension, CommFailure, ChannelTimeout,
    PeerDeadError, ReplicaDeadError, Backoff, Deadline,
    CheckpointCorruptError,
    CheckpointSkippedWarning, exit_code_for, classify_exit)
from chainermn_tpu.utils.schedules import (  # noqa
    linear_scaled_lr, gradual_warmup, distributed_sgd_schedule)
