"""Profiling / tracing.

The reference has NO tracing subsystem (SURVEY 5: closest artifact is
the dummy communicator built to time pack/unpack overhead,
``dummy_communicator.py:8-12``).  Here profiling is first-class:
``jax.profiler`` device traces (viewable in TensorBoard/Perfetto), a
step timer with throughput accounting, and a pack/unpack-style
microbenchmark helper that fills the dummy communicator's role.

Timing source of truth: :mod:`chainermn_tpu.telemetry`.  ``StepTimer``
and ``benchmark_op`` record into a telemetry
:class:`~chainermn_tpu.telemetry.Histogram` -- the ACTIVE session's
registry when telemetry is enabled (so step times ride the same
metrics export as everything else: ``metrics.json``, Prometheus), a
standalone histogram otherwise.  ``StepTimer`` additionally emits one
``step`` span per tick into the event timeline when a session is
active.
"""

import contextlib
import json
import os
import time

import jax

from chainermn_tpu import telemetry as _telemetry


@contextlib.contextmanager
def trace(logdir):
    """Capture a device trace for the enclosed block.

    Produces a TensorBoard-loadable trace under ``logdir`` (XLA op
    timeline, HBM usage on TPU)."""
    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()


def annotate(name):
    """Named region visible in the device trace."""
    return jax.profiler.TraceAnnotation(name)


class StepTimer:
    """Throughput accounting for a training loop.

    Trainer extension AND standalone: call ``tick(n_items)`` per step;
    ``summary()`` gives steps/sec, items/sec and latency percentiles
    (compile-affected first steps excluded via ``warmup``).

    Step durations land in a telemetry histogram (the active
    session's registry under ``metric_name`` when telemetry is
    enabled -- one timing source of truth, exported with everything
    else -- or a standalone :class:`~chainermn_tpu.telemetry.Histogram`
    otherwise); each tick additionally emits a ``step`` span into the
    active event timeline.
    """

    trigger = (1, 'iteration')
    priority = 150
    name = 'step_timer'

    def __init__(self, items_per_step=None, warmup=2,
                 metric_name='step_time_seconds'):
        self.items_per_step = items_per_step
        self.warmup = warmup
        self.metric_name = metric_name
        reg = _telemetry.registry()
        self._hist = (reg.histogram(metric_name) if reg is not None
                      else _telemetry.Histogram(metric_name))
        self._last = None
        self._ticks = 0

    def __call__(self, trainer):  # extension protocol
        self.tick()
        if self._hist.samples:
            trainer.observation.setdefault(
                'steps_per_sec', 1.0 / self._hist.samples[-1])

    def tick(self, n_items=None):
        now = time.perf_counter()
        if self._last is not None:
            dt = now - self._last
            self._hist.observe(dt)
            rec = _telemetry.active()
            if rec is not None:
                rec._append({'type': 'span', 'name': 'step',
                             'kind': 'compute',
                             't0': rec.now() - dt, 't1': rec.now(),
                             'timer': self.metric_name,
                             'tick': self._ticks})
        self._last = now
        self._ticks += 1

    def summary(self):
        times = (self._hist.samples[self.warmup:]
                 or self._hist.samples)
        if not times:
            return {}
        times = sorted(times)
        n = len(times)
        mean = sum(times) / n
        out = {
            'steps': n,
            'mean_step_s': mean,
            'steps_per_sec': 1.0 / mean,
            'p50_step_s': times[n // 2],
            'p99_step_s': times[min(n - 1, int(n * 0.99))],
        }
        if self.items_per_step:
            out['items_per_sec'] = self.items_per_step / mean
        return out

    def dump(self, path):
        with open(path, 'w') as f:
            json.dump(self.summary(), f, indent=1)


def benchmark_op(fn, *args, n_steps=20, warmup=3,
                 metric_name='benchmark_op_seconds'):
    """Time a jitted callable end-to-end (the role the reference's
    dummy communicator plays for pack/unpack overhead).  Returns
    mean seconds per call; the mean is also recorded into the active
    telemetry registry's ``metric_name`` histogram when a session is
    enabled."""
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n_steps):
        out = fn(*args)
    jax.block_until_ready(out)
    mean = (time.perf_counter() - t0) / n_steps
    reg = _telemetry.registry()
    if reg is not None:
        reg.histogram(metric_name).observe(mean)
    return mean


def memory_stats(device=None):
    """Per-device memory statistics where the backend exposes them
    (TPU: bytes_in_use / peak_bytes_in_use; CPU returns {})."""
    device = device or jax.devices()[0]
    stats = getattr(device, 'memory_stats', lambda: None)()
    return stats or {}


def save_device_profile(logdir, fn, *args):
    """Trace one execution of ``fn(*args)`` into ``logdir`` and return
    the output; convenience wrapper used by the examples'
    ``--profile`` flags."""
    os.makedirs(logdir, exist_ok=True)
    with trace(logdir):
        out = fn(*args)
        jax.block_until_ready(out)
    return out
