"""Synthetic open-loop load generators for the serving engines
(:func:`open_loop` for the batch :class:`InferenceEngine`,
:func:`open_loop_generate` for the autoregressive
:class:`GenerationEngine`).

OPEN loop means arrivals are scheduled by a clock, not by completions
(a closed-loop generator waits for each response and therefore can
never observe queueing collapse -- the p99 it reports under overload
is a fiction).  Requests are submitted at ``t0 + i/rate`` regardless
of how the engine is doing; when the engine falls behind, the bounded
queue fills and submissions start shedding with the typed
``OverloadError`` -- which is the MEASUREMENT, not a failure: the
report separates served throughput/latency from shed fraction, so a
rate above capacity shows up as graceful degradation, never a wedge.

Determinism: the size mix comes from a seeded ``numpy`` rng, so two
runs at the same (seed, rate, n) offer the identical request
sequence.  Latency percentiles come from the telemetry registry's
raw-sample histograms (exact merge semantics), never from averaged
percentiles.
"""

import threading
import time

import numpy as np

from chainermn_tpu import telemetry as _telemetry
from chainermn_tpu.utils import chaos as _chaos
from chainermn_tpu.utils.failure import OverloadError


def _hist_summary(reg, name):
    if reg is None:
        return {}
    snap = reg.snapshot().get(name)
    return (snap or {}).get('summary') or {}


def _worst_request(recorder):
    """The worst traced request's stage decomposition from the live
    recorder's in-memory records (``report.request_summary`` over the
    same ``kind='request'`` stream the offline report reads) -- what
    the bench rows carry so a bad p99 names its stage even when no
    capture directory was kept.  None when nothing was traced."""
    if recorder is None:
        return None
    try:
        from chainermn_tpu.telemetry.report import request_summary
        summary = request_summary(list(recorder.events))
    except Exception:
        return None
    if not summary:
        return None
    return {'e2e_ms': summary.get('e2e_ms'),
            'stage_p99_ms': summary.get('stage_p99_ms'),
            'worst': summary.get('worst'),
            'completed': summary.get('completed'),
            'shed': summary.get('shed')}


def open_loop_generate(engine, queue, rate, n_requests, seed=0,
                       prompt_len_range=None, max_new_tokens=16,
                       vocab_size=None, deadline_s=None,
                       result_timeout=60.0, clock=time.monotonic,
                       capture_dir=None, slo_monitor=None):
    """Open-loop driver for the autoregressive
    :class:`~chainermn_tpu.serving.GenerationEngine` -- same
    clock-scheduled arrival contract as :func:`open_loop` (shedding
    IS the measurement), but the unit of work is a SEQUENCE and the
    report's currency is TOKENS: generated tokens/s over the serve
    window, time-to-first-token and inter-token p50/p99 from the
    telemetry raw-sample histograms, plus the prefill/decode split's
    compile/trace accounting (flat decode trace count across slot
    refills is the continuous-batching no-recompile pin).

    Args:
      rate: offered request rate (req/s).
      prompt_len_range: ``(lo, hi)`` inclusive prompt-length mix
        (default ``(1, engine.max_prompt_len)``).
      max_new_tokens: tokens to generate per request.
      vocab_size: token-id range for the synthetic prompts (default
        the engine model's).
      deadline_s: per-request deadline -- expiry mid-generation sheds
        typed through the serve_cancel path.
      slo_monitor: optional
        :class:`~chainermn_tpu.telemetry.slo.SLOMonitor` attached to
        the recorder for the serve window; its live verdict rides in
        the report's ``slo`` field (and its ``slo_snapshot.json`` is
        written periodically when the monitor has an outdir).
    """
    lo, hi = prompt_len_range or (1, engine.max_prompt_len)
    vocab = vocab_size or engine.model.vocab_size
    rng = np.random.RandomState(seed)
    lens = rng.randint(lo, hi + 1, size=n_requests)
    prompts = [rng.randint(0, vocab, size=n).astype(np.int32)
               for n in lens]

    _installed = None
    if _telemetry.active() is None:
        _installed = _telemetry.enable()
    recorder = _telemetry.active()
    if slo_monitor is not None:
        slo_monitor.attach(recorder)

    st0 = engine.stats()
    stop = threading.Event()
    worker = threading.Thread(target=engine.run, args=(queue, stop),
                              daemon=True)
    worker.start()

    try:
        admitted, shed_submit = [], 0
        t0 = clock()
        longprompt_injected = 0
        for i, prompt in enumerate(prompts):
            target = t0 + i / float(rate)
            delay = target - clock()
            if delay > 0:
                time.sleep(delay)
            # serve_longprompt chaos: a burst of worst-case prefill
            # work (max-length prompts) landing at this arrival point
            # -- injected THROUGH the normal bounded submission path,
            # so the engine's prefill scheduling (monolithic vs
            # chunked) is what decides whether live sequences' inter-
            # token SLO survives the burst
            n_long = (_chaos.on_serve_longprompt()
                      if _chaos._active is not None else 0)
            for j in range(n_long):
                long_prompt = rng.randint(
                    0, vocab,
                    size=engine.max_prompt_len).astype(np.int32)
                try:
                    admitted.append(queue.submit(
                        long_prompt, max_new_tokens,
                        deadline=(None if deadline_s is None
                                  else clock() + deadline_s)))
                    longprompt_injected += 1
                except OverloadError:
                    shed_submit += 1
            try:
                admitted.append(queue.submit(
                    prompt, max_new_tokens,
                    deadline=(None if deadline_s is None
                              else clock() + deadline_s)))
            except OverloadError:
                shed_submit += 1
        served = shed_deadline = errored = 0
        tokens_served = 0
        for req in admitted:
            try:
                out = req.result(timeout=result_timeout)
                served += 1
                tokens_served += len(out)
            except OverloadError:
                shed_deadline += 1
            except Exception:
                errored += 1
        t1 = clock()
        reg = _telemetry.registry()
    finally:
        stop.set()
        worker.join(timeout=result_timeout)
        queue.close()
        if slo_monitor is not None:
            slo_monitor.detach()
            slo_monitor.write_snapshot()   # final live verdict
        if capture_dir is not None and _telemetry.active() is not None:
            try:
                _telemetry.active().flush(capture_dir)
            except Exception:
                pass  # the report below is the primary artifact
        worst = _worst_request(recorder)
        if _installed is not None:
            _telemetry.disable()
    ttft = _hist_summary(reg, 'serve_ttft_seconds')
    itl = _hist_summary(reg, 'serve_intertoken_seconds')
    dstep = _hist_summary(reg, 'serve_decode_seconds')
    st = engine.stats()
    wall = max(t1 - t0, 1e-9)
    offered = int(n_requests) + longprompt_injected
    shed = shed_submit + shed_deadline
    return {
        'offered': offered,
        'longprompt_injected': longprompt_injected,
        'offered_rate': float(rate),
        'admitted': len(admitted),
        'served': served,
        'shed_submit': shed_submit,
        'shed_deadline': shed_deadline,
        'errored': errored,
        'shed_fraction': shed / float(offered) if offered else 0.0,
        'served_req_per_s': served / wall,
        'tokens_served': tokens_served,
        'tokens_generated': (st['tokens_generated']
                             - st0['tokens_generated']),
        'tokens_per_s': tokens_served / wall,
        'wall_s': wall,
        'ttft_p50_ms': (ttft.get('p50') or 0.0) * 1e3
        if ttft else None,
        'ttft_p99_ms': (ttft.get('p99') or 0.0) * 1e3
        if ttft else None,
        'intertoken_p50_ms': (itl.get('p50') or 0.0) * 1e3
        if itl else None,
        'intertoken_p99_ms': (itl.get('p99') or 0.0) * 1e3
        if itl else None,
        'decode_step_p50_ms': (dstep.get('p50') or 0.0) * 1e3
        if dstep else None,
        'prefills': st['prefills'] - st0['prefills'],
        'decode_steps': st['decode_steps'] - st0['decode_steps'],
        'cancelled': st['cancelled'] - st0['cancelled'],
        'compile_count': st['compile_count'],
        'prefill_trace_count': st['prefill_trace_count'],
        'decode_trace_count': st['decode_trace_count'],
        'aot': st['aot'],
        'int8_kv': st['int8_kv'],
        'quantized': st['quantized'],
        'n_slots': st['n_slots'],
        'paged': ({k: st.get(k) for k in (
            'page_size', 'n_pages', 'pages_in_use', 'pages_free',
            'peak_pages_in_use', 'prefill_chunk', 'prefill_chunks',
            'cow_copies', 'copy_trace_count', 'prefix_lookups',
            'prefix_hits', 'prefix_hit_rate',
            'prefix_tokens_reused')} if st.get('paged') else None),
        'worst_request': worst,
        'speculative': _spec_report(st, st0),
        'slo': (slo_monitor.evaluate() if slo_monitor is not None
                else None),
    }


def _spec_report(st, st0):
    """The speculative-decoding slice of a generate report: windowed
    deltas of the engine's draft/verify accounting plus the two
    derived ratios the bench row banks -- ``accepted_draft_rate``
    (draft tokens whose target argmax agreed, over proposed) and
    ``verify_per_token`` (target executable invocations per generated
    token: < 1 IS the amortization).  ``None`` on non-speculative
    engines."""
    spec, spec0 = st.get('speculative'), st0.get('speculative')
    if not spec:
        return None
    spec0 = spec0 or {}
    proposed = (spec['draft_proposed']
                - spec0.get('draft_proposed', 0))
    accepted = (spec['draft_accepted']
                - spec0.get('draft_accepted', 0))
    verify_steps = (spec['verify_steps']
                    - spec0.get('verify_steps', 0))
    tokens = (st['tokens_generated'] - st0['tokens_generated'])
    return {
        'spec_tokens': spec['spec_tokens'],
        'draft_steps': spec['draft_steps'] - spec0.get(
            'draft_steps', 0),
        'verify_steps': verify_steps,
        'draft_proposed': proposed,
        'draft_accepted': accepted,
        'accepted_draft_rate': (accepted / proposed
                                if proposed else None),
        'verify_per_token': (verify_steps / tokens
                             if tokens else None),
        'draft_trace_count': spec['draft_trace_count'],
        'verify_trace_count': spec['verify_trace_count'],
    }


def open_loop(engine, queue, rate, n_requests, seed=0,
              max_request_items=None, deadline_s=None,
              result_timeout=30.0, clock=time.monotonic,
              capture_dir=None):
    """Drive ``engine`` through ``queue`` with an open-loop arrival
    process and return the serving report.

    Args:
      rate: offered request rate (req/s); arrivals at ``i / rate``.
      n_requests: total offered requests.
      seed: request-size mix seed (sizes uniform in
        ``[1, max_request_items]``).
      max_request_items: per-request item-count cap (default: half
        the queue's max_batch, so coalescing has something to do).
      deadline_s: per-request deadline; expired requests shed typed.
      result_timeout: drain allowance after the last arrival.
      capture_dir: when set, the telemetry window (events + serve
        histograms) is flushed there -- a capture ``python -m
        chainermn_tpu.telemetry doctor`` can read.

    Returns a dict: offered/admitted/served/shed counts + fractions,
    measured req/s over the serve window, latency and queue-wait
    p50/p99 (ms, from raw-sample histograms), pad-waste fraction,
    bucket hit-rate, and the engine's compile/trace accounting.
    """
    max_items = max_request_items or max(1, queue.max_batch // 2)
    rng = np.random.RandomState(seed)
    sizes = rng.randint(1, max_items + 1,
                        size=n_requests).astype(int)
    item_shape = engine._item_shape
    payload = rng.rand(max_items, *item_shape).astype(np.float32) \
        if np.issubdtype(engine._in_dtype, np.floating) else \
        rng.randint(0, 2, size=(max_items,) + item_shape)

    # latency/wait/pad percentiles come from the telemetry registry;
    # when the caller runs telemetry-free, install an in-memory
    # recorder for the window (the bench skew-capture idiom) so the
    # report never fabricates and never comes back empty-handed
    _installed = None
    if _telemetry.active() is None:
        _installed = _telemetry.enable()
    recorder = _telemetry.active()

    compiles_before = engine.compile_count
    stop = threading.Event()
    worker = threading.Thread(target=engine.run, args=(queue, stop),
                              daemon=True)
    worker.start()

    try:
        admitted, shed_submit = [], 0
        t0 = clock()
        for i, n in enumerate(sizes):
            target = t0 + i / float(rate)
            delay = target - clock()
            if delay > 0:
                time.sleep(delay)
            try:
                admitted.append(queue.submit(
                    payload[:n],
                    deadline=(None if deadline_s is None
                              else clock() + deadline_s)))
            except OverloadError:
                shed_submit += 1
        # drain: wait for every admitted request to resolve (result
        # or typed shed), then stop the worker
        served = shed_deadline = errored = 0
        for req in admitted:
            try:
                req.result(timeout=result_timeout)
                served += 1
            except OverloadError:
                shed_deadline += 1
            except Exception:
                errored += 1
        t1 = clock()
        reg = _telemetry.registry()
    finally:
        stop.set()
        worker.join(timeout=result_timeout)
        queue.close()
        if capture_dir is not None and _telemetry.active() is not None:
            try:
                _telemetry.active().flush(capture_dir)
            except Exception:
                pass  # the report below is the primary artifact
        worst = _worst_request(recorder)
        if _installed is not None:
            _telemetry.disable()
    lat = _hist_summary(reg, 'serve_latency_seconds')
    wait = _hist_summary(reg, 'serve_queue_wait')
    pad = _hist_summary(reg, 'serve_pad_waste')
    st = engine.stats()
    warm = len(st['buckets'])
    wall = max(t1 - t0, 1e-9)
    offered = int(n_requests)
    shed = shed_submit + shed_deadline
    return {
        'offered': offered,
        'offered_rate': float(rate),
        'admitted': len(admitted),
        'served': served,
        'shed_submit': shed_submit,
        'shed_deadline': shed_deadline,
        'errored': errored,
        'shed_fraction': shed / float(offered) if offered else 0.0,
        'served_req_per_s': served / wall,
        'wall_s': wall,
        'latency_p50_ms': (lat.get('p50') or 0.0) * 1e3
        if lat else None,
        'latency_p99_ms': (lat.get('p99') or 0.0) * 1e3
        if lat else None,
        'queue_wait_p50_ms': (wait.get('p50') or 0.0) * 1e3
        if wait else None,
        'queue_wait_p99_ms': (wait.get('p99') or 0.0) * 1e3
        if wait else None,
        'pad_waste_fraction': (pad.get('mean') if pad else None),
        # hit rate: executions that reused an executable compiled
        # BEFORE the traffic window -- with an eager warmup every
        # execution is a hit; a miss means the batcher produced a
        # bucket warmup did not compile (the signature guard refuses
        # shapes outside the edge set entirely)
        'bucket_hit_rate': (
            (st['executions']
             - max(0, st['compile_count'] - compiles_before))
            / float(st['executions']) if st['executions'] else None),
        'buckets_compiled': warm,
        'compile_count': st['compile_count'],
        'trace_count': st['trace_count'],
        'executions': st['executions'],
        'aot': st['aot'],
        'quantized': st['quantized'],
        'worst_request': worst,
    }
