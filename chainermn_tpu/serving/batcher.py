"""Dynamic request batching: coalesce variable-size requests into
padded, power-of-two-bucketed batches.

The jitted/AOT forward executables the engine holds are keyed on
batch SHAPE, so admission must map every traffic pattern onto a small
finite shape set -- that is the whole job of this module:

- **Buckets.**  :func:`bucket_edges` yields power-of-two edges up to
  ``max_batch`` (configurable); :func:`bucket_of` maps an item count
  to the smallest edge that fits.  A request larger than the largest
  edge is a CLIENT error (typed ``ValueError`` at submit, before it
  can occupy queue space it can never leave).
- **Deterministic packing.**  :func:`pack_sizes` packs a drained
  snapshot first-fit-decreasing over a CANONICAL order (size
  descending, arrival sequence among equals).  Grouping therefore
  depends only on the MULTISET of request sizes -- the same mix in
  any arrival order yields identical group sizes, identical bucket
  assignments and identical padded shapes (the no-recompile
  property ``tests/test_serving.py`` pins via the engine's
  SL007-style signature hash).  FFD also happens to be the classic
  low-waste bin packing, so determinism and pad-waste pull the same
  direction.
- **Bounded admission.**  ``max_queue`` items; a submit past it is
  answered NOW with the typed
  :class:`~chainermn_tpu.utils.failure.OverloadError` instead of
  growing an unbounded backlog (overload must degrade, not wedge --
  the chaos ``serve_burst`` site drives this path on purpose).
  Requests carry optional DEADLINES; a request whose deadline passed
  while queued is shed with the same typed error at drain time, not
  executed late for nobody.
- **Admission knobs.**  A drain triggers when ``max_batch`` items
  are waiting or the oldest request has waited ``max_wait`` --
  the latency/throughput trade dial.

Host-side collation reuses the precision layer's
:func:`~chainermn_tpu.training.convert.concat_examples` host-casting
(padding + f32 validity mask, floating columns cast to the policy's
compute dtype BEFORE the device copy).
"""

import itertools
import threading
import time

import numpy as np

from chainermn_tpu import telemetry as _telemetry
from chainermn_tpu.training.convert import concat_examples
from chainermn_tpu.utils import chaos as _chaos
from chainermn_tpu.utils.failure import OverloadError

#: default admission knobs
DEFAULT_MAX_BATCH = 32
DEFAULT_MAX_WAIT = 0.005
DEFAULT_MAX_QUEUE = 256

#: process-wide request-id source shared by every serving queue
#: (batch and generation): the numeric part is the MONOTONIC
#: admission stamp, so ids order by admission across queues
_request_counter = itertools.count(1)


def next_request_id():
    """Process-unique request id (``r<N>``); the counter is shared by
    the batch and generation queues, so the numeric suffix is a
    monotonic admission stamp across the whole serving process --
    what lets a merged capture order requests without a clock."""
    return 'r%d' % next(_request_counter)


def admission_order(request_id):
    """Sort key recovering the monotonic admission stamp from a
    :func:`next_request_id` id (``'r7'`` -> ``(0, 7)``) -- what the
    fleet's exact-replay recovery sorts a dead replica's in-flight
    worklist by, so requeue order is deterministic and matches the
    original admission order regardless of dict/journal iteration
    order.  Foreign ids (not ``r<N>``-shaped) sort after every native
    one, lexicographically."""
    try:
        return (0, int(str(request_id).lstrip('r')))
    except (TypeError, ValueError):
        return (1, str(request_id))


def record_shed(reason, request_id=None, queue_depth=None,
                count_total=True, **attrs):
    """Shed forensics, one call per turned-away request: bump the
    aggregate ``serve_shed_total`` (``count_total=False`` for
    shutdown drains, which the aggregate never counted) plus the
    per-reason ``serve_shed_<reason>_total`` counter, and emit a
    lightweight ``kind='request'`` ``shed`` event carrying the
    request id, the reason, and the queue depth at shed time -- so
    ``report.serve_summary`` shows a shed-reason breakdown and a
    single shed request's trace ends in a named verdict.  Zero-cost
    when telemetry is off; deliberately NO flight dump (sheds fire at
    request rate)."""
    reg = _telemetry.registry()
    if reg is not None:
        if count_total:
            reg.counter('serve_shed_total',
                        help='requests shed by the admission layer '
                             '(queue_full + deadline)').inc()
        reg.counter('serve_shed_%s_total' % reason,
                    help='requests shed with reason=%s' % reason).inc()
    _telemetry.request_event(request_id, 'shed', reason=reason,
                             queue_depth=queue_depth, **attrs)


def bucket_edges(max_batch, base=2):
    """Ascending bucket edges ``base**k`` up to and including
    ``max_batch`` (the top edge is always exactly ``max_batch`` so
    the largest executable matches the admission cap)."""
    if max_batch < 1:
        raise ValueError('max_batch must be >= 1, got %r' % max_batch)
    if base < 2:
        raise ValueError('bucket base must be >= 2, got %r' % base)
    edges, e = [], 1
    while e < max_batch:
        edges.append(e)
        e *= base
    edges.append(max_batch)
    return tuple(edges)


def bucket_of(n, edges):
    """The smallest edge >= ``n``.  ``n`` over the largest edge is a
    typed client error (the request can never be served whole)."""
    if n < 1:
        raise ValueError('request size must be >= 1, got %d' % n)
    for e in edges:
        if n <= e:
            return e
    raise ValueError(
        'request of %d items exceeds the largest bucket %d; split it '
        'client-side or raise max_batch' % (n, edges[-1]))


def pack_sizes(sizes, max_batch, edges):
    """Deterministic first-fit-decreasing packing of request sizes
    into groups of at most ``max_batch`` items (requests never split).

    ``sizes`` is indexable by request position; returns
    ``[(bucket, [positions])]``.  Canonical order -- size descending,
    position ascending among equal sizes -- makes the grouping a pure
    function of the size multiset: identical bucket assignments and
    padded shapes for the same mix in any arrival order."""
    order = sorted(range(len(sizes)), key=lambda i: (-sizes[i], i))
    groups = []  # [(remaining, [positions])]
    for i in order:
        n = sizes[i]
        if n > max_batch:
            raise ValueError(
                'request of %d items exceeds max_batch %d'
                % (n, max_batch))
        for g in groups:
            if g[0] >= n:
                g[0] -= n
                g[1].append(i)
                break
        else:
            groups.append([max_batch - n, [i]])
    return [(bucket_of(max_batch - rem, edges), members)
            for rem, members in groups]


class Request:
    """One in-flight request: payload ``x`` (leading dim = item
    count), optional absolute ``deadline`` (``clock()`` units), and a
    one-shot completion cell the engine fills with the result slice
    or a typed error.  ``request_id`` is the process-unique trace id
    (:func:`next_request_id`); ``t_trace0`` is the admission instant
    on the telemetry recorder's clock (None when telemetry was off at
    admission) -- the t0 of the request's ``queue_wait`` stage span.
    """

    __slots__ = ('x', 'n', 'deadline', 'seq', 't_submit', 'synthetic',
                 'request_id', 't_trace0', '_done', '_result',
                 '_error')

    def __init__(self, x, deadline=None, seq=0, t_submit=0.0,
                 synthetic=False, request_id=None):
        self.x = x
        self.n = int(x.shape[0])
        self.deadline = deadline
        self.seq = seq
        self.t_submit = t_submit
        self.synthetic = synthetic
        self.request_id = request_id or next_request_id()
        rec = _telemetry.active()
        self.t_trace0 = rec.now() if rec is not None else None
        self._done = threading.Event()
        self._result = None
        self._error = None

    def set_result(self, value):
        self._result = value
        self._done.set()

    def set_error(self, exc):
        self._error = exc
        self._done.set()

    def done(self):
        return self._done.is_set()

    def result(self, timeout=None):
        """Block for the response; re-raises the typed shed error."""
        if not self._done.wait(timeout):
            raise TimeoutError('request %d not completed within %rs'
                               % (self.seq, timeout))
        if self._error is not None:
            raise self._error
        return self._result


class PackedBatch:
    """One drained group ready for execution: the member requests (in
    canonical pack order), their total item count, and the bucket the
    padded batch fills."""

    __slots__ = ('requests', 'bucket', 'total', 't_drain')

    def __init__(self, requests, bucket, t_drain):
        self.requests = list(requests)
        self.bucket = int(bucket)
        self.total = sum(r.n for r in self.requests)
        self.t_drain = t_drain

    def collate(self, dtype=None):
        """``(x_padded, mask)``: member payloads stacked row-wise and
        padded to the bucket, floating data cast host-side to
        ``dtype`` (the policy compute dtype) -- the precision layer's
        ``concat_examples`` host-cast reused verbatim.  ``mask`` is
        the f32 validity row mask (padding rows 0)."""
        rows = [row for req in self.requests for row in req.x]
        x, mask = concat_examples(rows, padding=(self.bucket, 0.0),
                                  dtype=dtype)
        return x, mask

    def pad_waste(self):
        """Fraction of the padded batch that is padding."""
        return (self.bucket - self.total) / float(self.bucket)


class RequestQueue:
    """Bounded, deadline-aware coalescing queue (module docstring).

    ``submit`` is the client edge (any thread); ``take`` is the
    engine edge -- it blocks until an admission trigger, drains the
    ENTIRE waiting snapshot and returns it packed into
    :class:`PackedBatch` groups (every drain serves everything that
    was waiting, so canonical pack order cannot starve anyone).
    """

    def __init__(self, max_batch=DEFAULT_MAX_BATCH,
                 max_wait=DEFAULT_MAX_WAIT,
                 max_queue=DEFAULT_MAX_QUEUE, edges=None,
                 clock=time.monotonic, label=None):
        #: fleet replica name; when set, shed forensics carry it so a
        #: per-replica SLO monitor can attribute sheds
        self.label = label
        if max_queue < max_batch:
            raise ValueError('max_queue %d < max_batch %d: the queue '
                             'could never fill one full batch'
                             % (max_queue, max_batch))
        self.max_batch = int(max_batch)
        self.max_wait = float(max_wait)
        self.max_queue = int(max_queue)
        self.edges = tuple(edges) if edges else bucket_edges(max_batch)
        if self.edges[-1] != self.max_batch:
            raise ValueError(
                'largest bucket edge %d must equal max_batch %d'
                % (self.edges[-1], self.max_batch))
        self._clock = clock
        self._cond = threading.Condition()
        self._waiting = []
        self._seq = 0
        self._closed = False
        self.submitted = 0
        self.shed_queue_full = 0
        self.shed_deadline = 0

    # -- client edge ---------------------------------------------------
    def submit(self, x, deadline=None, timeout=None, request_id=None):
        """Enqueue one request (payload leading dim = item count >= 1)
        and return its :class:`Request` handle.

        Raises the typed :class:`OverloadError` when the bounded
        queue is full (``reason='queue_full'``) or the queue is
        closed (``reason='shutdown'``); an over-bucket payload raises
        ``ValueError`` before touching queue state.  The chaos
        ``serve_burst`` site amplifies this submit with synthetic
        copies through the SAME bounded admission.  ``request_id``
        lets an admission front (the fleet) pre-assign the trace id
        it already routed on."""
        x = np.asarray(x)
        if x.ndim < 1:
            x = x[None]
        bucket_of(x.shape[0], self.edges)  # typed oversize reject
        burst = (_chaos.on_serve_submit()
                 if _chaos._active is not None else 0)
        with self._cond:
            req = self._admit(x, deadline, request_id=request_id)
            for _ in range(burst):
                try:
                    self._admit(x, deadline, synthetic=True)
                except OverloadError:
                    break  # burst past capacity sheds; the real
                    # request above was already admitted
            self._cond.notify_all()
        return req

    def _admit(self, x, deadline, synthetic=False, request_id=None):
        if self._closed:
            raise OverloadError('serving queue is shut down',
                                reason='shutdown',
                                queue_depth=len(self._waiting))
        if len(self._waiting) >= self.max_queue:
            self.shed_queue_full += 1
            # the request never existed as an object; the routed id
            # (or a fresh one) still names this rejection
            record_shed('queue_full',
                        request_id=request_id or next_request_id(),
                        queue_depth=len(self._waiting),
                        **self._shed_attrs())
            raise OverloadError(
                'serving queue full (%d waiting requests); retry '
                'with backoff' % len(self._waiting),
                reason='queue_full', queue_depth=len(self._waiting))
        self._seq += 1
        self.submitted += 1
        req = Request(x, deadline=deadline, seq=self._seq,
                      t_submit=self._clock(), synthetic=synthetic,
                      request_id=request_id)
        self._waiting.append(req)
        return req

    def _shed_attrs(self):
        return {'replica': self.label} if self.label else {}

    # -- engine edge ---------------------------------------------------
    def depth(self):
        with self._cond:
            return len(self._waiting)

    def _ready_locked(self, now):
        if not self._waiting:
            return False
        if sum(r.n for r in self._waiting) >= self.max_batch:
            return True
        return (now - self._waiting[0].t_submit) >= self.max_wait

    def take(self, timeout=None):
        """Block until an admission trigger (or ``timeout``), then
        drain the whole waiting snapshot into packed batches.
        Expired-deadline requests are shed typed here -- executing
        them would spend device time on answers nobody waits for.
        Returns ``[]`` on timeout or when closed and drained."""
        deadline = (None if timeout is None
                    else self._clock() + timeout)
        with self._cond:
            while not self._ready_locked(self._clock()):
                if self._closed:
                    break
                wait = None
                if self._waiting:
                    wait = self.max_wait - (
                        self._clock() - self._waiting[0].t_submit)
                if deadline is not None:
                    remaining = deadline - self._clock()
                    if remaining <= 0:
                        return []
                    wait = (remaining if wait is None
                            else min(wait, remaining))
                self._cond.wait(wait if wait is None
                                else max(wait, 1e-4))
            snapshot, self._waiting = self._waiting, []
        now = self._clock()
        live = []
        for req in snapshot:
            if req.deadline is not None and now > req.deadline:
                self.shed_deadline += 1
                record_shed('deadline', request_id=req.request_id,
                            queue_depth=len(snapshot),
                            waited_ms=round(
                                (now - req.t_submit) * 1e3, 3),
                            **self._shed_attrs())
                req.set_error(OverloadError(
                    'deadline expired after %.1f ms in queue'
                    % ((now - req.t_submit) * 1e3), reason='deadline'))
                continue
            live.append(req)
        if not live:
            return []
        packed = pack_sizes([r.n for r in live], self.max_batch,
                            self.edges)
        return [PackedBatch([live[i] for i in members], bucket, now)
                for bucket, members in packed]

    def close(self):
        """Refuse new work and shed everything still waiting
        (``reason='shutdown'``; counted per-reason but NOT in
        ``serve_shed_total``, which stays the overload aggregate)."""
        with self._cond:
            self._closed = True
            pending, self._waiting = self._waiting, []
            self._cond.notify_all()
        for req in pending:
            record_shed('shutdown', request_id=req.request_id,
                        queue_depth=len(pending), count_total=False,
                        **self._shed_attrs())
            req.set_error(OverloadError('serving queue shut down',
                                        reason='shutdown'))

    def stats(self):
        return {'submitted': self.submitted,
                'shed_queue_full': self.shed_queue_full,
                'shed_deadline': self.shed_deadline,
                'depth': self.depth(),
                'edges': list(self.edges)}
