"""Host-side page accounting for the paged KV cache.

The device side is dumb on purpose: a fixed pool of KV pages
(:func:`chainermn_tpu.models.init_paged_kv_cache`) read through
per-sequence page tables
(:func:`chainermn_tpu.ops.flash_attention_decode_paged`).  Everything
that makes paging pay -- allocation, refcounting, prefix sharing,
copy-on-write -- is plain Python here, off the hot path: the scheduler
consults these structures BETWEEN device dispatches and the result is
just int32 page tables.

Three pieces:

- :class:`PagePool` -- free-list allocator over page ids with
  refcounts.  Page 0 is reserved as the SCRATCH page (pad rows and
  idle table entries point there; it is never handed out), so a
  garbage write can never land in live data.
- :class:`RadixPrefixIndex` -- a radix trie over page-sized token
  chunks of completed prompts.  A lookup walks the longest banked
  prefix and returns its pages; N requests sharing a system prompt
  then READ one banked copy, multiplying effective capacity
  (``docs/serving.md``).  The index holds its own reference on every
  banked page; leaves are LRU-evicted when the pool runs dry.
- :func:`prefix_key` -- a stable hash of the shareable (page-aligned)
  prompt prefix, stamped on requests at admission so the scheduler
  can co-admit shared-prefix requests.  It is a pure function of the
  token ids: arrival order can never change it
  (``tests/test_serving.py``).

Write-safety invariant (why decode never needs a copy): a sequence
only ever writes at positions ``>= its admission-time shared prefix``.
The page spanning that boundary is copy-on-write-duplicated ONCE at
admission (:meth:`RadixPrefixIndex.lookup` callers; counted by the
``serve_kv_cow_total`` telemetry counter); every later page is
privately allocated.  A page the index banks from a FINISHED prefill
may keep receiving that sequence's decode tokens, but only at offsets
beyond the indexed ``tail_len`` -- the banked tokens themselves are
immutable.
"""

import binascii

import numpy as np

__all__ = ['PagePool', 'RadixPrefixIndex', 'prefix_key']

SCRATCH_PAGE = 0


def prefix_key(prompt, page_size):
    """Stable key of the shareable prefix of ``prompt``: a CRC32 over
    the page-aligned prefix token ids (the whole prompt when shorter
    than one page -- short prompts still group exact duplicates).

    A pure function of the token values: two requests with the same
    prompt prefix get the same key no matter when or in what order
    they arrive, which is the property the co-admission test pins.
    """
    toks = np.asarray(prompt, np.int32).reshape(-1)
    cut = (toks.size // int(page_size)) * int(page_size)
    if cut == 0:
        cut = toks.size
    return int(binascii.crc32(toks[:cut].tobytes()) & 0xffffffff)


class PagePool:
    """Refcounted free-list allocator over ``n_pages`` page ids.

    Page ids are plain ints; the device-side pool array is indexed by
    them.  ``alloc`` hands out a free page at refcount 1; ``retain``/
    ``release`` move the count; a page returns to the free list when
    its count hits zero.  Page 0 (:data:`SCRATCH_PAGE`) is never
    allocated.
    """

    def __init__(self, n_pages, page_size):
        if n_pages < 2:
            raise ValueError('need at least 2 pages (1 scratch + 1 '
                             'live), got %d' % n_pages)
        if page_size < 1:
            raise ValueError('page_size must be >= 1, got %d'
                             % page_size)
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self._free = list(range(n_pages - 1, 0, -1))   # pop() -> low ids
        self._ref = {}
        self.peak_in_use = 0

    def available(self):
        return len(self._free)

    def in_use(self):
        return len(self._ref)

    def refcount(self, page):
        return self._ref.get(page, 0)

    def alloc(self):
        """One free page at refcount 1, or ``None`` when dry (the
        caller decides between eviction and shedding -- the pool
        itself never blocks)."""
        if not self._free:
            return None
        page = self._free.pop()
        self._ref[page] = 1
        self.peak_in_use = max(self.peak_in_use, len(self._ref))
        return page

    def retain(self, page):
        if page not in self._ref:
            raise ValueError('retain of free page %d' % page)
        self._ref[page] += 1

    def release(self, page):
        count = self._ref.get(page)
        if count is None:
            raise ValueError('release of free page %d' % page)
        if count == 1:
            del self._ref[page]
            self._free.append(page)
        else:
            self._ref[page] = count - 1


class _Node:
    __slots__ = ('children', 'page', 'tails', 'touch')

    def __init__(self, page=None):
        self.children = {}     # page-sized token tuple -> _Node
        self.page = page       # pool page banking this chunk (root: None)
        self.tails = {}        # partial-chunk token tuple -> [page, touch]
        self.touch = 0


class RadixPrefixIndex:
    """Radix trie over page-sized token chunks of banked prompts.

    Each trie edge is one FULL page worth of token ids; the node it
    leads to records the pool page holding that chunk's K/V.  Nodes
    additionally carry ``tails``: banked partial pages (a prompt whose
    length is not page-aligned) keyed by their token suffix.  The
    index owns one reference per banked page (taken at
    :meth:`insert`, dropped at eviction), so a banked page survives
    its sequence and is shared by every later lookup that matches it.

    ``lookup`` returns page ids only -- callers retain what they keep.
    Matching is exact on token ids (the radix property: one walk,
    longest banked prefix wins).
    """

    def __init__(self, pool):
        self.pool = pool
        self._root = _Node()
        self._clock = 0
        self.lookups = 0
        self.hits = 0
        self.tokens_reused = 0

    # -- stats ---------------------------------------------------------
    def hit_rate(self):
        return self.hits / self.lookups if self.lookups else 0.0

    def banked_pages(self):
        n = 0
        stack = [self._root]
        while stack:
            node = stack.pop()
            n += len(node.tails) + sum(
                1 for _ in node.children)
            stack.extend(node.children.values())
        return n

    # -- queries -------------------------------------------------------
    def lookup(self, prompt):
        """Longest banked prefix of ``prompt``.

        Returns ``(pages, tail_page, tail_len)``: ``pages`` are the
        FULL banked pages in position order (``len(pages) *
        page_size`` matched tokens) and ``tail_page`` (or ``None``)
        banks ``tail_len`` further tokens.  No references are taken
        -- the caller retains exactly the pages it keeps.
        """
        ps = self.pool.page_size
        toks = tuple(int(t) for t in np.asarray(prompt).reshape(-1))
        self.lookups += 1
        self._clock += 1
        node, pages = self._root, []
        i = 0
        while i + ps <= len(toks):
            child = node.children.get(toks[i:i + ps])
            if child is None:
                break
            child.touch = self._clock
            pages.append(child.page)
            node = child
            i += ps
        tail_page, tail_len = None, 0
        # longest banked partial page continuing the match
        rest = toks[i:]
        for tail, entry in node.tails.items():
            n = len(tail)
            if n > tail_len and rest[:n] == tail:
                tail_page, tail_len = entry[0], n
        if tail_page is not None:
            node.tails[self._tail_key(node, tail_page)][1] = self._clock
        matched = len(pages) * ps + tail_len
        if matched:
            self.hits += 1
            self.tokens_reused += matched
        return pages, tail_page, tail_len

    @staticmethod
    def _tail_key(node, page):
        for key, entry in node.tails.items():
            if entry[0] == page:
                return key
        raise KeyError(page)

    # -- updates -------------------------------------------------------
    def insert(self, prompt, pages):
        """Bank a finished prompt's pages: ``pages`` cover
        ``ceil(len(prompt) / page_size)`` pages in position order.
        Already-banked chunks keep their existing page (first banking
        wins -- later duplicates are simply not indexed); each NEWLY
        indexed page gains one index-owned reference.
        """
        ps = self.pool.page_size
        toks = tuple(int(t) for t in np.asarray(prompt).reshape(-1))
        self._clock += 1
        node = self._root
        i = 0
        while i + ps <= len(toks):
            chunk = toks[i:i + ps]
            child = node.children.get(chunk)
            if child is None:
                page = pages[i // ps]
                child = _Node(page)
                self.pool.retain(page)
                node.children[chunk] = child
            child.touch = self._clock
            node = child
            i += ps
        rest = toks[i:]
        if rest and rest not in node.tails:
            page = pages[i // ps]
            self.pool.retain(page)
            node.tails[rest] = [page, self._clock]
        elif rest:
            node.tails[rest][1] = self._clock

    def evict(self, n_needed=1):
        """LRU-drop banked leaves until ``n_needed`` pages could be
        freed or nothing evictable remains.  Only drops the INDEX's
        reference -- a page still used by live sequences stays
        allocated (and stays counted in ``in_use``) until they finish.
        Returns the number of references dropped."""
        dropped = 0
        while dropped < n_needed:
            victim = self._lru_leaf()
            if victim is None:
                break
            parent, kind, key, page = victim
            if kind == 'tail':
                del parent.tails[key]
            else:
                del parent.children[key]
            self.pool.release(page)
            dropped += 1
        return dropped

    def _lru_leaf(self):
        best = None
        stack = [(self._root, None, None)]
        while stack:
            node, parent, key = stack.pop()
            for tkey, (page, touch) in node.tails.items():
                if best is None or touch < best[0]:
                    best = (touch, node, 'tail', tkey, page)
            for ckey, child in node.children.items():
                if not child.children and not child.tails:
                    if best is None or child.touch < best[0]:
                        best = (child.touch, node, 'child', ckey,
                                child.page)
                stack.append((child, node, ckey))
        if best is None:
            return None
        return best[1], best[2], best[3], best[4]

    def flush(self):
        """Drop every banked reference (used by tests and by engines
        tearing down)."""
        while self.evict(1):
            pass
