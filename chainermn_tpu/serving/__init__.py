"""Serving subsystem: dynamic request batching over AOT-compiled
forward executables (ROADMAP item 2 -- the repo's first forward-only
request path).

Three cooperating layers (``docs/serving.md``):

- :mod:`~chainermn_tpu.serving.batcher` -- a bounded
  :class:`RequestQueue` that coalesces variable-size requests into
  padded, power-of-two-bucketed batches with deterministic packing,
  deadline tagging and typed
  :class:`~chainermn_tpu.utils.failure.OverloadError` shedding;
- :mod:`~chainermn_tpu.serving.engine` -- an :class:`InferenceEngine`
  holding one pre-lowered executable per bucket
  (``jax.jit(...).lower(...).compile()`` with a persistent
  compilation cache; plain-jit fallback on runtimes without the AOT
  surface), a warmup that compiles all buckets eagerly, an
  SL007-signature no-recompile runtime guard, MeshPlan-sharded and
  int8-quantized (:class:`~chainermn_tpu.precision.Int8Policy`)
  serving modes, and topology-portable parameter loading from
  elastic-resume checkpoints;
- :mod:`~chainermn_tpu.serving.loadgen` -- the synthetic OPEN-loop
  generator behind ``bench.py --serve`` and the tier-1 end-to-end
  proof (overload must shed typed, never wedge);
- :mod:`~chainermn_tpu.serving.generate` -- the AUTOREGRESSIVE path
  (ISSUE 11): a :class:`GenerationEngine` with a slot-addressed,
  bucketed KV cache living across calls, continuous token-level
  batching (a finished or cancelled sequence's slot refills from the
  queue at the next decode step), a prefill/decode AOT split (prefill
  bucketed by prompt length, decode by active-slot count), int8
  KV-cache mode, and the same no-recompile signature guard -- plus
  the PAGED mode: a pooled KV cache addressed through per-sequence
  page tables, radix-trie prompt-prefix sharing with copy-on-write,
  and SARATHI-style chunked prefill interleaved with decode ticks --
  and SPECULATIVE decoding (ISSUE 19): a small draft model
  (``draft_model=``) proposes ``spec_tokens`` tokens per tick, the
  target scores them all in ONE ``spec_verify`` pass, and the engine
  commits the longest draft/target-agreeing prefix plus the target's
  correction token -- exact greedy equivalence with the
  non-speculative oracle, with rollback of slot lengths and paged
  page-table tails to the accepted boundary;
- :mod:`~chainermn_tpu.serving.paged` -- the host-side page
  accounting behind paged mode: a refcounted :class:`PagePool`
  (page 0 reserved scratch), the :class:`RadixPrefixIndex` banking
  completed prompts for cross-request reuse, and the
  :func:`prefix_key` admission stamp;
- :mod:`~chainermn_tpu.serving.fleet` -- train-to-serve CONTINUOUS
  DEPLOYMENT (ISSUE 13): a :class:`FleetController` running N engine
  replicas behind a canary-routing :class:`FleetFront`, watching the
  training checkpoint chain (:class:`CheckpointWatcher`) and rolling
  new weights replica-by-replica without dropping requests -- live
  hot-swap via the engines' double-buffered ``swap_params``, a
  deterministic hash-slice canary judged by per-version SLO monitors
  (:class:`CanaryJudge`), automatic rollback on breach, and an
  append-only ``fleet_ledger.jsonl``.  CLI: ``python -m
  chainermn_tpu.serving.fleet``.  SERVING SELF-HEALING rides the same
  module: a crash-safe fsynced :class:`RequestJournal` records every
  admission and streamed token batch so a dead replica's in-flight
  generations recover by EXACT REPLAY (teacher-forced continuation of
  ``prompt + emitted`` on a survivor, token-for-token identical to the
  uninterrupted run, one seamless :class:`FrontHandle` stream); a
  :class:`ReplicaSupervisor` detects deaths, respawns replacements
  from the incumbent snapshot under the training ``RestartPolicy``
  (crash-loop abort), and drives the typed hysteresis-reversible
  :class:`DegradationPolicy` ladder (none -> evict_prefix -> no_spec
  -> shrink_admission -> shed) off the live SLO verdict and KV-page
  pressure.
"""

from chainermn_tpu.serving.batcher import (  # noqa: F401
    PackedBatch, Request, RequestQueue, admission_order, bucket_edges,
    bucket_of, next_request_id, pack_sizes, record_shed)
from chainermn_tpu.serving.engine import (  # noqa: F401
    InferenceEngine, load_params)
from chainermn_tpu.serving.fleet import (  # noqa: F401
    CanaryJudge, CheckpointWatcher, DegradationPolicy, FleetController,
    FleetFront, FrontHandle, LocalReplica, ReplicaSupervisor,
    RequestJournal, SubprocessReplica, apply_degradation_rung,
    build_local_fleet, canary_slice, local_respawn_fn,
    strip_oneshot_kills)
from chainermn_tpu.serving.generate import (  # noqa: F401
    GenerationEngine, GenerationQueue, GenRequest)
from chainermn_tpu.serving.loadgen import (  # noqa: F401
    open_loop, open_loop_generate)
from chainermn_tpu.serving.paged import (  # noqa: F401
    PagePool, RadixPrefixIndex, prefix_key)
from chainermn_tpu.utils.failure import OverloadError  # noqa: F401
