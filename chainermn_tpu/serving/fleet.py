"""serving.fleet -- train-to-serve continuous deployment (ISSUE 13).

PRs 5/9/10/12 built every ingredient of the loop -- elastic
topology-portable checkpoints, a supervisor that classifies and
restarts, engines that load from checkpoints, an SLO monitor whose
verdict dict was made doctor-shaped for exactly this gate -- but
training and serving were still two CLIs.  This module is the loop
that connects them: a supervisor-sibling that runs N
:class:`~chainermn_tpu.serving.InferenceEngine` /
:class:`~chainermn_tpu.serving.GenerationEngine` replicas behind one
admission front, watches the training checkpoint chain, and rolls new
weights through the fleet replica-by-replica WITHOUT dropping
requests:

1. **watch** (:class:`CheckpointWatcher`): poll
   :func:`~chainermn_tpu.training.recovery.snapshot_chain` through
   :func:`~chainermn_tpu.training.recovery.chain_heads` -- the PR 5
   manifest/sentinel completeness probe drops a sentinel-less newest
   snapshot, an mtime debounce never fires while a file is still
   settling, full crc verification rejects a bit-rotted newest with
   the typed
   :class:`~chainermn_tpu.utils.failure.CheckpointSkippedWarning` and
   falls back to the next-older valid candidate, and one snapshot can
   never fire two rolls;
2. **roll** (:class:`FleetController`): per-replica
   drain -> ``swap_params`` -> rejoin.  The front stops routing to
   the draining replica (its peers absorb the traffic -- nothing is
   shed BECAUSE of the swap), the engine's double-buffered handoff
   holds both parameter versions on device until the validation
   forward passes, and cutover is a pointer rebind under the
   already-compiled bucket executables (``trace_count`` flat: a roll
   never retraces);
3. **canary** (:class:`FleetFront` + :class:`CanaryJudge`): a
   deterministic hash-slice of request ids (:func:`canary_slice`)
   routes to the replica serving the NEW version first; a fresh
   per-(replica, version) :class:`~chainermn_tpu.telemetry.slo.
   SLOMonitor` pair judges the canary live -- the candidate's own
   burn-rate verdict plus TTFT / inter-token / latency / shed-fraction
   DELTAS against the incumbents' matched window;
4. **promote or roll back**: a clean canary window promotes the
   version through the remaining replicas (same drain -> swap ->
   rejoin ladder); a breach swaps the canary straight back to the
   incumbent snapshot and the fleet converges where it was;
5. **record** (:class:`~chainermn_tpu.utils.ledger.Ledger`):
   append-only fsynced ``fleet_ledger.jsonl`` mirroring
   ``supervisor_ledger.jsonl`` -- ``start`` / ``version_seen`` /
   ``roll_start`` / ``replica_swap`` / ``canary_verdict`` /
   ``promote`` / ``rollback`` / ``converged`` / ``complete``.

Chaos: the ``swap_kill`` site (:func:`chainermn_tpu.utils.chaos.
on_swap`) kills the controller at a swap point, leaving replicas on
MIXED versions; a restarted fleet re-reads the ledger, boots every
replica from the newest VALID snapshot and records ``converged`` --
one consistent version, chosen forward (the interrupted roll's
candidate is by construction the newest valid snapshot).  The
``serve_slow`` site models a latency regression shipped by a roll
(engines consult it only on a hot-swapped version), which is what
drives the canary-breach -> rollback scenario end to end.

``python -m chainermn_tpu.serving.fleet`` is the CLI: the default
mode is a self-contained demo/CI harness -- train a tiny
:class:`~chainermn_tpu.models.TransformerLM` for a few real CPU sgd
steps, snapshot with the full manifest discipline, serve open-loop
traffic from N replica SUBPROCESSES (``--replica`` workers speaking
newline-JSON over a local socket), and roll each new snapshot through
the fleet under live traffic.  ``--local`` swaps subprocess replicas
for in-process ones (the tier-1 test path).  See ``docs/serving.md``
("Continuous deployment").
"""

import argparse
import itertools
import json
import os
import socket
import subprocess
import sys
import threading
import time
import warnings
import zlib

import numpy as np

from chainermn_tpu import telemetry as _telemetry
from chainermn_tpu.serving.batcher import (admission_order,
                                           next_request_id, record_shed)
from chainermn_tpu.utils import chaos as _chaos
from chainermn_tpu.utils import failure
from chainermn_tpu.utils.failure import (OverloadError,
                                         ReplicaDeadError,
                                         WeightSwapError)
from chainermn_tpu.utils.ledger import Ledger

LEDGER_NAME = 'fleet_ledger.jsonl'
JOURNAL_NAME = 'request_journal.jsonl'

#: hash-slice resolution: canary fractions are exact to 1/10000
CANARY_MOD = 10000


def canary_slice(request_id, fraction):
    """Deterministic canary admission: True when ``request_id`` falls
    in the first ``fraction`` of the crc32 hash ring.  A request id is
    routed the same way on every evaluation (retries included), two
    fleets given the same ids pick the same slice, and no clock or
    rng is involved -- the property the canary A/B needs to be a
    controlled experiment rather than a coin flip."""
    if fraction <= 0:
        return False
    if fraction >= 1:
        return True
    return (zlib.crc32(str(request_id).encode()) % CANARY_MOD
            < int(fraction * CANARY_MOD))


# ----------------------------------------------------------------------
# the crash-safe request journal (the recovery source)
# ----------------------------------------------------------------------

class RequestJournal:
    """Crash-safe admission journal at the front -- the RECOVERY
    source for exact-replay requeue (the flight-recorder request
    table stays the *forensic* twin).

    One fsynced JSON line per state change, on
    :class:`~chainermn_tpu.utils.ledger.Ledger` underneath, so the
    append-survives-``os._exit`` and torn-tail-tolerant-read
    guarantees are inherited rather than re-implemented:

    - ``admit``: ``request_id``, prompt tokens, ``max_new``, absolute
      deadline (front clock), assigned ``replica``, params
      ``version``;
    - ``token``: the tokens a replica streamed back this scheduler
      tick -- after a death the journal knows each request's
      committed ``prompt + emitted`` prefix, which IS the
      continuation prompt that exact-replay recovery teacher-forces
      into a survivor;
    - ``reassign``: the requeue target after a replica death;
    - ``done``: terminal outcome (``served`` / ``shed`` / ``error``)
      with attribution fields.

    The in-memory mirror answers :meth:`inflight` without re-reading
    the file; :meth:`replay` rebuilds the same mirror from disk --
    what a restarted front would know.
    """

    def __init__(self, path):
        self.path = path
        self._ledger = Ledger(path)
        self._lock = threading.Lock()
        self._live = {}   # request_id -> entry
        self.admitted = 0
        self.completed = 0

    def admit(self, request_id, prompt, max_new_tokens, deadline,
              replica, version):
        toks = [int(t) for t in np.asarray(prompt).reshape(-1)]
        with self._lock:
            self._live[request_id] = {
                'prompt': toks, 'max_new': int(max_new_tokens),
                'deadline': deadline, 'replica': replica,
                'version': version, 'emitted': []}
            self.admitted += 1
        self._ledger.append('admit', request_id=request_id,
                            prompt=toks, max_new=int(max_new_tokens),
                            deadline=deadline, replica=replica,
                            version=version)

    def tokens(self, request_id, tokens):
        """The per-tick ``token`` frame sink (the shape of the
        engines' ``on_token`` callback and of the subprocess stream
        frames, so it plugs into either directly)."""
        toks = [int(t) for t in tokens]
        with self._lock:
            e = self._live.get(request_id)
            if e is None:
                return
            e['emitted'].extend(toks)
        self._ledger.append('token', request_id=request_id,
                            tokens=toks)

    def reassign(self, request_id, replica):
        with self._lock:
            e = self._live.get(request_id)
            if e is not None:
                e['replica'] = replica
        self._ledger.append('reassign', request_id=request_id,
                            replica=replica)

    def done(self, request_id, outcome='served', **fields):
        """Close a request; False when it was already closed -- the
        idempotency guard that makes a requeue racing a late
        completion frame harmless (greedy twins carry identical
        tokens, and only the first closer resolves the handle)."""
        with self._lock:
            if request_id not in self._live:
                return False
            del self._live[request_id]
            self.completed += 1
        self._ledger.append('done', request_id=request_id,
                            outcome=outcome, **fields)
        return True

    def inflight(self, replica=None):
        """Snapshot of the open requests (optionally one replica's)
        -- the requeue worklist at a death."""
        with self._lock:
            return {rid: dict(e, emitted=list(e['emitted']))
                    for rid, e in self._live.items()
                    if replica is None or e['replica'] == replica}

    @staticmethod
    def replay(path):
        """Rebuild the in-flight mirror from disk (torn tails from a
        killed writer skipped, inherited from ``Ledger.read``): what
        a RESTARTED front knows about committed prefixes."""
        live = {}
        for e in Ledger.read(path):
            rid, ev = e.get('request_id'), e.get('event')
            if ev == 'admit':
                live[rid] = {'prompt': list(e.get('prompt') or []),
                             'max_new': e.get('max_new'),
                             'deadline': e.get('deadline'),
                             'replica': e.get('replica'),
                             'version': e.get('version'),
                             'emitted': []}
            elif ev == 'token' and rid in live:
                live[rid]['emitted'].extend(e.get('tokens') or [])
            elif ev == 'reassign' and rid in live:
                live[rid]['replica'] = e.get('replica')
            elif ev == 'done':
                live.pop(rid, None)
        return live


class FrontHandle:
    """The completion handle a journaled front hands out: the same
    ``done()`` / ``result()`` surface as ``GenRequest`` / ``_Cell``,
    but OWNED by the front, so a replica death re-binds it to the
    requeued continuation invisibly -- the caller sees one seamless
    stream (journaled prefix + continuation tokens), never a
    duplicated or dropped token."""

    __slots__ = ('request_id', '_evt', '_tokens', '_error')

    def __init__(self, request_id):
        self.request_id = request_id
        self._evt = threading.Event()
        self._tokens = None
        self._error = None

    def _complete(self, tokens):
        if self._evt.is_set():
            return
        self._tokens = np.asarray([int(t) for t in tokens], np.int32)
        self._evt.set()

    def _fail(self, exc):
        if self._evt.is_set():
            return
        self._error = exc
        self._evt.set()

    def done(self):
        return self._evt.is_set()

    def result(self, timeout=None):
        if not self._evt.wait(timeout):
            raise TimeoutError('request %s not completed within %rs'
                               % (self.request_id, timeout))
        if self._error is not None:
            raise self._error
        return self._tokens


# ----------------------------------------------------------------------
# the load-degradation ladder
# ----------------------------------------------------------------------

#: the ladder's rungs, mildest first.  0 is healthy; 1-3 trade reuse/
#: speculation/admission concurrency for headroom on the ENGINES; 4
#: sheds a deterministic hash-slice of new admissions at the FRONT.
DEGRADATION_RUNGS = ('none', 'evict_prefix', 'no_spec',
                     'shrink_admission', 'shed')


def apply_degradation_rung(engine, rung, saved):
    """Walk one engine's load knobs to degradation rung ``rung``
    (idempotent -- every knob is set to its value AT that rung, so
    skipped intermediate calls cannot leave a stale knob behind).
    ``saved`` is a per-engine dict remembering the healthy values for
    the walk back.  Rungs: 1 evicts the radix prefix index (banked
    pages return to the pool; live sequences keep theirs), 2 disables
    speculative decoding (the target cache stays authoritative, so
    greedy output is unchanged), 3 halves ``spec_tokens`` and caps
    admission at one request per tick.  Rung 4 (shed) is applied at
    the FRONT, not here."""
    if 'speculative' not in saved:
        saved['speculative'] = bool(engine.speculative)
        saved['spec_tokens'] = int(engine.spec_tokens)
        saved['admit_cap'] = engine.admit_cap
    rung = max(0, min(int(rung), len(DEGRADATION_RUNGS) - 1))
    idx = getattr(engine, '_prefix_index', None)
    if rung >= 1 and idx is not None:
        while idx.evict(1):
            pass
    engine.speculative = saved['speculative'] and rung < 2
    if saved['spec_tokens']:
        engine.spec_tokens = (saved['spec_tokens'] if rung < 3
                              else max(2, saved['spec_tokens'] // 2))
    engine.admit_cap = saved['admit_cap'] if rung < 3 else 1
    return rung


class DegradationPolicy:
    """Typed, hysteresis-reversible load-degradation ladder over
    :data:`DEGRADATION_RUNGS`, driven by the live
    :class:`~chainermn_tpu.telemetry.slo.SLOMonitor` burn-rate
    verdict and KV-page pressure.

    Escalation: any observation with an SLO ``breach`` verdict or
    with free KV pages under ``kv_free_floor`` climbs ONE rung.
    Recovery walks back one rung only after ``recover_healthy``
    CONSECUTIVE observations whose verdict is ``ok`` -- the
    multi-window burn-rate verdict is ``ok`` only when both the fast
    and slow windows are healthy, which is the hysteresis that stops
    the ladder from oscillating on the edge of a breach.

    Every transition is a ``degrade`` ledger event and moves the
    ``fleet_degradation_rung`` gauge; per-rung wall-clock occupancy
    is accumulated for the bench sidecars.
    """

    def __init__(self, ledger=None, kv_free_floor=0.125,
                 recover_healthy=2, shed_fraction=0.5,
                 clock=time.monotonic):
        self.ledger = ledger
        self.kv_free_floor = float(kv_free_floor)
        self.recover_healthy = int(recover_healthy)
        self.shed_fraction = float(shed_fraction)
        self._clock = clock
        self.rung = 0
        self.transitions = 0
        self._healthy_streak = 0
        self._t_entered = clock()
        self.occupancy_s = {name: 0.0 for name in DEGRADATION_RUNGS}

    @property
    def rung_name(self):
        return DEGRADATION_RUNGS[self.rung]

    def sheds(self, request_id):
        """At the ``shed`` rung: True for the deterministic
        ``shed_fraction`` hash-slice of request ids (same ring
        discipline as :func:`canary_slice` -- retries of an id are
        shed consistently, and no rng is involved)."""
        if self.rung < len(DEGRADATION_RUNGS) - 1:
            return False
        return (zlib.crc32(('shed:%s' % request_id).encode())
                % CANARY_MOD < int(self.shed_fraction * CANARY_MOD))

    def observe(self, overall, breaches=(), kv_in_use=None,
                kv_total=None):
        """One observation of the live signals.  ``overall`` is the
        worst SLO verdict across serving replicas (``'ok'`` /
        ``'warn'`` / ``'breach'`` / None when monitors are quiet).
        Returns the new rung after a transition, None when the
        ladder did not move."""
        reasons = []
        if overall == 'breach':
            reasons.append('slo_breach:%s'
                           % ','.join(sorted(set(breaches))))
        if kv_total:
            free = (kv_total - (kv_in_use or 0)) / float(kv_total)
            if free < self.kv_free_floor:
                reasons.append('kv_pressure:%.0f%%_free'
                               % (100 * free))
        if reasons:
            self._healthy_streak = 0
            if self.rung < len(DEGRADATION_RUNGS) - 1:
                return self._move(self.rung + 1, 'escalate', reasons)
            return None
        if overall == 'ok':
            self._healthy_streak += 1
            if (self.rung > 0
                    and self._healthy_streak >= self.recover_healthy):
                self._healthy_streak = 0
                return self._move(
                    self.rung - 1, 'recover',
                    ['healthy_windows:%d' % self.recover_healthy])
        return None

    def _move(self, new, direction, reasons):
        now = self._clock()
        old = self.rung
        self.occupancy_s[DEGRADATION_RUNGS[old]] += \
            now - self._t_entered
        self._t_entered = now
        self.rung = new
        self.transitions += 1
        if self.ledger is not None:
            self.ledger.append(
                'degrade', direction=direction, from_rung=old,
                to_rung=new, from_name=DEGRADATION_RUNGS[old],
                to_name=DEGRADATION_RUNGS[new], reasons=reasons)
        reg = _telemetry.registry()
        if reg is not None:
            reg.gauge('fleet_degradation_rung',
                      help='current load-degradation ladder rung '
                           '(0 none .. 4 shed)').set(new)
        return new

    def occupancy(self):
        """Per-rung wall seconds including the currently-open rung --
        the bench sidecar payload."""
        now = self._clock()
        out = dict(self.occupancy_s)
        out[DEGRADATION_RUNGS[self.rung]] += now - self._t_entered
        return {k: round(v, 4) for k, v in out.items()}

    def describe(self):
        return {'rung': self.rung, 'rung_name': self.rung_name,
                'transitions': self.transitions,
                'kv_free_floor': self.kv_free_floor,
                'recover_healthy': self.recover_healthy,
                'shed_fraction': self.shed_fraction,
                'occupancy_s': self.occupancy()}


# ----------------------------------------------------------------------
# checkpoint-chain watching
# ----------------------------------------------------------------------

class CheckpointWatcher:
    """Poll the training checkpoint chain for a NEW snapshot that is
    safe to roll.

    Safety ladder, applied newest-first over
    :func:`~chainermn_tpu.training.recovery.chain_heads`:

    - **completeness** (inherited from ``chain_heads``): sentinel-less
      or zero-byte candidates -- a legacy/foreign file, or a writer
      without the atomic tmp+rename discipline -- are dropped before
      the watcher sees them, falling through to the next-older valid
      snapshot;
    - **mtime debounce**: a candidate fires only after its mtime has
      been STABLE for ``debounce_s`` seconds (an mtime change
      restarts the clock).  While the newest candidate is settling
      the watcher returns None rather than rolling an older one --
      rolling stale weights just to roll sooner is the wrong trade;
    - **crc verification** (``verify=True``): the full PR 5 per-leaf
      probe.  A corrupt newest is rejected ONCE with the typed
      :class:`~chainermn_tpu.utils.failure.CheckpointSkippedWarning`
      (+ a ``checkpoint_skipped`` telemetry event) and the chain
      falls back to the next-older valid candidate;
    - **once**: a returned snapshot advances ``last_iteration``, so
      one snapshot can never double-fire a roll -- and anything at or
      below the returned iteration is permanently out.

    ``start_after`` seeds ``last_iteration`` with the fleet's boot
    snapshot so the boot version is never re-rolled.
    """

    def __init__(self, ckpt_dir, debounce_s=0.3, verify=True,
                 start_after=None, clock=time.monotonic):
        self.ckpt_dir = ckpt_dir
        self.debounce_s = float(debounce_s)
        self.verify = verify
        self.last_iteration = (-1 if start_after is None
                               else int(start_after))
        self._clock = clock
        self._pending = {}    # path -> (mtime, first_seen_t)
        self._rejected = set()

    def poll(self):
        """``(kind, path, iteration)`` of the next snapshot to roll,
        or None (nothing new, still settling, or nothing valid)."""
        from chainermn_tpu import serializers
        from chainermn_tpu.training import recovery
        now = self._clock()
        for kind, path, it, mtime in recovery.chain_heads(
                self.ckpt_dir):
            if it <= self.last_iteration:
                return None   # newest-first: nothing newer exists
            if path in self._rejected:
                continue
            pend = self._pending.get(path)
            if pend is None or pend[0] != mtime:
                # first sight, or the file moved under us: (re)start
                # the debounce clock and WAIT -- never fall back to
                # an older snapshot while a newer one is settling
                self._pending[path] = (mtime, now)
                return None
            if now - pend[1] < self.debounce_s:
                return None
            if self.verify:
                try:
                    serializers.verify_checkpoint(path)
                except failure.CheckpointCorruptError as e:
                    self._rejected.add(path)
                    _telemetry.event('checkpoint_skipped',
                                     kind='checkpoint', path=path,
                                     reason=e.kind)
                    warnings.warn(
                        'fleet watcher: skipping corrupt snapshot %s '
                        '(%s: %s)' % (path, e.kind, e),
                        failure.CheckpointSkippedWarning,
                        stacklevel=2)
                    continue   # fall back to the next-older valid
            self.last_iteration = it
            self._pending.pop(path, None)
            return kind, path, it
        return None


# ----------------------------------------------------------------------
# replicas
# ----------------------------------------------------------------------

def _fresh_monitor(label, version, slos=None):
    """A per-(replica, version) SLO monitor attached to the active
    recorder -- the canary gate's measurement unit.  Filtering on the
    ``replica``/``version`` attrs the engines stamp means a monitor
    created at swap time sees ONLY post-swap traffic of its own
    replica, even on a recorder shared by the whole fleet.  Returns
    None when telemetry is off."""
    from chainermn_tpu.telemetry.slo import SLOMonitor
    rec = _telemetry.active()
    if rec is None:
        return None
    mon = SLOMonitor(
        slos=slos,
        record_filter=lambda r: (r.get('replica') == label
                                 and r.get('version') == version))
    mon.attach(rec)
    return mon


class LocalReplica:
    """One in-process replica: an engine, its own bounded admission
    queue, and a scheduler/worker thread.  The drain/swap surface the
    controller drives is this class's contract (the subprocess twin
    :class:`SubprocessReplica` speaks the same one over a socket):

    - ``state``: ``'serving'`` (front routes to it) or not (the
      controller parked it for a drain/swap);
    - :meth:`drain`: wait until the queue is empty and every admitted
      request has resolved (for a generation engine that includes
      every live cache slot) -- the front stopped routing first, so
      nothing new arrives;
    - :meth:`swap`: the engine's double-buffered
      ``swap_from_checkpoint`` (typed failure leaves the incumbent
      serving);
    - :meth:`reset_slo` / :meth:`slo_eval`: the per-version canary
      monitor.
    """

    def __init__(self, name, engine, max_queue=256, slos=None,
                 clock=time.monotonic):
        from chainermn_tpu.serving.batcher import RequestQueue
        from chainermn_tpu.serving.generate import GenerationQueue
        self.name = name
        self.engine = engine
        engine.label = name
        self.generation = hasattr(engine, 'decode_edges')
        if self.generation:
            self.queue = GenerationQueue(
                engine.max_prompt_len, max_queue=max_queue,
                label=name,
                # paged engines group admissions by radix prefix
                page_size=(engine.page_size
                           if getattr(engine, 'paged', False)
                           else None))
        else:
            self.queue = RequestQueue(max_batch=engine.max_batch,
                                      max_queue=max_queue, label=name)
        self.state = 'serving'
        self.slos = slos
        self._clock = clock
        self._stop = threading.Event()
        self._abort = threading.Event()
        self._thread = None
        self._outstanding = []
        self._monitor = None
        self._degrade_saved = {}

    @property
    def version(self):
        return self.engine.param_version

    def start(self):
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name='fleet-%s' % self.name)
        self._thread.start()
        return self

    def _run(self):
        # ``engine.run`` with an abort hatch: :meth:`kill` must stop
        # the scheduler MID-GENERATION (an unplanned death leaves
        # slots live), which run()'s drain-first exit cannot express
        while not self._abort.is_set():
            worked = self.engine.step(self.queue)
            if not worked:
                if (self._stop.is_set() and self.queue.depth() == 0
                        and not self.engine._slots
                        and not getattr(self.engine, '_prefilling',
                                        ())):
                    return
                time.sleep(0.002)

    def kill(self):
        """Hard-kill the replica in process -- the
        :class:`LocalReplica` twin of a ``replica_kill``'d
        subprocess.  The scheduler stops between ticks (tokens the
        final tick committed were already streamed to ``on_token``,
        so a journaling front's prefix stays exact), then every
        outstanding request resolves with the typed
        :class:`~chainermn_tpu.utils.failure.ReplicaDeadError` --
        exactly what the subprocess front sees at read-loop EOF."""
        self.state = 'dead'
        self._abort.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
        open_reqs = [r for r in self._outstanding if not r.done()]
        rids = [r.request_id for r in open_reqs]
        for req in open_reqs:
            req.set_error(ReplicaDeadError(
                'replica %s killed with %s in flight'
                % (self.name, req.request_id),
                replica=self.name, request_ids=rids))
        self._outstanding = []
        return rids

    def degrade(self, rung):
        """Apply one degradation-ladder rung to the engine (rung 4's
        shed lives at the front)."""
        return apply_degradation_rung(self.engine, rung,
                                      self._degrade_saved)

    def submit(self, *args, deadline=None, request_id=None, **kw):
        if self.state == 'dead':
            raise ReplicaDeadError('replica %s is dead' % self.name,
                                   replica=self.name)
        req = self.queue.submit(*args, deadline=deadline,
                                request_id=request_id, **kw)
        self._outstanding.append(req)
        if len(self._outstanding) > 512:
            self._prune()
        return req

    def _prune(self):
        self._outstanding = [r for r in self._outstanding
                             if not r.done()]

    def inflight(self):
        self._prune()
        return len(self._outstanding)

    def drain(self, timeout):
        """True when the replica went idle inside ``timeout``: queue
        empty, every admitted request resolved, no live cache slots.
        The engine thread keeps running (it idles) -- drain parks the
        WORK, not the machinery."""
        deadline = self._clock() + timeout
        while self._clock() < deadline:
            if (self.queue.depth() == 0 and self.inflight() == 0
                    and not getattr(self.engine, '_slots', None)):
                return True
            time.sleep(0.005)
        return False

    def swap(self, path, version):
        """Hot-swap from ``path``; returns wall seconds.  Typed
        failures (``WeightSwapError`` / ``CheckpointCorruptError``)
        propagate with the incumbent still serving."""
        t0 = time.perf_counter()
        self.engine.swap_from_checkpoint(path, version=version)
        return round(time.perf_counter() - t0, 4)

    def reset_slo(self):
        """Fresh monitor over THIS replica at its CURRENT version
        (call after a swap for the candidate, at roll start for the
        incumbents, so both windows start empty together)."""
        if self._monitor is not None:
            self._monitor.detach()
        self._monitor = _fresh_monitor(self.name, self.version,
                                       slos=self.slos)
        return self._monitor

    def slo_eval(self):
        return (self._monitor.evaluate()
                if self._monitor is not None else None)

    def shed_total(self):
        st = self.queue.stats()
        return st['shed_queue_full'] + st['shed_deadline']

    def stats(self):
        return {'name': self.name, 'state': self.state,
                'version': self.version, 'queue': self.queue.stats(),
                'inflight': self.inflight()}

    def close(self):
        self._stop.set()
        self.queue.close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        if self._monitor is not None:
            self._monitor.detach()
            self._monitor = None

# ----------------------------------------------------------------------
# subprocess replicas: newline-JSON over a local socket
# ----------------------------------------------------------------------

class _Cell:
    """Completion cell for one subprocess-served request (the
    socket-side twin of ``GenRequest``'s result surface).
    ``on_token`` (set at submit when the front journals) receives the
    incremental ``token`` frames the worker streams per scheduler
    tick; the final reply still carries the full token list."""

    __slots__ = ('request_id', '_evt', '_msg', 'on_token')

    def __init__(self, request_id, on_token=None):
        self.request_id = request_id
        self.on_token = on_token
        self._evt = threading.Event()
        self._msg = None

    def _resolve(self, msg):
        self._msg = msg
        self._evt.set()

    def done(self):
        return self._evt.is_set()

    def result(self, timeout=None):
        if not self._evt.wait(timeout):
            raise TimeoutError('request %s not completed within %rs'
                               % (self.request_id, timeout))
        m = self._msg
        if m.get('ok'):
            return np.asarray(m.get('tokens', []), np.int32)
        if m.get('error') == 'OverloadError':
            raise OverloadError(m.get('message', 'request shed'),
                                reason=m.get('reason', 'queue_full'))
        if m.get('error') == 'ReplicaDead':
            raise ReplicaDeadError(
                m.get('message', 'replica dead'),
                replica=m.get('replica'),
                request_ids=m.get('request_ids') or ())
        raise RuntimeError(m.get('message')
                           or 'replica error: %r' % (m,))


def _free_port():
    s = socket.socket()
    s.bind(('127.0.0.1', 0))
    port = s.getsockname()[1]
    s.close()
    return port


class SubprocessReplica:
    """One replica running as a REAL subprocess (``python -m
    chainermn_tpu.serving.fleet --replica``): its own interpreter,
    its own engine, its own telemetry recorder and per-version SLO
    monitor -- the deployment shape the CI leg chaos-tests.  Speaks
    the :class:`LocalReplica` contract over newline-JSON on a local
    socket; the ``CHAINERMN_TPU_CHAOS`` handout (``replica_chaos``)
    is how a scenario ships a ``serve_slow`` regression inside the
    "new build" only.
    """

    def __init__(self, name, proc, sock, version, logf=None):
        self.name = name
        self.proc = proc
        self.state = 'serving'
        self.generation = True
        self._sock = sock
        self._rfile = sock.makefile('r')
        self._wlock = threading.Lock()
        self._pending = {}
        self._ids = itertools.count(1)
        self._version = int(version)
        self._logf = logf
        self._dead = False
        self._reader = threading.Thread(target=self._read_loop,
                                        daemon=True,
                                        name='fleet-rx-%s' % name)
        self._reader.start()

    # -- spawn ---------------------------------------------------------
    @classmethod
    def spawn(cls, name, snapshot, version, out, n_slots=2,
              max_prompt_len=4, max_queue=64, replica_chaos=None,
              env=None, python=None, boot_timeout=240.0,
              engine_args=None, replica_index=None, worker_out=None):
        port = _free_port()
        logdir = os.path.join(out, 'logs')
        os.makedirs(logdir, exist_ok=True)
        logf = open(os.path.join(logdir, '%s.log' % name), 'ab')
        env_base = {k: v for k, v in
                    (os.environ if env is None else env).items()
                    if k not in ('JAX_PLATFORMS', 'XLA_FLAGS',
                                 _chaos.ENV_VAR, _chaos.REPLICA_ENV_VAR,
                                 'CHAINERMN_TPU_TELEMETRY')}
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env_base['PYTHONPATH'] = (
            root + os.pathsep + env_base.get('PYTHONPATH', ''))
        if replica_chaos:
            env_base[_chaos.ENV_VAR] = replica_chaos
        if replica_index is not None:
            # the replica_kill site's membership gate: the handout
            # names WHICH fleet position this worker occupies
            env_base[_chaos.REPLICA_ENV_VAR] = str(int(replica_index))
        argv = [python or sys.executable, '-m',
                'chainermn_tpu.serving.fleet', '--replica',
                '--name', name, '--port', str(port),
                '--snapshot', snapshot, '--version', str(version),
                '--parent-pid', str(os.getpid()),
                '--n-slots', str(n_slots),
                '--max-prompt-len', str(max_prompt_len),
                '--max-queue', str(max_queue)]
        if worker_out:
            # disk-backed telemetry: an in-memory recorder's flight
            # dump is a no-op, and the supervisor's post-mortem
            # quick_verdict needs the dead worker's capture on disk
            argv += ['--worker-out', worker_out]
        for extra in (engine_args or ()):
            argv.append(str(extra))
        proc = subprocess.Popen(argv, env=env_base, stdout=logf,
                                stderr=subprocess.STDOUT)
        deadline = time.monotonic() + boot_timeout
        sock = None
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                logf.close()
                raise RuntimeError(
                    'replica %s died during boot (rc %s); see %s'
                    % (name, proc.returncode,
                       os.path.join(logdir, '%s.log' % name)))
            try:
                sock = socket.create_connection(('127.0.0.1', port),
                                                timeout=2.0)
                # the connect timeout must not become a READ timeout:
                # the reader blocks on this socket for the process's
                # whole life, and an idle gap is not a dead replica
                sock.settimeout(None)
                break
            except OSError:
                time.sleep(0.2)
        if sock is None:
            proc.kill()
            raise TimeoutError('replica %s did not open its port '
                               'within %.0fs' % (name, boot_timeout))
        rep = cls(name, proc, sock, version, logf=logf)
        rep._call('ping', timeout=boot_timeout)  # engine warmed
        return rep

    # -- transport -----------------------------------------------------
    def _read_loop(self):
        try:
            for line in self._rfile:
                try:
                    msg = json.loads(line)
                except ValueError:
                    continue
                if 'token' in msg:
                    # incremental stream frame: the request is still
                    # in flight, so the cell stays pending
                    cell = self._pending.get(msg.get('id'))
                    if cell is not None and cell.on_token is not None:
                        try:
                            cell.on_token(cell.request_id,
                                          msg['token'])
                        except Exception:
                            pass
                    continue
                cell = self._pending.pop(msg.get('id'), None)
                if cell is not None:
                    cell._resolve(msg)
        except Exception:
            pass
        # read-loop EOF IS the positive death signal: resolve every
        # pending request typed, naming the whole in-flight set (the
        # front's requeue worklist travels with the error)
        self._dead = True
        rids = [c.request_id for c in self._pending.values()]
        for cell in list(self._pending.values()):
            cell._resolve({'ok': False, 'error': 'ReplicaDead',
                           'replica': self.name, 'request_ids': rids,
                           'message': 'replica %s connection closed'
                                      % self.name})
        self._pending.clear()

    def _send(self, msg):
        data = (json.dumps(msg) + '\n').encode()
        with self._wlock:
            self._sock.sendall(data)

    def _rpc(self, cmd, on_token=None, rid=None, **fields):
        if self._dead:
            raise ReplicaDeadError('replica %s is dead' % self.name,
                                   replica=self.name)
        mid = next(self._ids)
        cell = _Cell(rid or '%s#%d' % (cmd, mid), on_token=on_token)
        self._pending[mid] = cell
        self._send(dict(fields, id=mid, cmd=cmd))
        return cell

    def _call(self, cmd, timeout=60.0, **fields):
        cell = self._rpc(cmd, **fields)
        if not cell._evt.wait(timeout):
            raise TimeoutError('replica %s: %s timed out after %.0fs'
                               % (self.name, cmd, timeout))
        msg = cell._msg
        if not msg.get('ok'):
            if msg.get('error') == 'ReplicaDead':
                raise ReplicaDeadError(
                    msg.get('message', 'replica dead'),
                    replica=msg.get('replica', self.name),
                    request_ids=msg.get('request_ids') or ())
            raise RuntimeError('replica %s: %s failed: %s'
                               % (self.name, cmd,
                                  msg.get('message') or msg))
        return msg

    # -- the replica contract ------------------------------------------
    @property
    def version(self):
        return self._version

    def submit(self, prompt, max_new_tokens, deadline=None,
               request_id=None, on_token=None):
        # absolute controller-clock deadline -> relative seconds (the
        # worker re-anchors on its own monotonic clock)
        deadline_s = (None if deadline is None
                      else max(0.0, deadline - time.monotonic()))
        try:
            cell = self._rpc(
                'serve', on_token=on_token, rid=request_id,
                prompt=[int(t) for t in np.asarray(prompt).reshape(-1)],
                max_new_tokens=int(max_new_tokens),
                deadline_s=deadline_s, request_id=request_id,
                stream=on_token is not None)
        except ReplicaDeadError:
            raise   # typed: the front decides requeue-or-shed
        except OSError as e:
            self._dead = True
            raise ReplicaDeadError(
                'replica %s write failed: %s' % (self.name, e),
                replica=self.name)
        except Exception as e:
            raise OverloadError('replica %s unavailable: %s'
                                % (self.name, e),
                                reason='no_replica')
        return cell

    def inflight(self):
        return len(self._pending)

    def drain(self, timeout):
        try:
            msg = self._call('drain', timeout=timeout + 10.0,
                             timeout_s=timeout)
            return bool(msg.get('drained'))
        except Exception:
            return False

    def swap(self, path, version):
        msg = self._call('swap', timeout=300.0, path=path,
                         version=int(version))
        if not msg.get('swapped'):
            raise WeightSwapError(msg.get('message')
                                  or 'replica %s refused the swap'
                                  % self.name, version=version)
        self._version = int(version)
        return msg.get('swap_s')

    def reset_slo(self):
        self._call('reset_slo', timeout=30.0)

    def degrade(self, rung):
        """Ship one degradation-ladder rung to the worker engine."""
        try:
            return self._call('degrade', timeout=30.0,
                              rung=int(rung)).get('rung')
        except Exception:
            return None

    def slo_eval(self):
        try:
            return self._call('stats', timeout=30.0).get('slo')
        except Exception:
            return None

    def shed_total(self):
        try:
            q = self._call('stats', timeout=30.0).get('queue') or {}
            return (q.get('shed_queue_full', 0)
                    + q.get('shed_deadline', 0))
        except Exception:
            return 0

    def stats(self):
        try:
            st = self._call('stats', timeout=30.0)
        except Exception:
            st = {'ok': False}
        return dict(st, name=self.name, state=self.state,
                    version=self._version)

    def close(self):
        try:
            self._call('shutdown', timeout=10.0)
        except Exception:
            pass
        try:
            self.proc.terminate()
            self.proc.wait(timeout=10.0)
        except Exception:
            try:
                self.proc.kill()
                self.proc.wait(timeout=5.0)
            except Exception:
                pass
        try:
            self._sock.close()
        except OSError:
            pass
        if self._logf is not None:
            self._logf.close()

# ----------------------------------------------------------------------
# the admission front
# ----------------------------------------------------------------------

class FleetFront:
    """One admission edge over N replicas, with canary routing.

    Routing ladder per request: (1) a fresh
    :func:`~chainermn_tpu.serving.next_request_id` is drawn FIRST so
    the hash-slice decision and the trace id are the same object;
    (2) while a canary is live (``canary_version`` set), ids inside
    the :func:`canary_slice` go to the replicas serving the candidate
    version, everything else to the incumbents; (3) round-robin
    within the chosen group's SERVING replicas; (4) a group emptied
    by a drain falls back to ANY serving replica -- version affinity
    yields to availability, which is precisely why a drain -> swap ->
    rejoin never sheds a request: traffic routes around the parked
    replica instead of queueing on it.  Only a fleet with NOTHING
    serving sheds (typed ``reason='no_replica'``); with N >= 2
    replicas and the one-at-a-time roll ladder, that cannot happen
    mid-roll.
    """

    def __init__(self, replicas, current_version, canary_fraction=0.25,
                 journal=None, clock=time.monotonic):
        self.replicas = list(replicas)
        self.current_version = int(current_version)
        self.canary_version = None
        self.canary_fraction = float(canary_fraction)
        #: :class:`RequestJournal` (None: journaling off, the
        #: zero-overhead default -- submit returns the replica's own
        #: handle and nothing survives a replica death).  With a
        #: journal, submit returns a :class:`FrontHandle` and
        #: :meth:`recover` can requeue a dead replica's in-flight
        #: requests as exact continuations.  Generation replicas
        #: only: the journal streams per-tick tokens.
        self.journal = journal
        #: :class:`DegradationPolicy` whose ``shed`` rung this front
        #: enforces at admission (set by the supervisor)
        self.degradation = None
        self.result_timeout = 120.0
        self._rr = itertools.count()
        self._clock = clock
        self._handles = {}
        self._hlock = threading.Lock()
        self.submitted = 0
        self.routed_canary = 0
        self.shed_no_replica = 0
        self.shed_degraded = 0
        self.recovered_requests = 0

    def by_name(self, name):
        for r in self.replicas:
            if r.name == name:
                return r
        raise KeyError(name)

    def serving(self, version=None):
        return [r for r in self.replicas
                if r.state == 'serving'
                and (version is None or r.version == version)]

    def submit(self, *args, deadline=None, **kw):
        rid = next_request_id()
        if (self.degradation is not None
                and self.degradation.sheds(rid)):
            self.shed_degraded += 1
            record_shed('degraded', request_id=rid)
            raise OverloadError(
                'degradation ladder at shed rung (request %s in the '
                'shed slice)' % rid, reason='degraded')
        to_canary = (self.canary_version is not None
                     and canary_slice(rid, self.canary_fraction))
        handle, admitted = None, False
        while True:
            group = self.serving(self.canary_version if to_canary
                                 else self.current_version)
            if not group:
                group = self.serving()  # availability beats affinity
            if not group:
                self.shed_no_replica += 1
                record_shed('no_replica', request_id=rid)
                if admitted:
                    self.journal.done(rid, outcome='shed',
                                      reason='no_replica')
                    self._drop_handle(rid)
                raise OverloadError(
                    'no serving replica available (all parked)',
                    reason='no_replica')
            r = group[next(self._rr) % len(group)]
            if self.journal is not None:
                if not admitted:
                    handle = FrontHandle(rid)
                    with self._hlock:
                        self._handles[rid] = handle
                    self.journal.admit(rid, args[0], args[1],
                                       deadline, r.name, r.version)
                    admitted = True
                else:
                    self.journal.reassign(rid, r.name)
                kw = dict(kw, on_token=self.journal.tokens)
            try:
                backend = r.submit(*args, deadline=deadline,
                                   request_id=rid, **kw)
            except ReplicaDeadError:
                # positively dead: park it (the supervisor requeues
                # ITS in-flight separately) and re-route this request
                r.state = 'dead'
                continue
            except OverloadError as e:
                if admitted:
                    self.journal.done(rid, outcome='shed',
                                      reason=e.reason)
                    self._drop_handle(rid)
                raise
            break
        self.submitted += 1
        if to_canary and r.version == self.canary_version:
            self.routed_canary += 1
        if self.journal is None:
            return backend
        self._watch(handle, backend, prefix=())
        return handle

    def _drop_handle(self, rid):
        with self._hlock:
            self._handles.pop(rid, None)

    def _watch(self, handle, backend, prefix):
        """Bind ``handle`` to ``backend``'s eventual resolution; a
        typed :class:`ReplicaDeadError` leaves the handle OPEN -- the
        journal still holds the request, and :meth:`recover` re-binds
        it to a continuation on a survivor."""
        rid = handle.request_id
        prefix = [int(t) for t in prefix]

        def wait():
            try:
                toks = backend.result(timeout=self.result_timeout)
            except ReplicaDeadError:
                return
            except OverloadError as e:
                if self.journal.done(rid, outcome='shed',
                                     reason=e.reason):
                    handle._fail(e)
                    self._drop_handle(rid)
            except Exception as e:
                if self.journal.done(rid, outcome='error',
                                     reason=type(e).__name__):
                    handle._fail(e)
                    self._drop_handle(rid)
            else:
                if self.journal.done(rid, outcome='served'):
                    handle._complete(prefix
                                     + [int(t) for t in toks])
                    self._drop_handle(rid)

        threading.Thread(target=wait, daemon=True,
                         name='fleet-front-%s' % rid).start()

    def recover(self, dead, ledger=None):
        """Exact-replay recovery of ``dead``'s journaled in-flight
        requests: each is re-dispatched to a survivor as a
        CONTINUATION -- teacher-forced prefill of ``prompt +
        emitted`` (the existing prefill path; chunked prefill meters
        long continuations), then greedy decode resumes.  Greedy
        determinism makes the continuation token-for-token identical
        to the uninterrupted run; the client's :class:`FrontHandle`
        resolves with journaled prefix + continuation, one seamless
        stream.  Already-expired deadlines shed TYPED with per-request
        attribution, never silently.  Returns ``(requeued_ids,
        shed_ids)``; ``ledger`` (the fleet ledger) gets ``requeue`` /
        ``requeue_shed`` / ``recovered`` events."""
        dead.state = 'dead'
        if self.journal is None:
            return [], []
        work = self.journal.inflight(replica=dead.name)
        requeued, shed, completed = [], [], []
        now = self._clock()
        for rid in sorted(work, key=admission_order):
            e = work[rid]
            with self._hlock:
                handle = self._handles.get(rid)
            if handle is None:
                handle = FrontHandle(rid)
                with self._hlock:
                    self._handles[rid] = handle
            emitted = [int(t) for t in e['emitted']]
            remaining = e['max_new'] - len(emitted)
            if remaining <= 0:
                # fully generated -- only the completion frame died
                # with the replica; the journal already holds every
                # token
                if self.journal.done(rid, outcome='served',
                                     recovered=True):
                    handle._complete(emitted)
                    self._drop_handle(rid)
                self.recovered_requests += 1
                completed.append(rid)
                continue
            if e['deadline'] is not None and now > e['deadline']:
                if self.journal.done(rid, outcome='shed',
                                     reason='deadline',
                                     replica=dead.name):
                    record_shed('deadline', request_id=rid,
                                replica=dead.name, phase='requeue')
                    handle._fail(OverloadError(
                        'deadline of %s expired before requeue '
                        '(died with replica %s)' % (rid, dead.name),
                        reason='deadline'))
                    self._drop_handle(rid)
                if ledger is not None:
                    ledger.append('requeue_shed', request_id=rid,
                                  replica=dead.name,
                                  reason='deadline')
                shed.append(rid)
                continue
            cont = list(e['prompt']) + emitted
            survivors = [r for r in self.serving() if r is not dead]
            backend, target, reason = None, None, 'no_replica'
            while survivors:
                cand = survivors[next(self._rr) % len(survivors)]
                try:
                    backend = cand.submit(
                        np.asarray(cont, np.int32), remaining,
                        deadline=e['deadline'], request_id=rid,
                        on_token=self.journal.tokens)
                except ReplicaDeadError:
                    cand.state = 'dead'
                    survivors = [r for r in survivors
                                 if r is not cand]
                    continue
                except OverloadError as exc:
                    reason = exc.reason
                except ValueError:
                    # continuation longer than the survivor's
                    # max_prompt_len: size recovery scenarios with
                    # max_prompt_len >= prompt + max_new - 1
                    reason = 'continuation_too_long'
                target = cand
                break
            if backend is None:
                if self.journal.done(rid, outcome='shed',
                                     reason=reason,
                                     replica=dead.name):
                    record_shed(reason, request_id=rid,
                                replica=dead.name, phase='requeue')
                    handle._fail(OverloadError(
                        'requeue of %s shed: %s' % (rid, reason),
                        reason=reason))
                    self._drop_handle(rid)
                if ledger is not None:
                    ledger.append('requeue_shed', request_id=rid,
                                  replica=dead.name, reason=reason)
                shed.append(rid)
                continue
            self.journal.reassign(rid, target.name)
            if ledger is not None:
                ledger.append('requeue', request_id=rid,
                              from_replica=dead.name,
                              to_replica=target.name,
                              emitted=len(emitted),
                              remaining=remaining)
            self._watch(handle, backend, prefix=emitted)
            self.recovered_requests += 1
            requeued.append(rid)
        if ledger is not None:
            ledger.append('recovered', replica=dead.name,
                          request_ids=requeued, shed=shed,
                          completed_at_death=completed)
        return requeued, shed

    def shed_total(self):
        return (self.shed_no_replica + self.shed_degraded
                + sum(r.shed_total() for r in self.replicas
                      if r.state != 'dead'))

    def stats(self):
        out = {'submitted': self.submitted,
               'routed_canary': self.routed_canary,
               'shed_no_replica': self.shed_no_replica,
               'shed_degraded': self.shed_degraded,
               'recovered_requests': self.recovered_requests,
               'current_version': self.current_version,
               'canary_version': self.canary_version,
               'replicas': [r.stats() for r in self.replicas]}
        if self.journal is not None:
            out['journal'] = {'admitted': self.journal.admitted,
                              'completed': self.journal.completed,
                              'inflight': len(self.journal.inflight())}
        return out


# ----------------------------------------------------------------------
# the canary judge
# ----------------------------------------------------------------------

class CanaryJudge:
    """Live A/B verdict over per-(replica, version) SLO evaluations.

    Two gates, both required to pass:

    - the candidate's OWN multi-window burn-rate verdict
      (:class:`~chainermn_tpu.telemetry.slo.SLOMonitor`): an absolute
      SLO breach on the canary slice is a breach, full stop;
    - DELTAS against the incumbents' matched window: fast-window p99
      of each latency series (TTFT, inter-token, batch e2e) must stay
      under ``latency_ratio`` x the incumbents' (with an absolute
      ``latency_floor_ms`` so microsecond noise on a fast model can
      never page), and the shed fraction must not exceed the
      incumbents' by more than ``shed_delta``.

    The incumbent baseline is the MAX across incumbent replicas with
    enough data -- deliberately the loosest honest bar, so a noisy
    single incumbent sample cannot fake a regression.  Fewer than
    ``min_events`` fast-window samples on a series keeps that series
    out of the verdict; a window with NO judgeable series is
    ``'pending'`` (the controller's ``promote_on_quiet`` decides what
    a quiet canary means).
    """

    LATENCY_ROWS = ('ttft_p99', 'intertoken_p99', 'latency_p99')

    def __init__(self, latency_ratio=1.5, latency_floor_ms=5.0,
                 shed_delta=0.05, min_events=6):
        self.latency_ratio = float(latency_ratio)
        self.latency_floor_ms = float(latency_floor_ms)
        self.shed_delta = float(shed_delta)
        self.min_events = int(min_events)

    def describe(self):
        return {'latency_ratio': self.latency_ratio,
                'latency_floor_ms': self.latency_floor_ms,
                'shed_delta': self.shed_delta,
                'min_events': self.min_events}

    @staticmethod
    def _fast(row):
        return row.get('fast') or {}

    def judge(self, candidate, incumbents):
        """``{'verdict': 'ok'|'breach'|'pending', 'reasons': [...],
        'deltas': {...}}`` from one candidate evaluation and a list
        of incumbent evaluations (Nones tolerated)."""
        out = {'verdict': 'pending', 'reasons': [], 'deltas': {},
               'candidate_overall': None}
        if not candidate:
            return out
        verdict = candidate.get('verdict') or {}
        out['candidate_overall'] = verdict.get('overall')
        if verdict.get('overall') == 'breach':
            out['reasons'].append(
                'slo_breach:%s' % ','.join(verdict.get('breaches')
                                           or ()))
        rows = candidate.get('slos') or {}
        inc_rows = [(e.get('slos') or {}) for e in incumbents if e]
        judged_any = False
        for name in self.LATENCY_ROWS:
            crow = rows.get(name)
            if not crow:
                continue
            c_p99 = self._fast(crow).get('p99')
            c_n = self._fast(crow).get('count', 0)
            if c_p99 is None or c_n < self.min_events:
                continue
            baselines = []
            for ir in inc_rows:
                irow = ir.get(name)
                if not irow:
                    continue
                i_p99 = self._fast(irow).get('p99')
                if (i_p99 is not None and self._fast(irow).get(
                        'count', 0) >= self.min_events):
                    baselines.append(i_p99)
            if not baselines:
                continue
            judged_any = True
            base = max(baselines)
            out['deltas'][name] = {
                'candidate_p99_ms': round(c_p99 * 1e3, 3),
                'incumbent_p99_ms': round(base * 1e3, 3)}
            if (c_p99 > base * self.latency_ratio
                    and (c_p99 - base) * 1e3 > self.latency_floor_ms):
                out['reasons'].append(
                    '%s:%.1fms vs %.1fms incumbent (%.1fx)'
                    % (name, c_p99 * 1e3, base * 1e3,
                       c_p99 / max(base, 1e-9)))
        crow = rows.get('shed_fraction')
        if crow:
            c_frac = self._fast(crow).get('value') or 0.0
            c_n = self._fast(crow).get('count', 0)
            if c_n >= self.min_events:
                judged_any = True
                bases = [(self._fast(ir['shed_fraction']).get('value')
                          or 0.0)
                         for ir in inc_rows
                         if ir.get('shed_fraction')]
                base = max(bases) if bases else 0.0
                out['deltas']['shed_fraction'] = {
                    'candidate': round(c_frac, 4),
                    'incumbent': round(base, 4)}
                if c_frac - base > self.shed_delta:
                    out['reasons'].append(
                        'shed_fraction:%.1f%% vs %.1f%% incumbent'
                        % (100 * c_frac, 100 * base))
        if out['reasons']:
            out['verdict'] = 'breach'
        elif judged_any:
            out['verdict'] = 'ok'
        return out

# ----------------------------------------------------------------------
# the controller
# ----------------------------------------------------------------------

class FleetController:
    """Watch -> roll -> canary -> promote/rollback -> record, in a
    loop (module docstring).  Owns the append-only
    ``fleet_ledger.jsonl`` and the roll state machine; the front and
    replicas are handed in (built by :func:`build_local_fleet`, the
    CLI, or a test).

    ``boot`` is the ``(path, iteration)`` the replicas were loaded
    from -- the incumbent a breached canary rolls back to until the
    first promote replaces it.
    """

    def __init__(self, front, ckpt_dir, out, boot, watcher=None,
                 judge=None, canary_seconds=4.0, judge_interval=0.4,
                 drain_timeout=60.0, promote_on_quiet=True,
                 poll_interval=0.1, clock=time.monotonic,
                 sleep=time.sleep):
        self.front = front
        self.replicas = front.replicas
        self.ckpt_dir = ckpt_dir
        self.out = out
        self.current_path, self.current_version = boot
        self.current_version = int(self.current_version)
        self.watcher = watcher if watcher is not None else \
            CheckpointWatcher(ckpt_dir,
                              start_after=self.current_version)
        self.judge = judge if judge is not None else CanaryJudge()
        self.canary_seconds = float(canary_seconds)
        self.judge_interval = float(judge_interval)
        self.drain_timeout = float(drain_timeout)
        self.promote_on_quiet = promote_on_quiet
        self.poll_interval = float(poll_interval)
        self._clock = clock
        self._sleep = sleep
        self.ledger = Ledger(os.path.join(out, LEDGER_NAME))
        self.rolling = False
        self.promotes = 0
        self.rollbacks = 0
        self.swap_failures = 0
        self.dropped_during_swap = 0
        self.last_handled_version = None
        self.swap_downtimes = []   # per-replica out-of-rotation secs

    # -- lifecycle -----------------------------------------------------
    def start(self):
        """Append ``start``; when a PRIOR controller died mid-roll
        (a ``roll_start`` with no later ``promote``/``rollback`` in
        the ledger -- the ``swap_kill`` wreckage), record the
        reconciliation: every replica was booted from the newest
        VALID snapshot, so the fleet is already on ONE consistent
        version, and ``converged`` names it plus the roll it
        recovered from."""
        prior = Ledger.read(self.ledger.path)
        open_roll = None
        for e in prior:
            if e.get('event') == 'roll_start':
                open_roll = e
            elif e.get('event') in ('promote', 'rollback'):
                open_roll = None
        self.ledger.append(
            'start', out=self.out, ckpt_dir=self.ckpt_dir,
            version=self.current_version, path=self.current_path,
            replicas=[r.name for r in self.replicas],
            canary_fraction=self.front.canary_fraction,
            judge=self.judge.describe(),
            canary_seconds=self.canary_seconds)
        if open_roll is not None:
            # mixed-version stragglers cannot survive a restart (every
            # replica boots from the newest valid snapshot), so the
            # reconciliation is pure bookkeeping -- but it is the
            # bookkeeping the convergence contract is asserted on
            self._converged(recovered_roll=open_roll.get('version'))
        return self

    def _converged(self, **fields):
        self.ledger.append(
            'converged', version=self.current_version,
            replicas={r.name: r.version for r in self.replicas},
            **fields)

    def tick(self):
        """One watch-and-maybe-roll step; True when a roll ran."""
        cand = self.watcher.poll()
        if cand is None:
            return False
        kind, path, it = cand
        self.roll(kind, path, it)
        return True

    def run(self, stop=None, duration=None):
        """Tick until ``stop`` is set (and/or ``duration`` elapsed)."""
        t_end = (None if duration is None
                 else self._clock() + duration)
        while True:
            if stop is not None and stop.is_set():
                return
            if t_end is not None and self._clock() >= t_end:
                return
            if not self.tick():
                self._sleep(self.poll_interval)

    # -- the roll ladder -----------------------------------------------
    def roll(self, kind, path, version):
        """Roll snapshot ``path`` (iteration = ``version``) through
        the fleet: canary first, judged live, then promote or roll
        back.  Returns True on promote."""
        version = int(version)
        self.rolling = True
        try:
            return self._roll(kind, path, version)
        finally:
            self.rolling = False
            self.last_handled_version = version
            self.front.canary_version = None

    def _roll(self, kind, path, version):
        front = self.front
        canary, incumbents = self.replicas[0], self.replicas[1:]
        self.ledger.append('version_seen', kind=kind, path=path,
                           iteration=version, version=version)
        prev_path, prev_version = (self.current_path,
                                   self.current_version)
        self.ledger.append(
            'roll_start', version=version, from_version=prev_version,
            canary=canary.name,
            replicas=[r.name for r in self.replicas],
            canary_fraction=front.canary_fraction)
        if not self._swap_replica(canary, path, version,
                                  roll_version=version):
            self.ledger.append('rollback', version=version,
                               to_version=prev_version,
                               reason='canary_swap_failed')
            self.rollbacks += 1
            self._converged()
            return False
        # canary admission ON: fresh matched SLO windows on both arms
        canary.reset_slo()
        for r in incumbents:
            r.reset_slo()
        front.canary_version = version
        verdict = self._canary_window(canary, incumbents)
        self.ledger.append(
            'canary_verdict', version=version,
            verdict=verdict['verdict'], reasons=verdict['reasons'],
            deltas=verdict['deltas'],
            candidate_overall=verdict.get('candidate_overall'),
            routed_canary=front.routed_canary)
        if verdict['verdict'] == 'breach' or (
                verdict['verdict'] == 'pending'
                and not self.promote_on_quiet):
            front.canary_version = None
            ok = self._swap_replica(canary, prev_path, prev_version,
                                    roll_version=version,
                                    rollback=True)
            self.ledger.append(
                'rollback', version=version, to_version=prev_version,
                reason=('; '.join(verdict['reasons'])
                        or 'quiet canary (promote_on_quiet=False)'),
                swap_ok=ok)
            self.rollbacks += 1
            self._converged()
            return False
        # promote: the same ladder through the remaining replicas
        for r in incumbents:
            if self._swap_replica(r, path, version,
                                  roll_version=version):
                continue
            # a mid-promote swap failure: converge BACKWARD -- swap
            # every already-promoted replica (canary included) back
            front.canary_version = None
            for rr in self.replicas:
                if rr.version == version:
                    self._swap_replica(rr, prev_path, prev_version,
                                       roll_version=version,
                                       rollback=True)
            self.ledger.append(
                'rollback', version=version, to_version=prev_version,
                reason='replica %s swap failed mid-promote' % r.name)
            self.rollbacks += 1
            self._converged()
            return False
        self.current_path, self.current_version = path, version
        front.current_version = version
        front.canary_version = None
        self.promotes += 1
        self.ledger.append('promote', version=version,
                           from_version=prev_version)
        self._converged()
        return True

    def _canary_window(self, canary, incumbents):
        """Poll the judge every ``judge_interval`` for
        ``canary_seconds``; a breach returns IMMEDIATELY (the canary
        slice stops bleeding at detection, not at window end)."""
        t_end = self._clock() + self.canary_seconds
        verdict = {'verdict': 'pending', 'reasons': [], 'deltas': {},
                   'candidate_overall': None}
        while True:
            self._sleep(self.judge_interval)
            evals = [r.slo_eval() for r in incumbents]
            verdict = self.judge.judge(canary.slo_eval(),
                                       [e for e in evals if e])
            if verdict['verdict'] == 'breach':
                return verdict
            if self._clock() >= t_end:
                return verdict

    def _swap_replica(self, r, path, version, roll_version,
                      rollback=False):
        """drain -> swap -> rejoin for one replica, ledgered.  The
        ``swap_kill`` chaos point sits at the TOP: a fired site dies
        before this swap, leaving every prior ledger entry fsynced --
        the mid-roll wreckage the restart-convergence test replays.
        Returns True when the replica now serves ``version``."""
        if _chaos._active is not None:
            _chaos.on_swap(phase='rollback' if rollback else 'roll')
        shed0 = r.shed_total()
        old_version = r.version
        r.state = 'draining'   # the front routes around it from here
        t0 = self._clock()
        drained = r.drain(self.drain_timeout)
        t_drained = self._clock()
        r.state = 'swapping'
        err, swap_s = None, None
        try:
            swap_s = r.swap(path, version)
        except (WeightSwapError, failure.CheckpointCorruptError,
                RuntimeError, TimeoutError) as e:
            err = '%s: %s' % (type(e).__name__, e)
        r.state = 'serving'   # at the new version, or still the old
        t_back = self._clock()
        shed = r.shed_total() - shed0
        self.dropped_during_swap += shed
        if err is not None:
            self.swap_failures += 1
        else:
            self.swap_downtimes.append(t_back - t0)
        self.ledger.append(
            'replica_swap', roll_version=roll_version,
            replica=r.name, from_version=old_version,
            to_version=(version if err is None else old_version),
            ok=err is None, error=err, rollback=rollback,
            drained=drained, drain_s=round(t_drained - t0, 4),
            swap_s=swap_s,
            out_of_rotation_s=round(t_back - t0, 4),
            shed_during_swap=shed)
        return err is None

    # -- teardown ------------------------------------------------------
    def complete(self, **fields):
        """Final accounting entry (the CLI's exit record)."""
        return self.ledger.append(
            'complete', version=self.current_version,
            promotes=self.promotes, rollbacks=self.rollbacks,
            swap_failures=self.swap_failures,
            dropped_during_swap=self.dropped_during_swap,
            front=self.front.stats(), **fields)

    def close(self):
        for r in self.replicas:
            try:
                r.close()
            except Exception:
                pass

# ----------------------------------------------------------------------
# the replica supervisor: detect -> requeue -> respawn -> degrade
# ----------------------------------------------------------------------

def strip_oneshot_kills(spec, site='replica_kill'):
    """Drop one-shot ``@``-scheduled ``site`` rules from a chaos spec
    handout (keep ``p`` and ``*`` rules).  A respawned worker's
    occurrence counters restart at zero, so handing it the original
    ``replica_kill=@N`` rule would re-fire the already-consumed kill
    on every respawn -- while a ``*`` rule SHOULD keep firing: that
    is the crash-loop the restart policy must abort on."""
    if not spec:
        return spec
    kept = []
    for item in str(spec).split(';'):
        item = item.strip()
        if not item:
            continue
        name, _, rhs = item.partition('=')
        if name.strip() == site and rhs.strip().startswith('@'):
            continue
        kept.append(item)
    return ';'.join(kept)


class ReplicaSupervisor:
    """Fleet-level self-healing loop -- the serving twin of the
    training supervisor.  One :meth:`check` pass:

    1. **detect**: a subprocess replica whose process exited or whose
       read loop hit EOF, or a :class:`LocalReplica` marked ``dead``
       (by :meth:`LocalReplica.kill` or a typed submit failure);
    2. **classify + record**: ``classify_exit`` on the worker's
       returncode, the dead worker's flight dump read through the
       doctor's ``quick_verdict`` (when workers capture to disk via
       ``--worker-out``), a ``replica_dead`` ledger event naming
       every in-flight request id;
    3. **requeue**: :meth:`FleetFront.recover` -- exact-replay
       continuations on survivors, per-request attribution;
    4. **decide**: the training-side
       :class:`~chainermn_tpu.training.supervisor.RestartPolicy`
       (crash-loop window, restart budget,
       :class:`~chainermn_tpu.utils.failure.Backoff` pacing).  A
       crash loop (``replica_kill=*`` on every respawn) ABORTS typed
       instead of burning the budget;
    5. **respawn**: ``spawn_fn(name, path, version, index)`` boots a
       replacement from the controller's incumbent snapshot (the
       newest valid rolled head of the ``CheckpointWatcher`` /
       ``chain_heads`` chain) and splices it into the front at the
       dead replica's slot -- ``respawn`` ledger event, fresh name.

    The same loop drives the :class:`DegradationPolicy` from the live
    per-replica SLO verdicts and KV-page pressure (``degrade_interval``
    cadence), applying rungs 0-3 to every serving engine; rung 4's
    shed is enforced by the front itself.
    """

    def __init__(self, controller, spawn_fn=None, policy=None,
                 degradation=None, poll_interval=0.15,
                 degrade_interval=0.5, worker_out=None,
                 clock=time.monotonic):
        from chainermn_tpu.training.supervisor import RestartPolicy
        self.controller = controller
        self.front = controller.front
        self.ledger = controller.ledger
        self.spawn_fn = spawn_fn
        self.policy = policy if policy is not None else RestartPolicy(
            max_restarts=8, crash_window=120.0, crash_threshold=3,
            shrink_causes=(),   # serving never shrinks: respawn or abort
            backoff=failure.Backoff(initial=0.2, factor=2.0,
                                    max_delay=2.0))
        self.degradation = degradation
        if degradation is not None:
            if degradation.ledger is None:
                degradation.ledger = self.ledger
            self.front.degradation = degradation
        self.poll_interval = float(poll_interval)
        self.degrade_interval = float(degrade_interval)
        self.worker_out = worker_out
        self._clock = clock
        self._stop = threading.Event()
        self._thread = None
        self._handled = set()
        self._respawn_gen = {}
        self._t_next_degrade = 0.0
        self.deaths = 0
        self.respawns = 0
        self.requeued = []
        self.shed = []
        self.aborted = False
        self.abort_reason = None

    # -- lifecycle -----------------------------------------------------
    def start(self):
        self._thread = threading.Thread(target=self._loop,
                                        daemon=True,
                                        name='fleet-supervisor')
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.check()
            except Exception:
                pass
            if self.aborted:
                return
            self._stop.wait(self.poll_interval)

    # -- one pass ------------------------------------------------------
    @staticmethod
    def _is_dead(r):
        if getattr(r, 'state', None) == 'dead':
            return True
        proc = getattr(r, 'proc', None)
        if proc is not None and (proc.poll() is not None
                                 or getattr(r, '_dead', False)):
            return True
        return False

    def check(self):
        """One detect/requeue/respawn/degrade pass (tests call this
        directly for determinism; :meth:`start` polls it)."""
        for idx, r in enumerate(list(self.front.replicas)):
            if r.name in self._handled or self.aborted:
                continue
            if self._is_dead(r):
                self._handle_death(idx, r)
        self._drive_degradation()
        return {'deaths': self.deaths, 'respawns': self.respawns,
                'aborted': self.aborted}

    def _quick_verdict(self, r):
        if not self.worker_out:
            return None
        try:
            from chainermn_tpu.telemetry.diagnosis import quick_verdict
            v = quick_verdict(os.path.join(self.worker_out, r.name))
            if not v:
                return None
            return {'verdict': v.get('verdict'),
                    'causes': v.get('causes')}
        except Exception:
            return None

    def _handle_death(self, idx, r):
        self._handled.add(r.name)
        self.deaths += 1
        r.state = 'dead'
        proc = getattr(r, 'proc', None)
        rc = None
        if proc is not None:
            # the journal's committed prefix is only final at read-loop
            # EOF: frames already in the socket buffer land before it,
            # so wait for the reader before computing the worklist
            t_end = time.monotonic() + 5.0
            while (not getattr(r, '_dead', False)
                   and time.monotonic() < t_end):
                time.sleep(0.01)
            try:
                rc = proc.wait(timeout=10.0)
            except Exception:
                rc = proc.returncode
        exit_kind = (failure.classify_exit(rc)
                     if rc is not None else 'killed')
        inflight = sorted(
            self.front.journal.inflight(replica=r.name)
            if self.front.journal is not None else (),
            key=admission_order)
        self.ledger.append(
            'replica_dead', replica=r.name, returncode=rc,
            exit=exit_kind, request_ids=inflight,
            quick_verdict=self._quick_verdict(r))
        requeued, shed = self.front.recover(r, ledger=self.ledger)
        self.requeued.extend(requeued)
        self.shed.extend(shed)
        try:
            r.close()
        except Exception:
            pass
        cause = 'crash' if rc is not None else 'killed'
        decision = self.policy.on_failure(
            cause, nprocs=len(self.front.serving()) + 1)
        if decision.action == 'abort':
            self.aborted = True
            self.abort_reason = decision.reason
            self.ledger.append('abort', replica=r.name,
                               reason=decision.reason,
                               restarts=self.policy.restarts)
            return
        if self.spawn_fn is None:
            return   # requeue-only mode: survivors absorb the load
        if decision.delay:
            self._stop.wait(decision.delay)
        gen = self._respawn_gen.get(idx, 0) + 1
        self._respawn_gen[idx] = gen
        name = 'replica-%dr%d' % (idx, gen)
        try:
            replacement = self.spawn_fn(
                name=name, path=self.controller.current_path,
                version=self.controller.current_version, index=idx)
        except Exception as e:
            self.ledger.append('respawn_failed', replica=name,
                               replaces=r.name, error=str(e))
            return
        self.front.replicas[idx] = replacement
        self.respawns += 1
        self.policy.on_success()   # healthy boot: backoff resets
        self.ledger.append(
            'respawn', replica=name, replaces=r.name,
            version=self.controller.current_version,
            path=self.controller.current_path,
            delay_s=round(decision.delay, 4),
            restarts=self.policy.restarts)

    # -- degradation driving -------------------------------------------
    def _drive_degradation(self):
        pol = self.degradation
        if pol is None:
            return
        now = self._clock()
        if now < self._t_next_degrade:
            return
        self._t_next_degrade = now + self.degrade_interval
        order = {'ok': 0, 'warn': 1, 'breach': 2}
        worst, breaches = None, []
        kv_used = kv_total = 0
        for r in self.front.serving():
            try:
                ev = r.slo_eval()
            except Exception:
                ev = None
            if ev:
                verdict = ev.get('verdict') or {}
                o = verdict.get('overall')
                if o in order and (worst is None
                                   or order[o] > order[worst]):
                    worst = o
                if o == 'breach':
                    breaches.extend(verdict.get('breaches') or ())
            eng = getattr(r, 'engine', None)
            if eng is not None and getattr(eng, 'pool',
                                           None) is not None:
                kv_used += eng.pool.in_use()
                kv_total += eng.n_pages
        moved = pol.observe(worst, breaches=breaches,
                            kv_in_use=kv_used or None,
                            kv_total=kv_total or None)
        if moved is not None:
            for r in self.front.serving():
                try:
                    r.degrade(min(moved, 3))
                except Exception:
                    pass

    def describe(self):
        out = {'deaths': self.deaths, 'respawns': self.respawns,
               'requeued': sorted(self.requeued,
                                  key=admission_order),
               'shed': sorted(self.shed,
                              key=admission_order),
               'aborted': self.aborted,
               'abort_reason': self.abort_reason,
               'policy': self.policy.describe()}
        if self.degradation is not None:
            out['degradation'] = self.degradation.describe()
        if self.front.journal is not None:
            out['lost_requests'] = len(self.front.journal.inflight())
        return out


# ----------------------------------------------------------------------
# the built-in demo: a tiny LM trained for real, served for real
# ----------------------------------------------------------------------

#: demo TransformerLM geometry -- small enough that a replica boots
#: (imports jax, compiles every prefill/decode bucket) in seconds on
#: CPU, real enough that the whole train->snapshot->roll->serve loop
#: runs genuine sgd steps and genuine generation
DEMO_MODEL = dict(vocab_size=32, d_model=16, n_heads=2, n_layers=1,
                  d_ff=32, max_len=32)
DEMO_SEED = 0


def demo_model():
    import jax.numpy as jnp

    from chainermn_tpu.models import TransformerLM
    return TransformerLM(dtype=jnp.float32, **DEMO_MODEL)


def demo_params(seed=DEMO_SEED):
    """``(model, params)`` -- the deterministic init every fleet
    process (trainer, controller, replica workers) shares, so a
    snapshot's shape template never has to travel."""
    import jax
    import jax.numpy as jnp
    model = demo_model()
    params = model.init(jax.random.PRNGKey(seed),
                        jnp.zeros((1, 4), jnp.int32))['params']
    return model, params


def demo_train(ckpt_dir, steps, snapshot_every, lr=0.05,
               data_seed=1234):
    """Real next-token sgd on the demo LM, continuing from the newest
    valid snapshot under ``ckpt_dir`` (fresh init otherwise), writing
    a manifest-tagged ``snapshot_iter_<it>.npz`` every
    ``snapshot_every`` steps -- the train half of train-to-serve.
    Returns the list of snapshot paths written."""
    import jax
    import jax.numpy as jnp
    import optax

    from chainermn_tpu import serializers
    from chainermn_tpu.serving.engine import load_params
    from chainermn_tpu.training import recovery

    model, params = demo_params()
    _, _, start_it = recovery.latest_snapshot(ckpt_dir)
    if start_it is None:
        start_it = 0
    else:
        _, path, _ = recovery.latest_snapshot(ckpt_dir)
        params = load_params(path, params)
    rng = np.random.RandomState(data_seed)
    toks = jnp.asarray(rng.randint(
        0, DEMO_MODEL['vocab_size'], size=(8, 12)), jnp.int32)

    def loss_fn(p):
        logits = model.apply({'params': p}, toks[:, :-1])
        logp = jax.nn.log_softmax(logits)
        ll = jnp.take_along_axis(logp, toks[:, 1:, None], axis=-1)
        return -jnp.mean(ll)

    opt = optax.sgd(lr)
    state = opt.init(params)

    @jax.jit
    def step(p, s):
        loss, grads = jax.value_and_grad(loss_fn)(p)
        updates, s = opt.update(grads, s, p)
        return optax.apply_updates(p, updates), s, loss

    written = []
    os.makedirs(ckpt_dir, exist_ok=True)
    for it in range(start_it + 1, start_it + steps + 1):
        params, state, _loss = step(params, state)
        if it % snapshot_every == 0 or it == start_it + steps:
            written.append(serializers.save_npz(
                os.path.join(ckpt_dir, 'snapshot_iter_%d' % it),
                {'params': jax.device_get(params)}))
    return written


def build_local_fleet(ckpt_dir, out, n_replicas=2, n_slots=2,
                      max_prompt_len=4, max_queue=64, slos=None,
                      canary_fraction=0.25, engine_kw=None,
                      journal=False, warmup=True, **controller_kw):
    """An in-process demo-LM fleet booted from the newest VALID
    snapshot under ``ckpt_dir`` -- the tier-1 test and bench-arm
    path (the CLI's default is subprocess replicas).  Returns the
    started :class:`FleetController`.  ``journal=True`` arms the
    crash-safe :class:`RequestJournal` (``OUT/request_journal.jsonl``)
    so a :class:`ReplicaSupervisor` can exact-replay-recover a dead
    replica's in-flight generations.  ``warmup=False`` skips the
    eager full-bucket-family compile and lets each executable
    compile on first use (tests that only touch a few buckets)."""
    from chainermn_tpu.serving.generate import GenerationEngine
    from chainermn_tpu.training import recovery
    kind, path, it = recovery.latest_snapshot(ckpt_dir)
    if path is None:
        raise ValueError('no valid snapshot under %r to boot the '
                         'fleet from' % ckpt_dir)
    model, template = demo_params()
    replicas = []
    for i in range(n_replicas):
        name = 'replica-%d' % i
        eng = GenerationEngine.from_checkpoint(
            path, model, template, n_slots=n_slots,
            max_prompt_len=max_prompt_len, label=name, version=it,
            **(engine_kw or {}))
        if warmup:
            eng.warmup()
        replicas.append(LocalReplica(name, eng, max_queue=max_queue,
                                     slos=slos).start())
    front = FleetFront(
        replicas, current_version=it,
        canary_fraction=canary_fraction,
        journal=(RequestJournal(os.path.join(out, JOURNAL_NAME))
                 if journal else None))
    return FleetController(front, ckpt_dir, out, boot=(path, it),
                           **controller_kw)

def local_respawn_fn(n_slots=2, max_prompt_len=4, max_queue=64,
                     slos=None, engine_kw=None, warmup=True):
    """A ``spawn_fn`` for :class:`ReplicaSupervisor` over IN-PROCESS
    replicas (the tier-1/bench twin of ``SubprocessReplica.spawn``):
    boots a fresh demo engine from the incumbent snapshot and starts
    a :class:`LocalReplica` under the replacement name."""
    from chainermn_tpu.serving.generate import GenerationEngine
    model, template = demo_params()

    def spawn_fn(name, path, version, index):
        eng = GenerationEngine.from_checkpoint(
            path, model, template, n_slots=n_slots,
            max_prompt_len=max_prompt_len, label=name,
            version=version, **(engine_kw or {}))
        if warmup:
            eng.warmup()
        return LocalReplica(name, eng, max_queue=max_queue,
                            slos=slos).start()

    return spawn_fn


# ----------------------------------------------------------------------
# replica worker (the --replica subprocess)
# ----------------------------------------------------------------------

def _watch_parent(ppid):
    while True:
        if os.getppid() != ppid:
            os._exit(0)   # orphaned by a dead controller: leave
        time.sleep(0.5)


def _replica_main(args):
    """The ``--replica`` worker: boot the demo engine from
    ``--snapshot``, warm up, then serve newline-JSON commands from
    the controller over ``--port`` (serve / drain / swap /
    reset_slo / stats / ping / shutdown).  Chaos comes from the
    ``CHAINERMN_TPU_CHAOS`` handout (the ``serve_slow``-on-swapped
    regression lives HERE, in the replica's own process), telemetry
    is an in-memory recorder feeding the per-version SLO monitor the
    controller polls through ``stats``."""
    os.environ['JAX_PLATFORMS'] = 'cpu'
    import jax
    jax.config.update('jax_platforms', 'cpu')
    from chainermn_tpu.serving.generate import (GenerationEngine,
                                                GenerationQueue)
    _chaos.maybe_install_from_env()
    # --worker-out: capture to disk so a chaos kill's pre-exit flight
    # dump survives for the supervisor's post-mortem quick_verdict
    # (an in-memory recorder's dump_flight is a no-op)
    _telemetry.enable(outdir=args.worker_out or None)
    if args.parent_pid:
        threading.Thread(target=_watch_parent,
                         args=(args.parent_pid,),
                         daemon=True).start()
    model, template = demo_params()
    engine = GenerationEngine.from_checkpoint(
        args.snapshot, model, template, n_slots=args.n_slots,
        max_prompt_len=args.max_prompt_len, label=args.name,
        version=args.version)
    engine.warmup()
    queue = GenerationQueue(args.max_prompt_len,
                            max_queue=args.max_queue,
                            label=args.name)
    stop = threading.Event()
    threading.Thread(target=engine.run, args=(queue, stop),
                     daemon=True).start()
    monitor = [_fresh_monitor(args.name, engine.param_version)]

    srv = socket.create_server(('127.0.0.1', args.port))
    conn, _addr = srv.accept()
    rfile = conn.makefile('r')
    wlock = threading.Lock()
    outstanding = [0]
    olock = threading.Lock()
    degrade_saved = {}

    def reply(obj):
        with wlock:
            conn.sendall((json.dumps(obj) + '\n').encode())

    def handle_serve(msg):
        mid = msg.get('id')
        on_token = None
        if msg.get('stream'):
            # incremental token frames per scheduler tick: the
            # journaling front's committed-prefix feed
            def on_token(_rid, toks):
                reply({'id': mid, 'token': toks})
        try:
            dl = (None if msg.get('deadline_s') is None
                  else time.monotonic() + float(msg['deadline_s']))
            req = queue.submit(msg['prompt'], msg['max_new_tokens'],
                               deadline=dl,
                               request_id=msg.get('request_id'),
                               on_token=on_token)
        except OverloadError as e:
            reply({'id': mid, 'ok': False, 'error': 'OverloadError',
                   'reason': e.reason, 'message': str(e)})
            return
        except Exception as e:
            reply({'id': mid, 'ok': False,
                   'error': type(e).__name__, 'message': str(e)})
            return

        def wait_result():
            try:
                toks = req.result(
                    timeout=msg.get('result_timeout', 120.0))
                reply({'id': mid, 'ok': True,
                       'tokens': [int(t) for t in toks]})
            except OverloadError as e:
                reply({'id': mid, 'ok': False,
                       'error': 'OverloadError', 'reason': e.reason,
                       'message': str(e)})
            except Exception as e:
                reply({'id': mid, 'ok': False,
                       'error': type(e).__name__, 'message': str(e)})
            finally:
                with olock:
                    outstanding[0] -= 1

        with olock:
            outstanding[0] += 1
        threading.Thread(target=wait_result, daemon=True).start()

    def drained():
        with olock:
            busy = outstanding[0]
        return (busy == 0 and queue.depth() == 0
                and not engine._slots)

    for line in rfile:
        try:
            msg = json.loads(line)
        except ValueError:
            continue
        cmd, mid = msg.get('cmd'), msg.get('id')
        if cmd == 'serve':
            handle_serve(msg)
        elif cmd == 'ping':
            reply({'id': mid, 'ok': True,
                   'version': engine.param_version})
        elif cmd == 'drain':
            deadline = time.monotonic() + float(
                msg.get('timeout_s', 30.0))
            while time.monotonic() < deadline and not drained():
                time.sleep(0.005)
            reply({'id': mid, 'ok': True, 'drained': drained()})
        elif cmd == 'swap':
            t0 = time.perf_counter()
            try:
                engine.swap_from_checkpoint(msg['path'],
                                            version=msg['version'])
            except (WeightSwapError,
                    failure.CheckpointCorruptError) as e:
                reply({'id': mid, 'ok': True, 'swapped': False,
                       'message': '%s: %s' % (type(e).__name__, e)})
                continue
            if monitor[0] is not None:
                monitor[0].detach()
            monitor[0] = _fresh_monitor(args.name,
                                        engine.param_version)
            reply({'id': mid, 'ok': True, 'swapped': True,
                   'swap_s': round(time.perf_counter() - t0, 4)})
        elif cmd == 'reset_slo':
            if monitor[0] is not None:
                monitor[0].detach()
            monitor[0] = _fresh_monitor(args.name,
                                        engine.param_version)
            reply({'id': mid, 'ok': True})
        elif cmd == 'degrade':
            rung = apply_degradation_rung(engine, msg.get('rung', 0),
                                          degrade_saved)
            reply({'id': mid, 'ok': True, 'rung': rung})
        elif cmd == 'stats':
            reply({'id': mid, 'ok': True,
                   'version': engine.param_version,
                   'slo': (monitor[0].evaluate()
                           if monitor[0] is not None else None),
                   'queue': queue.stats(),
                   'engine': {k: engine.stats()[k] for k in
                              ('prefills', 'decode_steps',
                               'tokens_generated', 'cancelled',
                               'decode_trace_count',
                               'compile_count', 'param_version')}})
        elif cmd == 'shutdown':
            reply({'id': mid, 'ok': True})
            break
        else:
            reply({'id': mid, 'ok': False,
                   'message': 'unknown cmd %r' % cmd})
    stop.set()
    queue.close()
    try:
        conn.close()
        srv.close()
    except OSError:
        pass
    return 0

# ----------------------------------------------------------------------
# demo traffic + the CLI
# ----------------------------------------------------------------------

class _TrafficGen:
    """Open-loop demo traffic through the front (the loadgen
    contract: arrivals on a clock, shedding is a measurement)."""

    def __init__(self, front, rate, max_new_tokens=6,
                 prompt_len_range=(1, 4), deadline_s=None, seed=0):
        self.front = front
        self.rate = float(rate)
        self.max_new_tokens = int(max_new_tokens)
        self.lo, self.hi = prompt_len_range
        self.deadline_s = deadline_s
        self._rng = np.random.RandomState(seed)
        self._stop = threading.Event()
        self._handles = []
        self._hlock = threading.Lock()
        self.offered = 0
        self.shed_submit = 0
        self.served = 0
        self.shed_result = 0
        self.errors = 0
        self.tokens = 0
        self._threads = []

    def _submit_loop(self):
        t0 = time.monotonic()
        i = 0
        vocab = DEMO_MODEL['vocab_size']
        while not self._stop.is_set():
            target = t0 + i / self.rate
            delay = target - time.monotonic()
            if delay > 0:
                time.sleep(min(delay, 0.05))
                continue
            i += 1
            n = self._rng.randint(self.lo, self.hi + 1)
            prompt = self._rng.randint(0, vocab, size=n)
            self.offered += 1
            try:
                h = self.front.submit(
                    prompt, self.max_new_tokens,
                    deadline=(None if self.deadline_s is None
                              else time.monotonic()
                              + self.deadline_s))
            except OverloadError:
                self.shed_submit += 1
                continue
            with self._hlock:
                self._handles.append(h)

    def _resolve_loop(self):
        while True:
            with self._hlock:
                h = self._handles.pop(0) if self._handles else None
            if h is None:
                if self._stop.is_set():
                    return
                time.sleep(0.01)
                continue
            try:
                toks = h.result(timeout=120.0)
                self.served += 1
                self.tokens += len(toks)
            except OverloadError:
                self.shed_result += 1
            except Exception:
                self.errors += 1

    def start(self):
        for fn in (self._submit_loop, self._resolve_loop):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=150.0)

    def stats(self):
        return {'offered': self.offered, 'served': self.served,
                'shed_submit': self.shed_submit,
                'shed_result': self.shed_result,
                'errors': self.errors, 'tokens': self.tokens}


def _demo_main(args):
    """The default CLI mode: the whole train-to-serve loop in one
    invocation (module docstring).  Exit 0; the scenario verdicts
    live in ``fleet_ledger.jsonl`` and the summary JSON on stdout."""
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    import jax
    jax.config.update('jax_platforms', 'cpu')
    from chainermn_tpu.telemetry.slo import default_slos
    from chainermn_tpu.training import recovery
    _chaos.maybe_install_from_env()   # controller-side swap_kill
    _telemetry.enable()
    out = args.out
    ckpt_dir = args.ckpt_dir or os.path.join(out, 'ckpt')
    os.makedirs(out, exist_ok=True)
    if recovery.latest_snapshot(ckpt_dir)[1] is None:
        demo_train(ckpt_dir, steps=args.boot_steps,
                   snapshot_every=args.boot_steps)
    kind, path, it = recovery.latest_snapshot(ckpt_dir)
    if path is None:
        print('fleet: no valid snapshot under %s' % ckpt_dir,
              file=sys.stderr)
        return 2
    slos = default_slos(ttft_s=args.slo_ttft_s,
                        intertoken_s=args.slo_intertoken_s)
    judge = CanaryJudge(latency_ratio=args.latency_ratio,
                        latency_floor_ms=args.latency_floor_ms,
                        shed_delta=args.shed_delta,
                        min_events=args.min_events)
    worker_out = os.path.join(out, 'telemetry')
    if args.local:
        controller = build_local_fleet(
            ckpt_dir, out, n_replicas=args.replicas,
            n_slots=args.n_slots,
            max_prompt_len=args.max_prompt_len,
            max_queue=args.max_queue, slos=slos,
            canary_fraction=args.canary_fraction, judge=judge,
            canary_seconds=args.canary_seconds,
            judge_interval=args.judge_interval,
            drain_timeout=args.drain_timeout,
            watcher=None, journal=args.recover)
        controller.watcher.debounce_s = args.debounce
        spawn_fn = local_respawn_fn(
            n_slots=args.n_slots,
            max_prompt_len=args.max_prompt_len,
            max_queue=args.max_queue, slos=slos)
    else:
        replicas = [SubprocessReplica.spawn(
            'replica-%d' % i, path, it, out,
            n_slots=args.n_slots,
            max_prompt_len=args.max_prompt_len,
            max_queue=args.max_queue,
            replica_chaos=args.replica_chaos,
            replica_index=i,
            worker_out=(os.path.join(worker_out, 'replica-%d' % i)
                        if args.recover else None))
            for i in range(args.replicas)]
        front = FleetFront(
            replicas, current_version=it,
            canary_fraction=args.canary_fraction,
            journal=(RequestJournal(os.path.join(out, JOURNAL_NAME))
                     if args.recover else None))
        controller = FleetController(
            front, ckpt_dir, out, boot=(path, it),
            watcher=CheckpointWatcher(ckpt_dir,
                                      debounce_s=args.debounce,
                                      start_after=it),
            judge=judge, canary_seconds=args.canary_seconds,
            judge_interval=args.judge_interval,
            drain_timeout=args.drain_timeout)
        # respawned workers never inherit the one-shot @N kill (their
        # occurrence counters restart) -- but * rules stay so a
        # crash-loop keeps crashing into the restart-policy abort
        respawn_chaos = strip_oneshot_kills(args.replica_chaos)

        def spawn_fn(name, path, version, index):
            return SubprocessReplica.spawn(
                name, path, version, out,
                n_slots=args.n_slots,
                max_prompt_len=args.max_prompt_len,
                max_queue=args.max_queue,
                replica_chaos=respawn_chaos,
                replica_index=index,
                worker_out=os.path.join(worker_out, name))
    controller.start()
    supervisor = None
    if args.recover:
        supervisor = ReplicaSupervisor(
            controller, spawn_fn=spawn_fn,
            degradation=DegradationPolicy(),
            worker_out=worker_out).start()
    stop_ctl = threading.Event()
    ctl_thread = threading.Thread(
        target=controller.run, args=(stop_ctl,), daemon=True)
    ctl_thread.start()
    traffic = _TrafficGen(
        controller.front, rate=args.rate,
        max_new_tokens=args.max_new_tokens,
        prompt_len_range=(1, args.traffic_prompt_max
                          or args.max_prompt_len),
        seed=args.seed).start()
    rc = 0
    try:
        # the train half: each round of sgd steps ends in a snapshot
        # the watcher picks up and rolls under the live traffic above
        for k in range(args.rolls):
            demo_train(ckpt_dir, steps=args.steps_per_roll,
                       snapshot_every=args.steps_per_roll)
            target = it + (k + 1) * args.steps_per_roll
            deadline = time.monotonic() + args.roll_timeout
            while time.monotonic() < deadline:
                if (controller.last_handled_version is not None
                        and controller.last_handled_version
                        >= target):
                    break
                time.sleep(0.1)
            else:
                print('fleet: roll of iteration %d timed out'
                      % target, file=sys.stderr)
                rc = 3
                break
        t_end = time.monotonic() + args.duration
        while time.monotonic() < t_end:
            if supervisor is not None and supervisor.aborted:
                break
            time.sleep(0.05)
    finally:
        traffic.stop()   # before supervisor.stop(): outstanding
        if supervisor is not None:   # handles may need a recovery
            supervisor.stop()
        stop_ctl.set()
        ctl_thread.join(timeout=60.0)
        summary = controller.complete(traffic=traffic.stats())
        controller.close()
    payload = {k: summary[k] for k in
               ('version', 'promotes', 'rollbacks',
                'swap_failures', 'dropped_during_swap', 'traffic')}
    if supervisor is not None:
        payload['recovery'] = supervisor.describe()
        if supervisor.aborted:
            rc = 1
    print(json.dumps(payload, sort_keys=True, default=repr))
    return rc


def main(argv=None):
    p = argparse.ArgumentParser(
        prog='python -m chainermn_tpu.serving.fleet',
        description='train-to-serve continuous deployment: live '
                    'weight hot-swap, canary admission, SLO-gated '
                    'rollback (docs/serving.md)')
    p.add_argument('--replica', action='store_true',
                   help='internal: run as a replica worker')
    p.add_argument('--name', default='replica-0')
    p.add_argument('--port', type=int, default=0)
    p.add_argument('--snapshot', default=None)
    p.add_argument('--version', type=int, default=0)
    p.add_argument('--parent-pid', type=int, default=0)
    p.add_argument('--out', default='result/fleet')
    p.add_argument('--ckpt-dir', default=None,
                   help='checkpoint chain to watch (default '
                        'OUT/ckpt, demo-trained when empty)')
    p.add_argument('--replicas', type=int, default=2)
    p.add_argument('--local', action='store_true',
                   help='in-process replicas instead of subprocesses')
    p.add_argument('--rolls', type=int, default=1,
                   help='new snapshots the inline trainer writes '
                        '(0: no training, just boot/converge/serve)')
    p.add_argument('--boot-steps', type=int, default=2)
    p.add_argument('--steps-per-roll', type=int, default=2)
    p.add_argument('--roll-timeout', type=float, default=300.0)
    p.add_argument('--duration', type=float, default=2.0,
                   help='extra serving seconds after the last roll')
    p.add_argument('--rate', type=float, default=30.0)
    p.add_argument('--max-new-tokens', type=int, default=6)
    p.add_argument('--n-slots', type=int, default=2)
    p.add_argument('--max-prompt-len', type=int, default=4)
    p.add_argument('--max-queue', type=int, default=64)
    p.add_argument('--canary-fraction', type=float, default=0.5)
    p.add_argument('--canary-seconds', type=float, default=3.0)
    p.add_argument('--judge-interval', type=float, default=0.3)
    p.add_argument('--latency-ratio', type=float, default=1.5)
    p.add_argument('--latency-floor-ms', type=float, default=20.0)
    p.add_argument('--shed-delta', type=float, default=0.05)
    p.add_argument('--min-events', type=int, default=6)
    p.add_argument('--slo-ttft-s', type=float, default=1.0)
    p.add_argument('--slo-intertoken-s', type=float, default=0.25)
    p.add_argument('--drain-timeout', type=float, default=60.0)
    p.add_argument('--debounce', type=float, default=0.3)
    p.add_argument('--replica-chaos', default=None,
                   help='CHAINERMN_TPU_CHAOS handout to replica '
                        'subprocesses (e.g. serve_slow=*:0.3 -- the '
                        'regression only bites on a swapped version; '
                        'replica_kill=@N:IDX hard-kills replica IDX '
                        'at its Nth decode tick)')
    p.add_argument('--recover', action='store_true',
                   help='arm the crash-safe request journal and the '
                        'ReplicaSupervisor self-healing loop '
                        '(exact-replay requeue + respawn + '
                        'degradation ladder)')
    p.add_argument('--traffic-prompt-max', type=int, default=None,
                   help='cap demo-traffic prompt length below '
                        '--max-prompt-len so recovery continuations '
                        '(prompt + emitted tokens) still fit the '
                        'prefill window')
    p.add_argument('--worker-out', default=None,
                   help='internal: replica worker telemetry capture '
                        'dir (set by the controller under --recover)')
    p.add_argument('--seed', type=int, default=0)
    args = p.parse_args(argv)
    if args.replica:
        return _replica_main(args)
    return _demo_main(args)


if __name__ == '__main__':
    sys.exit(main())
