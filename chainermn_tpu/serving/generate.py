"""Autoregressive generation: bucketed KV-cache decode with
continuous token-level batching over a prefill/decode AOT split.

The forward-only :class:`~chainermn_tpu.serving.InferenceEngine`
serves one batch per request mix; token-by-token generation is a
different machine with a different bound in each phase:

- **Prefill** (the prompt pass) is compute-bound -- whole-prompt
  matmuls through the fused flash kernel -- and its natural bucket
  axis is PROMPT LENGTH: one AOT executable per power-of-two token
  length, one prompt per call, writing every layer's K/V into one
  cache SLOT (:func:`chainermn_tpu.models.prefill`).
- **Decode** (every subsequent token) is HBM-bandwidth-bound -- one
  query row per live sequence against its cached K/V
  (:func:`chainermn_tpu.ops.flash_attention_decode`, one HBM pass)
  -- and its bucket axis is ACTIVE-SLOT COUNT: one AOT executable per
  power-of-two slot count over the SAME persistent cache
  (:func:`chainermn_tpu.models.decode_step`).

Between the two sits **continuous batching**: admission happens at
TOKEN granularity, not batch granularity.  A sequence that finishes
(or whose deadline expires mid-generation -- the ``serve_cancel``
chaos site drives exactly this) frees its cache slot, and the slot is
refilled from the queue at the NEXT decode step; the rest of the
in-flight batch never waits for stragglers, which is what makes
tokens/s/chip under a mixed-length workload approach the steady-state
decode rate instead of the worst sequence's (the batch-level
alternative idles every finished slot until the whole batch drains).

Both executable families reuse the engine machinery wholesale: AOT
compilation through :func:`~chainermn_tpu.utils.jax_compat.
aot_compile` over the persistent compilation cache, the SL007
``abstract_signature`` set as a runtime no-recompile guard (refused,
never retraced -- the static twin is the ``step:decode_forward``
shardlint target), :class:`~chainermn_tpu.parallel.MeshPlan`
tensor-parallel sharding (cache heads shard with the attention
weights, :func:`chainermn_tpu.models.kv_cache_specs`), float policies
cast weights at load, :class:`~chainermn_tpu.precision.Int8Policy`
quantizes them, and ``int8_kv=True`` stores the CACHE itself int8
with per-(position, head) scales
(:func:`~chainermn_tpu.precision.quantize_kv`) -- halving the bytes
the decode step is bound by.

The cache is DONATED into every prefill/decode executable and the
returned buffer rebound, so steady-state decode allocates nothing
cache-sized.  Telemetry: ``serve_prefill``/``serve_decode`` spans
(``iteration`` = decode step index), a per-step ``active_slots``
gauge, ``serve_ttft_seconds`` / ``serve_intertoken_seconds`` /
``serve_decode_seconds`` raw-sample histograms and
``serve_tokens_total`` -- the ``telemetry report``/``doctor`` serve
section renders tokens/s and TTFT from them (``docs/serving.md``).
"""

import threading
import time

import numpy as np

import jax
import jax.numpy as jnp

from chainermn_tpu import telemetry as _telemetry
from chainermn_tpu.analysis.walker import abstract_signature
from chainermn_tpu.serving.batcher import (bucket_edges, bucket_of,
                                           next_request_id,
                                           record_shed)
from chainermn_tpu.utils import chaos as _chaos
from chainermn_tpu.utils import jax_compat
from chainermn_tpu.utils.failure import OverloadError

#: default admission knobs (the generation twins of batcher's)
DEFAULT_MAX_QUEUE = 256


class GenRequest:
    """One in-flight generation request: ``prompt`` (1-D int32 token
    ids), ``max_new_tokens``, optional absolute ``deadline``
    (``clock()`` units, enforced at admission AND between decode
    steps), and a one-shot completion cell filled with the generated
    token ids or a typed error.  ``request_id`` is the process-unique
    trace id (monotonic admission stamp in the suffix); ``t_trace0``
    is the admission instant on the telemetry recorder's clock (None
    when telemetry was off) -- the t0 of the ``queue_wait`` stage.

    ``prefix_key`` (stamped by a paged-engine queue at admission) is
    a STABLE hash of the shareable prompt prefix
    (:func:`chainermn_tpu.serving.paged.prefix_key`): a pure function
    of the token ids, so arrival order can never change it -- the
    scheduler uses it to co-admit shared-prefix requests.

    ``on_token`` (optional) streams committed tokens incrementally:
    the engine calls ``on_token(request_id, [int, ...])`` from the
    scheduler thread each time tokens are emitted (first token at
    prefill completion, one per decode tick, an accepted window per
    speculative tick).  The callback is passed at SUBMIT time (not
    attached later) so there is no race against the scheduler thread;
    it must be cheap and never raise -- the engine guards it, but a
    slow callback stalls the tick.  The fleet front's crash-safe
    request journal rides exactly this hook."""

    __slots__ = ('prompt', 'max_new_tokens', 'deadline', 'seq',
                 't_submit', 'synthetic', 'request_id', 't_trace0',
                 'prefix_key', 'on_token', '_done', '_result',
                 '_error')

    def __init__(self, prompt, max_new_tokens, deadline=None, seq=0,
                 t_submit=0.0, synthetic=False, request_id=None,
                 prefix_key=None, on_token=None):
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        if self.prompt.size < 1:
            raise ValueError('empty prompt')
        if max_new_tokens < 1:
            raise ValueError('max_new_tokens must be >= 1, got %d'
                             % max_new_tokens)
        self.max_new_tokens = int(max_new_tokens)
        self.deadline = deadline
        self.seq = seq
        self.t_submit = t_submit
        self.synthetic = synthetic
        self.prefix_key = prefix_key
        self.on_token = on_token
        self.request_id = request_id or next_request_id()
        rec = _telemetry.active()
        self.t_trace0 = rec.now() if rec is not None else None
        self._done = threading.Event()
        self._result = None
        self._error = None

    def set_result(self, tokens):
        self._result = np.asarray(tokens, np.int32)
        self._done.set()

    def notify_tokens(self, tokens):
        """Stream newly COMMITTED tokens to ``on_token`` (no-op when
        no callback was registered).  Guarded: a journal/stream
        callback failure must never take the scheduler thread down
        with it -- the request still completes via ``set_result``."""
        if self.on_token is None or not tokens:
            return
        try:
            self.on_token(self.request_id,
                          [int(t) for t in tokens])
        except Exception:
            pass

    def set_error(self, exc):
        self._error = exc
        self._done.set()

    def done(self):
        return self._done.is_set()

    def result(self, timeout=None):
        """Block for the generated tokens; re-raises the typed shed
        error (``OverloadError`` with reason queue_full / deadline /
        shutdown)."""
        if not self._done.wait(timeout):
            raise TimeoutError('request %d not completed within %rs'
                               % (self.seq, timeout))
        if self._error is not None:
            raise self._error
        return self._result


class GenerationQueue:
    """Bounded admission queue for generation requests.

    Unlike the batch queue there is no packing: the engine pops AT
    MOST as many requests as it has free cache slots each decode step
    (token-level admission).  The bounded-backlog / typed-shed /
    ``serve_burst`` contracts are identical to
    :class:`~chainermn_tpu.serving.RequestQueue`.

    ``page_size`` (set when feeding a paged engine) stamps each
    admitted request's :attr:`GenRequest.prefix_key` -- the stable
    hash of its page-aligned prompt prefix -- and unlocks
    ``pop(..., group_prefix=True)`` co-admission."""

    def __init__(self, max_prompt_len, max_queue=DEFAULT_MAX_QUEUE,
                 clock=time.monotonic, label=None, page_size=None):
        self.label = label  # fleet replica name (shed forensics)
        self.max_prompt_len = int(max_prompt_len)
        self.page_size = int(page_size) if page_size else None
        self.max_queue = int(max_queue)
        self._clock = clock
        self._lock = threading.Lock()
        self._waiting = []
        self._seq = 0
        self._closed = False
        self.submitted = 0
        self.shed_queue_full = 0
        self.shed_deadline = 0

    def submit(self, prompt, max_new_tokens, deadline=None,
               request_id=None, on_token=None):
        """Enqueue one prompt; returns the :class:`GenRequest`.
        Over-length prompts raise ``ValueError`` before touching
        queue state; a full or closed queue sheds typed.
        ``request_id`` lets an admission front (the fleet) pre-assign
        the trace id it already routed on; ``on_token`` is the
        incremental token-stream callback installed at admission (see
        :class:`GenRequest`)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size > self.max_prompt_len:
            raise ValueError(
                'prompt of %d tokens exceeds max_prompt_len %d; '
                'truncate client-side or raise the engine limit'
                % (prompt.size, self.max_prompt_len))
        burst = (_chaos.on_serve_submit()
                 if _chaos._active is not None else 0)
        with self._lock:
            req = self._admit(prompt, max_new_tokens, deadline,
                              request_id=request_id,
                              on_token=on_token)
            for _ in range(burst):
                try:
                    self._admit(prompt, max_new_tokens, deadline,
                                synthetic=True)
                except OverloadError:
                    break
        return req

    def _admit(self, prompt, max_new_tokens, deadline,
               synthetic=False, request_id=None, on_token=None):
        if self._closed:
            raise OverloadError('generation queue is shut down',
                                reason='shutdown',
                                queue_depth=len(self._waiting))
        if len(self._waiting) >= self.max_queue:
            self.shed_queue_full += 1
            record_shed('queue_full',
                        request_id=request_id or next_request_id(),
                        queue_depth=len(self._waiting),
                        **self._shed_attrs())
            raise OverloadError(
                'generation queue full (%d waiting); retry with '
                'backoff' % len(self._waiting),
                reason='queue_full', queue_depth=len(self._waiting))
        self._seq += 1
        self.submitted += 1
        key = None
        if self.page_size is not None:
            from chainermn_tpu.serving.paged import prefix_key
            key = prefix_key(prompt, self.page_size)
        req = GenRequest(prompt, max_new_tokens, deadline=deadline,
                         seq=self._seq, t_submit=self._clock(),
                         synthetic=synthetic, request_id=request_id,
                         prefix_key=key, on_token=on_token)
        self._waiting.append(req)
        return req

    def _shed_attrs(self):
        return {'replica': self.label} if self.label else {}

    def pop(self, k, group_prefix=False):
        """Up to ``k`` live requests in arrival order; requests whose
        deadline already expired while queued are shed typed here (the
        queue-side twin of the engine's mid-generation expiry).

        ``group_prefix=True`` (the paged engine's admission): after
        the head request is taken in arrival order, later waiters
        sharing its ``prefix_key`` are pulled forward so
        shared-prefix requests land in the SAME admission wave --
        their suffix prefills all read the prefix banked by the first
        completer.  Relative order within a key group is preserved,
        and requests without a key are never reordered past each
        other."""
        now = self._clock()
        out = []
        with self._lock:
            head_key = None
            while self._waiting and len(out) < k:
                idx = 0
                if group_prefix and head_key is not None:
                    idx = next(
                        (j for j, r in enumerate(self._waiting)
                         if r.prefix_key == head_key), 0)
                req = self._waiting.pop(idx)
                if req.deadline is not None and now > req.deadline:
                    self.shed_deadline += 1
                    record_shed('deadline',
                                request_id=req.request_id,
                                queue_depth=len(self._waiting),
                                waited_ms=round(
                                    (now - req.t_submit) * 1e3, 3),
                                **self._shed_attrs())
                    req.set_error(OverloadError(
                        'deadline expired after %.1f ms in queue'
                        % ((now - req.t_submit) * 1e3),
                        reason='deadline'))
                    continue
                if not out and group_prefix:
                    head_key = req.prefix_key
                out.append(req)
        return out

    def depth(self):
        with self._lock:
            return len(self._waiting)

    def close(self):
        with self._lock:
            self._closed = True
            pending, self._waiting = self._waiting, []
        for req in pending:
            record_shed('shutdown', request_id=req.request_id,
                        queue_depth=len(pending), count_total=False,
                        **self._shed_attrs())
            req.set_error(OverloadError('generation queue shut down',
                                        reason='shutdown'))

    def stats(self):
        return {'submitted': self.submitted,
                'shed_queue_full': self.shed_queue_full,
                'shed_deadline': self.shed_deadline,
                'depth': self.depth()}


class _Slot:
    """Host-side state of one cache slot."""

    __slots__ = ('request', 'position', 'remaining', 'generated',
                 't_last_token', 't_stage_end', 'pages')

    def __init__(self, request, position, remaining, first_token,
                 t_now, t_stage_end=None, pages=None):
        self.request = request
        self.position = position          # next token's position
        self.remaining = remaining        # tokens still to generate
        self.generated = [first_token]
        self.t_last_token = t_now
        # telemetry-clock end of this request's newest recorded trace
        # stage: each decode stage span starts here, so the stages
        # tile the request's lifetime gap-free (None: telemetry off)
        self.t_stage_end = t_stage_end
        # paged engine: this sequence's page table (one pool ref per
        # entry, released on completion/cancel); None on slot engines
        self.pages = pages


class _PrefillState:
    """Host-side state of one sequence whose prompt is still being
    prefilled (paged engine only): chunked prefill runs one chunk per
    scheduler tick, so a long prompt spends several ticks here before
    graduating to a :class:`_Slot`."""

    __slots__ = ('request', 'pages', 'pos', 'matched', 'chunks',
                 't_pop', 't_stage_end')

    def __init__(self, request, pages, pos, matched, t_pop=None,
                 t_stage_end=None):
        self.request = request
        self.pages = pages       # page table so far (refs held)
        self.pos = pos           # next absolute position to prefill
        self.matched = matched   # prefix tokens reused from the index
        self.chunks = 0          # chunks dispatched so far
        self.t_pop = t_pop
        self.t_stage_end = t_stage_end


class GenerationEngine:
    """Continuous-batching autoregressive server for one
    :class:`~chainermn_tpu.models.TransformerLM`.

    Args:
      model: the flax module (``tp_axis`` set when serving over
        ``plan``/``param_specs``).
      params: the parameter pytree (the UNSHARDED oracle tree; tp
        placement is spec-driven).
      n_slots: cache slots = max concurrent sequences.  Decode
        executables are bucketed by power-of-two ACTIVE-slot count up
        to this.
      max_prompt_len: prompt-length cap; prefill executables are
        bucketed by power-of-two prompt length up to this.
      max_len: cache depth per slot (prompt + generated tokens;
        default ``model.max_len``).
      eos_id: optional stop token (greedy decode stops early on it).
      policy: float policy casts weights at load;
        :class:`~chainermn_tpu.precision.Int8Policy` quantizes them
        (dequant in-graph; refused under ``param_specs`` like the
        batch engine).
      int8_kv: store the KV cache int8 with per-(position, head)
        scales -- half the decode-bound HBM bytes of bf16.
      paged: replace the private per-slot cache slabs with a PAGED
        pool (:func:`chainermn_tpu.models.init_paged_kv_cache`):
        ``n_pages`` pages of ``page_size`` tokens shared by all
        sequences through per-sequence page tables, with refcounted
        prefix sharing (a radix index over completed prompts -- N
        requests with one system prompt read ONE banked copy),
        copy-on-write at divergence, and LRU eviction of banked
        prefixes when the pool runs dry.  Greedy outputs are
        IDENTICAL to the slot engine (tests/test_serving.py).
      page_size / n_pages: paged-mode geometry.  ``n_pages`` defaults
        to ``1 + n_slots * ceil(max_len / page_size)`` -- the slot
        engine's capacity plus the scratch page; LOWER it to
        oversubscribe (prefix sharing is what makes that safe).
      prefill_chunk: paged mode only -- split prompts into chunks of
        this many tokens, ONE chunk per scheduler tick interleaved
        with decode steps (SARATHI-style), so a long-prompt burst
        cannot freeze inter-token latency (the ``serve_longprompt``
        chaos site is the acceptance driver).  ``None`` prefills each
        prompt in one tick.
      prefix_sharing: disable the radix index (pages still pool, no
        cross-request reuse) -- an ablation knob for the bench.
      draft_model / draft_params: enable SPECULATIVE DECODING -- a
        smaller ``TransformerLM`` (fewer layers/heads, SAME vocab,
        never tensor-parallel) that autoregressively proposes
        ``spec_tokens - 1`` tokens per scheduler tick; the target
        scores the whole window in ONE verify executable
        (:func:`chainermn_tpu.models.spec_verify`) and the longest
        draft prefix whose argmaxes agree is committed plus the
        target's own next token (the correction at the first
        divergence, the bonus on full acceptance).  Greedy outputs
        are EXACTLY the non-speculative engine's token for token --
        acceptance rate only changes THROUGHPUT, never content
        (tests/test_serving.py pins all four cache modes).  The draft
        rides its own KV cache through the same slot ids, page
        tables, pool refcounts, prefix-shared pages and CoW copies
        as the target; rejected positions roll back by position
        rewind (+ page-table tail release in paged mode) -- stale
        rows are masked exactly like a reused slot.
      spec_tokens: verify window width ``k`` (>= 2): one tick runs
        ``k`` draft-decode steps and one k-token verify, committing
        1..k tokens, so accepted drafts amortize the HBM-bound
        target cache read (``verify_steps / tokens_generated < 1``
        whenever anything is accepted).
      plan / param_specs: MeshPlan tensor-parallel serving (the cache
        shards its head dim over ``plan.model_axis``).
      cache_dir / aot: the engine's persistent-compilation-cache and
        AOT knobs, verbatim.
      label / version: fleet identity (the engine.py contract): when
        ``label`` is set, serve-path records carry
        ``replica``/``version`` attrs for per-replica SLO filtering;
        ``version`` is the boot parameter version and
        :meth:`swap_params` advances it.

    Decoding is GREEDY (argmax in-graph -- the sampled token never
    round-trips a vocab-sized buffer to the host), which also makes
    every test and A/B deterministic.
    """

    def __init__(self, model, params, n_slots=8, max_prompt_len=64,
                 max_len=None, eos_id=None, policy=None,
                 int8_kv=False, paged=False, page_size=16,
                 n_pages=None, prefill_chunk=None, prefix_sharing=True,
                 draft_model=None, draft_params=None, spec_tokens=4,
                 plan=None, param_specs=None, cache_dir=None, aot=True,
                 label=None, version=0):
        import os

        from chainermn_tpu.models import (init_kv_cache,
                                          init_paged_kv_cache,
                                          kv_cache_specs)
        from chainermn_tpu.serving.paged import (PagePool,
                                                 RadixPrefixIndex)

        self.model = model
        self.label = label
        self.param_version = int(version)
        self._boot_version = self.param_version
        self.n_slots = int(n_slots)
        #: admissions per scheduler tick cap (None: every free slot).
        #: The fleet degradation ladder's "shrink admission" rung sets
        #: this to 1 and restores None on recovery.
        self.admit_cap = None
        self.max_prompt_len = int(max_prompt_len)
        self.max_len = int(max_len or model.max_len)
        if self.max_prompt_len > self.max_len:
            raise ValueError('max_prompt_len %d exceeds cache depth '
                             '%d' % (self.max_prompt_len, self.max_len))
        self.eos_id = eos_id
        self.policy = policy
        self.plan = plan
        if param_specs is not None and plan is None:
            raise ValueError('param_specs requires a plan')
        self.param_specs = param_specs
        if (plan is not None) != (model.tp_axis is not None):
            raise ValueError(
                'serve a tp_axis model over a plan and a plain model '
                'without one (tp_axis=%r, plan=%r)'
                % (model.tp_axis, plan))
        self.cache_dir = cache_dir
        self.cache_persistent = False
        if cache_dir:
            os.makedirs(cache_dir, exist_ok=True)
            self.cache_persistent = jax_compat.enable_compilation_cache(
                cache_dir)
        self.aot_requested = bool(aot)

        self.prefill_edges = bucket_edges(self.max_prompt_len)
        self.decode_edges = bucket_edges(self.n_slots)

        # load-time parameter transform, the engine.py idiom
        quantize = getattr(policy, 'quantize', None)
        if quantize is not None and param_specs is not None:
            raise NotImplementedError(
                'int8 weights under tensor-parallel param_specs '
                'are not wired yet (quantize per shard after '
                'resharding); int8_kv composes with tp, int8 '
                'WEIGHTS do not')
        self.quantized = quantize is not None
        self._params_template = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(
                jnp.shape(x), x.dtype if hasattr(x, 'dtype')
                else np.asarray(x).dtype), params)
        self.params = self._place_params(params)

        self.int8_kv = bool(int8_kv)
        self.paged = bool(paged)
        self.page_size = int(page_size)
        self.prefill_chunk = (int(prefill_chunk) if prefill_chunk
                              else None)
        if self.prefill_chunk is not None and not self.paged:
            raise ValueError('prefill_chunk requires paged=True (the '
                             'slot cache prefills whole prompts)')
        if self.prefill_chunk is not None \
                and self.prefill_chunk > self.max_prompt_len:
            raise ValueError('prefill_chunk %d exceeds max_prompt_len '
                             '%d' % (self.prefill_chunk,
                                     self.max_prompt_len))
        tp = plan.model_size if plan is not None else 1
        del tp  # the GLOBAL cache is built unsharded; specs shard it
        if self.paged:
            self.pages_per_seq = -(-self.max_len // self.page_size)
            self.n_pages = int(
                n_pages or 1 + self.n_slots * self.pages_per_seq)
            self.pool = PagePool(self.n_pages, self.page_size)
            self._prefix_index = (RadixPrefixIndex(self.pool)
                                  if prefix_sharing else None)
            cache = init_paged_kv_cache(model, self.n_pages,
                                        self.page_size,
                                        int8_kv=self.int8_kv, tp=1)
        else:
            if n_pages is not None:
                raise ValueError('n_pages requires paged=True')
            self.pages_per_seq = None
            self.n_pages = None
            self.pool = None
            self._prefix_index = None
            cache = init_kv_cache(model, self.n_slots, self.max_len,
                                  int8_kv=self.int8_kv, tp=1)
        self._cache_specs = (kv_cache_specs(cache, plan.model_axis)
                             if plan is not None else None)
        self._cache = jax.device_put(cache, self._cache_sharding())

        # -- speculative decoding: the draft twin ----------------------
        self.spec_tokens = int(spec_tokens)
        self.draft_model = draft_model
        self.speculative = draft_model is not None
        if draft_params is not None and draft_model is None:
            raise ValueError('draft_params requires draft_model')
        self._draft_params = None
        self._draft_cache = None
        if self.speculative:
            if draft_params is None:
                raise ValueError('draft_model requires draft_params')
            if self.spec_tokens < 2:
                raise ValueError('spec_tokens must be >= 2 (1 is '
                                 'plain decode), got %d'
                                 % self.spec_tokens)
            if draft_model.vocab_size != model.vocab_size:
                raise ValueError(
                    'draft vocab %d != target vocab %d -- speculative '
                    'decoding compares token ids, so the tokenizer '
                    'must be shared' % (draft_model.vocab_size,
                                        model.vocab_size))
            if draft_model.max_len < self.max_len:
                raise ValueError(
                    'draft max_len %d cannot cover the cache depth %d'
                    % (draft_model.max_len, self.max_len))
            if draft_model.tp_axis is not None:
                raise ValueError(
                    'the draft model is small by construction and '
                    'runs replicated; build it without tp_axis')
            host = draft_params
            if self.policy is not None and not self.quantized:
                from chainermn_tpu.precision import cast_floating
                host = cast_floating(host, self.policy.compute_dtype)
            self._draft_params = jax.device_put(
                host, self._draft_sharding())
            if self.paged:
                # SAME pool geometry as the target: the draft cache is
                # addressed through the same page tables and refcounts,
                # so one allocation/CoW/eviction decision serves both
                dcache = init_paged_kv_cache(
                    draft_model, self.n_pages, self.page_size,
                    int8_kv=self.int8_kv, tp=1)
            else:
                dcache = init_kv_cache(
                    draft_model, self.n_slots, self.max_len,
                    int8_kv=self.int8_kv, tp=1)
            self._draft_cache = jax.device_put(
                dcache, self._draft_sharding())

        # prefill executable widths: chunked paged mode compiles ONE
        # fixed-width chunk executable; otherwise one per prompt bucket
        self._prefill_widths = (
            (self.prefill_chunk,) if self.prefill_chunk is not None
            else tuple(self.prefill_edges))

        self._slots = {}      # slot id -> _Slot (decode phase)
        self._prefilling = {} # slot id -> _PrefillState (paged only)
        self._free = list(range(self.n_slots))
        self._prefill = {}    # prompt/chunk bucket -> callable
        self._decode = {}     # slot bucket -> callable
        self._copy = None     # paged CoW page-copy executable
        self._draft_prefill = {}  # speculative: draft prompt buckets
        self._draft_decode = {}   # speculative: draft slot buckets
        self._verify = {}         # speculative: k-token verify buckets
        self._draft_copy = None   # speculative paged: draft CoW copy
        self._signatures = set()
        self._lock = threading.Lock()
        self.prefill_trace_count = 0
        self.decode_trace_count = 0
        self.copy_trace_count = 0
        self.draft_trace_count = 0
        self.verify_trace_count = 0
        self.compile_count = 0
        self.prefills = 0
        self.prefill_chunks = 0
        self.cow_copies = 0
        self.decode_steps = 0
        self.draft_steps = 0
        self.verify_steps = 0
        self.draft_proposed = 0
        self.draft_accepted = 0
        self.tokens_generated = 0
        self.cancelled = 0
        self._step_index = 0
        self._last_queue_depth = 0

    # -- sharding ------------------------------------------------------
    def _param_sharding(self):
        if self.plan is None:
            return jax.devices()[0]
        if self.param_specs is None:
            return self.plan.replicated()
        return self.plan.param_shardings(self.param_specs)

    def _place_params(self, params):
        """Load-time transform + placement, shared by construction
        and hot-swaps (the engine.py contract)."""
        if self.quantized:
            return jax.device_put(self.policy.quantize(params),
                                  self._param_sharding())
        host = params
        if self.policy is not None:
            from chainermn_tpu.precision import cast_floating
            host = cast_floating(host, self.policy.compute_dtype)
        return jax.device_put(host, self._param_sharding())

    def _ident(self):
        if self.label is None:
            return {}
        return {'replica': self.label, 'version': self.param_version}

    # -- live weight hot-swap (fleet roll) -----------------------------
    def swap_params(self, params, version=None, validate=True):
        """Hot-swap the served parameter tree without recompiling
        (executables are shape-keyed; ``decode_trace_count`` stays
        flat across a swap).

        REFUSED (typed :class:`~chainermn_tpu.utils.failure.
        WeightSwapError`, engine unchanged) while sequences are in
        flight: their KV caches were banked under the incumbent
        weights, and decoding them under new weights would silently
        corrupt the tail of every live generation -- the fleet drains
        the replica first, which is exactly the per-replica
        drain -> swap -> rejoin ladder.  Validation runs the
        full-slot decode executable once with the new tree over the
        (all-free) cache -- the warmup garbage-write contract -- and
        checks the sampled tokens materialize; only then is
        ``self.params`` cut over and the old buffer freed."""
        from chainermn_tpu.utils.failure import WeightSwapError
        if self._slots or self._prefilling:
            raise WeightSwapError(
                'swap requires a drained replica: %d sequence(s) '
                'still in flight hold KV state banked under the '
                'incumbent weights'
                % (len(self._slots) + len(self._prefilling)),
                version=version)
        new = self._place_params(params)
        if validate and self.n_slots in self._decode:
            exe = self._decode[self.n_slots][0]
            val_args = [jnp.zeros((self.n_slots,), jnp.int32),
                        jnp.zeros((self.n_slots,), jnp.int32)]
            if self.paged:
                val_args.append(jnp.zeros(
                    (self.n_slots, self.pages_per_seq), jnp.int32))
            try:
                tok, cache = exe(new, self._cache, *val_args)
                tok = jax.block_until_ready(tok)
            except Exception as e:
                raise WeightSwapError(
                    'swap validation decode failed (%s: %s) -- '
                    'keeping the incumbent parameters'
                    % (type(e).__name__, e), version=version) from e
            # the donated cache was consumed either way: rebind
            self._cache = cache
        old = self.params
        self.params = new
        self.param_version = (int(version) if version is not None
                              else self.param_version + 1)
        _telemetry.event('weight_swap', kind='serve',
                         **self._ident())
        del old  # double buffer freed after cutover
        return self.param_version

    def swap_from_checkpoint(self, path, version=None, validate=True):
        """:meth:`swap_params` fed from an elastic-resume checkpoint
        (crc-verified load against the boot tree's shape template)."""
        from chainermn_tpu.serving.engine import load_params
        return self.swap_params(
            load_params(path, self._params_template), version=version,
            validate=validate)

    def _cache_sharding(self):
        if self.plan is None:
            return jax.devices()[0]
        return self.plan.param_shardings(self._cache_specs)

    def _draft_sharding(self):
        """The draft model is always replicated: it is small by
        construction, so sharding it would trade cheap FLOPs for
        collective latency on the critical decode path."""
        if self.plan is None:
            return jax.devices()[0]
        return self.plan.replicated()

    # -- traced bodies -------------------------------------------------
    def _prepare_params(self, params):
        if self.quantized:
            return self.policy.dequantize(params)
        return params

    def _prefill_body(self, params, cache, tokens, length, slot):
        from chainermn_tpu.models import prefill as model_prefill
        self.prefill_trace_count += 1  # trace-time counter
        logits, cache = model_prefill(
            self.model, self._prepare_params(params), cache, tokens,
            length, slot)
        return jnp.argmax(logits).astype(jnp.int32), cache

    def _decode_body(self, params, cache, tokens, positions,
                     slots=None):
        from chainermn_tpu.models import decode_step
        self.decode_trace_count += 1   # trace-time counter
        logits, cache = decode_step(
            self.model, self._prepare_params(params), cache, tokens,
            positions, slots=slots)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache

    def _prefill_body_paged(self, params, cache, tokens, length, pos0,
                            table):
        from chainermn_tpu.models import prefill_paged
        self.prefill_trace_count += 1  # trace-time counter
        logits, cache = prefill_paged(
            self.model, self._prepare_params(params), cache, tokens,
            length, table, pos0)
        return jnp.argmax(logits).astype(jnp.int32), cache

    def _decode_body_paged(self, params, cache, tokens, positions,
                           tables):
        from chainermn_tpu.models import decode_step_paged
        self.decode_trace_count += 1   # trace-time counter
        logits, cache = decode_step_paged(
            self.model, self._prepare_params(params), cache, tokens,
            positions, tables)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache

    def _copy_body(self, params, cache, src, dst):
        """Copy-on-write page duplication: every leaf's page ``src``
        row copied to page ``dst`` in one donated pass.  ``params``
        rides along unused to keep the shared ``_compile`` calling
        convention (one signature family, cache donated at arg 1).
        Shape-generic: the speculative engine compiles a second
        instance of this body over the DRAFT cache, so one CoW
        decision duplicates the page in both pools."""
        del params
        self.copy_trace_count += 1     # trace-time counter
        return {key: leaf.at[:, dst].set(leaf[:, src])
                for key, leaf in cache.items()}

    # -- speculative traced bodies (the draft twin + verify) -----------
    def _draft_prefill_body(self, params, cache, tokens, length, slot):
        from chainermn_tpu.models import prefill as model_prefill
        self.draft_trace_count += 1    # trace-time counter
        logits, cache = model_prefill(
            self.draft_model, params, cache, tokens, length, slot)
        return jnp.argmax(logits).astype(jnp.int32), cache

    def _draft_prefill_body_paged(self, params, cache, tokens, length,
                                  pos0, table):
        from chainermn_tpu.models import prefill_paged
        self.draft_trace_count += 1    # trace-time counter
        logits, cache = prefill_paged(
            self.draft_model, params, cache, tokens, length, table,
            pos0)
        return jnp.argmax(logits).astype(jnp.int32), cache

    def _draft_decode_body(self, params, cache, tokens, positions,
                           slots=None):
        from chainermn_tpu.models import decode_step
        self.draft_trace_count += 1    # trace-time counter
        logits, cache = decode_step(
            self.draft_model, params, cache, tokens, positions,
            slots=slots)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache

    def _draft_decode_body_paged(self, params, cache, tokens,
                                 positions, tables):
        from chainermn_tpu.models import decode_step_paged
        self.draft_trace_count += 1    # trace-time counter
        logits, cache = decode_step_paged(
            self.draft_model, params, cache, tokens, positions,
            tables)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache

    def _verify_body(self, params, cache, tokens, positions,
                     slots=None):
        from chainermn_tpu.models import spec_verify
        self.verify_trace_count += 1   # trace-time counter
        logits, cache = spec_verify(
            self.model, self._prepare_params(params), cache, tokens,
            positions, slots=slots)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache

    def _verify_body_paged(self, params, cache, tokens, positions,
                           tables):
        from chainermn_tpu.models import spec_verify_paged
        self.verify_trace_count += 1   # trace-time counter
        logits, cache = spec_verify_paged(
            self.model, self._prepare_params(params), cache, tokens,
            positions, tables)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache

    def _draft_mapped(self, body, n_extra):
        """The draft twin of :meth:`_mapped`: everything replicated
        (draft params, draft cache, small int operands)."""
        if self.plan is None:
            return body
        from jax.sharding import PartitionSpec as P
        return jax.shard_map(
            body, mesh=self.plan.mesh,
            in_specs=(P(), P()) + (P(),) * n_extra,
            out_specs=(P(), P()), check_vma=False)

    def _mapped(self, body, n_extra):
        """Wrap a traced body in the plan's shard_map (params sharded
        per spec, cache per its spec, small int operands replicated)."""
        if self.plan is None:
            return body
        from jax.sharding import PartitionSpec as P
        pspecs = (self.param_specs if self.param_specs is not None
                  else P())
        return jax.shard_map(
            body, mesh=self.plan.mesh,
            in_specs=(pspecs, self._cache_specs) + (P(),) * n_extra,
            out_specs=(P(), self._cache_specs), check_vma=False)

    # -- compilation ---------------------------------------------------
    def _compile(self, fn, args, table, key, params=None):
        jitted = jax.jit(fn, donate_argnums=(1,))
        exe = None
        if self.aot_requested:
            exe = jax_compat.aot_compile(
                jitted, self.params if params is None else params,
                *args)
        aot = exe is not None
        if exe is None:
            exe = jitted
        table[key] = (exe, aot)
        self._signatures.add(abstract_signature(args))
        self.compile_count += 1
        return exe, aot

    def _token_structs(self, bucket):
        i32 = jnp.int32
        if self.paged:
            # (tokens, length, pos0, page_table)
            return (jax.ShapeDtypeStruct((1, bucket), i32),
                    jax.ShapeDtypeStruct((), i32),
                    jax.ShapeDtypeStruct((), i32),
                    jax.ShapeDtypeStruct((self.pages_per_seq,), i32))
        return (jax.ShapeDtypeStruct((1, bucket), i32),
                jax.ShapeDtypeStruct((), i32),
                jax.ShapeDtypeStruct((), i32))

    def _decode_structs(self, bucket):
        i32 = jnp.int32
        if self.paged:
            # (tokens, positions, page_tables) -- every bucket reads
            # through tables, so there is no full-vs-compacted split
            return (jax.ShapeDtypeStruct((bucket,), i32),
                    jax.ShapeDtypeStruct((bucket,), i32),
                    jax.ShapeDtypeStruct((bucket, self.pages_per_seq),
                                         i32))
        if bucket == self.n_slots:
            return (jax.ShapeDtypeStruct((bucket,), i32),
                    jax.ShapeDtypeStruct((bucket,), i32))
        return (jax.ShapeDtypeStruct((bucket,), i32),
                jax.ShapeDtypeStruct((bucket,), i32),
                jax.ShapeDtypeStruct((bucket,), i32))

    def _verify_structs(self, bucket):
        """Verify operand structs for one slot bucket: the decode
        structs with the token vector widened to the (bucket,
        spec_tokens) window."""
        i32 = jnp.int32
        kk = self.spec_tokens
        if self.paged:
            return (jax.ShapeDtypeStruct((bucket, kk), i32),
                    jax.ShapeDtypeStruct((bucket,), i32),
                    jax.ShapeDtypeStruct((bucket, self.pages_per_seq),
                                         i32))
        if bucket == self.n_slots:
            return (jax.ShapeDtypeStruct((bucket, kk), i32),
                    jax.ShapeDtypeStruct((bucket,), i32))
        return (jax.ShapeDtypeStruct((bucket, kk), i32),
                jax.ShapeDtypeStruct((bucket,), i32),
                jax.ShapeDtypeStruct((bucket,), i32))

    def _cache_struct(self):
        return jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            self._cache)

    def _draft_cache_struct(self):
        return jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            self._draft_cache)

    def _get_prefill(self, bucket):
        hit = self._prefill.get(bucket)
        if hit is not None:
            return hit[0]
        with self._lock:
            hit = self._prefill.get(bucket)
            if hit is not None:
                return hit[0]
            if bucket not in self._prefill_widths:
                raise RuntimeError(
                    'prompt bucket %d is not an edge %r'
                    % (bucket, list(self._prefill_widths)))
            body = (self._mapped(self._prefill_body_paged, 4)
                    if self.paged
                    else self._mapped(self._prefill_body, 3))
            exe, _ = self._compile(
                body,
                (self._cache_struct(),) + self._token_structs(bucket),
                self._prefill, bucket)
            return exe

    def _get_copy(self):
        """The CoW page-copy executable (paged only): compiled once,
        shape-keyed like every bucket executable, so admission-time
        copies never retrace."""
        if self._copy is not None:
            return self._copy[0]
        with self._lock:
            if self._copy is not None:
                return self._copy[0]
            body = self._copy_mapped()
            table = {}
            exe, aot = self._compile(
                body,
                (self._cache_struct(),
                 jax.ShapeDtypeStruct((), jnp.int32),
                 jax.ShapeDtypeStruct((), jnp.int32)),
                table, 'copy')
            self._copy = table['copy']
            return exe

    def _copy_mapped(self):
        if self.plan is None:
            return self._copy_body
        from jax.sharding import PartitionSpec as P
        pspecs = (self.param_specs if self.param_specs is not None
                  else P())
        return jax.shard_map(
            self._copy_body, mesh=self.plan.mesh,
            in_specs=(pspecs, self._cache_specs, P(), P()),
            out_specs=self._cache_specs, check_vma=False)

    def _copy_page(self, src, dst):
        """Duplicate pool page ``src`` into the private page ``dst``
        (already allocated by the caller).  A speculative engine
        duplicates the page in the DRAFT cache too: both caches are
        addressed through the same page table, so a copy-on-write
        divergence must fork them together."""
        exe = self._get_copy()
        self._cache = exe(self.params, self._cache,
                          jnp.asarray(src, jnp.int32),
                          jnp.asarray(dst, jnp.int32))
        if self.speculative:
            dexe = self._get_draft_copy()
            self._draft_cache = dexe(self._draft_params,
                                     self._draft_cache,
                                     jnp.asarray(src, jnp.int32),
                                     jnp.asarray(dst, jnp.int32))
        self.cow_copies += 1
        reg = _telemetry.registry()
        if reg is not None:
            reg.counter('serve_kv_cow_total',
                        help='copy-on-write page duplications at '
                             'prefix divergence').inc()

    def _decode_mapped(self, bucket):
        """The decode callable for one slot-count bucket -- what gets
        AOT-compiled, and what ``traceable_decode`` hands shardlint."""
        if self.paged:
            # paged operand order: (tokens, positions, page_tables);
            # the cache is read THROUGH the tables for every bucket
            return self._mapped(self._decode_body_paged, 3)
        if bucket == self.n_slots:
            # full bucket: every slot decodes, the cache is read IN
            # PLACE (no slots operand); rows are slots in order
            return self._mapped(
                lambda p, c, t, pos: self._decode_body(p, c, t, pos),
                2)
        # compacted bucket operand order: (tokens, slots, positions)
        # -- what _decode_structs declares and the scheduler passes
        return self._mapped(
            lambda p, c, t, s, pos: self._decode_body(
                p, c, t, pos, slots=s), 3)

    def _get_decode(self, bucket):
        hit = self._decode.get(bucket)
        if hit is not None:
            return hit[0]
        with self._lock:
            hit = self._decode.get(bucket)
            if hit is not None:
                return hit[0]
            if bucket not in self.decode_edges:
                raise RuntimeError(
                    'decode bucket %d is not an edge %r'
                    % (bucket, list(self.decode_edges)))
            exe, _ = self._compile(
                self._decode_mapped(bucket),
                (self._cache_struct(),) + self._decode_structs(bucket),
                self._decode, bucket)
            return exe

    def traceable_decode(self, bucket=None):
        """``(fn, args)`` for ``jax.make_jaxpr`` -- the EXACT mapped
        decode callable the engine compiles for ``bucket`` (default:
        the full-slot bucket, whose cache read is in place), on zero
        operands over the real cache/params: the shardlint
        ``step:decode_forward`` target traces production code."""
        bucket = bucket or self.n_slots
        fn = self._decode_mapped(bucket)
        args = [self.params, self._cache,
                jnp.zeros((bucket,), jnp.int32)]
        if self.paged:
            args.append(jnp.zeros((bucket,), jnp.int32))
            args.append(jnp.zeros((bucket, self.pages_per_seq),
                                  jnp.int32))
            return fn, tuple(args)
        if bucket != self.n_slots:
            args.append(jnp.arange(bucket, dtype=jnp.int32))
        args.append(jnp.zeros((bucket,), jnp.int32))
        return fn, tuple(args)

    # -- speculative executables ---------------------------------------
    def _get_draft_prefill(self, bucket):
        hit = self._draft_prefill.get(bucket)
        if hit is not None:
            return hit[0]
        with self._lock:
            hit = self._draft_prefill.get(bucket)
            if hit is not None:
                return hit[0]
            body = (self._draft_mapped(self._draft_prefill_body_paged,
                                       4)
                    if self.paged
                    else self._draft_mapped(self._draft_prefill_body,
                                            3))
            exe, _ = self._compile(
                body, (self._draft_cache_struct(),)
                + self._token_structs(bucket),
                self._draft_prefill, bucket,
                params=self._draft_params)
            return exe

    def _draft_decode_mapped(self, bucket):
        if self.paged:
            return self._draft_mapped(self._draft_decode_body_paged,
                                      3)
        if bucket == self.n_slots:
            return self._draft_mapped(
                lambda p, c, t, pos: self._draft_decode_body(
                    p, c, t, pos), 2)
        return self._draft_mapped(
            lambda p, c, t, s, pos: self._draft_decode_body(
                p, c, t, pos, slots=s), 3)

    def _get_draft_decode(self, bucket):
        hit = self._draft_decode.get(bucket)
        if hit is not None:
            return hit[0]
        with self._lock:
            hit = self._draft_decode.get(bucket)
            if hit is not None:
                return hit[0]
            exe, _ = self._compile(
                self._draft_decode_mapped(bucket),
                (self._draft_cache_struct(),)
                + self._decode_structs(bucket),
                self._draft_decode, bucket,
                params=self._draft_params)
            return exe

    def _verify_mapped(self, bucket):
        """The k-token verify callable for one slot bucket -- the
        decode callable's windowed twin, same operand orders."""
        if self.paged:
            return self._mapped(self._verify_body_paged, 3)
        if bucket == self.n_slots:
            return self._mapped(
                lambda p, c, t, pos: self._verify_body(p, c, t, pos),
                2)
        return self._mapped(
            lambda p, c, t, s, pos: self._verify_body(
                p, c, t, pos, slots=s), 3)

    def _get_verify(self, bucket):
        hit = self._verify.get(bucket)
        if hit is not None:
            return hit[0]
        with self._lock:
            hit = self._verify.get(bucket)
            if hit is not None:
                return hit[0]
            if bucket not in self.decode_edges:
                raise RuntimeError(
                    'verify bucket %d is not an edge %r'
                    % (bucket, list(self.decode_edges)))
            exe, _ = self._compile(
                self._verify_mapped(bucket),
                (self._cache_struct(),) + self._verify_structs(bucket),
                self._verify, bucket)
            return exe

    def _get_draft_copy(self):
        if self._draft_copy is not None:
            return self._draft_copy[0]
        with self._lock:
            if self._draft_copy is not None:
                return self._draft_copy[0]
            body = self._copy_body
            if self.plan is not None:
                from jax.sharding import PartitionSpec as P
                body = jax.shard_map(
                    self._copy_body, mesh=self.plan.mesh,
                    in_specs=(P(), P(), P(), P()), out_specs=P(),
                    check_vma=False)
            table = {}
            exe, aot = self._compile(
                body,
                (self._draft_cache_struct(),
                 jax.ShapeDtypeStruct((), jnp.int32),
                 jax.ShapeDtypeStruct((), jnp.int32)),
                table, 'copy', params=self._draft_params)
            self._draft_copy = table['copy']
            return exe

    def traceable_verify(self, bucket=None):
        """``(fn, args)`` for ``jax.make_jaxpr`` -- the EXACT mapped
        verify callable the speculative engine compiles for
        ``bucket``, on zero operands over the real cache/params: the
        shardlint ``step:spec_verify_forward`` target traces
        production code (the :meth:`traceable_decode` contract)."""
        bucket = bucket or self.n_slots
        fn = self._verify_mapped(bucket)
        args = [self.params, self._cache,
                jnp.zeros((bucket, self.spec_tokens), jnp.int32)]
        if self.paged:
            args.append(jnp.zeros((bucket,), jnp.int32))
            args.append(jnp.zeros((bucket, self.pages_per_seq),
                                  jnp.int32))
            return fn, tuple(args)
        if bucket != self.n_slots:
            args.append(jnp.arange(bucket, dtype=jnp.int32))
        args.append(jnp.zeros((bucket,), jnp.int32))
        return fn, tuple(args)

    def warmup(self):
        """Compile (or cache-load) every prefill and decode bucket
        executable eagerly, largest first.  Fallback (plain-jit)
        executables are forced to compile by running them on the real
        cache -- slots are all free, so the garbage they write is
        never attended (reads mask by live length).  Returns
        ``{'prefill': {bucket: aot}, 'decode': {bucket: aot}}``."""
        for bucket in sorted(self._prefill_widths, reverse=True):
            with _telemetry.span('serve_warmup', kind='serve',
                                 phase='prefill', bucket=bucket):
                exe = self._get_prefill(bucket)
                if not self._prefill[bucket][1]:
                    args = [jnp.zeros((1, bucket), jnp.int32),
                            jnp.asarray(1, jnp.int32),
                            jnp.asarray(0, jnp.int32)]
                    if self.paged:
                        # zero table: warmup garbage lands on the
                        # scratch page, never in a live table
                        args.append(jnp.zeros((self.pages_per_seq,),
                                              jnp.int32))
                    tok, cache = exe(self.params, self._cache, *args)
                    jax.block_until_ready(tok)
                    self._cache = cache
        for bucket in sorted(self.decode_edges, reverse=True):
            with _telemetry.span('serve_warmup', kind='serve',
                                 phase='decode', bucket=bucket):
                exe = self._get_decode(bucket)
                if not self._decode[bucket][1]:
                    if self.paged:
                        args = [jnp.zeros((bucket,), jnp.int32),
                                jnp.zeros((bucket,), jnp.int32),
                                jnp.zeros((bucket,
                                           self.pages_per_seq),
                                          jnp.int32)]
                    else:
                        args = [jnp.zeros((bucket,), jnp.int32),
                                jnp.zeros((bucket,), jnp.int32)]
                        if bucket != self.n_slots:
                            args.insert(1, jnp.arange(
                                bucket, dtype=jnp.int32))
                    tok, cache = exe(self.params, self._cache,
                                     args[0], *args[1:])
                    jax.block_until_ready(tok)
                    self._cache = cache
        if self.paged:
            with _telemetry.span('serve_warmup', kind='serve',
                                 phase='copy_page'):
                exe = self._get_copy()
                if not self._copy[1]:
                    zero = jnp.asarray(0, jnp.int32)
                    self._cache = exe(self.params, self._cache,
                                      zero, zero)
        if self.speculative:
            self._warmup_speculative()
        out = {'prefill': {b: a for b, (_, a)
                           in sorted(self._prefill.items())},
               'decode': {b: a for b, (_, a)
                          in sorted(self._decode.items())}}
        if self.speculative:
            out['draft_prefill'] = {
                b: a for b, (_, a)
                in sorted(self._draft_prefill.items())}
            out['draft_decode'] = {
                b: a for b, (_, a)
                in sorted(self._draft_decode.items())}
            out['verify'] = {b: a for b, (_, a)
                             in sorted(self._verify.items())}
        return out

    def _warmup_speculative(self):
        """Warm the draft-prefill / draft-decode / verify bucket
        families (largest first, same fallback force-run contract as
        the base families: free slots + zero tables make warmup
        garbage structurally unattendable)."""
        for bucket in sorted(self._prefill_widths, reverse=True):
            with _telemetry.span('serve_warmup', kind='serve',
                                 phase='draft_prefill',
                                 bucket=bucket):
                exe = self._get_draft_prefill(bucket)
                if not self._draft_prefill[bucket][1]:
                    args = [jnp.zeros((1, bucket), jnp.int32),
                            jnp.asarray(1, jnp.int32),
                            jnp.asarray(0, jnp.int32)]
                    if self.paged:
                        args.append(jnp.zeros((self.pages_per_seq,),
                                              jnp.int32))
                    tok, dcache = exe(self._draft_params,
                                      self._draft_cache, *args)
                    jax.block_until_ready(tok)
                    self._draft_cache = dcache
        for bucket in sorted(self.decode_edges, reverse=True):
            with _telemetry.span('serve_warmup', kind='serve',
                                 phase='draft_decode', bucket=bucket):
                exe = self._get_draft_decode(bucket)
                if not self._draft_decode[bucket][1]:
                    args = self._zero_decode_args(bucket)
                    tok, dcache = exe(self._draft_params,
                                      self._draft_cache, *args)
                    jax.block_until_ready(tok)
                    self._draft_cache = dcache
            with _telemetry.span('serve_warmup', kind='serve',
                                 phase='verify', bucket=bucket):
                exe = self._get_verify(bucket)
                if not self._verify[bucket][1]:
                    args = self._zero_decode_args(
                        bucket, window=self.spec_tokens)
                    tok, cache = exe(self.params, self._cache, *args)
                    jax.block_until_ready(tok)
                    self._cache = cache
        if self.paged:
            with _telemetry.span('serve_warmup', kind='serve',
                                 phase='draft_copy_page'):
                exe = self._get_draft_copy()
                if not self._draft_copy[1]:
                    zero = jnp.asarray(0, jnp.int32)
                    self._draft_cache = exe(self._draft_params,
                                            self._draft_cache,
                                            zero, zero)

    def _zero_decode_args(self, bucket, window=None):
        """Zero operands matching :meth:`_decode_structs` (or the
        verify structs when ``window`` is set) -- the warmup
        force-run inputs."""
        shape = (bucket,) if window is None else (bucket, window)
        args = [jnp.zeros(shape, jnp.int32)]
        if self.paged:
            args.append(jnp.zeros((bucket,), jnp.int32))
            args.append(jnp.zeros((bucket, self.pages_per_seq),
                                  jnp.int32))
        else:
            if bucket != self.n_slots:
                args.append(jnp.arange(bucket, dtype=jnp.int32))
            args.append(jnp.zeros((bucket,), jnp.int32))
        return args

    def guard_signature(self, args):
        """The SL007 machinery as a runtime pin (the engine.py
        contract): refuse any operand signature outside the
        precompiled prefill/decode set instead of silently
        retracing."""
        sig = abstract_signature(args)
        if sig not in self._signatures:
            raise RuntimeError(
                'no-recompile guard: operand signature %r is outside '
                'the precompiled prefill/decode bucket set -- the '
                'scheduler and executables disagree on bucket '
                'geometry' % (sig,))
        return sig

    # -- the continuous-batching scheduler -----------------------------
    def _expire(self, now, force=0):
        """Shed active requests whose deadline passed (or the
        ``force`` oldest, for the serve_cancel chaos site): typed
        ``OverloadError(reason='deadline')`` NOW, slot freed for
        refill at the next step's admission."""
        doomed = []
        for sid, slot in self._slots.items():
            dl = slot.request.deadline
            if dl is not None and now > dl:
                doomed.append(sid)
        if force:
            for sid in sorted(
                    (s for s in self._slots if s not in doomed),
                    key=lambda s: self._slots[s].request.t_submit
            )[:force]:
                doomed.append(sid)
        for sid in doomed:
            slot = self._slots.pop(sid)
            self._release_pages(slot.pages)
            self._free.append(sid)
            self.cancelled += 1
            slot.request.set_error(OverloadError(
                'deadline expired mid-generation after %d tokens'
                % len(slot.generated), reason='deadline'))
            _telemetry.event('serve_cancel', kind='serve', slot=sid,
                             tokens=len(slot.generated))
            record_shed('deadline',
                        request_id=slot.request.request_id,
                        queue_depth=self._last_queue_depth,
                        slot=sid, tokens=len(slot.generated),
                        **self._ident())
        # mid-prefill expiry (paged): a chunked prompt can outlive its
        # deadline between chunks
        for sid in [s for s, st in self._prefilling.items()
                    if st.request.deadline is not None
                    and now > st.request.deadline]:
            state = self._prefilling.pop(sid)
            self._release_pages(state.pages)
            self._free.append(sid)
            self.cancelled += 1
            doomed.append(sid)
            state.request.set_error(OverloadError(
                'deadline expired mid-prefill at position %d'
                % state.pos, reason='deadline'))
            _telemetry.event('serve_cancel', kind='serve', slot=sid,
                             tokens=0)
            record_shed('deadline',
                        request_id=state.request.request_id,
                        queue_depth=self._last_queue_depth,
                        slot=sid, position=state.pos, **self._ident())
        return len(doomed)

    # -- paged-mode page accounting ------------------------------------
    def _release_pages(self, pages):
        if pages:
            for page in pages:
                self.pool.release(page)

    def _alloc_page(self):
        """One free page, LRU-evicting banked prefixes when the pool
        is dry; ``None`` only when nothing is evictable either (the
        caller sheds typed)."""
        page = self.pool.alloc()
        while page is None and self._prefix_index is not None \
                and self._prefix_index.evict(1):
            page = self.pool.alloc()
        return page

    def _table_array(self, pages):
        table = np.zeros((self.pages_per_seq,), np.int32)
        table[:len(pages)] = pages
        return table

    def _shed_paged(self, req, pages, where):
        """Typed shed when the page pool is exhausted (the paged twin
        of queue_full): pages retained so far go back, the client
        gets ``OverloadError(reason='kv_pages')``."""
        self._release_pages(pages)
        self.cancelled += 1
        record_shed('kv_pages', request_id=req.request_id,
                    queue_depth=self._last_queue_depth, where=where,
                    **self._ident())
        req.set_error(OverloadError(
            'KV page pool exhausted (%d/%d pages live, nothing '
            'evictable) during %s; retry with backoff'
            % (self.pool.in_use(), self.pool.n_pages, where),
            reason='kv_pages'))

    def _admit_budget(self):
        """Admissions this tick: every free slot, unless the fleet
        degradation ladder capped it (``admit_cap``)."""
        if self.admit_cap is None:
            return len(self._free)
        return min(len(self._free), max(0, int(self.admit_cap)))

    def _admit(self, queue, now, clock):
        """Refill free slots from the queue: one PREFILL per request
        (bucketed by prompt length), TTFT recorded when its first
        token lands.  With telemetry on, each admitted request gets
        its trace stages recorded: ``queue_wait`` (admission stamp ->
        pop), ``bucket_pack`` (pop -> prefill dispatch, carrying the
        prompt bucket + pad fraction) and ``prefill`` (-> first
        token), each starting where the previous ended."""
        if self.paged:
            self._admit_paged(queue, now, clock)
            return
        rec = _telemetry.active()
        reg = _telemetry.registry()
        ident = self._ident()
        for req in queue.pop(self._admit_budget()):
            sid = self._free.pop(0)
            prompt = req.prompt
            t_pop = rec.now() if rec is not None else None
            if rec is not None:
                t0 = req.t_trace0
                if t0 is None:   # telemetry enabled mid-flight
                    t0 = t_pop - (clock() - req.t_submit)
                rec.child_span(req.request_id, 'queue_wait', t0,
                               t_pop, seq=req.seq, **ident)
            bucket = bucket_of(prompt.size, self.prefill_edges)
            tokens = np.zeros((1, bucket), np.int32)
            tokens[0, :prompt.size] = prompt
            exe = self._get_prefill(bucket)
            args = (jnp.asarray(tokens),
                    jnp.asarray(prompt.size, jnp.int32),
                    jnp.asarray(sid, jnp.int32))
            self.guard_signature((self._cache_struct(),) + tuple(
                jax.ShapeDtypeStruct(a.shape, a.dtype) for a in args))
            t_pf0 = rec.now() if rec is not None else None
            if rec is not None:
                rec.child_span(
                    req.request_id, 'bucket_pack', t_pop, t_pf0,
                    bucket=bucket, pad_fraction=round(
                        (bucket - prompt.size) / float(bucket), 4),
                    **ident)
            if _chaos._active is not None:
                _chaos.on_serve_slow(
                    self.param_version != self._boot_version)
            with _telemetry.span('serve_prefill', kind='serve',
                                 bucket=bucket, slot=sid,
                                 iteration=self._step_index,
                                 **ident):
                tok, cache = exe(self.params, self._cache, *args)
                tok = int(jax.block_until_ready(tok))
            self._cache = cache
            if self.speculative:
                # the draft prefills the same prompt into ITS cache at
                # the same slot (its proposals need the prompt's K/V);
                # the draft's own first-token logits are discarded --
                # the target's token is authoritative
                dexe = self._get_draft_prefill(bucket)
                self.guard_signature(
                    (self._draft_cache_struct(),) + tuple(
                        jax.ShapeDtypeStruct(a.shape, a.dtype)
                        for a in args))
                with _telemetry.span('serve_draft', kind='serve',
                                     stage='prefill', bucket=bucket,
                                     slot=sid,
                                     iteration=self._step_index,
                                     **ident):
                    dtok, dcache = dexe(self._draft_params,
                                        self._draft_cache, *args)
                    jax.block_until_ready(dtok)
                self._draft_cache = dcache
            self.prefills += 1
            self.tokens_generated += 1
            t_first = clock()
            t_first_tele = None
            if rec is not None:
                t_first_tele = rec.now()
                rec.child_span(req.request_id, 'prefill', t_pf0,
                               t_first_tele, bucket=bucket, slot=sid,
                               prompt_tokens=int(prompt.size),
                               **ident)
            if reg is not None:
                reg.histogram(
                    'serve_ttft_seconds',
                    help='submit-to-first-token latency (s)'
                ).observe(t_first - req.t_submit)
                reg.counter('serve_tokens_total',
                            help='generated tokens').inc()
            req.notify_tokens([tok])
            if self.eos_id is not None and tok == self.eos_id \
                    or req.max_new_tokens == 1:
                req.set_result([tok])
                self._free.append(sid)
                if rec is not None:
                    rec.event('complete', kind='request',
                              request_id=req.request_id, tokens=1,
                              slot=sid, **ident)
                continue
            self._slots[sid] = _Slot(req, prompt.size,
                                     req.max_new_tokens - 1, tok,
                                     t_first,
                                     t_stage_end=t_first_tele)

    def _admit_paged(self, queue, now, clock):
        """Paged admission: claim a slot id, walk the prefix index for
        the longest banked prefix (retaining shared FULL pages; a
        partially-covered boundary page is copy-on-write-duplicated
        ONCE, here), and park the request in ``self._prefilling`` --
        the actual prefill work happens chunk-by-chunk in
        :meth:`_prefill_tick`, interleaved with decode steps."""
        rec = _telemetry.active()
        reg = _telemetry.registry()
        ident = self._ident()
        group = self._prefix_index is not None
        for req in queue.pop(self._admit_budget(), group_prefix=group):
            sid = self._free.pop(0)
            prompt = req.prompt
            t_pop = rec.now() if rec is not None else None
            if rec is not None:
                t0 = req.t_trace0
                if t0 is None:   # telemetry enabled mid-flight
                    t0 = t_pop - (clock() - req.t_submit)
                rec.child_span(req.request_id, 'queue_wait', t0,
                               t_pop, seq=req.seq, **ident)
            pages, matched = [], 0
            if self._prefix_index is not None:
                shared, tail_page, tail_len = \
                    self._prefix_index.lookup(prompt)
                # always recompute >= 1 prompt token: the final chunk
                # must produce first-token logits, so cap the match at
                # size-1 and demote an over-covering full page to a
                # copy-on-write tail candidate
                max_match = prompt.size - 1
                dropped = None
                while len(shared) * self.page_size > max_match:
                    dropped = shared.pop()
                for page in shared:
                    self.pool.retain(page)
                    pages.append(page)
                matched = len(shared) * self.page_size
                if dropped is not None:
                    tail_page, tail_len = dropped, self.page_size
                tail_use = (min(tail_len, max_match - matched)
                            if tail_page is not None else 0)
                if tail_use > 0:
                    dst = self._alloc_page()
                    if dst is None:
                        self._shed_paged(req, pages, 'admission')
                        self._free.append(sid)
                        continue
                    self._copy_page(tail_page, dst)
                    pages.append(dst)
                    matched += tail_use
                if reg is not None and matched:
                    reg.counter(
                        'serve_prefix_hits_total',
                        help='admissions that reused a banked '
                             'prompt prefix').inc()
                    reg.counter(
                        'serve_prefix_tokens_total',
                        help='prompt tokens served from banked '
                             'prefix pages').inc(matched)
            self._prefilling[sid] = _PrefillState(
                req, pages, matched, matched, t_pop=t_pop,
                t_stage_end=t_pop)

    def _prefill_tick(self, clock):
        """Advance every mid-prefill sequence by ONE chunk (SARATHI
        schedule: chunks interleave with decode ticks so a long
        prompt's compute cannot monopolize the device and blow up
        inter-token latency for live sequences).  Without
        ``prefill_chunk`` configured the whole remaining prompt runs
        as a single chunk (bucketed like slot-mode prefill).

        The final chunk -- the only one producing first-token logits
        -- emits the ``prefill`` trace stage (so TTFT accounting is
        unchanged); intermediate chunks emit ``prefill_chunk`` spans
        the SLO monitor ignores.  A finished prompt's pages are banked
        into the prefix index before the sequence moves to decode."""
        rec = _telemetry.active()
        reg = _telemetry.registry()
        ident = self._ident()
        worked = False
        for sid in sorted(self._prefilling):
            st = self._prefilling[sid]
            req = st.request
            prompt = req.prompt
            remaining = prompt.size - st.pos
            if self.prefill_chunk is not None:
                width = self.prefill_chunk
            else:
                width = bucket_of(remaining, self.prefill_edges)
            n = min(width, remaining)
            last_page = (st.pos + n - 1) // self.page_size
            dry = False
            while len(st.pages) <= last_page:
                page = self._alloc_page()
                if page is None:
                    dry = True
                    break
                st.pages.append(page)
            if dry:
                del self._prefilling[sid]
                self._shed_paged(req, st.pages, 'prefill')
                self._free.append(sid)
                continue
            worked = True
            tokens = np.zeros((1, width), np.int32)
            tokens[0, :n] = prompt[st.pos:st.pos + n]
            exe = self._get_prefill(width)
            args = (jnp.asarray(tokens),
                    jnp.asarray(n, jnp.int32),
                    jnp.asarray(st.pos, jnp.int32),
                    jnp.asarray(self._table_array(st.pages)))
            self.guard_signature((self._cache_struct(),) + tuple(
                jax.ShapeDtypeStruct(a.shape, a.dtype) for a in args))
            if rec is not None and st.chunks == 0:
                t_c0 = rec.now()
                rec.child_span(
                    req.request_id, 'bucket_pack', st.t_stage_end,
                    t_c0, bucket=width,
                    pad_fraction=round((width - n) / float(width), 4),
                    prefix_tokens=st.matched, **ident)
                st.t_stage_end = t_c0
            if _chaos._active is not None:
                _chaos.on_serve_slow(
                    self.param_version != self._boot_version)
            with _telemetry.span('serve_prefill', kind='serve',
                                 bucket=width, slot=sid,
                                 chunk=st.chunks, pos=st.pos,
                                 iteration=self._step_index, **ident):
                tok, cache = exe(self.params, self._cache, *args)
                tok = jax.block_until_ready(tok)
            self._cache = cache
            if self.speculative:
                # same chunk, same pages, into the draft cache: banked
                # prefix pages stay valid for BOTH caches, so a future
                # prefix hit serves the draft too
                dexe = self._get_draft_prefill(width)
                self.guard_signature(
                    (self._draft_cache_struct(),) + tuple(
                        jax.ShapeDtypeStruct(a.shape, a.dtype)
                        for a in args))
                with _telemetry.span('serve_draft', kind='serve',
                                     stage='prefill', bucket=width,
                                     slot=sid, chunk=st.chunks,
                                     iteration=self._step_index,
                                     **ident):
                    dtok, dcache = dexe(self._draft_params,
                                        self._draft_cache, *args)
                    jax.block_until_ready(dtok)
                self._draft_cache = dcache
            st.pos += n
            st.chunks += 1
            self.prefill_chunks += 1
            if st.pos < prompt.size:
                if rec is not None:
                    now_tele = rec.now()
                    rec.child_span(req.request_id, 'prefill_chunk',
                                   st.t_stage_end, now_tele,
                                   bucket=width, slot=sid,
                                   chunk=st.chunks - 1, pos=st.pos,
                                   **ident)
                    st.t_stage_end = now_tele
                continue
            tok = int(tok)
            del self._prefilling[sid]
            self.prefills += 1
            self.tokens_generated += 1
            t_first = clock()
            t_first_tele = None
            if rec is not None:
                t_first_tele = rec.now()
                rec.child_span(req.request_id, 'prefill',
                               st.t_stage_end, t_first_tele,
                               bucket=width, slot=sid,
                               prompt_tokens=int(prompt.size),
                               chunks=st.chunks,
                               prefix_tokens=st.matched, **ident)
            if reg is not None:
                reg.histogram(
                    'serve_ttft_seconds',
                    help='submit-to-first-token latency (s)'
                ).observe(t_first - req.t_submit)
                reg.counter('serve_tokens_total',
                            help='generated tokens').inc()
            if self._prefix_index is not None:
                n_cover = -(-prompt.size // self.page_size)
                self._prefix_index.insert(prompt,
                                          st.pages[:n_cover])
            req.notify_tokens([tok])
            if self.eos_id is not None and tok == self.eos_id \
                    or req.max_new_tokens == 1:
                req.set_result([tok])
                self._release_pages(st.pages)
                self._free.append(sid)
                if rec is not None:
                    rec.event('complete', kind='request',
                              request_id=req.request_id, tokens=1,
                              slot=sid, **ident)
                continue
            self._slots[sid] = _Slot(req, prompt.size,
                                     req.max_new_tokens - 1, tok,
                                     t_first,
                                     t_stage_end=t_first_tele,
                                     pages=st.pages)
        return worked

    def _decode_once(self, clock):
        """One decode step over every active slot, compacted to the
        smallest slot-count bucket; finished sequences resolve and
        free their slots (refilled at the NEXT step)."""
        if self.paged:
            # grow page tables across page boundaries BEFORE dispatch
            # (a sequence whose next token starts a new page gets one
            # allocated now; a dry pool sheds typed)
            for sid in sorted(self._slots):
                slot = self._slots[sid]
                need = slot.position // self.page_size
                while len(slot.pages) <= need:
                    page = self._alloc_page()
                    if page is None:
                        del self._slots[sid]
                        self._shed_paged(slot.request, slot.pages,
                                         'decode')
                        self._free.append(sid)
                        break
                    slot.pages.append(page)
            if not self._slots:
                return
        active = sorted(self._slots)
        k = len(active)
        bucket = bucket_of(k, self.decode_edges)
        if self.paged:
            # paged rows are positional (the page table IS the
            # addressing); pad rows carry all-zero tables, so their
            # garbage token lands on the scratch page
            rows = active + [None] * (bucket - k)
        elif bucket == self.n_slots:
            # the full-slot executable reads the cache IN PLACE (no
            # slots operand): row i IS slot i, so rows must be every
            # slot in id order even when k < n_slots -- an inactive
            # row writes a garbage token at position 0 of its FREE
            # slot, overwritten by that slot's next prefill
            rows = list(range(self.n_slots))
        else:
            # compacted bucket: pad with FREE slots (guaranteed
            # available: bucket < n_slots and only k are active) --
            # same garbage-write-to-a-free-slot contract as above
            rows = active + self._free[:bucket - k]
        tokens = np.asarray(
            [self._slots[s].generated[-1] if s in self._slots else 0
             for s in rows], np.int32)
        positions = np.asarray(
            [self._slots[s].position if s in self._slots else 0
             for s in rows], np.int32)
        exe = self._get_decode(bucket)
        if self.paged:
            tables = np.zeros((bucket, self.pages_per_seq), np.int32)
            for i, sid in enumerate(rows):
                if sid is not None:
                    pages = self._slots[sid].pages
                    tables[i, :len(pages)] = pages
            args = (jnp.asarray(tokens), jnp.asarray(positions),
                    jnp.asarray(tables))
        elif bucket == self.n_slots:
            args = (jnp.asarray(tokens), jnp.asarray(positions))
        else:
            args = (jnp.asarray(tokens),
                    jnp.asarray(np.asarray(rows, np.int32)),
                    jnp.asarray(positions))
        self.guard_signature((self._cache_struct(),) + tuple(
            jax.ShapeDtypeStruct(a.shape, a.dtype) for a in args))
        rec = _telemetry.active()
        reg = _telemetry.registry()
        ident = self._ident()
        if reg is not None:
            reg.gauge('active_slots',
                      help='live sequences at this decode step'
                      ).set(k)
        if _chaos._active is not None:
            _chaos.on_serve_slow(
                self.param_version != self._boot_version)
        t0 = clock()
        with _telemetry.span('serve_decode', kind='serve',
                             iteration=self._step_index,
                             active_slots=k, bucket=bucket,
                             n_slots=self.n_slots,
                             queue_depth=self._last_queue_depth,
                             **ident):
            toks, cache = exe(self.params, self._cache, *args)
            toks = np.asarray(jax.block_until_ready(toks))
        self._cache = cache
        now = clock()
        now_tele = rec.now() if rec is not None else None
        if reg is not None:
            reg.histogram('serve_decode_seconds',
                          help='per-decode-step wall time (s)'
                          ).observe(now - t0)
            reg.counter('serve_tokens_total',
                        help='generated tokens').inc(k)
        itl = (reg.histogram('serve_intertoken_seconds',
                             help='per-sequence gap between '
                                  'consecutive tokens (s)')
               if reg is not None else None)
        for i, sid in enumerate(rows):
            slot = self._slots.get(sid)
            if slot is None:
                continue   # free pad row (or inactive full-bucket row)
            tok = int(toks[i])
            slot.generated.append(tok)
            slot.request.notify_tokens([tok])
            slot.position += 1
            slot.remaining -= 1
            if itl is not None:
                itl.observe(now - slot.t_last_token)
            slot.t_last_token = now
            if rec is not None:
                # one decode stage per live slot per tick, starting at
                # the request's previous stage end: the span absorbs
                # any scheduler wait between ticks (a neighbor's slow
                # prefill IS latency this request paid), which is
                # exactly what makes the stage budgets sum to the
                # end-to-end latency
                t_prev = slot.t_stage_end
                if t_prev is None:
                    t_prev = now_tele - (now - t0)
                rec.child_span(slot.request.request_id, 'decode',
                               t_prev, now_tele, slot=sid,
                               step=self._step_index,
                               token_index=len(slot.generated) - 1,
                               **ident)
                slot.t_stage_end = now_tele
            if slot.remaining == 0 or (self.eos_id is not None
                                       and tok == self.eos_id):
                slot.request.set_result(slot.generated)
                if rec is not None:
                    rec.event('complete', kind='request',
                              request_id=slot.request.request_id,
                              tokens=len(slot.generated), slot=sid,
                              **ident)
                self._release_pages(slot.pages)
                del self._slots[sid]
                self._free.append(sid)
        self.decode_steps += 1
        self.tokens_generated += k

    def _spec_once(self, clock):
        """One SPECULATIVE tick over every active slot: ``spec_tokens``
        draft-decode steps propose a window, ONE target verify
        executable scores all of it, and each slot commits the longest
        prefix where draft and target argmax agree PLUS the target's
        own next token (the correction at the first divergence, the
        bonus on full acceptance) -- so every tick emits 1..k tokens
        for one expensive target pass, and a rejection at draft
        position 0 degenerates to exactly the plain decode step.

        Rollback is a position rewind: rejected window positions'
        K/V (and int8 scales) in BOTH caches stay as garbage masked
        by the live length -- the reused-slot contract -- and in
        paged mode the page-table tail past the accepted boundary is
        released back to the pool so refcounts track committed tokens
        only."""
        kk = self.spec_tokens
        if self.paged:
            # grow page tables to cover the WHOLE window [position,
            # position + k) before dispatch; overhang past the cache
            # depth is clamped (those rows write scratch, never commit)
            for sid in sorted(self._slots):
                slot = self._slots[sid]
                last = min(slot.position + kk - 1, self.max_len - 1)
                need = last // self.page_size
                while len(slot.pages) <= need:
                    page = self._alloc_page()
                    if page is None:
                        del self._slots[sid]
                        self._shed_paged(slot.request, slot.pages,
                                         'decode')
                        self._free.append(sid)
                        break
                    slot.pages.append(page)
            if not self._slots:
                return
        active = sorted(self._slots)
        k = len(active)
        bucket = bucket_of(k, self.decode_edges)
        if self.paged:
            rows = active + [None] * (bucket - k)
        elif bucket == self.n_slots:
            rows = list(range(self.n_slots))
        else:
            rows = active + self._free[:bucket - k]
        base_tok = np.asarray(
            [self._slots[s].generated[-1] if s in self._slots else 0
             for s in rows], np.int32)
        base_pos = np.asarray(
            [self._slots[s].position if s in self._slots else 0
             for s in rows], np.int32)
        tables = None
        if self.paged:
            tables = np.zeros((bucket, self.pages_per_seq), np.int32)
            for i, sid in enumerate(rows):
                if sid is not None:
                    pages = self._slots[sid].pages
                    tables[i, :len(pages)] = pages
        rec = _telemetry.active()
        reg = _telemetry.registry()
        ident = self._ident()
        if reg is not None:
            reg.gauge('active_slots',
                      help='live sequences at this decode step'
                      ).set(k)
        if _chaos._active is not None:
            _chaos.on_serve_slow(
                self.param_version != self._boot_version)
        t0 = clock()

        def operand_args(tok, pos):
            if self.paged:
                return (jnp.asarray(tok), jnp.asarray(pos),
                        jnp.asarray(tables))
            if bucket == self.n_slots:
                return (jnp.asarray(tok), jnp.asarray(pos))
            return (jnp.asarray(tok),
                    jnp.asarray(np.asarray(rows, np.int32)),
                    jnp.asarray(pos))

        # -- draft loop: k cheap steps propose the window -------------
        d_exe = self._get_draft_decode(bucket)
        proposals = np.zeros((bucket, kk), np.int32)
        cur = base_tok
        with _telemetry.span('serve_draft', kind='serve',
                             stage='decode',
                             iteration=self._step_index,
                             active_slots=k, bucket=bucket,
                             window=kk, **ident):
            for j in range(kk):
                # clamp overhang past the cache depth: the write lands
                # on a not-yet-committed row, the proposal is garbage,
                # and garbage past the boundary is never committed
                pos = np.minimum(base_pos + j,
                                 self.max_len - 1).astype(np.int32)
                args = operand_args(cur, pos)
                self.guard_signature(
                    (self._draft_cache_struct(),) + tuple(
                        jax.ShapeDtypeStruct(a.shape, a.dtype)
                        for a in args))
                toks, dcache = d_exe(self._draft_params,
                                     self._draft_cache, *args)
                self._draft_cache = dcache
                cur = np.asarray(jax.block_until_ready(toks))
                proposals[:, j] = cur
                self.draft_steps += 1
        # window row: [last committed token, draft_1 .. draft_{k-1}];
        # the k-th draft proposal is never verified -- its draft step
        # exists to keep the draft cache covering every position the
        # window can commit
        win = np.zeros((bucket, kk), np.int32)
        win[:, 0] = base_tok
        win[:, 1:] = proposals[:, :kk - 1]
        # -- the ONE target pass --------------------------------------
        v_exe = self._get_verify(bucket)
        vargs = operand_args(win, base_pos)
        self.guard_signature((self._cache_struct(),) + tuple(
            jax.ShapeDtypeStruct(a.shape, a.dtype) for a in vargs))
        with _telemetry.span('serve_verify', kind='serve',
                             iteration=self._step_index,
                             active_slots=k, bucket=bucket,
                             window=kk, n_slots=self.n_slots,
                             queue_depth=self._last_queue_depth,
                             **ident):
            tgt, cache = v_exe(self.params, self._cache, *vargs)
            tgt = np.asarray(jax.block_until_ready(tgt))
        self._cache = cache
        self.verify_steps += 1
        now = clock()
        now_tele = rec.now() if rec is not None else None
        itl = (reg.histogram('serve_intertoken_seconds',
                             help='per-sequence gap between '
                                  'consecutive tokens (s)')
               if reg is not None else None)
        proposed_tick = accepted_tick = emitted_total = 0
        # -- host-side accept-prefix + commit/rollback ----------------
        for i, sid in enumerate(rows):
            slot = self._slots.get(sid)
            if slot is None:
                continue   # pad row (or inactive full-bucket row)
            drafts = win[i, 1:]       # the k-1 verified proposals
            targets = tgt[i]          # target argmax after win[i, j]
            m = 0
            while m < kk - 1 and drafts[m] == targets[m]:
                m += 1
            proposed_tick += kk - 1
            accepted_tick += m
            emitted = ([int(x) for x in drafts[:m]]
                       + [int(targets[m])])
            # clip to the request's budget (a window near the end
            # proposes more than max_new_tokens allows)
            emitted = emitted[:min(len(emitted), slot.remaining)]
            if self.eos_id is not None and self.eos_id in emitted:
                # EOS inside the accepted prefix ends the request
                # exactly where the oracle loop would have stopped
                emitted = emitted[:emitted.index(self.eos_id) + 1]
            c = len(emitted)
            slot.generated.extend(emitted)
            slot.request.notify_tokens(emitted)
            slot.position += c
            slot.remaining -= c
            emitted_total += c
            if itl is not None:
                gap = (now - slot.t_last_token) / c
                for _ in range(c):
                    itl.observe(gap)
            slot.t_last_token = now
            if rec is not None:
                t_prev = slot.t_stage_end
                if t_prev is None:
                    t_prev = now_tele - (now - t0)
                rec.child_span(slot.request.request_id, 'decode',
                               t_prev, now_tele, slot=sid,
                               step=self._step_index,
                               token_index=len(slot.generated) - 1,
                               tokens=c, accepted=m, **ident)
                slot.t_stage_end = now_tele
            if slot.remaining == 0 or (self.eos_id is not None
                                       and emitted[-1] == self.eos_id):
                slot.request.set_result(slot.generated)
                if rec is not None:
                    rec.event('complete', kind='request',
                              request_id=slot.request.request_id,
                              tokens=len(slot.generated), slot=sid,
                              **ident)
                self._release_pages(slot.pages)
                del self._slots[sid]
                self._free.append(sid)
            elif self.paged:
                # rollback the page-table tail to the accepted
                # boundary: pages grown for rejected window positions
                # go back to the pool NOW (refcounts track committed
                # tokens, not speculation)
                keep = (slot.position - 1) // self.page_size + 1
                while len(slot.pages) > keep:
                    self.pool.release(slot.pages.pop())
        self.draft_proposed += proposed_tick
        self.draft_accepted += accepted_tick
        self.decode_steps += 1
        self.tokens_generated += emitted_total
        if reg is not None:
            reg.histogram('serve_decode_seconds',
                          help='per-decode-step wall time (s)'
                          ).observe(now - t0)
            reg.counter('serve_tokens_total',
                        help='generated tokens').inc(emitted_total)
            reg.counter(
                'serve_draft_proposed_total',
                help='draft tokens submitted to target verify'
            ).inc(proposed_tick)
            reg.counter(
                'serve_draft_accepted_total',
                help='draft tokens whose target argmax agreed'
            ).inc(accepted_tick)
        if rec is not None:
            rec.event('serve_spec', kind='serve',
                      iteration=self._step_index,
                      proposed=proposed_tick, accepted=accepted_tick,
                      tokens=emitted_total, **ident)

    def _flight_table(self):
        """The in-flight request table embedded in every flight dump
        (:attr:`Recorder.flight_sources`): which requests were alive,
        in which slot, at which stage, with how many tokens emitted --
        so a crash mid-generation names which requests died where."""
        active = []
        for sid in sorted(self._prefilling):
            try:
                st = self._prefilling[sid]
            except KeyError:
                continue   # racing refill on the dying process
            active.append({'slot': sid,
                           'request_id': st.request.request_id,
                           'stage': 'prefill',
                           'tokens': 0,
                           'position': st.pos,
                           'remaining': st.request.max_new_tokens})
        for sid in sorted(self._slots):
            try:
                slot = self._slots[sid]
            except KeyError:
                continue   # racing refill on the dying process
            active.append({'slot': sid,
                           'request_id': slot.request.request_id,
                           'stage': 'decode',
                           'tokens': len(slot.generated),
                           'position': slot.position,
                           'remaining': slot.remaining})
        return {'active': active,
                'free_slots': list(self._free),
                'step_index': self._step_index,
                'queue_depth': self._last_queue_depth}

    def step(self, queue, clock=time.monotonic):
        """One scheduler tick: expire -> admit (slot refill) -> one
        decode step.  Returns True when any work happened.

        With telemetry on, queue pressure is sampled EVERY tick --
        ``serve_queue_depth`` (waiting requests, all still needing
        prefill) and the backlog split ``serve_prefill_backlog`` /
        ``serve_decode_backlog`` (live slots still generating) -- so
        pressure ONSET is visible in captures, not just its latency
        consequences; the engine's in-flight request table is also
        registered as a flight-dump source."""
        rec = _telemetry.active()
        depth = queue.depth()
        self._last_queue_depth = depth
        if rec is not None:
            if rec.flight_sources.get('serve_requests') \
                    != self._flight_table:
                rec.flight_sources['serve_requests'] = \
                    self._flight_table
            reg = rec.registry
            reg.gauge('serve_queue_depth',
                      help='requests waiting in the generation '
                           'queue at the scheduler tick').set(depth)
            reg.gauge('serve_prefill_backlog',
                      help='queued requests still needing their '
                           'prefill pass (queued + mid-prefill)'
                      ).set(depth + len(self._prefilling))
            reg.gauge('serve_decode_backlog',
                      help='live slots still generating at the '
                           'scheduler tick').set(len(self._slots))
            if self.paged:
                reg.gauge('serve_kv_pages_in_use',
                          help='allocated KV pages (live sequences '
                               '+ banked prefixes) at the tick'
                          ).set(self.pool.in_use())
                reg.gauge('serve_kv_pages_free',
                          help='free KV pages at the tick'
                          ).set(self.pool.available())
        now = clock()
        force = (_chaos.on_serve_cancel()
                 if _chaos._active is not None else 0)
        self._expire(now, force=force)
        self._admit(queue, now, clock)
        worked = False
        if self.paged and self._prefilling:
            worked = self._prefill_tick(clock)
        if self._slots:
            if _chaos._active is not None:
                # replica_kill counts DECODE ticks (slots live), so a
                # fired site always dies with generations in flight --
                # the unplanned-death scenario the fleet front's
                # journal replay must recover
                _chaos.on_replica_kill()
            if self.speculative:
                self._spec_once(clock)
            else:
                self._decode_once(clock)
            worked = True
        if not worked:
            return False
        self._step_index += 1
        return True

    def run(self, queue, stop=None, idle_sleep=0.002):
        """Scheduler loop: tick until ``stop`` is set AND the queue
        and slot table are drained (the loadgen worker loop)."""
        while True:
            worked = self.step(queue)
            if not worked:
                if stop is not None and stop.is_set() \
                        and queue.depth() == 0 and not self._slots \
                        and not self._prefilling:
                    return
                time.sleep(idle_sleep)

    def stats(self):
        paged = {}
        if self.paged:
            paged = {
                'paged': True,
                'page_size': self.page_size,
                'n_pages': self.n_pages,
                'pages_per_seq': self.pages_per_seq,
                'pages_in_use': self.pool.in_use(),
                'pages_free': self.pool.available(),
                'peak_pages_in_use': self.pool.peak_in_use,
                'prefill_chunk': self.prefill_chunk,
                'prefill_chunks': self.prefill_chunks,
                'cow_copies': self.cow_copies,
                'copy_trace_count': self.copy_trace_count,
                'prefilling': len(self._prefilling),
            }
            if self._prefix_index is not None:
                paged.update(
                    prefix_lookups=self._prefix_index.lookups,
                    prefix_hits=self._prefix_index.hits,
                    prefix_hit_rate=self._prefix_index.hit_rate(),
                    prefix_tokens_reused=(
                        self._prefix_index.tokens_reused))
        base = {
            'prefill_buckets': sorted(self._prefill),
            'decode_buckets': sorted(self._decode),
            'label': self.label,
            'param_version': self.param_version,
            'prefill_edges': list(self.prefill_edges),
            'decode_edges': list(self.decode_edges),
            'n_slots': self.n_slots,
            'aot': {'prefill': {b: a for b, (_, a)
                                in sorted(self._prefill.items())},
                    'decode': {b: a for b, (_, a)
                               in sorted(self._decode.items())}},
            'aot_requested': self.aot_requested,
            'cache_persistent': self.cache_persistent,
            'quantized': self.quantized,
            'int8_kv': self.int8_kv,
            'prefill_trace_count': self.prefill_trace_count,
            'decode_trace_count': self.decode_trace_count,
            'compile_count': self.compile_count,
            'prefills': self.prefills,
            'decode_steps': self.decode_steps,
            'tokens_generated': self.tokens_generated,
            'cancelled': self.cancelled,
            'active_slots': len(self._slots),
        }
        base.update(paged)
        if self.speculative:
            rate = (self.draft_accepted / self.draft_proposed
                    if self.draft_proposed else None)
            base['speculative'] = {
                'spec_tokens': self.spec_tokens,
                'draft_steps': self.draft_steps,
                'verify_steps': self.verify_steps,
                'draft_proposed': self.draft_proposed,
                'draft_accepted': self.draft_accepted,
                'accepted_draft_rate': rate,
                'draft_trace_count': self.draft_trace_count,
                'verify_trace_count': self.verify_trace_count,
                'draft_decode_buckets': sorted(self._draft_decode),
                'verify_buckets': sorted(self._verify),
                'aot': {
                    'draft_prefill': {
                        b: a for b, (_, a)
                        in sorted(self._draft_prefill.items())},
                    'draft_decode': {
                        b: a for b, (_, a)
                        in sorted(self._draft_decode.items())},
                    'verify': {b: a for b, (_, a)
                               in sorted(self._verify.items())},
                },
            }
        else:
            base['speculative'] = False
        return base

    # -- constructors --------------------------------------------------
    @classmethod
    def from_checkpoint(cls, path, model, params_template, **kw):
        """Engine loaded from an elastic-resume training checkpoint
        (the :func:`chainermn_tpu.serving.load_params` contract)."""
        from chainermn_tpu.serving.engine import load_params
        return cls(model, load_params(path, params_template), **kw)
