"""Autoregressive generation: bucketed KV-cache decode with
continuous token-level batching over a prefill/decode AOT split.

The forward-only :class:`~chainermn_tpu.serving.InferenceEngine`
serves one batch per request mix; token-by-token generation is a
different machine with a different bound in each phase:

- **Prefill** (the prompt pass) is compute-bound -- whole-prompt
  matmuls through the fused flash kernel -- and its natural bucket
  axis is PROMPT LENGTH: one AOT executable per power-of-two token
  length, one prompt per call, writing every layer's K/V into one
  cache SLOT (:func:`chainermn_tpu.models.prefill`).
- **Decode** (every subsequent token) is HBM-bandwidth-bound -- one
  query row per live sequence against its cached K/V
  (:func:`chainermn_tpu.ops.flash_attention_decode`, one HBM pass)
  -- and its bucket axis is ACTIVE-SLOT COUNT: one AOT executable per
  power-of-two slot count over the SAME persistent cache
  (:func:`chainermn_tpu.models.decode_step`).

Between the two sits **continuous batching**: admission happens at
TOKEN granularity, not batch granularity.  A sequence that finishes
(or whose deadline expires mid-generation -- the ``serve_cancel``
chaos site drives exactly this) frees its cache slot, and the slot is
refilled from the queue at the NEXT decode step; the rest of the
in-flight batch never waits for stragglers, which is what makes
tokens/s/chip under a mixed-length workload approach the steady-state
decode rate instead of the worst sequence's (the batch-level
alternative idles every finished slot until the whole batch drains).

Both executable families reuse the engine machinery wholesale: AOT
compilation through :func:`~chainermn_tpu.utils.jax_compat.
aot_compile` over the persistent compilation cache, the SL007
``abstract_signature`` set as a runtime no-recompile guard (refused,
never retraced -- the static twin is the ``step:decode_forward``
shardlint target), :class:`~chainermn_tpu.parallel.MeshPlan`
tensor-parallel sharding (cache heads shard with the attention
weights, :func:`chainermn_tpu.models.kv_cache_specs`), float policies
cast weights at load, :class:`~chainermn_tpu.precision.Int8Policy`
quantizes them, and ``int8_kv=True`` stores the CACHE itself int8
with per-(position, head) scales
(:func:`~chainermn_tpu.precision.quantize_kv`) -- halving the bytes
the decode step is bound by.

The cache is DONATED into every prefill/decode executable and the
returned buffer rebound, so steady-state decode allocates nothing
cache-sized.  Telemetry: ``serve_prefill``/``serve_decode`` spans
(``iteration`` = decode step index), a per-step ``active_slots``
gauge, ``serve_ttft_seconds`` / ``serve_intertoken_seconds`` /
``serve_decode_seconds`` raw-sample histograms and
``serve_tokens_total`` -- the ``telemetry report``/``doctor`` serve
section renders tokens/s and TTFT from them (``docs/serving.md``).
"""

import threading
import time

import numpy as np

import jax
import jax.numpy as jnp

from chainermn_tpu import telemetry as _telemetry
from chainermn_tpu.analysis.walker import abstract_signature
from chainermn_tpu.serving.batcher import (bucket_edges, bucket_of,
                                           next_request_id,
                                           record_shed)
from chainermn_tpu.utils import chaos as _chaos
from chainermn_tpu.utils import jax_compat
from chainermn_tpu.utils.failure import OverloadError

#: default admission knobs (the generation twins of batcher's)
DEFAULT_MAX_QUEUE = 256


class GenRequest:
    """One in-flight generation request: ``prompt`` (1-D int32 token
    ids), ``max_new_tokens``, optional absolute ``deadline``
    (``clock()`` units, enforced at admission AND between decode
    steps), and a one-shot completion cell filled with the generated
    token ids or a typed error.  ``request_id`` is the process-unique
    trace id (monotonic admission stamp in the suffix); ``t_trace0``
    is the admission instant on the telemetry recorder's clock (None
    when telemetry was off) -- the t0 of the ``queue_wait`` stage."""

    __slots__ = ('prompt', 'max_new_tokens', 'deadline', 'seq',
                 't_submit', 'synthetic', 'request_id', 't_trace0',
                 '_done', '_result', '_error')

    def __init__(self, prompt, max_new_tokens, deadline=None, seq=0,
                 t_submit=0.0, synthetic=False, request_id=None):
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        if self.prompt.size < 1:
            raise ValueError('empty prompt')
        if max_new_tokens < 1:
            raise ValueError('max_new_tokens must be >= 1, got %d'
                             % max_new_tokens)
        self.max_new_tokens = int(max_new_tokens)
        self.deadline = deadline
        self.seq = seq
        self.t_submit = t_submit
        self.synthetic = synthetic
        self.request_id = request_id or next_request_id()
        rec = _telemetry.active()
        self.t_trace0 = rec.now() if rec is not None else None
        self._done = threading.Event()
        self._result = None
        self._error = None

    def set_result(self, tokens):
        self._result = np.asarray(tokens, np.int32)
        self._done.set()

    def set_error(self, exc):
        self._error = exc
        self._done.set()

    def done(self):
        return self._done.is_set()

    def result(self, timeout=None):
        """Block for the generated tokens; re-raises the typed shed
        error (``OverloadError`` with reason queue_full / deadline /
        shutdown)."""
        if not self._done.wait(timeout):
            raise TimeoutError('request %d not completed within %rs'
                               % (self.seq, timeout))
        if self._error is not None:
            raise self._error
        return self._result


class GenerationQueue:
    """Bounded admission queue for generation requests.

    Unlike the batch queue there is no packing: the engine pops AT
    MOST as many requests as it has free cache slots each decode step
    (token-level admission).  The bounded-backlog / typed-shed /
    ``serve_burst`` contracts are identical to
    :class:`~chainermn_tpu.serving.RequestQueue`."""

    def __init__(self, max_prompt_len, max_queue=DEFAULT_MAX_QUEUE,
                 clock=time.monotonic, label=None):
        self.label = label  # fleet replica name (shed forensics)
        self.max_prompt_len = int(max_prompt_len)
        self.max_queue = int(max_queue)
        self._clock = clock
        self._lock = threading.Lock()
        self._waiting = []
        self._seq = 0
        self._closed = False
        self.submitted = 0
        self.shed_queue_full = 0
        self.shed_deadline = 0

    def submit(self, prompt, max_new_tokens, deadline=None,
               request_id=None):
        """Enqueue one prompt; returns the :class:`GenRequest`.
        Over-length prompts raise ``ValueError`` before touching
        queue state; a full or closed queue sheds typed.
        ``request_id`` lets an admission front (the fleet) pre-assign
        the trace id it already routed on."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size > self.max_prompt_len:
            raise ValueError(
                'prompt of %d tokens exceeds max_prompt_len %d; '
                'truncate client-side or raise the engine limit'
                % (prompt.size, self.max_prompt_len))
        burst = (_chaos.on_serve_submit()
                 if _chaos._active is not None else 0)
        with self._lock:
            req = self._admit(prompt, max_new_tokens, deadline,
                              request_id=request_id)
            for _ in range(burst):
                try:
                    self._admit(prompt, max_new_tokens, deadline,
                                synthetic=True)
                except OverloadError:
                    break
        return req

    def _admit(self, prompt, max_new_tokens, deadline,
               synthetic=False, request_id=None):
        if self._closed:
            raise OverloadError('generation queue is shut down',
                                reason='shutdown',
                                queue_depth=len(self._waiting))
        if len(self._waiting) >= self.max_queue:
            self.shed_queue_full += 1
            record_shed('queue_full',
                        request_id=request_id or next_request_id(),
                        queue_depth=len(self._waiting),
                        **self._shed_attrs())
            raise OverloadError(
                'generation queue full (%d waiting); retry with '
                'backoff' % len(self._waiting),
                reason='queue_full', queue_depth=len(self._waiting))
        self._seq += 1
        self.submitted += 1
        req = GenRequest(prompt, max_new_tokens, deadline=deadline,
                         seq=self._seq, t_submit=self._clock(),
                         synthetic=synthetic, request_id=request_id)
        self._waiting.append(req)
        return req

    def _shed_attrs(self):
        return {'replica': self.label} if self.label else {}

    def pop(self, k):
        """Up to ``k`` live requests in arrival order; requests whose
        deadline already expired while queued are shed typed here (the
        queue-side twin of the engine's mid-generation expiry)."""
        now = self._clock()
        out = []
        with self._lock:
            while self._waiting and len(out) < k:
                req = self._waiting.pop(0)
                if req.deadline is not None and now > req.deadline:
                    self.shed_deadline += 1
                    record_shed('deadline',
                                request_id=req.request_id,
                                queue_depth=len(self._waiting),
                                waited_ms=round(
                                    (now - req.t_submit) * 1e3, 3),
                                **self._shed_attrs())
                    req.set_error(OverloadError(
                        'deadline expired after %.1f ms in queue'
                        % ((now - req.t_submit) * 1e3),
                        reason='deadline'))
                    continue
                out.append(req)
        return out

    def depth(self):
        with self._lock:
            return len(self._waiting)

    def close(self):
        with self._lock:
            self._closed = True
            pending, self._waiting = self._waiting, []
        for req in pending:
            record_shed('shutdown', request_id=req.request_id,
                        queue_depth=len(pending), count_total=False,
                        **self._shed_attrs())
            req.set_error(OverloadError('generation queue shut down',
                                        reason='shutdown'))

    def stats(self):
        return {'submitted': self.submitted,
                'shed_queue_full': self.shed_queue_full,
                'shed_deadline': self.shed_deadline,
                'depth': self.depth()}


class _Slot:
    """Host-side state of one cache slot."""

    __slots__ = ('request', 'position', 'remaining', 'generated',
                 't_last_token', 't_stage_end')

    def __init__(self, request, position, remaining, first_token,
                 t_now, t_stage_end=None):
        self.request = request
        self.position = position          # next token's position
        self.remaining = remaining        # tokens still to generate
        self.generated = [first_token]
        self.t_last_token = t_now
        # telemetry-clock end of this request's newest recorded trace
        # stage: each decode stage span starts here, so the stages
        # tile the request's lifetime gap-free (None: telemetry off)
        self.t_stage_end = t_stage_end


class GenerationEngine:
    """Continuous-batching autoregressive server for one
    :class:`~chainermn_tpu.models.TransformerLM`.

    Args:
      model: the flax module (``tp_axis`` set when serving over
        ``plan``/``param_specs``).
      params: the parameter pytree (the UNSHARDED oracle tree; tp
        placement is spec-driven).
      n_slots: cache slots = max concurrent sequences.  Decode
        executables are bucketed by power-of-two ACTIVE-slot count up
        to this.
      max_prompt_len: prompt-length cap; prefill executables are
        bucketed by power-of-two prompt length up to this.
      max_len: cache depth per slot (prompt + generated tokens;
        default ``model.max_len``).
      eos_id: optional stop token (greedy decode stops early on it).
      policy: float policy casts weights at load;
        :class:`~chainermn_tpu.precision.Int8Policy` quantizes them
        (dequant in-graph; refused under ``param_specs`` like the
        batch engine).
      int8_kv: store the KV cache int8 with per-(position, head)
        scales -- half the decode-bound HBM bytes of bf16.
      plan / param_specs: MeshPlan tensor-parallel serving (the cache
        shards its head dim over ``plan.model_axis``).
      cache_dir / aot: the engine's persistent-compilation-cache and
        AOT knobs, verbatim.
      label / version: fleet identity (the engine.py contract): when
        ``label`` is set, serve-path records carry
        ``replica``/``version`` attrs for per-replica SLO filtering;
        ``version`` is the boot parameter version and
        :meth:`swap_params` advances it.

    Decoding is GREEDY (argmax in-graph -- the sampled token never
    round-trips a vocab-sized buffer to the host), which also makes
    every test and A/B deterministic.
    """

    def __init__(self, model, params, n_slots=8, max_prompt_len=64,
                 max_len=None, eos_id=None, policy=None,
                 int8_kv=False, plan=None, param_specs=None,
                 cache_dir=None, aot=True, label=None, version=0):
        import os

        from chainermn_tpu.models import init_kv_cache, kv_cache_specs

        self.model = model
        self.label = label
        self.param_version = int(version)
        self._boot_version = self.param_version
        self.n_slots = int(n_slots)
        self.max_prompt_len = int(max_prompt_len)
        self.max_len = int(max_len or model.max_len)
        if self.max_prompt_len > self.max_len:
            raise ValueError('max_prompt_len %d exceeds cache depth '
                             '%d' % (self.max_prompt_len, self.max_len))
        self.eos_id = eos_id
        self.policy = policy
        self.plan = plan
        if param_specs is not None and plan is None:
            raise ValueError('param_specs requires a plan')
        self.param_specs = param_specs
        if (plan is not None) != (model.tp_axis is not None):
            raise ValueError(
                'serve a tp_axis model over a plan and a plain model '
                'without one (tp_axis=%r, plan=%r)'
                % (model.tp_axis, plan))
        self.cache_dir = cache_dir
        self.cache_persistent = False
        if cache_dir:
            os.makedirs(cache_dir, exist_ok=True)
            self.cache_persistent = jax_compat.enable_compilation_cache(
                cache_dir)
        self.aot_requested = bool(aot)

        self.prefill_edges = bucket_edges(self.max_prompt_len)
        self.decode_edges = bucket_edges(self.n_slots)

        # load-time parameter transform, the engine.py idiom
        quantize = getattr(policy, 'quantize', None)
        if quantize is not None and param_specs is not None:
            raise NotImplementedError(
                'int8 weights under tensor-parallel param_specs '
                'are not wired yet (quantize per shard after '
                'resharding); int8_kv composes with tp, int8 '
                'WEIGHTS do not')
        self.quantized = quantize is not None
        self._params_template = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(
                jnp.shape(x), x.dtype if hasattr(x, 'dtype')
                else np.asarray(x).dtype), params)
        self.params = self._place_params(params)

        self.int8_kv = bool(int8_kv)
        tp = plan.model_size if plan is not None else 1
        del tp  # the GLOBAL cache is built unsharded; specs shard it
        cache = init_kv_cache(model, self.n_slots, self.max_len,
                              int8_kv=self.int8_kv, tp=1)
        self._cache_specs = (kv_cache_specs(cache, plan.model_axis)
                             if plan is not None else None)
        self._cache = jax.device_put(cache, self._cache_sharding())

        self._slots = {}      # slot id -> _Slot (active only)
        self._free = list(range(self.n_slots))
        self._prefill = {}    # prompt bucket -> callable
        self._decode = {}     # slot bucket -> callable
        self._signatures = set()
        self._lock = threading.Lock()
        self.prefill_trace_count = 0
        self.decode_trace_count = 0
        self.compile_count = 0
        self.prefills = 0
        self.decode_steps = 0
        self.tokens_generated = 0
        self.cancelled = 0
        self._step_index = 0
        self._last_queue_depth = 0

    # -- sharding ------------------------------------------------------
    def _param_sharding(self):
        if self.plan is None:
            return jax.devices()[0]
        if self.param_specs is None:
            return self.plan.replicated()
        return self.plan.param_shardings(self.param_specs)

    def _place_params(self, params):
        """Load-time transform + placement, shared by construction
        and hot-swaps (the engine.py contract)."""
        if self.quantized:
            return jax.device_put(self.policy.quantize(params),
                                  self._param_sharding())
        host = params
        if self.policy is not None:
            from chainermn_tpu.precision import cast_floating
            host = cast_floating(host, self.policy.compute_dtype)
        return jax.device_put(host, self._param_sharding())

    def _ident(self):
        if self.label is None:
            return {}
        return {'replica': self.label, 'version': self.param_version}

    # -- live weight hot-swap (fleet roll) -----------------------------
    def swap_params(self, params, version=None, validate=True):
        """Hot-swap the served parameter tree without recompiling
        (executables are shape-keyed; ``decode_trace_count`` stays
        flat across a swap).

        REFUSED (typed :class:`~chainermn_tpu.utils.failure.
        WeightSwapError`, engine unchanged) while sequences are in
        flight: their KV caches were banked under the incumbent
        weights, and decoding them under new weights would silently
        corrupt the tail of every live generation -- the fleet drains
        the replica first, which is exactly the per-replica
        drain -> swap -> rejoin ladder.  Validation runs the
        full-slot decode executable once with the new tree over the
        (all-free) cache -- the warmup garbage-write contract -- and
        checks the sampled tokens materialize; only then is
        ``self.params`` cut over and the old buffer freed."""
        from chainermn_tpu.utils.failure import WeightSwapError
        if self._slots:
            raise WeightSwapError(
                'swap requires a drained replica: %d sequence(s) '
                'still in flight hold KV state banked under the '
                'incumbent weights' % len(self._slots),
                version=version)
        new = self._place_params(params)
        if validate and self.n_slots in self._decode:
            exe = self._decode[self.n_slots][0]
            try:
                tok, cache = exe(
                    new, self._cache,
                    jnp.zeros((self.n_slots,), jnp.int32),
                    jnp.zeros((self.n_slots,), jnp.int32))
                tok = jax.block_until_ready(tok)
            except Exception as e:
                raise WeightSwapError(
                    'swap validation decode failed (%s: %s) -- '
                    'keeping the incumbent parameters'
                    % (type(e).__name__, e), version=version) from e
            # the donated cache was consumed either way: rebind
            self._cache = cache
        old = self.params
        self.params = new
        self.param_version = (int(version) if version is not None
                              else self.param_version + 1)
        _telemetry.event('weight_swap', kind='serve',
                         **self._ident())
        del old  # double buffer freed after cutover
        return self.param_version

    def swap_from_checkpoint(self, path, version=None, validate=True):
        """:meth:`swap_params` fed from an elastic-resume checkpoint
        (crc-verified load against the boot tree's shape template)."""
        from chainermn_tpu.serving.engine import load_params
        return self.swap_params(
            load_params(path, self._params_template), version=version,
            validate=validate)

    def _cache_sharding(self):
        if self.plan is None:
            return jax.devices()[0]
        return self.plan.param_shardings(self._cache_specs)

    # -- traced bodies -------------------------------------------------
    def _prepare_params(self, params):
        if self.quantized:
            return self.policy.dequantize(params)
        return params

    def _prefill_body(self, params, cache, tokens, length, slot):
        from chainermn_tpu.models import prefill as model_prefill
        self.prefill_trace_count += 1  # trace-time counter
        logits, cache = model_prefill(
            self.model, self._prepare_params(params), cache, tokens,
            length, slot)
        return jnp.argmax(logits).astype(jnp.int32), cache

    def _decode_body(self, params, cache, tokens, positions,
                     slots=None):
        from chainermn_tpu.models import decode_step
        self.decode_trace_count += 1   # trace-time counter
        logits, cache = decode_step(
            self.model, self._prepare_params(params), cache, tokens,
            positions, slots=slots)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache

    def _mapped(self, body, n_extra):
        """Wrap a traced body in the plan's shard_map (params sharded
        per spec, cache per its spec, small int operands replicated)."""
        if self.plan is None:
            return body
        from jax.sharding import PartitionSpec as P
        pspecs = (self.param_specs if self.param_specs is not None
                  else P())
        return jax.shard_map(
            body, mesh=self.plan.mesh,
            in_specs=(pspecs, self._cache_specs) + (P(),) * n_extra,
            out_specs=(P(), self._cache_specs), check_vma=False)

    # -- compilation ---------------------------------------------------
    def _compile(self, fn, args, table, key):
        jitted = jax.jit(fn, donate_argnums=(1,))
        exe = None
        if self.aot_requested:
            exe = jax_compat.aot_compile(jitted, self.params, *args)
        aot = exe is not None
        if exe is None:
            exe = jitted
        table[key] = (exe, aot)
        self._signatures.add(abstract_signature(args))
        self.compile_count += 1
        return exe, aot

    def _token_structs(self, bucket):
        i32 = jnp.int32
        return (jax.ShapeDtypeStruct((1, bucket), i32),
                jax.ShapeDtypeStruct((), i32),
                jax.ShapeDtypeStruct((), i32))

    def _decode_structs(self, bucket):
        i32 = jnp.int32
        if bucket == self.n_slots:
            return (jax.ShapeDtypeStruct((bucket,), i32),
                    jax.ShapeDtypeStruct((bucket,), i32))
        return (jax.ShapeDtypeStruct((bucket,), i32),
                jax.ShapeDtypeStruct((bucket,), i32),
                jax.ShapeDtypeStruct((bucket,), i32))

    def _cache_struct(self):
        return jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            self._cache)

    def _get_prefill(self, bucket):
        hit = self._prefill.get(bucket)
        if hit is not None:
            return hit[0]
        with self._lock:
            hit = self._prefill.get(bucket)
            if hit is not None:
                return hit[0]
            if bucket not in self.prefill_edges:
                raise RuntimeError(
                    'prompt bucket %d is not an edge %r'
                    % (bucket, list(self.prefill_edges)))
            exe, _ = self._compile(
                self._mapped(self._prefill_body, 3),
                (self._cache_struct(),) + self._token_structs(bucket),
                self._prefill, bucket)
            return exe

    def _decode_mapped(self, bucket):
        """The decode callable for one slot-count bucket -- what gets
        AOT-compiled, and what ``traceable_decode`` hands shardlint."""
        if bucket == self.n_slots:
            # full bucket: every slot decodes, the cache is read IN
            # PLACE (no gather); rows are slots in order
            return self._mapped(
                lambda p, c, t, pos: self._decode_body(p, c, t, pos),
                2)
        # compacted bucket operand order: (tokens, slots, positions)
        # -- what _decode_structs declares and the scheduler passes
        return self._mapped(
            lambda p, c, t, s, pos: self._decode_body(
                p, c, t, pos, slots=s), 3)

    def _get_decode(self, bucket):
        hit = self._decode.get(bucket)
        if hit is not None:
            return hit[0]
        with self._lock:
            hit = self._decode.get(bucket)
            if hit is not None:
                return hit[0]
            if bucket not in self.decode_edges:
                raise RuntimeError(
                    'decode bucket %d is not an edge %r'
                    % (bucket, list(self.decode_edges)))
            exe, _ = self._compile(
                self._decode_mapped(bucket),
                (self._cache_struct(),) + self._decode_structs(bucket),
                self._decode, bucket)
            return exe

    def traceable_decode(self, bucket=None):
        """``(fn, args)`` for ``jax.make_jaxpr`` -- the EXACT mapped
        decode callable the engine compiles for ``bucket`` (default:
        the full-slot bucket, whose cache read is in place), on zero
        operands over the real cache/params: the shardlint
        ``step:decode_forward`` target traces production code."""
        bucket = bucket or self.n_slots
        fn = self._decode_mapped(bucket)
        args = [self.params, self._cache,
                jnp.zeros((bucket,), jnp.int32)]
        if bucket != self.n_slots:
            args.append(jnp.arange(bucket, dtype=jnp.int32))
        args.append(jnp.zeros((bucket,), jnp.int32))
        return fn, tuple(args)

    def warmup(self):
        """Compile (or cache-load) every prefill and decode bucket
        executable eagerly, largest first.  Fallback (plain-jit)
        executables are forced to compile by running them on the real
        cache -- slots are all free, so the garbage they write is
        never attended (reads mask by live length).  Returns
        ``{'prefill': {bucket: aot}, 'decode': {bucket: aot}}``."""
        for bucket in sorted(self.prefill_edges, reverse=True):
            with _telemetry.span('serve_warmup', kind='serve',
                                 phase='prefill', bucket=bucket):
                exe = self._get_prefill(bucket)
                if not self._prefill[bucket][1]:
                    tok, cache = exe(
                        self.params, self._cache,
                        jnp.zeros((1, bucket), jnp.int32),
                        jnp.asarray(1, jnp.int32),
                        jnp.asarray(0, jnp.int32))
                    jax.block_until_ready(tok)
                    self._cache = cache
        for bucket in sorted(self.decode_edges, reverse=True):
            with _telemetry.span('serve_warmup', kind='serve',
                                 phase='decode', bucket=bucket):
                exe = self._get_decode(bucket)
                if not self._decode[bucket][1]:
                    args = [jnp.zeros((bucket,), jnp.int32),
                            jnp.zeros((bucket,), jnp.int32)]
                    if bucket != self.n_slots:
                        args.insert(1, jnp.arange(bucket,
                                                  dtype=jnp.int32))
                    tok, cache = exe(self.params, self._cache,
                                     args[0], *args[1:])
                    jax.block_until_ready(tok)
                    self._cache = cache
        return {'prefill': {b: a for b, (_, a)
                            in sorted(self._prefill.items())},
                'decode': {b: a for b, (_, a)
                           in sorted(self._decode.items())}}

    def guard_signature(self, args):
        """The SL007 machinery as a runtime pin (the engine.py
        contract): refuse any operand signature outside the
        precompiled prefill/decode set instead of silently
        retracing."""
        sig = abstract_signature(args)
        if sig not in self._signatures:
            raise RuntimeError(
                'no-recompile guard: operand signature %r is outside '
                'the precompiled prefill/decode bucket set -- the '
                'scheduler and executables disagree on bucket '
                'geometry' % (sig,))
        return sig

    # -- the continuous-batching scheduler -----------------------------
    def _expire(self, now, force=0):
        """Shed active requests whose deadline passed (or the
        ``force`` oldest, for the serve_cancel chaos site): typed
        ``OverloadError(reason='deadline')`` NOW, slot freed for
        refill at the next step's admission."""
        doomed = []
        for sid, slot in self._slots.items():
            dl = slot.request.deadline
            if dl is not None and now > dl:
                doomed.append(sid)
        if force:
            for sid in sorted(
                    (s for s in self._slots if s not in doomed),
                    key=lambda s: self._slots[s].request.t_submit
            )[:force]:
                doomed.append(sid)
        for sid in doomed:
            slot = self._slots.pop(sid)
            self._free.append(sid)
            self.cancelled += 1
            slot.request.set_error(OverloadError(
                'deadline expired mid-generation after %d tokens'
                % len(slot.generated), reason='deadline'))
            _telemetry.event('serve_cancel', kind='serve', slot=sid,
                             tokens=len(slot.generated))
            record_shed('deadline',
                        request_id=slot.request.request_id,
                        queue_depth=self._last_queue_depth,
                        slot=sid, tokens=len(slot.generated),
                        **self._ident())
        return len(doomed)

    def _admit(self, queue, now, clock):
        """Refill free slots from the queue: one PREFILL per request
        (bucketed by prompt length), TTFT recorded when its first
        token lands.  With telemetry on, each admitted request gets
        its trace stages recorded: ``queue_wait`` (admission stamp ->
        pop), ``bucket_pack`` (pop -> prefill dispatch, carrying the
        prompt bucket + pad fraction) and ``prefill`` (-> first
        token), each starting where the previous ended."""
        rec = _telemetry.active()
        reg = _telemetry.registry()
        ident = self._ident()
        for req in queue.pop(len(self._free)):
            sid = self._free.pop(0)
            prompt = req.prompt
            t_pop = rec.now() if rec is not None else None
            if rec is not None:
                t0 = req.t_trace0
                if t0 is None:   # telemetry enabled mid-flight
                    t0 = t_pop - (clock() - req.t_submit)
                rec.child_span(req.request_id, 'queue_wait', t0,
                               t_pop, seq=req.seq, **ident)
            bucket = bucket_of(prompt.size, self.prefill_edges)
            tokens = np.zeros((1, bucket), np.int32)
            tokens[0, :prompt.size] = prompt
            exe = self._get_prefill(bucket)
            args = (jnp.asarray(tokens),
                    jnp.asarray(prompt.size, jnp.int32),
                    jnp.asarray(sid, jnp.int32))
            self.guard_signature((self._cache_struct(),) + tuple(
                jax.ShapeDtypeStruct(a.shape, a.dtype) for a in args))
            t_pf0 = rec.now() if rec is not None else None
            if rec is not None:
                rec.child_span(
                    req.request_id, 'bucket_pack', t_pop, t_pf0,
                    bucket=bucket, pad_fraction=round(
                        (bucket - prompt.size) / float(bucket), 4),
                    **ident)
            if _chaos._active is not None:
                _chaos.on_serve_slow(
                    self.param_version != self._boot_version)
            with _telemetry.span('serve_prefill', kind='serve',
                                 bucket=bucket, slot=sid,
                                 iteration=self._step_index,
                                 **ident):
                tok, cache = exe(self.params, self._cache, *args)
                tok = int(jax.block_until_ready(tok))
            self._cache = cache
            self.prefills += 1
            self.tokens_generated += 1
            t_first = clock()
            t_first_tele = None
            if rec is not None:
                t_first_tele = rec.now()
                rec.child_span(req.request_id, 'prefill', t_pf0,
                               t_first_tele, bucket=bucket, slot=sid,
                               prompt_tokens=int(prompt.size),
                               **ident)
            if reg is not None:
                reg.histogram(
                    'serve_ttft_seconds',
                    help='submit-to-first-token latency (s)'
                ).observe(t_first - req.t_submit)
                reg.counter('serve_tokens_total',
                            help='generated tokens').inc()
            if self.eos_id is not None and tok == self.eos_id \
                    or req.max_new_tokens == 1:
                req.set_result([tok])
                self._free.append(sid)
                if rec is not None:
                    rec.event('complete', kind='request',
                              request_id=req.request_id, tokens=1,
                              slot=sid, **ident)
                continue
            self._slots[sid] = _Slot(req, prompt.size,
                                     req.max_new_tokens - 1, tok,
                                     t_first,
                                     t_stage_end=t_first_tele)

    def _decode_once(self, clock):
        """One decode step over every active slot, compacted to the
        smallest slot-count bucket; finished sequences resolve and
        free their slots (refilled at the NEXT step)."""
        active = sorted(self._slots)
        k = len(active)
        bucket = bucket_of(k, self.decode_edges)
        if bucket == self.n_slots:
            # the full-slot executable reads the cache IN PLACE (no
            # slots operand): row i IS slot i, so rows must be every
            # slot in id order even when k < n_slots -- an inactive
            # row writes a garbage token at position 0 of its FREE
            # slot, overwritten by that slot's next prefill
            rows = list(range(self.n_slots))
        else:
            # compacted bucket: pad with FREE slots (guaranteed
            # available: bucket < n_slots and only k are active) --
            # same garbage-write-to-a-free-slot contract as above
            rows = active + self._free[:bucket - k]
        tokens = np.asarray(
            [self._slots[s].generated[-1] if s in self._slots else 0
             for s in rows], np.int32)
        positions = np.asarray(
            [self._slots[s].position if s in self._slots else 0
             for s in rows], np.int32)
        exe = self._get_decode(bucket)
        if bucket == self.n_slots:
            args = (jnp.asarray(tokens), jnp.asarray(positions))
        else:
            args = (jnp.asarray(tokens),
                    jnp.asarray(np.asarray(rows, np.int32)),
                    jnp.asarray(positions))
        self.guard_signature((self._cache_struct(),) + tuple(
            jax.ShapeDtypeStruct(a.shape, a.dtype) for a in args))
        rec = _telemetry.active()
        reg = _telemetry.registry()
        ident = self._ident()
        if reg is not None:
            reg.gauge('active_slots',
                      help='live sequences at this decode step'
                      ).set(k)
        if _chaos._active is not None:
            _chaos.on_serve_slow(
                self.param_version != self._boot_version)
        t0 = clock()
        with _telemetry.span('serve_decode', kind='serve',
                             iteration=self._step_index,
                             active_slots=k, bucket=bucket,
                             n_slots=self.n_slots,
                             queue_depth=self._last_queue_depth,
                             **ident):
            toks, cache = exe(self.params, self._cache, *args)
            toks = np.asarray(jax.block_until_ready(toks))
        self._cache = cache
        now = clock()
        now_tele = rec.now() if rec is not None else None
        if reg is not None:
            reg.histogram('serve_decode_seconds',
                          help='per-decode-step wall time (s)'
                          ).observe(now - t0)
            reg.counter('serve_tokens_total',
                        help='generated tokens').inc(k)
        itl = (reg.histogram('serve_intertoken_seconds',
                             help='per-sequence gap between '
                                  'consecutive tokens (s)')
               if reg is not None else None)
        for i, sid in enumerate(rows):
            slot = self._slots.get(sid)
            if slot is None:
                continue   # free pad row (or inactive full-bucket row)
            tok = int(toks[i])
            slot.generated.append(tok)
            slot.position += 1
            slot.remaining -= 1
            if itl is not None:
                itl.observe(now - slot.t_last_token)
            slot.t_last_token = now
            if rec is not None:
                # one decode stage per live slot per tick, starting at
                # the request's previous stage end: the span absorbs
                # any scheduler wait between ticks (a neighbor's slow
                # prefill IS latency this request paid), which is
                # exactly what makes the stage budgets sum to the
                # end-to-end latency
                t_prev = slot.t_stage_end
                if t_prev is None:
                    t_prev = now_tele - (now - t0)
                rec.child_span(slot.request.request_id, 'decode',
                               t_prev, now_tele, slot=sid,
                               step=self._step_index,
                               token_index=len(slot.generated) - 1,
                               **ident)
                slot.t_stage_end = now_tele
            if slot.remaining == 0 or (self.eos_id is not None
                                       and tok == self.eos_id):
                slot.request.set_result(slot.generated)
                if rec is not None:
                    rec.event('complete', kind='request',
                              request_id=slot.request.request_id,
                              tokens=len(slot.generated), slot=sid,
                              **ident)
                del self._slots[sid]
                self._free.append(sid)
        self.decode_steps += 1
        self.tokens_generated += k

    def _flight_table(self):
        """The in-flight request table embedded in every flight dump
        (:attr:`Recorder.flight_sources`): which requests were alive,
        in which slot, at which stage, with how many tokens emitted --
        so a crash mid-generation names which requests died where."""
        active = []
        for sid in sorted(self._slots):
            try:
                slot = self._slots[sid]
            except KeyError:
                continue   # racing refill on the dying process
            active.append({'slot': sid,
                           'request_id': slot.request.request_id,
                           'stage': 'decode',
                           'tokens': len(slot.generated),
                           'position': slot.position,
                           'remaining': slot.remaining})
        return {'active': active,
                'free_slots': list(self._free),
                'step_index': self._step_index,
                'queue_depth': self._last_queue_depth}

    def step(self, queue, clock=time.monotonic):
        """One scheduler tick: expire -> admit (slot refill) -> one
        decode step.  Returns True when any work happened.

        With telemetry on, queue pressure is sampled EVERY tick --
        ``serve_queue_depth`` (waiting requests, all still needing
        prefill) and the backlog split ``serve_prefill_backlog`` /
        ``serve_decode_backlog`` (live slots still generating) -- so
        pressure ONSET is visible in captures, not just its latency
        consequences; the engine's in-flight request table is also
        registered as a flight-dump source."""
        rec = _telemetry.active()
        depth = queue.depth()
        self._last_queue_depth = depth
        if rec is not None:
            if rec.flight_sources.get('serve_requests') \
                    != self._flight_table:
                rec.flight_sources['serve_requests'] = \
                    self._flight_table
            reg = rec.registry
            reg.gauge('serve_queue_depth',
                      help='requests waiting in the generation '
                           'queue at the scheduler tick').set(depth)
            reg.gauge('serve_prefill_backlog',
                      help='queued requests still needing their '
                           'prefill pass').set(depth)
            reg.gauge('serve_decode_backlog',
                      help='live slots still generating at the '
                           'scheduler tick').set(len(self._slots))
        now = clock()
        force = (_chaos.on_serve_cancel()
                 if _chaos._active is not None else 0)
        self._expire(now, force=force)
        self._admit(queue, now, clock)
        if not self._slots:
            return False
        self._decode_once(clock)
        self._step_index += 1
        return True

    def run(self, queue, stop=None, idle_sleep=0.002):
        """Scheduler loop: tick until ``stop`` is set AND the queue
        and slot table are drained (the loadgen worker loop)."""
        while True:
            worked = self.step(queue)
            if not worked:
                if stop is not None and stop.is_set() \
                        and queue.depth() == 0 and not self._slots:
                    return
                time.sleep(idle_sleep)

    def stats(self):
        return {
            'prefill_buckets': sorted(self._prefill),
            'decode_buckets': sorted(self._decode),
            'label': self.label,
            'param_version': self.param_version,
            'prefill_edges': list(self.prefill_edges),
            'decode_edges': list(self.decode_edges),
            'n_slots': self.n_slots,
            'aot': {'prefill': {b: a for b, (_, a)
                                in sorted(self._prefill.items())},
                    'decode': {b: a for b, (_, a)
                               in sorted(self._decode.items())}},
            'aot_requested': self.aot_requested,
            'cache_persistent': self.cache_persistent,
            'quantized': self.quantized,
            'int8_kv': self.int8_kv,
            'prefill_trace_count': self.prefill_trace_count,
            'decode_trace_count': self.decode_trace_count,
            'compile_count': self.compile_count,
            'prefills': self.prefills,
            'decode_steps': self.decode_steps,
            'tokens_generated': self.tokens_generated,
            'cancelled': self.cancelled,
            'active_slots': len(self._slots),
        }

    # -- constructors --------------------------------------------------
    @classmethod
    def from_checkpoint(cls, path, model, params_template, **kw):
        """Engine loaded from an elastic-resume training checkpoint
        (the :func:`chainermn_tpu.serving.load_params` contract)."""
        from chainermn_tpu.serving.engine import load_params
        return cls(model, load_params(path, params_template), **kw)
