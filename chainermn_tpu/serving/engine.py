"""AOT-compiled inference engine over bucketed batch shapes.

The training stack compiles one step and reuses it; a serving path
faces the opposite shape economy -- every request mix is a new batch
shape, and an XLA retrace mid-traffic is a multi-second p99 cliff.
The engine closes that hole with three mechanisms:

- **Pre-lowered per-bucket executables.**  For every bucket edge the
  batcher can emit, the forward-only ``apply`` is compiled ONCE --
  through the modern AOT path ``jax.jit(...).lower(...).compile()``
  when the runtime has it (:func:`chainermn_tpu.utils.jax_compat.
  aot_compile`), plain ``jit`` otherwise -- and stored keyed on the
  bucket.  ``warmup()`` compiles all buckets eagerly so the first
  request pays file-read latency, not trace latency.
- **Persistent compilation cache.**  ``cache_dir`` points jax's
  persistent compilation cache at a directory
  (:func:`~chainermn_tpu.utils.jax_compat.enable_compilation_cache`),
  so a RESTARTED engine's warmup deserializes executables instead of
  re-tracing -- cold start becomes a file read.  The cache layout is
  jax's own (one ``...-cache`` entry per executable fingerprint);
  ``docs/serving.md`` documents it.
- **No-recompile runtime guard.**  The SL007 recompilation rule's
  signature machinery (:func:`chainermn_tpu.analysis.walker.
  abstract_signature` -- what jit keys its cache on) doubles as a
  runtime pin: the engine precomputes the signature of every bucket
  shape and REFUSES any batch whose signature is not in that set
  (typed ``RuntimeError``) instead of silently retracing.  The
  static twin is the ``step:serve_forward`` shardlint target.

Sharded serving composes with the PR 7 :class:`~chainermn_tpu.
parallel.MeshPlan`: pass ``plan=`` (and ``param_specs=`` for
tensor-parallel weights) and the forward runs shard_mapped over the
plan mesh -- the batch sharded over ``data``, tensor-parallel psums
over ``model`` inserted by the model itself.  Quantized serving
composes with :class:`~chainermn_tpu.precision.Int8Policy`: weights
are stored int8 + per-channel scales and dequantized IN the compiled
graph (:mod:`chainermn_tpu.ops.int8_matmul`).

Telemetry (PR 6 registry): per-batch ``serve_queue_wait`` /
``serve_h2d`` / ``serve_execute`` spans, raw-sample histograms of the
same phases plus per-request ``serve_latency_seconds`` and per-batch
``serve_pad_waste`` -- p50/p99 come from the histograms, never from
averaged percentiles.
"""

import os
import threading
import time

import numpy as np

import jax
import jax.numpy as jnp

from chainermn_tpu import telemetry as _telemetry
from chainermn_tpu.analysis.walker import abstract_signature
from chainermn_tpu.serving.batcher import bucket_edges
from chainermn_tpu.utils import chaos as _chaos
from chainermn_tpu.utils import jax_compat


def load_params(path, template, prefix='params'):
    """Topology-portable parameter load from an elastic-resume
    checkpoint (PR 5): the npz snapshots the preemption handler and
    the snapshot extension write carry collectively regathered,
    crc-verified leaves, so ANY process layout can read them back --
    a serving replica needs no knowledge of the training topology.
    Integrity failures raise the typed ``CheckpointCorruptError``
    chain unchanged."""
    from chainermn_tpu import serializers
    by_key, _manifest = serializers.read_npz(path)
    return serializers._fetch_tree(by_key, template, prefix, path)


class InferenceEngine:
    """Forward-only serving executable set for one model.

    Args:
      apply_fn: ``apply_fn(params, x) -> y`` -- the forward pass
        (e.g. ``lambda p, x: model.apply({'params': p}, x)``).
      params: the parameter pytree (host or device).
      example: ONE item (no batch dim) as array/ShapeDtypeStruct --
        the shape template bucket executables are lowered against.
      max_batch / edges: bucket geometry (power-of-two by default,
        ``edges`` overrides; the engine serves exactly these shapes).
      policy: optional :class:`~chainermn_tpu.precision.Policy`.
        A float policy casts params + inputs to its compute dtype; an
        :class:`~chainermn_tpu.precision.Int8Policy` quantizes the
        params at load and dequantizes in-graph.
      plan / param_specs: optional MeshPlan sharded serving (batch
        over the data axes, params per ``param_specs`` or
        replicated).  Buckets not divisible by the data-axis size are
        dropped (a shard_map batch must split evenly).
      cache_dir: persistent compilation cache directory (AOT
        executables survive restarts).  ``aot=False`` forces the
        plain-jit fallback (what a runtime without the AOT surface
        degrades to anyway).
      label / version: fleet identity.  ``label`` names this engine
        as a replica; when set, every serve-path record (spans,
        request stage spans, complete/shed events) carries
        ``replica``/``version`` attributes so a per-replica,
        per-version SLO monitor can filter one engine's traffic out
        of a shared recorder stream.  ``version`` is the parameter
        version served at boot (:meth:`swap_params` advances it).
    """

    def __init__(self, apply_fn, params, example, max_batch=32,
                 edges=None, policy=None, plan=None, param_specs=None,
                 cache_dir=None, aot=True, label=None, version=0):
        self.apply_fn = apply_fn
        self.policy = policy
        self.plan = plan
        self.label = label
        self.param_version = int(version)
        self._boot_version = self.param_version
        self.max_batch = int(max_batch)
        edges = tuple(edges) if edges else bucket_edges(max_batch)
        if plan is not None:
            kept = tuple(e for e in edges if e % plan.data_size == 0)
            if not kept:
                raise ValueError(
                    'no bucket edge in %r divides over the data axes '
                    '(size %d); raise max_batch or pass edges'
                    % (edges, plan.data_size))
            edges = kept
        self.edges = edges
        self.cache_dir = cache_dir
        self.cache_persistent = False
        if cache_dir:
            os.makedirs(cache_dir, exist_ok=True)
            self.cache_persistent = jax_compat.enable_compilation_cache(
                cache_dir)
        self.aot_requested = bool(aot)

        ex = (example if hasattr(example, 'shape')
              else np.asarray(example))
        self._item_shape = tuple(ex.shape)
        in_dtype = np.dtype(getattr(ex, 'dtype', np.float32))
        if policy is not None and np.issubdtype(in_dtype, np.floating):
            in_dtype = np.dtype(policy.compute_dtype)
        self._in_dtype = in_dtype

        if param_specs is not None and plan is None:
            raise ValueError('param_specs requires a plan')
        self.param_specs = param_specs

        # load-time parameter transform: quantize (int8 policy) or
        # cast to compute dtype (float policy; an inference engine
        # holds no f32 masters -- there is no optimizer to feed)
        quantize = getattr(policy, 'quantize', None)
        if quantize is not None and param_specs is not None:
            raise NotImplementedError(
                'int8 weights under tensor-parallel param_specs '
                'are not wired yet: quantize per shard after '
                'resharding, or serve the tp model in bf16')
        self.quantized = quantize is not None
        # structure/shape template of the UNtransformed host tree --
        # what checkpoint loads for later hot-swaps validate against
        # (shapes only; no host copy is retained)
        self._params_template = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(
                np.shape(x), np.asarray(x).dtype
                if not hasattr(x, 'dtype') else x.dtype), params)
        self.params = self._place_params(params)

        self._compiled = {}   # bucket -> callable(params, x)
        self._aot = {}        # bucket -> True when AOT-compiled
        self._signatures = {} # bucket -> abstract signature
        self._lock = threading.Lock()
        self.trace_count = 0  # incremented INSIDE the traced forward
        self.compile_count = 0
        self.executions = 0
        self._batch_index = 0
        self._mapped = self._build_mapped(param_specs)

    # -- forward construction ------------------------------------------
    def _param_sharding(self):
        if self.plan is None:
            return jax.devices()[0]
        if self.param_specs is None:
            return self.plan.replicated()
        return self.plan.param_shardings(self.param_specs)

    def _place_params(self, params):
        """The load-time parameter transform (quantize under an int8
        policy, cast under a float one) + device placement -- shared
        by construction and every later hot-swap, so a swapped tree
        goes through the identical pipeline the boot tree did."""
        if self.quantized:
            return jax.device_put(self.policy.quantize(params),
                                  self._param_sharding())
        host = params
        if self.policy is not None:
            from chainermn_tpu.precision import cast_floating
            host = cast_floating(host, self.policy.compute_dtype)
        return jax.device_put(host, self._param_sharding())

    def _ident(self):
        """Replica/version attrs stamped on serve-path records when
        the engine has a fleet identity (empty otherwise, keeping
        single-engine record schemas unchanged)."""
        if self.label is None:
            return {}
        return {'replica': self.label, 'version': self.param_version}

    def _forward(self, params, x):
        # tracing-only counter: the body runs at trace time, so this
        # increments exactly once per compilation -- the warm-start /
        # no-retrace assertion tests pin it
        self.trace_count += 1
        policy = self.policy
        if self.quantized:
            params = policy.dequantize(params)
        y = self.apply_fn(params, x)
        if policy is not None:
            from chainermn_tpu.precision import cast_floating
            y = cast_floating(y, policy.output_dtype
                              or policy.compute_dtype)
        return y

    def _build_mapped(self, param_specs):
        if self.plan is None:
            return self._forward
        from jax.sharding import PartitionSpec as P
        plan = self.plan
        in_specs = (param_specs if param_specs is not None else P(),
                    plan.batch_spec())
        return jax.shard_map(
            self._forward, mesh=plan.mesh, in_specs=in_specs,
            out_specs=plan.batch_spec(), check_vma=False)

    def traceable_forward(self, bucket=None):
        """``(fn, args)`` for ``jax.make_jaxpr`` -- the EXACT mapped
        callable the engine compiles, on a zeros batch of ``bucket``
        items: the shardlint ``step:serve_forward`` target traces
        production code, not a test double."""
        bucket = bucket or self.edges[-1]
        x = jnp.zeros((bucket,) + self._item_shape, self._in_dtype)
        return self._mapped, (self.params, x)

    def _batch_struct(self, bucket):
        return jax.ShapeDtypeStruct((bucket,) + self._item_shape,
                                    self._in_dtype)

    def _compile_bucket(self, bucket):
        jitted = jax.jit(self._mapped)
        exe = None
        if self.aot_requested:
            exe = jax_compat.aot_compile(jitted, self.params,
                                         self._batch_struct(bucket))
        if exe is None:
            # no AOT surface on this runtime (or aot=False): plain
            # jit -- first call traces+compiles, later calls hit the
            # jit cache; results identical, cold start slower
            exe = jitted
        self._aot[bucket] = exe is not jitted
        self._compiled[bucket] = exe
        self._signatures[bucket] = abstract_signature(
            (self._batch_struct(bucket),))
        self.compile_count += 1
        return exe

    # -- public surface ------------------------------------------------
    def warmup(self):
        """Compile (or cache-load) every bucket executable eagerly,
        largest first (the largest compile dominates; failing fast on
        it beats discovering the OOM at traffic time).  Returns
        ``{bucket: aot?}``."""
        reg = _telemetry.registry()
        for bucket in sorted(self.edges, reverse=True):
            if bucket in self._compiled:
                continue
            with _telemetry.span('serve_warmup', kind='serve',
                                 bucket=bucket):
                t0 = time.perf_counter()
                exe = self._compile_bucket(bucket)
                if not self._aot[bucket]:
                    # fallback jit: force the compile NOW -- warmup
                    # exists so traffic never traces
                    x = jnp.zeros((bucket,) + self._item_shape,
                                  self._in_dtype)
                    jax.block_until_ready(exe(self.params, x))
                if reg is not None:
                    reg.histogram(
                        'serve_warmup_seconds',
                        help='per-bucket warmup compile/load time'
                    ).observe(time.perf_counter() - t0)
        return dict(self._aot)

    # -- live weight hot-swap (fleet roll) -----------------------------
    def swap_params(self, params, version=None, validate=True):
        """Hot-swap the served parameter tree WITHOUT recompiling.

        The bucket executables are keyed on shapes, not values, so a
        same-shape tree slots straight in: the new tree is placed
        through :meth:`_place_params` (double-buffered -- both
        versions live on device from here), optionally validated by
        running the largest compiled bucket on zeros and checking the
        output finite, and only then CUT OVER by rebinding
        ``self.params`` (in-flight executions keep the old reference
        they already loaded; the old buffers are freed when the last
        of them completes).  ``trace_count`` stays flat across a swap
        -- the no-retrace property the fleet's roll depends on.

        Raises :class:`~chainermn_tpu.utils.failure.WeightSwapError`
        (engine unchanged, still serving the old version) when
        validation fails."""
        from chainermn_tpu.utils.failure import WeightSwapError
        new = self._place_params(params)
        if validate and self._compiled:
            bucket = max(self._compiled)
            x = jnp.zeros((bucket,) + self._item_shape, self._in_dtype)
            try:
                y = jax.block_until_ready(
                    self._compiled[bucket](new, x))
            except Exception as e:
                raise WeightSwapError(
                    'swap validation forward failed (%s: %s) -- '
                    'keeping the incumbent parameters'
                    % (type(e).__name__, e), version=version) from e
            probe = y[0] if isinstance(y, (tuple, list)) else y
            if not bool(np.isfinite(
                    np.asarray(jax.device_get(probe))).all()):
                raise WeightSwapError(
                    'swap validation produced non-finite outputs -- '
                    'refusing cutover to version %r' % (version,),
                    version=version)
        old = self.params
        self.params = new
        self.param_version = (int(version) if version is not None
                              else self.param_version + 1)
        _telemetry.event('weight_swap', kind='serve',
                         **self._ident())
        del old  # the double buffer: freed after cutover
        return self.param_version

    def swap_from_checkpoint(self, path, version=None, validate=True):
        """:meth:`swap_params` fed from an elastic-resume checkpoint:
        the crc-verified ``params`` subtree is loaded against the
        boot tree's shape template (a changed architecture fails
        typed, before any cutover) and hot-swapped in."""
        return self.swap_params(
            load_params(path, self._params_template), version=version,
            validate=validate)

    def allowed_signatures(self):
        return set(self._signatures.values())

    def guard_signature(self, x):
        """The SL007 machinery as a runtime pin: refuse any batch
        whose jit signature is not one of the precompiled bucket
        signatures -- serving a shape outside the bucket set would
        retrace mid-traffic, exactly the hazard the static rule
        flags on training steps."""
        sig = abstract_signature((x,))
        if sig not in self.allowed_signatures():
            raise RuntimeError(
                'no-recompile guard: batch signature %r is outside '
                'the precompiled bucket set %r -- the batcher and '
                'engine disagree on bucket geometry'
                % (sig, sorted(self._signatures)))
        return sig

    def infer(self, x):
        """Run one already-padded batch (leading dim must be a bucket
        edge).  Compiles on first use of a bucket if ``warmup`` was
        skipped; after warmup this never traces (``trace_count``
        pins it)."""
        x = np.asarray(x)
        bucket = x.shape[0]
        exe = self._compiled.get(bucket)
        if exe is None:
            with self._lock:
                exe = self._compiled.get(bucket)
                if exe is None:
                    if bucket not in self.edges:
                        raise RuntimeError(
                            'batch of %d items is not a bucket edge '
                            '%r' % (bucket, list(self.edges)))
                    exe = self._compile_bucket(bucket)
        if x.dtype != self._in_dtype and np.issubdtype(
                x.dtype, np.floating):
            x = x.astype(self._in_dtype)
        self.guard_signature(x)
        if _chaos._active is not None:
            _chaos.on_serve_slow(
                self.param_version != self._boot_version)
        with _telemetry.span('serve_h2d', kind='h2d', bucket=bucket):
            xd = jax.device_put(
                x, self.plan.batch_sharding() if self.plan is not None
                else jax.devices()[0])
        with _telemetry.span('serve_execute', kind='serve',
                             bucket=bucket,
                             iteration=self._batch_index,
                             **self._ident()) as sp:
            y = exe(self.params, xd)
            y = jax.block_until_ready(y)
            sp.set(aot=self._aot.get(bucket, False))
        self.executions += 1
        self._batch_index += 1
        return y

    def serve_packed(self, pb, clock=None):
        """Execute one :class:`~chainermn_tpu.serving.batcher.
        PackedBatch`: collate+pad host-side (policy compute dtype),
        run the bucket executable, split the output rows back to the
        member requests, and record the serve telemetry (phase
        histograms + per-request latency + per-request trace stages
        ``queue_wait`` -> ``bucket_pack`` -> ``execute`` ->
        ``complete``, tiled so the stage budgets sum to the
        end-to-end latency)."""
        clock = clock or time.monotonic
        rec = _telemetry.active()
        reg = _telemetry.registry()
        ident = self._ident()
        t_exec0 = clock()
        queue_wait = t_exec0 - min(r.t_submit for r in pb.requests)
        # queue wait is PASSIVE time that already elapsed, so it is
        # recorded as an event + histogram, not a wrapping span
        _telemetry.event('serve_queue_wait', kind='serve',
                         seconds=queue_wait, bucket=pb.bucket,
                         iteration=self._batch_index)
        t_pack0 = rec.now() if rec is not None else None
        if rec is not None:
            pad = pb.pad_waste()
            for req in pb.requests:
                # stage 1: the wait that already elapsed, from the
                # admission stamp (or reconstructed when telemetry
                # came up mid-flight) to this drain
                t0 = req.t_trace0
                if t0 is None:
                    t0 = t_pack0 - (clock() - req.t_submit)
                rec.child_span(req.request_id, 'queue_wait', t0,
                               t_pack0, seq=req.seq, **ident)
        try:
            x, _mask = pb.collate(
                dtype=self.policy.compute_dtype
                if self.policy is not None else None)
            t_h2d0 = clock()
            t_exe0 = rec.now() if rec is not None else None
            if rec is not None:
                for req in pb.requests:
                    rec.child_span(req.request_id, 'bucket_pack',
                                   t_pack0, t_exe0, bucket=pb.bucket,
                                   pad_fraction=round(pad, 4),
                                   items=req.n, **ident)
            y = self.infer(x)
            t_done = clock()
            y_host = np.asarray(
                jax.device_get(y if not isinstance(y, (tuple, list))
                               else y[0]))
            off = 0
            for req in pb.requests:
                req.set_result(y_host[off:off + req.n])
                off += req.n
            if rec is not None:
                t_done_tele = rec.now()
                for req in pb.requests:
                    rec.child_span(req.request_id, 'execute', t_exe0,
                                   t_done_tele, bucket=pb.bucket,
                                   **ident)
                    rec.event('complete', kind='request',
                              request_id=req.request_id,
                              bucket=pb.bucket, **ident)
        except Exception as e:
            for req in pb.requests:
                if not req.done():
                    req.set_error(e)
                    if rec is not None:
                        rec.event('error', kind='request',
                                  request_id=req.request_id,
                                  error=type(e).__name__, **ident)
            raise
        if reg is not None:
            reg.histogram(
                'serve_queue_wait',
                help='oldest-request queue wait per served batch (s)'
            ).observe(queue_wait)
            reg.histogram(
                'serve_h2d',
                help='host collation + device placement + execute '
                     'dispatch per batch (s)').observe(t_h2d0 - t_exec0)
            reg.histogram(
                'serve_execute',
                help='bucket executable run-to-completion per batch '
                     '(s)').observe(t_done - t_h2d0)
            reg.histogram(
                'serve_pad_waste',
                help='padding fraction of each served batch'
            ).observe(pb.pad_waste())
            reg.histogram(
                'serve_batch_items',
                help='valid items per served batch').observe(pb.total)
            lat = reg.histogram(
                'serve_latency_seconds',
                help='submit-to-response latency per request (s)')
            now = clock()
            for req in pb.requests:
                lat.observe(now - req.t_submit)
            reg.counter('serve_requests_total',
                        help='requests answered with a result'
                        ).inc(len(pb.requests))
            reg.counter('serve_batches_total',
                        help='bucket executions').inc()
        return y_host

    def run(self, queue, stop=None, take_timeout=0.05):
        """Drain ``queue`` until ``stop`` is set and the queue is
        empty -- the serving worker loop (a daemon thread in the
        bench/load generator; errors land on the affected requests,
        never kill the loop)."""
        while True:
            batches = queue.take(timeout=take_timeout)
            if not batches:
                if stop is not None and stop.is_set() \
                        and queue.depth() == 0:
                    return
                continue
            for pb in batches:
                try:
                    self.serve_packed(pb)
                except Exception:
                    continue  # requests already carry the error

    def stats(self):
        return {
            'buckets': sorted(self._compiled),
            'edges': list(self.edges),
            'label': self.label,
            'param_version': self.param_version,
            'aot': dict(self._aot),
            'aot_requested': self.aot_requested,
            'cache_dir': self.cache_dir,
            'cache_persistent': self.cache_persistent,
            'quantized': self.quantized,
            'trace_count': self.trace_count,
            'compile_count': self.compile_count,
            'executions': self.executions,
        }

    # -- constructors --------------------------------------------------
    @classmethod
    def for_model(cls, model, variables, example, apply_kwargs=None,
                  **kw):
        """Engine over a flax zoo module: ``variables`` is the full
        ``model.init`` result (params + any BatchNorm state -- the
        non-param collections ride along un-quantized and the forward
        runs them in eval mode via ``apply_kwargs``, e.g.
        ``{'train': False}`` for the conv zoo)."""
        apply_kwargs = dict(apply_kwargs or {})

        def apply_fn(vars_, x):
            return model.apply(vars_, x, **apply_kwargs)

        return cls(apply_fn, dict(variables), example, **kw)

    @classmethod
    def from_checkpoint(cls, path, model, variables_template, example,
                        apply_kwargs=None, **kw):
        """Engine loaded from an elastic-resume training checkpoint
        (:func:`load_params`): ``variables_template`` supplies
        structure/shapes (an ``eval_shape``-style init is enough);
        the npz's crc-verified ``params`` subtree replaces the
        template's."""
        variables = dict(variables_template)
        variables['params'] = load_params(
            path, variables_template['params'])
        return cls.for_model(model, variables, example,
                             apply_kwargs=apply_kwargs, **kw)
