"""Goodput: one number for "how much of the wall clock trained".

A fault-tolerant run's headline is not its step time -- it is the
fraction of elapsed wall clock that produced useful training steps
after everything the robustness machinery COSTS (checkpoint stalls,
exposed communication, input stalls, restart downtime) is charged
against it.  This module joins the supervisor ledger
(``supervisor_ledger.jsonl``) with the merged step timeline of every
attempt's telemetry capture and decomposes the run's wall clock into
disjoint buckets::

    wall = useful_step + bubble + exposed_collective + checkpoint
         + input_bound + restart_downtime + other

- **useful_step**: wall time covered by at least one rank's
  ``jitted_step`` span (union across ranks and attempts), minus the
  pipeline bubble;
- **bubble**: the static pipe-idle share of that step time, from the
  ``pipeline:schedule`` trace events (0 when the run has no pipeline
  axis);
- **exposed_collective**: eager-collective span time no step span
  overlaps -- communication the device visibly waited on;
- **checkpoint**: checkpoint span time on the critical path: snapshot
  + synchronous writes + resume restores, NOT overlapped by a step.
  Spans stamped ``background=True`` (the async writer's thread) are
  excluded -- hidden checkpoint I/O is the point of async
  checkpointing and is not charged;
- **input_bound**: input-side span time (``host_batch_prep``,
  ``data_decode``) not hidden behind a step;
- **restart_downtime**: the ledger's failure -> first-progress
  windows (one per ``recovered`` event);
- **other**: the exact remainder (launch/compile/teardown, backoff
  sleep beyond measured downtime).  Buckets are computed by interval
  subtraction against a running covered-union, so they are disjoint
  by construction and sum to the wall clock exactly.

``goodput_fraction = useful_step / wall``.  The CLI
(``python -m chainermn_tpu.telemetry goodput OUT``) renders the
decomposition, writes ``goodput_report.json`` next to the ledger,
and can enforce a floor (``--floor``) for CI chaos legs.

Accepts either a supervisor out dir (ledger + ``telemetry/a*``
attempt captures) or a single plain telemetry session directory
(no ledger: the wall window is the span extent and
``restart_downtime`` is 0).
"""

import glob
import json
import os

from chainermn_tpu.telemetry import report as report_mod

#: decomposition vocabulary, charge order (earlier buckets win ties)
BUCKETS = ('useful_step', 'bubble', 'exposed_collective',
           'checkpoint', 'input_bound', 'restart_downtime', 'other')

#: span names charged to the input_bound bucket when exposed
INPUT_SPAN_NAMES = ('host_batch_prep', 'data_decode')


# ---------------------------------------------------------------------
# interval arithmetic on top of report.merge_intervals

def subtract_intervals(intervals, covered):
    """The parts of ``intervals`` (merged, disjoint) not covered by
    ``covered`` (merged, disjoint)."""
    out = []
    for t0, t1 in intervals:
        cur = t0
        for c0, c1 in covered:
            if c1 <= cur:
                continue
            if c0 >= t1:
                break
            if c0 > cur:
                out.append((cur, c0))
            cur = max(cur, c1)
            if cur >= t1:
                break
        if cur < t1:
            out.append((cur, t1))
    return out


def clip_intervals(intervals, lo, hi):
    """Intervals intersected with the ``[lo, hi]`` window."""
    return [(max(t0, lo), min(t1, hi)) for t0, t1 in intervals
            if min(t1, hi) > max(t0, lo)]


def _total(intervals):
    return sum(t1 - t0 for t0, t1 in intervals)


# ---------------------------------------------------------------------
# loading

def load_ledger(path):
    """Ledger events (list of dicts) from a supervisor ledger jsonl;
    unparseable lines skipped (a torn tail must not hide the run)."""
    events = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass
    return events


def find_captures(out):
    """The telemetry capture directories of a run: the supervisor's
    per-attempt ``telemetry/a*`` subdirs, or ``out`` itself when it
    holds per-rank event logs directly (attempt order preserved)."""
    adirs = sorted(
        glob.glob(os.path.join(out, 'telemetry', 'a*')),
        key=lambda p: (len(os.path.basename(p)), p))
    caps = [d for d in adirs
            if glob.glob(os.path.join(d, 'events-rank*.jsonl'))]
    if caps:
        return caps
    if glob.glob(os.path.join(out, 'events-rank*.jsonl')):
        return [out]
    return []


def downtime_intervals(ledger, first_progress=None):
    """``(intervals, total_s)`` -- one downtime window per
    ``recovered`` ledger event.  ``downtime_s`` is measured by the
    supervisor from the moment progress STOPPED (the victim's last
    heartbeat advance -- before detection, which lags by the stall/
    drain grace) to the first iteration advance of the recovered
    attempt; the window is therefore anchored at its END: the
    recovered attempt's first completed step (``first_progress``
    maps attempt index -> that wall time; the event's own stamp --
    attempt teardown -- is the fallback).  ``total_s`` is the
    ledger's own sum (the MTTR numerator), independent of the
    interval accounting."""
    first_progress = first_progress or {}
    intervals, total = [], 0.0
    for ev in ledger:
        if ev.get('event') != 'recovered':
            continue
        d = ev.get('downtime_s')
        if d is None:
            continue
        total += d
        end = first_progress.get(ev.get('attempt'), ev.get('t', 0.0))
        intervals.append((end - d, end))
    return report_mod.merge_intervals(intervals), total


# ---------------------------------------------------------------------
# the decomposition

def build_goodput(out):
    """The goodput report for a run directory (see module
    docstring).  Returns a dict; ``wall_s`` is None when neither a
    ledger window nor any spans exist (an empty capture)."""
    out = os.path.normpath(out)
    ledger = load_ledger(os.path.join(out, 'supervisor_ledger.jsonl'))
    caps = find_captures(out)

    spans, events = [], []
    attempts = []
    first_progress = {}
    for cap in caps:
        _metas, s, e, _bad = report_mod.load_rank_logs(cap)
        spans.extend(s)
        events.extend(e)
        steps_t1 = [rec['t1'] for rec in s
                    if rec.get('name') == 'jitted_step']
        base = os.path.basename(cap)
        if base.startswith('a') and base[1:].isdigit() and steps_t1:
            first_progress[int(base[1:])] = min(steps_t1)
        attempts.append({
            'capture': cap,
            'n_spans': len(s),
            'ranks': sorted({rec.get('rank', 0) for rec in s}),
        })

    # wall window: ledger start -> terminal event when supervised,
    # else the span extent of a bare capture
    t_lo = t_hi = None
    terminal = None
    for ev in ledger:
        if ev.get('event') == 'start':
            t_lo = ev.get('t')
        elif ev.get('event') in ('complete', 'abort', 'timeout'):
            t_hi = ev.get('t')
            terminal = ev.get('event')
    if spans:
        s_lo = min(s['t0'] for s in spans)
        s_hi = max(s['t1'] for s in spans)
        if t_lo is None:
            t_lo, t_hi = s_lo, s_hi
        elif t_hi is None:
            t_hi = s_hi  # supervisor killed mid-run: best evidence
    if t_lo is None or t_hi is None or t_hi <= t_lo:
        return {'out': out, 'wall_s': None, 'attempts': attempts,
                'ledger_events': len(ledger)}
    wall = t_hi - t_lo

    def union(pred):
        return clip_intervals(report_mod.merge_intervals(
            [(s['t0'], s['t1']) for s in spans if pred(s)]),
            t_lo, t_hi)

    step_u = union(lambda s: s.get('name') == 'jitted_step')
    step_s = _total(step_u)

    # pipeline bubble: the static pipe-idle share of the step time
    pipe = report_mod.pipeline_summary(events)
    bubble_frac = max((row['bubble_fraction'] for row in pipe),
                      default=0.0) if pipe else 0.0
    bubble_s = step_s * bubble_frac
    useful_s = step_s - bubble_s

    covered = list(step_u)

    def charge(intervals):
        exposed = subtract_intervals(intervals, covered)
        covered[:] = report_mod.merge_intervals(covered + exposed)
        return _total(exposed)

    coll_s = charge(union(
        lambda s: s.get('kind') in report_mod.COLLECTIVE_KINDS))
    ckpt_s = charge(union(
        lambda s: s.get('kind') == 'checkpoint'
        and not s.get('background')))
    input_s = charge(union(
        lambda s: s.get('name') in INPUT_SPAN_NAMES))
    down_iv, ledger_down_s = downtime_intervals(ledger,
                                                first_progress)
    down_s = charge(clip_intervals(down_iv, t_lo, t_hi))
    other_s = wall - (step_s + coll_s + ckpt_s + input_s + down_s)

    # async checkpointing's receipt: background-writer span time that
    # was NOT charged (reported for the story, not in the sum)
    hidden_ckpt = _total(union(
        lambda s: s.get('kind') == 'checkpoint'
        and s.get('background')))

    restarts = sum(1 for ev in ledger
                   if ev.get('event') == 'failure')
    shrinks = [ev for ev in ledger
               if ev.get('event') == 'decision'
               and ev.get('action') == 'shrink']
    mttr = None
    for ev in ledger:
        if ev.get('event') == 'complete' \
                and ev.get('mttr_s') is not None:
            mttr = ev['mttr_s']

    def r(x):
        return round(x, 6)

    buckets = {
        'useful_step': r(useful_s),
        'bubble': r(bubble_s),
        'exposed_collective': r(coll_s),
        'checkpoint': r(ckpt_s),
        'input_bound': r(input_s),
        'restart_downtime': r(down_s),
        'other': r(other_s),
    }
    return {
        'out': out,
        'wall_s': r(wall),
        'window': {'t0': t_lo, 't1': t_hi,
                   'terminal': terminal or 'capture'},
        'goodput_fraction': r(useful_s / wall),
        'buckets_s': buckets,
        'buckets_fraction': {k: r(v / wall)
                             for k, v in buckets.items()},
        'hidden_checkpoint_s': r(hidden_ckpt),
        'ledger': {
            'events': len(ledger),
            'failures': restarts,
            'shrinks': len(shrinks),
            'slice_shrinks': sum(
                1 for ev in shrinks
                if ev.get('granularity') == 'slice'),
            'restart_downtime_s': r(ledger_down_s),
            'mttr_s': mttr,
        } if ledger else None,
        'attempts': attempts,
        'n_steps': sum(1 for s in spans
                       if s.get('name') == 'jitted_step'),
    }


# ---------------------------------------------------------------------
# rendering + export + floor

def render_text(gp):
    if gp.get('wall_s') is None:
        return ('goodput: EMPTY capture under %s (no ledger window '
                'and no spans)' % gp['out'])
    lines = ['goodput: %s' % gp['out'],
             'wall clock %.3f s (%s), %d step spans over %d '
             'attempt(s)'
             % (gp['wall_s'], gp['window']['terminal'],
                gp['n_steps'], len(gp['attempts']))]
    for name in BUCKETS:
        lines.append('  %-20s %10.3f s  %6.2f%%'
                     % (name, gp['buckets_s'][name],
                        gp['buckets_fraction'][name] * 100.0))
    check = sum(gp['buckets_s'].values())
    lines.append('  %-20s %10.3f s  (decomposition check: '
                 'buckets sum to wall)' % ('sum', check))
    if gp.get('hidden_checkpoint_s'):
        lines.append(
            'async checkpointing hid %.3f s of checkpoint I/O '
            'behind the step (not charged)'
            % gp['hidden_checkpoint_s'])
    led = gp.get('ledger')
    if led:
        lines.append(
            'supervisor: %d failure(s), %d shrink(s) (%d by slice), '
            'ledger downtime %.3f s%s'
            % (led['failures'], led['shrinks'],
               led['slice_shrinks'], led['restart_downtime_s'],
               (', MTTR %.3f s' % led['mttr_s'])
               if led.get('mttr_s') is not None else ''))
    lines.append('GOODPUT FRACTION: %.4f'
                 % gp['goodput_fraction'])
    return '\n'.join(lines)


def export(out, gp=None):
    """Write ``goodput_report.json`` into the run directory."""
    gp = gp or build_goodput(out)
    with open(os.path.join(out, 'goodput_report.json'), 'w') as f:
        json.dump(gp, f, indent=1)
    return gp
