"""Live sliding-window SLO monitor with multi-window burn-rate
verdicts over the serving path's request traces.

``telemetry report``/``doctor`` are post-hoc: they tell you that p99
was bad after the capture ends.  A canary gate (ROADMAP item 3) needs
the opposite -- a machine-readable latency verdict WHILE the engine
is serving -- which is what this module provides, shaped after the
two production-SRE staples:

- **Sliding windows as a ring of time-bucketed sub-histograms.**
  :class:`WindowedHistogram` keeps raw samples in fixed-width time
  buckets (default 1 s) and answers any windowed percentile by
  MERGING the buckets inside the window -- the registry's raw-sample
  merge discipline applied along the time axis, so a windowed p99 is
  exact over the window, never an average of per-bucket percentiles.
  Buckets are keyed by absolute index (``floor(t / bucket_s)``), so
  per-rank windows merge across ranks bucket-wise
  (:meth:`WindowedHistogram.merge`).
- **Multi-window burn rates.**  A declarative :class:`SLO` carries a
  target, an objective (the good-event fraction), and a FAST and a
  SLOW window.  The burn rate is the observed bad-event fraction over
  the error budget (``1 - objective``); the verdict is ``breach``
  only when BOTH windows burn above ``page_burn`` (a transient spike
  ages out of the fast window and stops paging -- the classic
  Prometheus multi-window multi-burn-rate rule), ``warn`` when both
  exceed ``warn_burn``, else ``ok``.

Five series are tracked, all fed from the per-request trace records
the serving path emits (``kind='request'`` spans/events plus the
``serve_decode`` scheduler span): time-to-first-token, inter-token
gap, tokens/s, shed fraction, and slot occupancy.

Two consumption modes share one code path
(:meth:`SLOMonitor.ingest`):

- **Live**: ``monitor.attach(recorder)`` registers the monitor as a
  streaming listener on the active recorder; verdicts are available
  from :meth:`SLOMonitor.evaluate` at any instant and a periodic
  ``slo_snapshot.json`` is written when the monitor was given an
  ``outdir`` (paced by RECORD time, so replay is deterministic).
- **Offline**: ``python -m chainermn_tpu.telemetry slo DIR``
  (:func:`evaluate_capture`) replays a capture's records in time
  order and emits the verdict as of the capture's last instant --
  byte-identical to what the live monitor would have said then.

The verdict dict mirrors the doctor's shape (``healthy`` +
``summary`` lines under ``verdict``) so the canary gate ROADMAP item
3 consumes both through one reader.  See ``docs/observability.md``
("Serving SLOs and burn rates").
"""

import collections
import json
import os

from chainermn_tpu.telemetry.recorder import _percentile

#: sub-histogram bucket width (seconds): the time resolution of the
#: sliding window -- windows round outward to whole buckets
DEFAULT_BUCKET_SECONDS = 1.0
#: ring retention: buckets older than this many behind the newest are
#: evicted (bounds memory for an engine left serving for days)
DEFAULT_MAX_BUCKETS = 600
DEFAULT_FAST_WINDOW_S = 30.0
DEFAULT_SLOW_WINDOW_S = 150.0

#: verdict tiers, mildest first (index = severity)
VERDICT_TIERS = ('ok', 'warn', 'breach')


class WindowedHistogram:
    """Raw-sample distribution over a sliding time window.

    Samples land in fixed-width time buckets keyed by ABSOLUTE bucket
    index, kept in a bounded ring (insertion-ordered dict; the oldest
    bucket is evicted when the ring outgrows ``max_buckets``).  Any
    windowed summary merges the raw samples of the buckets that
    intersect ``[now - window_s, now]`` -- exact percentiles over the
    window, the same no-averaged-percentiles contract as the registry
    histograms.  Bucket keys are absolute, so two ranks' histograms
    over the same wall clock merge bucket-wise."""

    def __init__(self, bucket_s=DEFAULT_BUCKET_SECONDS,
                 max_buckets=DEFAULT_MAX_BUCKETS):
        if bucket_s <= 0:
            raise ValueError('bucket_s must be > 0, got %r' % bucket_s)
        self.bucket_s = float(bucket_s)
        self.max_buckets = int(max_buckets)
        self._buckets = collections.OrderedDict()  # index -> [samples]

    def _index(self, t):
        return int(t // self.bucket_s)

    def observe(self, value, t):
        idx = self._index(t)
        bucket = self._buckets.get(idx)
        if bucket is None:
            bucket = self._buckets[idx] = []
            self._evict()
        bucket.append(float(value))

    def _evict(self):
        if not self._buckets:
            return
        newest = max(self._buckets)
        floor = newest - self.max_buckets + 1
        for idx in [i for i in self._buckets if i < floor]:
            del self._buckets[idx]

    def window_samples(self, window_s, now):
        """Ascending raw samples from the buckets intersecting
        ``[now - window_s, now]`` (window rounded outward to whole
        buckets; an empty window returns ``[]``)."""
        lo = self._index(now - window_s)
        hi = self._index(now)
        out = []
        for idx, samples in self._buckets.items():
            if lo <= idx <= hi:
                out.extend(samples)
        out.sort()
        return out

    def summary(self, window_s, now):
        """Exact windowed summary: ``{'count': 0}`` when the window
        holds nothing (absence reported as absence, never fabricated
        zeros)."""
        s = self.window_samples(window_s, now)
        if not s:
            return {'count': 0}
        return {
            'count': len(s),
            'mean': sum(s) / len(s),
            'min': s[0],
            'max': s[-1],
            'p50': _percentile(s, 0.50),
            'p99': _percentile(s, 0.99),
        }

    def merge(self, other):
        """Fold ``other``'s time buckets into this histogram (the
        cross-rank merge: bucket indices are absolute, so the same
        wall-clock second lands in the same bucket on every rank).
        Bucket widths must match -- merging mismatched resolutions
        would silently mis-bucket."""
        if abs(other.bucket_s - self.bucket_s) > 1e-12:
            raise ValueError(
                'cannot merge windowed histograms with bucket_s %r '
                'and %r' % (self.bucket_s, other.bucket_s))
        for idx, samples in other._buckets.items():
            self._buckets.setdefault(idx, []).extend(samples)
        self._evict()
        return self

    def total_count(self):
        return sum(len(b) for b in self._buckets.values())


class WindowedCounter:
    """Time-bucketed event counts (the windowed twin of the registry
    ``Counter``): windowed totals back the rate and fraction SLOs."""

    def __init__(self, bucket_s=DEFAULT_BUCKET_SECONDS,
                 max_buckets=DEFAULT_MAX_BUCKETS):
        self._hist = WindowedHistogram(bucket_s, max_buckets)

    def inc(self, t, n=1.0):
        self._hist.observe(n, t)

    def total(self, window_s, now):
        return sum(self._hist.window_samples(window_s, now))

    def merge(self, other):
        self._hist.merge(other._hist)
        return self


class SLO:
    """One declarative service-level objective.

    Args:
      name: verdict key (``ttft_p99``, ``shed_fraction``, ...).
      metric: the monitored series -- one of ``ttft_seconds``,
        ``intertoken_seconds``, ``latency_seconds`` (the batch
        path's submit-to-result e2e), ``tokens_per_s``,
        ``shed_fraction``, ``slot_occupancy``.
      kind: how the series is judged:

        - ``'latency'``: good event = sample <= ``target`` seconds;
          error budget = ``1 - objective``; burn rate = bad fraction
          over budget, judged multi-window.
        - ``'fraction'``: the bad fraction is tracked directly (shed
          requests over outcomes) and ``target`` IS the budget.
        - ``'rate_min'``: the windowed rate must stay >= ``target``;
          ``warn`` when below in both windows, ``breach`` when below
          ``breach_ratio * target`` in both.
        - ``'level_max'``: the windowed mean must stay < ``target``;
          ``warn`` when at/above in both windows, ``breach`` when
          ``breach_level`` is set and reached in both (the default
          occupancy SLO leaves it None: saturation is a capacity
          heads-up, not an outage).

      target: the objective's threshold, in the metric's own unit.
      objective: good-event fraction for ``'latency'`` (default 0.99).
      fast_window_s / slow_window_s: the multi-window pair.
      page_burn / warn_burn: burn-rate thresholds (both windows must
        exceed them).
      min_events: below this many slow-window events the verdict is
        ``ok`` with ``data=False`` -- a cold window must not page.
      breach_ratio / breach_level: the ``rate_min`` / ``level_max``
        escalation knobs.
    """

    def __init__(self, name, metric, kind, target, objective=0.99,
                 fast_window_s=DEFAULT_FAST_WINDOW_S,
                 slow_window_s=DEFAULT_SLOW_WINDOW_S,
                 page_burn=8.0, warn_burn=2.0, min_events=4,
                 breach_ratio=0.5, breach_level=None):
        if kind not in ('latency', 'fraction', 'rate_min',
                        'level_max'):
            raise ValueError('unknown SLO kind %r' % kind)
        if fast_window_s > slow_window_s:
            raise ValueError(
                'fast window %.1fs exceeds slow window %.1fs'
                % (fast_window_s, slow_window_s))
        self.name = name
        self.metric = metric
        self.kind = kind
        self.target = float(target)
        self.objective = float(objective)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.page_burn = float(page_burn)
        self.warn_burn = float(warn_burn)
        self.min_events = int(min_events)
        self.breach_ratio = float(breach_ratio)
        self.breach_level = breach_level

    def budget(self):
        """The error budget the burn rate is measured against."""
        if self.kind == 'fraction':
            return max(self.target, 1e-9)
        return max(1.0 - self.objective, 1e-9)

    def judge_burn(self, bad_frac_fast, bad_frac_slow, n_slow):
        """Multi-window burn-rate verdict for the event kinds
        (``latency`` / ``fraction``)."""
        if n_slow < self.min_events:
            return {'verdict': 'ok', 'data': False,
                    'detail': 'insufficient data (%d events in the '
                              'slow window, need %d)'
                              % (n_slow, self.min_events)}
        budget = self.budget()
        burn_fast = bad_frac_fast / budget
        burn_slow = bad_frac_slow / budget
        if burn_fast >= self.page_burn and burn_slow >= self.page_burn:
            verdict = 'breach'
        elif (burn_fast >= self.warn_burn
              and burn_slow >= self.warn_burn):
            verdict = 'warn'
        else:
            verdict = 'ok'
        return {'verdict': verdict, 'data': True,
                'burn_fast': round(burn_fast, 3),
                'burn_slow': round(burn_slow, 3)}

    def judge_level(self, value_fast, value_slow):
        """Threshold verdict for the level kinds (``rate_min`` /
        ``level_max``); ``None`` values mean no data."""
        if value_fast is None or value_slow is None:
            return {'verdict': 'ok', 'data': False,
                    'detail': 'insufficient data (empty window)'}
        if self.kind == 'rate_min':
            floor = self.target * self.breach_ratio
            if value_fast < floor and value_slow < floor:
                verdict = 'breach'
            elif value_fast < self.target and value_slow < self.target:
                verdict = 'warn'
            else:
                verdict = 'ok'
        else:  # level_max
            if (self.breach_level is not None
                    and value_fast >= self.breach_level
                    and value_slow >= self.breach_level):
                verdict = 'breach'
            elif (value_fast >= self.target
                  and value_slow >= self.target):
                verdict = 'warn'
            else:
                verdict = 'ok'
        return {'verdict': verdict, 'data': True}


def default_slos(ttft_s=1.0, intertoken_s=0.25, objective=0.99,
                 max_shed_fraction=0.05, max_occupancy=0.98,
                 min_tokens_per_s=None, latency_s=None,
                 fast_window_s=DEFAULT_FAST_WINDOW_S,
                 slow_window_s=DEFAULT_SLOW_WINDOW_S):
    """The serving SLO set the bench and the CLI start from;
    every threshold is a keyword so a deployment (or a test pinning
    determinism) declares its own numbers.  ``latency_s`` adds the
    batch path's end-to-end request-latency objective (fed from
    ``execute`` stage spans) -- the generation metrics stay silent on
    a batch fleet, so this is what its canary gate judges."""
    slos = [
        SLO('ttft_p99', 'ttft_seconds', 'latency', ttft_s,
            objective=objective, fast_window_s=fast_window_s,
            slow_window_s=slow_window_s),
        SLO('intertoken_p99', 'intertoken_seconds', 'latency',
            intertoken_s, objective=objective,
            fast_window_s=fast_window_s, slow_window_s=slow_window_s),
        SLO('shed_fraction', 'shed_fraction', 'fraction',
            max_shed_fraction, fast_window_s=fast_window_s,
            slow_window_s=slow_window_s),
        SLO('slot_occupancy', 'slot_occupancy', 'level_max',
            max_occupancy, fast_window_s=fast_window_s,
            slow_window_s=slow_window_s),
    ]
    if min_tokens_per_s is not None:
        slos.append(SLO('tokens_per_s', 'tokens_per_s', 'rate_min',
                        min_tokens_per_s,
                        fast_window_s=fast_window_s,
                        slow_window_s=slow_window_s))
    if latency_s is not None:
        slos.append(SLO('latency_p99', 'latency_seconds', 'latency',
                        latency_s, objective=objective,
                        fast_window_s=fast_window_s,
                        slow_window_s=slow_window_s))
    return slos


class SLOMonitor:
    """In-process sliding-window aggregator + verdict engine.

    Feed it records through :meth:`ingest` -- either live, by
    :meth:`attach`-ing it to the active recorder as a streaming
    listener, or offline, by replaying a capture
    (:func:`evaluate_capture`).  Time comes exclusively from the
    RECORDS (never the wall clock), so a replay reproduces the live
    verdicts exactly.

    Args:
      slos: :class:`SLO` list (default :func:`default_slos`).
      bucket_s: sub-histogram bucket width.
      n_slots: occupancy denominator fallback when the
        ``serve_decode`` span carries no ``n_slots`` attribute.
      outdir / snapshot_every_s: when ``outdir`` is set, a
        ``slo_snapshot.json`` verdict is (re)written there every
        ``snapshot_every_s`` seconds of RECORD time.
      record_filter: optional predicate over raw records, applied
        BEFORE the serving-vocabulary dispatch.  The fleet's canary
        gate runs one monitor per (replica, parameter version) over a
        SHARED recorder stream by filtering on the ``replica`` /
        ``version`` attrs the engines stamp -- two monitors with
        disjoint filters see disjoint traffic and judge independently.
    """

    def __init__(self, slos=None, bucket_s=DEFAULT_BUCKET_SECONDS,
                 max_buckets=DEFAULT_MAX_BUCKETS, n_slots=None,
                 outdir=None, snapshot_every_s=5.0,
                 record_filter=None):
        self.record_filter = record_filter
        self.slos = list(slos) if slos is not None else default_slos()
        self.n_slots = n_slots
        self.outdir = outdir
        self.snapshot_every_s = float(snapshot_every_s)
        mk_h = lambda: WindowedHistogram(bucket_s, max_buckets)  # noqa: E731
        mk_c = lambda: WindowedCounter(bucket_s, max_buckets)    # noqa: E731
        self.ttft = mk_h()
        self.intertoken = mk_h()
        self.latency = mk_h()   # batch path: submit-to-result e2e
        self.occupancy = mk_h()
        self.tokens = mk_c()
        self.completed = mk_c()
        self.shed = mk_c()
        # speculative decoding (serving/generate.py spec mode): the
        # per-tick ``serve_spec`` event feeds windowed proposed /
        # accepted totals; evaluate() surfaces their ratio
        self.draft_proposed = mk_c()
        self.draft_accepted = mk_c()
        self._t_first = None
        self._t_last = None
        self._last_snapshot_t = None
        self._t0_by_request = collections.OrderedDict()
        self.n_ingested = 0
        self._attached = None

    # -- the one ingestion path (live listener AND offline replay) ----
    def ingest(self, rec):
        """Consume one recorder record (span or event dict); records
        that are not part of the serving vocabulary -- or that the
        ``record_filter`` rejects -- are ignored."""
        if self.record_filter is not None \
                and not self.record_filter(rec):
            return
        kind = rec.get('kind')
        if kind == 'request':
            self._ingest_request(rec)
        elif kind == 'serve' and rec.get('name') in ('serve_decode',
                                                     'serve_verify'):
            # the speculative engine's verify span IS its decode tick
            # (same active_slots/n_slots attrs), so occupancy keeps
            # flowing in spec mode
            self._ingest_decode_tick(rec)
        elif kind == 'serve' and rec.get('name') == 'serve_spec':
            self._ingest_spec_tick(rec)
        else:
            return
        if (self.outdir is not None and self._t_last is not None
                and (self._last_snapshot_t is None
                     or self._t_last - self._last_snapshot_t
                     >= self.snapshot_every_s)):
            self._last_snapshot_t = self._t_last
            self.write_snapshot()

    def _seen(self, t):
        self.n_ingested += 1
        if self._t_first is None or t < self._t_first:
            self._t_first = t
        if self._t_last is None or t > self._t_last:
            self._t_last = t

    def _ingest_request(self, rec):
        name = rec.get('name')
        rid = rec.get('request_id')
        if 't0' in rec and 't1' in rec:           # stage span
            t0, t1 = rec['t0'], rec['t1']
            self._seen(t1)
            if name == 'queue_wait':
                self._t0_by_request[rid] = t0
                # bound the in-flight map: a shed/complete that never
                # arrives (torn capture) must not grow it forever
                while len(self._t0_by_request) > 4096:
                    self._t0_by_request.popitem(last=False)
            elif name == 'prefill':
                start = self._t0_by_request.get(rid, t0)
                self.ttft.observe(t1 - start, t1)
                self.tokens.inc(t1, 1.0)          # the first token
            elif name == 'decode':
                self.intertoken.observe(t1 - t0, t1)
                self.tokens.inc(t1, 1.0)
            elif name == 'execute':
                # the batch path's terminal stage: the request's
                # end-to-end latency (admission stamp -> execute end)
                # feeds the latency series the batch-fleet canary
                # gate judges; a served request is an outcome even
                # though it generates no tokens
                start = self._t0_by_request.get(rid, t0)
                self.latency.observe(t1 - start, t1)
        elif 't' in rec:                          # terminal event
            t = rec['t']
            self._seen(t)
            if name == 'complete':
                self.completed.inc(t, 1.0)
            elif name == 'shed':
                self.shed.inc(t, 1.0)
            self._t0_by_request.pop(rid, None)

    def _ingest_decode_tick(self, rec):
        if 't1' not in rec:
            return
        self._seen(rec['t1'])
        n_slots = rec.get('n_slots') or self.n_slots
        active = rec.get('active_slots')
        if n_slots and active is not None:
            self.occupancy.observe(active / float(n_slots), rec['t1'])

    def _ingest_spec_tick(self, rec):
        """Per-tick speculative accounting (the ``serve_spec`` event):
        draft tokens submitted to the target verify vs accepted."""
        if 't' not in rec:
            return
        t = rec['t']
        self._seen(t)
        self.draft_proposed.inc(t, float(rec.get('proposed') or 0))
        self.draft_accepted.inc(t, float(rec.get('accepted') or 0))

    # -- live attachment ----------------------------------------------
    def attach(self, recorder):
        """Register as a streaming listener on ``recorder``."""
        recorder.add_listener(self.ingest)
        self._attached = recorder
        return self

    def detach(self):
        if self._attached is not None:
            self._attached.remove_listener(self.ingest)
            self._attached = None

    # -- evaluation ----------------------------------------------------
    def _effective_window(self, window_s, now):
        """Rate denominators clamp to the observed span: a 10-second
        capture judged over a 150-second window must not report a
        15x-diluted tokens/s."""
        if self._t_first is None:
            return window_s
        seen = max(now - self._t_first, 0.0)
        return max(min(window_s, seen),
                   min(window_s, DEFAULT_BUCKET_SECONDS))

    def _hist_for(self, metric):
        return {'ttft_seconds': self.ttft,
                'intertoken_seconds': self.intertoken,
                'latency_seconds': self.latency}[metric]

    def _window_view(self, metric, window_s, now):
        """``(bad_fraction_or_None, value, n_events, stats)`` for one
        metric over one window."""
        if metric in ('ttft_seconds', 'intertoken_seconds',
                      'latency_seconds'):
            hist = self._hist_for(metric)
            samples = hist.window_samples(window_s, now)
            stats = hist.summary(window_s, now)
            return None, stats.get('p99'), len(samples), stats
        if metric == 'shed_fraction':
            shed = self.shed.total(window_s, now)
            done = self.completed.total(window_s, now)
            n = shed + done
            frac = (shed / n) if n else 0.0
            return frac, frac, int(n), {'shed': shed,
                                        'completed': done,
                                        'count': int(n)}
        if metric == 'tokens_per_s':
            eff = self._effective_window(window_s, now)
            total = self.tokens.total(window_s, now)
            rate = total / eff if eff > 0 else None
            return None, rate, int(total), {'tokens': total,
                                            'window_s': eff}
        if metric == 'slot_occupancy':
            stats = self.occupancy.summary(window_s, now)
            return None, stats.get('mean'), stats.get('count', 0), stats
        raise ValueError('unknown SLO metric %r' % metric)

    def _evaluate_one(self, slo, now):
        bf_f, value_f, n_f, stats_f = self._window_view(
            slo.metric, slo.fast_window_s, now)
        bf_s, value_s, n_s, stats_s = self._window_view(
            slo.metric, slo.slow_window_s, now)
        if slo.kind == 'latency':
            samples_f = self._hist_for(slo.metric).window_samples(
                slo.fast_window_s, now)
            samples_s = self._hist_for(slo.metric).window_samples(
                slo.slow_window_s, now)
            bf_f = (sum(1 for v in samples_f if v > slo.target)
                    / len(samples_f)) if samples_f else 0.0
            bf_s = (sum(1 for v in samples_s if v > slo.target)
                    / len(samples_s)) if samples_s else 0.0
            judged = slo.judge_burn(bf_f, bf_s, len(samples_s))
        elif slo.kind == 'fraction':
            judged = slo.judge_burn(bf_f or 0.0, bf_s or 0.0, n_s)
        else:
            judged = slo.judge_level(value_f, value_s)
        row = {
            'metric': slo.metric,
            'kind': slo.kind,
            'target': slo.target,
            'fast_window_s': slo.fast_window_s,
            'slow_window_s': slo.slow_window_s,
            'fast': dict(stats_f, value=value_f),
            'slow': dict(stats_s, value=value_s),
        }
        if slo.kind == 'latency':
            row['objective'] = slo.objective
            row['bad_fraction_fast'] = round(bf_f, 4)
            row['bad_fraction_slow'] = round(bf_s, 4)
        row.update(judged)
        return row

    def evaluate(self, now=None):
        """The full verdict dict as of ``now`` (default: the newest
        ingested record's time).  Shape mirrors the doctor's --
        ``verdict.healthy`` + ``verdict.summary`` lines -- so the
        canary gate reads both through one path."""
        now = self._t_last if now is None else now
        rows = {}
        if now is not None:
            for slo in self.slos:
                rows[slo.name] = self._evaluate_one(slo, now)
        worst = 'ok'
        breaches, warnings = [], []
        for name, row in sorted(rows.items()):
            v = row['verdict']
            if VERDICT_TIERS.index(v) > VERDICT_TIERS.index(worst):
                worst = v
            if v == 'breach':
                breaches.append(name)
            elif v == 'warn':
                warnings.append(name)
        summary = []
        for name in breaches + warnings:
            row = rows[name]
            line = '%s %s: %s' % (name, row['verdict'].upper(),
                                  _describe_row(row))
            summary.append(line)
        if not summary:
            summary.append(
                'all %d SLOs ok over the fast/slow windows'
                % len(rows) if rows else 'no serving records ingested')
        speculative = None
        if now is not None:
            proposed = self.draft_proposed.total(
                DEFAULT_SLOW_WINDOW_S, now)
            accepted = self.draft_accepted.total(
                DEFAULT_SLOW_WINDOW_S, now)
            if proposed:
                # informational, not an SLO verdict: the windowed
                # accepted-draft-rate a canary dashboard reads next
                # to the latency verdicts
                speculative = {
                    'window_s': DEFAULT_SLOW_WINDOW_S,
                    'draft_proposed': proposed,
                    'draft_accepted': accepted,
                    'accepted_draft_rate': accepted / proposed,
                }
        return {
            'now': now,
            'n_ingested': self.n_ingested,
            'window_first_t': self._t_first,
            'window_last_t': self._t_last,
            'speculative': speculative,
            'slos': rows,
            'verdict': {
                'overall': worst,
                'healthy': worst == 'ok',
                'breaches': breaches,
                'warnings': warnings,
                'summary': summary,
            },
        }

    # -- snapshots -----------------------------------------------------
    def write_snapshot(self, path=None, now=None):
        """Atomically (tmp + rename) write the current verdict as
        ``slo_snapshot.json`` -- the file a canary gate polls while
        the engine serves.  Best-effort: returns the path or None."""
        path = path or (os.path.join(self.outdir, 'slo_snapshot.json')
                        if self.outdir else None)
        if path is None:
            return None
        try:
            os.makedirs(os.path.dirname(path) or '.', exist_ok=True)
            tmp = path + '.tmp.%d' % os.getpid()
            with open(tmp, 'w') as f:
                json.dump(self.evaluate(now=now), f, indent=1,
                          default=repr)
            os.replace(tmp, path)
            return path
        except Exception:
            return None


def _describe_row(row):
    if row['kind'] == 'latency':
        return ('%.1f%% of events over %.0f ms in the fast window '
                '(burn %.1fx budget fast / %.1fx slow)'
                % (100 * row.get('bad_fraction_fast', 0.0),
                   row['target'] * 1e3, row.get('burn_fast', 0.0),
                   row.get('burn_slow', 0.0)))
    if row['kind'] == 'fraction':
        return ('%.1f%% shed vs %.1f%% budget (burn %.1fx fast / '
                '%.1fx slow)'
                % (100 * (row['fast'].get('value') or 0.0),
                   100 * row['target'], row.get('burn_fast', 0.0),
                   row.get('burn_slow', 0.0)))
    if row['kind'] == 'rate_min':
        return ('%.1f/s vs a %.1f/s floor'
                % (row['fast'].get('value') or 0.0, row['target']))
    return ('level %.3f vs a %.3f ceiling'
            % (row['fast'].get('value') or 0.0, row['target']))


# ---------------------------------------------------------------------
# offline: replay a capture directory

def evaluate_capture(outdir, slos=None,
                     bucket_s=DEFAULT_BUCKET_SECONDS, now=None):
    """Replay a capture directory's records in time order through an
    :class:`SLOMonitor` and return its verdict as of the capture's
    last instant (or ``now``).  Deterministic: the same capture always
    yields the same verdict.  The result additionally carries
    ``outdir`` and ``n_request_records`` (0 means the capture holds
    no serving trace at all -- the CLI exits 2 on it)."""
    from chainermn_tpu.telemetry.report import load_rank_logs
    _metas, spans, events, bad = load_rank_logs(outdir)
    records = sorted(
        spans + events,
        key=lambda r: r.get('t1', r.get('t', r.get('t0', 0.0))))
    mon = SLOMonitor(slos=slos, bucket_s=bucket_s)
    for rec in records:
        mon.ingest(rec)
    result = mon.evaluate(now=now)
    result['outdir'] = outdir
    result['n_request_records'] = mon.n_ingested
    result['n_unparseable_lines'] = bad
    return result


def render_slo_text(result):
    lines = ['telemetry slo: %s' % result.get('outdir', '<live>'),
             'records ingested: %d' % result.get('n_ingested', 0)]
    for name, row in sorted((result.get('slos') or {}).items()):
        fast, slow = row['fast'], row['slow']
        detail = ''
        if row['kind'] == 'latency':
            detail = ('  p99 fast %s ms / slow %s ms'
                      % (_ms(fast.get('p99')), _ms(slow.get('p99'))))
        elif row['kind'] == 'fraction':
            detail = ('  shed fast %.1f%% / slow %.1f%%'
                      % (100 * (fast.get('value') or 0.0),
                         100 * (slow.get('value') or 0.0)))
        elif fast.get('value') is not None:
            detail = ('  value fast %.3f / slow %.3f'
                      % (fast.get('value') or 0.0,
                         slow.get('value') or 0.0))
        burn = ''
        if row.get('burn_fast') is not None:
            burn = ('  burn %.1fx/%.1fx'
                    % (row['burn_fast'], row['burn_slow']))
        lines.append('  %-16s %-6s%s%s%s'
                     % (name, row['verdict'].upper(), detail, burn,
                        '' if row.get('data', True)
                        else '  [no data: %s]' % row.get('detail')))
    spec = result.get('speculative')
    if spec:
        lines.append(
            '  speculative: accepted_draft_rate %.3f (%d/%d drafts '
            'over %.0fs)' % (spec['accepted_draft_rate'],
                             spec['draft_accepted'],
                             spec['draft_proposed'],
                             spec['window_s']))
    v = result['verdict']
    lines.append('verdict: %s' % v['overall'].upper())
    for s in v['summary']:
        lines.append('  - %s' % s)
    return '\n'.join(lines)


def _ms(v):
    return '-' if v is None else '%.3f' % (v * 1e3)


def export(outdir, result=None, slos=None):
    """Write ``slo_report.json`` next to the per-rank logs and return
    the result (the offline twin of the live ``slo_snapshot.json``)."""
    result = result or evaluate_capture(outdir, slos=slos)
    path = os.path.join(outdir, 'slo_report.json')
    tmp = path + '.tmp'
    with open(tmp, 'w') as f:
        json.dump(result, f, indent=1, default=repr)
    os.replace(tmp, path)
    return result
