"""Offline side of the telemetry subsystem: merge per-rank event
logs into one step timeline, compute the overlap fraction, aggregate
metrics, and export Prometheus text.

Overlap definition (the number ROADMAP item 5 asks for, and the
dynamic twin of shardlint SL009):

- **total collective time**: the summed wall duration of every
  ``kind='collective'`` span (eager collectives, bounded rendezvous);
- **exposed collective time**: the part of that duration during which
  NO ``kind='compute'`` span was running on the same rank -- i.e. the
  device had nothing dispatched to hide the communication behind;
- ``overlap_fraction = 1 - exposed / total`` (``None`` when the
  capture recorded no collective spans at all: absence of evidence is
  reported as absence, never as a fabricated 0 or 1).

The same interval arithmetic is exported as pure functions
(:func:`merge_intervals`, :func:`exposed_time`,
:func:`overlap_from_intervals`) so ``benchmarks/trace_report.py`` can
apply the identical definition to banked device profiles.
"""

import glob
import json
import os
import re

from chainermn_tpu.telemetry.recorder import (
    _percentile, snapshot_to_prometheus)

#: span names the per-step table columns come from (issue order);
#: ``data_decode`` is the streaming loader's per-batch decode span
#: (``chainermn_tpu/data/loader.py``) -- it rides the same table so
#: the doctor's straggler-phase attribution covers the input path
STEP_PHASES = ('data_decode', 'host_batch_prep', 'h2d',
               'jitted_step', 'metrics_sync')

#: serve-phase vocabulary (``chainermn_tpu/serving``): per-batch
#: spans/events the engine emits and the registry histograms of the
#: same names.  The doctor/report layers recognize these so a
#: forward-only serving capture -- which records NO training step
#: spans, and in the bench's in-memory mode no events at all, only
#: metrics -- is never misreported as an empty capture (exit 2)
#: ``serve_prefill``/``serve_decode`` are the autoregressive-path
#: phases (``serving/generate.py``): prefill spans carry the prompt
#: bucket, decode spans the step index (``iteration``) and
#: ``active_slots`` -- both feed the doctor's anomaly scan the way
#: ``serve_execute`` batches do.  ``serve_draft``/``serve_verify``
#: are the SPECULATIVE-decoding phases: the draft model's propose
#: loop (one span wrapping all ``spec_tokens`` cheap steps, plus the
#: lockstep draft prefill with ``stage='prefill'``) and the single
#: target verify pass of the whole window (carrying the decode-tick
#: attrs, so occupancy/tick dashboards keep working in spec mode)
SERVE_PHASES = ('serve_queue_wait', 'serve_h2d', 'serve_execute',
                'serve_warmup', 'serve_prefill', 'serve_decode',
                'serve_draft', 'serve_verify')

#: span kinds whose time counts as "compute the collective could
#: hide behind"
COMPUTE_KINDS = ('compute',)
#: span kinds audited for exposure
COLLECTIVE_KINDS = ('collective',)

#: per-request trace stage vocabulary (``kind='request'`` spans the
#: serving path records, issue order): the generation path emits
#: ``queue_wait`` -> ``bucket_pack`` -> ``prefill`` -> one ``decode``
#: per tick; the batch path emits ``queue_wait`` -> ``bucket_pack``
#: -> ``execute``.  Stages TILE the request's lifetime (each stage's
#: t0 is the previous stage's t1), so per-stage budgets telescope to
#: the end-to-end latency -- the property the p99 decomposition pin
#: asserts to +-1 ms
REQUEST_STAGES = ('queue_wait', 'bucket_pack', 'prefill', 'decode',
                  'execute')

#: terminal ``kind='request'`` event vocabulary
REQUEST_OUTCOMES = ('complete', 'shed', 'error')


# ---------------------------------------------------------------------
# interval arithmetic (shared with benchmarks/trace_report.py)

def merge_intervals(intervals):
    """Union of ``(t0, t1)`` pairs as a sorted disjoint list."""
    ivs = sorted((t0, t1) for t0, t1 in intervals if t1 > t0)
    out = []
    for t0, t1 in ivs:
        if out and t0 <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], t1))
        else:
            out.append((t0, t1))
    return out


def exposed_time(span, merged):
    """Length of ``span`` not covered by the merged interval union."""
    t0, t1 = span
    exposed = t1 - t0
    for m0, m1 in merged:
        if m1 <= t0:
            continue
        if m0 >= t1:
            break
        exposed -= min(t1, m1) - max(t0, m0)
    return max(exposed, 0.0)


def overlap_from_intervals(collective, compute):
    """Overlap statistics for two interval lists (seconds in, seconds
    out).  ``overlap_fraction`` is None when there are no collective
    intervals.  Collective intervals are UNIONED first so nested or
    concurrent spans (an evaluator wrapper around per-key
    allreduces, two async buckets in flight) count wall time once."""
    coll = merge_intervals(collective)
    total = sum(t1 - t0 for t0, t1 in coll)
    merged = merge_intervals(compute)
    exposed = sum(exposed_time((t0, t1), merged) for t0, t1 in coll)
    return {
        'total_collective_s': total,
        'exposed_collective_s': exposed,
        'hidden_collective_s': max(total - exposed, 0.0),
        'overlap_fraction': (None if total <= 0.0
                             else max(0.0, min(1.0, 1.0 - exposed
                                               / total))),
    }


def span_axes_key(span):
    """The mesh-axis tag of a collective span (``'data'``, ``'model'``,
    ``'inter,intra'`` ...), from the ``axes`` attribute the
    communicator layer records; ``'untagged'`` for spans that predate
    the tagging."""
    axes = span.get('axes')
    if isinstance(axes, (list, tuple)) and axes:
        return ','.join(str(a) for a in axes)
    return 'untagged'


def overlap_stats(spans):
    """Overlap statistics over merged telemetry spans, exposure
    judged per rank (a collective is hidden only by compute running
    on the SAME rank).  ``per_axis`` splits the same accounting by
    the collective spans' mesh-axis tag, so a composed dp x tp run
    shows WHICH axis's communication is exposed (the data-parallel
    gradient reduction vs the tensor-parallel block psums)."""
    ranks = sorted({s.get('rank', 0) for s in spans})
    total = exposed = 0.0
    per_axis = {}
    for rank in ranks:
        comp = [(s['t0'], s['t1']) for s in spans
                if s.get('rank', 0) == rank
                and s.get('kind') in COMPUTE_KINDS]
        merged = merge_intervals(comp)
        coll_spans = [s for s in spans
                      if s.get('rank', 0) == rank
                      and s.get('kind') in COLLECTIVE_KINDS]
        st = overlap_from_intervals(
            [(s['t0'], s['t1']) for s in coll_spans], comp)
        total += st['total_collective_s']
        exposed += st['exposed_collective_s']
        for s in coll_spans:
            key = span_axes_key(s)
            agg = per_axis.setdefault(
                key, {'total_collective_s': 0.0,
                      'exposed_collective_s': 0.0, 'spans': 0})
            agg['spans'] += 1
            agg['total_collective_s'] += max(s['t1'] - s['t0'], 0.0)
            agg['exposed_collective_s'] += exposed_time(
                (s['t0'], s['t1']), merged)
    for agg in per_axis.values():
        t, e = agg['total_collective_s'], agg['exposed_collective_s']
        agg['overlap_fraction'] = (
            None if t <= 0.0 else max(0.0, min(1.0, 1.0 - e / t)))
    return {
        'total_collective_s': total,
        'exposed_collective_s': exposed,
        'hidden_collective_s': max(total - exposed, 0.0),
        'overlap_fraction': (None if total <= 0.0
                             else max(0.0, min(1.0,
                                               1.0 - exposed / total))),
        'per_axis': per_axis,
    }


# ---------------------------------------------------------------------
# loading + merging

def load_rank_logs(outdir):
    """``(metas, spans, events)`` from every ``events-rank*.jsonl``
    under a session directory.  Unparseable lines are counted, not
    fatal (a crashed rank leaves a torn tail)."""
    metas, spans, events = [], [], []
    bad = 0
    for path in sorted(glob.glob(
            os.path.join(outdir, 'events-rank*.jsonl'))):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    bad += 1
                    continue
                t = rec.get('type')
                if t == 'meta':
                    metas.append(rec)
                elif t == 'span':
                    spans.append(rec)
                elif t == 'event':
                    events.append(rec)
    return metas, spans, events, bad


def load_rank_metrics(outdir):
    """Per-rank metrics snapshots (``metrics-rank*.json``)."""
    out = []
    for path in sorted(glob.glob(
            os.path.join(outdir, 'metrics-rank*.json'))):
        try:
            with open(path) as f:
                out.append(json.load(f))
        except (ValueError, OSError):
            continue
    return out


def aggregate_metrics(rank_metrics):
    """One merged snapshot from per-rank snapshots: counters sum,
    gauges keep per-rank values plus the max, histograms merge raw
    samples and recompute the percentile summary (averaging per-rank
    percentiles would be wrong for skewed distributions)."""
    merged = {}
    for rm in rank_metrics:
        for name, snap in (rm.get('metrics') or {}).items():
            kind = snap.get('type')
            cur = merged.get(name)
            if kind == 'counter':
                if cur is None:
                    cur = merged[name] = {'type': 'counter',
                                          'value': 0.0}
                cur['value'] += snap.get('value') or 0.0
            elif kind == 'gauge':
                if cur is None:
                    cur = merged[name] = {'type': 'gauge',
                                          'value': None,
                                          'per_rank': []}
                v = snap.get('value')
                cur['per_rank'].append(v)
                if v is not None:
                    cur['value'] = (v if cur['value'] is None
                                    else max(cur['value'], v))
            elif kind == 'histogram':
                if cur is None:
                    cur = merged[name] = {'type': 'histogram',
                                          'count': 0, 'sum': 0.0,
                                          'samples': []}
                cur['count'] += snap.get('count') or 0
                cur['sum'] += snap.get('sum') or 0.0
                cur['samples'].extend(snap.get('samples') or [])
            if name in merged and snap.get('help'):
                merged[name].setdefault('help', snap['help'])
    for snap in merged.values():
        if snap.get('type') == 'histogram':
            s = sorted(snap['samples'])
            snap['summary'] = ({} if not s else {
                'count': snap['count'], 'sum': snap['sum'],
                'min': s[0], 'max': s[-1],
                'mean': sum(s) / len(s),
                'p50': _percentile(s, 0.50),
                'p90': _percentile(s, 0.90),
                'p99': _percentile(s, 0.99)})
    return merged


def step_table(spans):
    """Per-(rank, iteration) phase durations from the step-phase
    spans both updaters emit.  Rows sorted by (iteration, rank)."""
    rows = {}
    for s in spans:
        if s.get('name') not in STEP_PHASES or 'iteration' not in s:
            continue
        key = (int(s['iteration']), int(s.get('rank', 0)))
        row = rows.setdefault(key, {'iteration': key[0],
                                    'rank': key[1], 't0': s['t0']})
        row[s['name'] + '_ms'] = round((s['t1'] - s['t0']) * 1e3, 3)
        row['t0'] = min(row['t0'], s['t0'])
    return [rows[k] for k in sorted(rows)]


#: per-step input-side phases charged against the device step by the
#: input-bound verdict (decode overlaps prep when the loader runs
#: under a prefetch iterator, so prep -- the span on the consuming
#: thread -- is the charged one; data_decode is reported alongside)
INPUT_PHASES = ('host_batch_prep',)


def input_bound_stats(steps, warmup=1):
    """The input-bound verdict of a training capture: per-rank p50 of
    the input-side phases (``host_batch_prep``) vs the device step
    (``jitted_step``), worst rank reported.  ``input_bound`` is True
    when input prep's p50 meets or exceeds the step's -- the loader,
    not the device, is pacing the run.  The first ``warmup``
    iterations are exempt per (phase, rank), mirroring the doctor's
    compile-step discipline.  ``None`` when the capture has no
    step-phase rows to judge."""
    per_rank = {}
    for row in steps:
        if int(row.get('iteration', 0)) < warmup:
            continue
        d = per_rank.setdefault(int(row.get('rank', 0)),
                                {'prep': [], 'step': [],
                                 'decode': []})
        prep = sum(row.get(p + '_ms', 0.0) for p in INPUT_PHASES)
        if prep > 0.0:
            d['prep'].append(prep)
        if 'jitted_step_ms' in row:
            d['step'].append(row['jitted_step_ms'])
        if 'data_decode_ms' in row:
            d['decode'].append(row['data_decode_ms'])
    worst = None
    for rank, d in sorted(per_rank.items()):
        if not d['prep'] or not d['step']:
            continue
        prep50 = _percentile(sorted(d['prep']), 0.50)
        step50 = _percentile(sorted(d['step']), 0.50)
        frac = prep50 / max(prep50 + step50, 1e-9)
        cand = {
            'rank': rank,
            'host_batch_prep_p50_ms': round(prep50, 3),
            'jitted_step_p50_ms': round(step50, 3),
            'data_decode_p50_ms': (
                round(_percentile(sorted(d['decode']), 0.50), 3)
                if d['decode'] else None),
            'input_fraction': round(frac, 4),
            'n_steps': len(d['step']),
            'input_bound': prep50 >= step50,
        }
        if worst is None or cand['input_fraction'] > \
                worst['input_fraction']:
            worst = cand
    return worst


def pipeline_summary(events):
    """The pipeline view of a capture: one row per distinct pipelined
    step configuration, from the ``pipeline:schedule`` trace-time
    events the pipeline updaters stamp once per compilation
    (``kind='pipeline'``; schedule name, micro-batch count, stage
    count, scan ticks, stage axis).

    The **bubble fraction** -- pipe-idle work slots per stage per
    step, the pipeline twin of the overlap fraction -- is computed
    from the schedule arithmetic
    (:func:`chainermn_tpu.parallel.pipeline.bubble_fraction`): both
    schedules are SPMD scans whose idle is the masked slots, a static
    property of ``(n_micro, n_stages)``, so the number here is exact,
    not sampled.  Always in ``[0, 1]`` per stage, and strictly
    decreasing in the micro-batch count at fixed stages -- the
    property CI pins.  ``None`` when the capture recorded no pipeline
    events."""
    scheds = [e for e in events
              if e.get('kind') == 'pipeline'
              and e.get('name') == 'pipeline:schedule']
    if not scheds:
        return None
    from chainermn_tpu.parallel.pipeline import (
        bubble_fractions_per_stage)
    out, seen = [], set()
    for e in scheds:
        try:
            key = (e.get('schedule') or '1f1b',
                   int(e.get('n_micro') or 0),
                   int(e.get('n_stages') or 0))
        except (TypeError, ValueError):
            continue
        if key in seen or key[1] < 1 or key[2] < 1:
            continue
        seen.add(key)
        per_stage = bubble_fractions_per_stage(key[1], key[2], key[0])
        axes = e.get('axes')
        out.append({
            'schedule': key[0],
            'n_micro': key[1],
            'n_stages': key[2],
            'total_ticks': e.get('total_ticks'),
            'axis': (axes[0] if isinstance(axes, (list, tuple))
                     and axes else 'stage'),
            'bubble_fraction': round(per_stage[0], 6),
            'bubble_fraction_per_stage': [round(b, 6)
                                          for b in per_stage],
        })
    return out or None


def serve_summary(metrics):
    """The serving view of an aggregated metrics snapshot: request /
    batch / shed totals and the latency / queue-wait / pad-waste
    distributions the ``serve_*`` histograms carry (p50/p99 from the
    merged raw samples).  ``None`` when the snapshot records no
    serving activity -- the presence test the empty-capture checks
    consult."""
    if not metrics:
        return None
    serve = {k: v for k, v in metrics.items()
             if k.startswith('serve_')}
    if not serve:
        return None

    def summ(name):
        return (serve.get(name) or {}).get('summary') or {}

    def total(name):
        return (serve.get(name) or {}).get('value') or 0.0

    lat, wait, pad = (summ('serve_latency_seconds'),
                      summ('serve_queue_wait'),
                      summ('serve_pad_waste'))
    # shed forensics: the admission layers bump a per-reason counter
    # next to the aggregate, so an overload capture says WHY requests
    # were turned away (queue_full vs deadline vs shutdown) -- only
    # reasons that actually fired appear
    shed_reasons = {
        reason: total('serve_shed_%s_total' % reason)
        for reason in ('queue_full', 'deadline', 'shutdown')
        if ('serve_shed_%s_total' % reason) in serve}
    out = {
        'requests': total('serve_requests_total'),
        'batches': total('serve_batches_total'),
        'shed': total('serve_shed_total'),
        'shed_reasons': shed_reasons or None,
        'latency_ms': {
            'count': lat.get('count', 0),
            'p50': (lat.get('p50') or 0.0) * 1e3 if lat else None,
            'p99': (lat.get('p99') or 0.0) * 1e3 if lat else None,
        } if lat else None,
        'queue_wait_ms': {
            'p50': (wait.get('p50') or 0.0) * 1e3,
            'p99': (wait.get('p99') or 0.0) * 1e3,
        } if wait else None,
        'pad_waste_mean': pad.get('mean') if pad else None,
        'metrics': sorted(serve),
    }
    # the autoregressive-decode view (serving/generate.py): tokens
    # generated, TTFT and inter-token distributions, and tokens/s
    # derived from the decode-step histogram's own wall time (sum =
    # mean * count -- raw samples, never an averaged percentile)
    ttft = summ('serve_ttft_seconds')
    itl = summ('serve_intertoken_seconds')
    dstep = summ('serve_decode_seconds')
    tokens = total('serve_tokens_total')
    if tokens or ttft or itl:
        decode_wall = ((dstep.get('mean') or 0.0)
                       * dstep.get('count', 0)) if dstep else 0.0
        # the gauge is named per the scheduler's vocabulary (no
        # serve_ prefix), so read it off the full snapshot
        gauge = metrics.get('active_slots') or {}
        out['generate'] = {
            'tokens': tokens,
            'ttft_ms': {
                'count': ttft.get('count', 0),
                'p50': (ttft.get('p50') or 0.0) * 1e3,
                'p99': (ttft.get('p99') or 0.0) * 1e3,
            } if ttft else None,
            'intertoken_ms': {
                'count': itl.get('count', 0),
                'p50': (itl.get('p50') or 0.0) * 1e3,
                'p99': (itl.get('p99') or 0.0) * 1e3,
            } if itl else None,
            'decode_steps': dstep.get('count', 0) if dstep else 0,
            'tokens_per_s': (tokens / decode_wall
                             if tokens and decode_wall > 0 else None),
            'active_slots': gauge.get('value'),
        }
        # the speculative-decoding view: draft tokens submitted to
        # the target verify pass vs those whose target argmax agreed
        # -- the rate is the amortization lever (accepted tokens per
        # expensive target pass); ``None`` rate when the engine
        # proposed nothing (non-speculative captures omit the block)
        proposed = total('serve_draft_proposed_total')
        accepted = total('serve_draft_accepted_total')
        if 'serve_draft_proposed_total' in serve:
            out['generate']['speculative'] = {
                'draft_proposed': proposed,
                'draft_accepted': accepted,
                'accepted_draft_rate': (accepted / proposed
                                        if proposed else None),
            }
    return out


# ---------------------------------------------------------------------
# per-request trace reconstruction (kind='request' records)

def request_traces(records):
    """Reconstruct per-request span trees from ``kind='request'``
    records (stage spans + terminal events), keyed by ``request_id``.

    Accepts any iterable of record dicts -- merged span/event lists
    from :func:`load_rank_logs`, or a live recorder's raw ``events``
    list -- and ignores everything that is not a request record.

    Each trace carries the ordered ``stages`` (name/t0/t1/duration +
    the recorded attrs: slot, bucket, pad_fraction, step), per-stage
    total budgets ``stage_ms``, the decode tick count, the terminal
    ``outcome`` (``complete`` / ``shed`` / ``error`` /
    ``in_flight``), and ``e2e_ms`` -- last stage end minus first
    stage start, which the tiled stage contract makes equal to the
    stage-budget sum."""
    traces = {}
    for rec in records:
        if rec.get('kind') != 'request':
            continue
        rid = rec.get('request_id')
        if rid is None:
            continue
        tr = traces.setdefault(str(rid), {
            'request_id': str(rid), 'stages': [], 'outcome':
            'in_flight', 'outcome_attrs': None})
        if 't0' in rec and 't1' in rec:
            tr['stages'].append(rec)
        elif rec.get('name') in REQUEST_OUTCOMES:
            tr['outcome'] = rec['name']
            tr['outcome_attrs'] = {
                k: v for k, v in rec.items()
                if k not in ('type', 'name', 'kind', 'request_id')}
    for tr in traces.values():
        tr['stages'].sort(key=lambda s: (s['t0'], s['t1']))
        stage_ms = {}
        n_decode = 0
        for s in tr['stages']:
            dur = max(s['t1'] - s['t0'], 0.0) * 1e3
            stage_ms[s['name']] = stage_ms.get(s['name'], 0.0) + dur
            if s['name'] == 'decode':
                n_decode += 1
        tr['stage_ms'] = {k: round(v, 3)
                          for k, v in sorted(stage_ms.items())}
        tr['n_decode'] = n_decode
        if tr['stages']:
            tr['t0'] = min(s['t0'] for s in tr['stages'])
            tr['t1'] = max(s['t1'] for s in tr['stages'])
            tr['e2e_ms'] = round((tr['t1'] - tr['t0']) * 1e3, 3)
        else:
            tr['t0'] = tr['t1'] = None
            tr['e2e_ms'] = None
    return traces


def request_summary(records):
    """The request-centric view of a capture: how many requests were
    traced, their end-to-end latency distribution, per-stage p99
    budgets, and the WORST completed request's full decomposition --
    what ``telemetry report`` prints so a bad p99 names its stage.
    ``None`` when the capture holds no request records."""
    traces = request_traces(records)
    if not traces:
        return None
    timed = [t for t in traces.values() if t['e2e_ms'] is not None]
    done = [t for t in timed if t['outcome'] == 'complete']
    shed = [t for t in traces.values() if t['outcome'] == 'shed']
    e2e = sorted(t['e2e_ms'] for t in done)
    stage_samples = {}
    for t in done:
        for name, ms in t['stage_ms'].items():
            stage_samples.setdefault(name, []).append(ms)
    worst = max(done, key=lambda t: t['e2e_ms']) if done else None
    out = {
        'count': len(traces),
        'completed': len(done),
        'shed': len(shed),
        'in_flight': sum(1 for t in traces.values()
                         if t['outcome'] == 'in_flight'),
        'e2e_ms': ({} if not e2e else {
            'count': len(e2e),
            'p50': round(_percentile(e2e, 0.50), 3),
            'p99': round(_percentile(e2e, 0.99), 3),
            'max': round(e2e[-1], 3)}),
        'stage_p99_ms': {
            name: round(_percentile(sorted(vals), 0.99), 3)
            for name, vals in sorted(stage_samples.items())},
    }
    if worst is not None:
        out['worst'] = {
            'request_id': worst['request_id'],
            'e2e_ms': worst['e2e_ms'],
            'stage_ms': worst['stage_ms'],
            'stage_sum_ms': round(sum(worst['stage_ms'].values()), 3),
            'n_decode': worst['n_decode'],
            'outcome': worst['outcome'],
        }
    return out


def render_request_text(trace):
    """One request's reconstructed timeline, stage by stage (what
    ``telemetry report --request ID`` prints)."""
    lines = ['request %s: e2e %s ms over %d stage(s), outcome %s'
             % (trace['request_id'],
                '-' if trace['e2e_ms'] is None else
                '%.3f' % trace['e2e_ms'],
                len(trace['stages']), trace['outcome'])]
    t_base = trace.get('t0')
    for s in trace['stages']:
        attrs = ', '.join(
            '%s=%s' % (k, v) for k, v in sorted(s.items())
            if k not in ('type', 'name', 'kind', 'request_id',
                         't0', 't1', 'rank'))
        lines.append(
            '  t+%9.3f ms  %-12s %9.3f ms%s'
            % ((s['t0'] - t_base) * 1e3, s['name'],
               (s['t1'] - s['t0']) * 1e3,
               ('  (%s)' % attrs) if attrs else ''))
    if trace.get('outcome_attrs'):
        lines.append('  outcome attrs: %s' % ', '.join(
            '%s=%s' % (k, v)
            for k, v in sorted(trace['outcome_attrs'].items())))
    return '\n'.join(lines)


def build_report(outdir):
    """The merged session report: timeline summary, per-step phase
    table, overlap statistics, aggregated metrics, chaos events."""
    metas, spans, events, bad = load_rank_logs(outdir)
    rank_metrics = load_rank_metrics(outdir)
    spans.sort(key=lambda s: s.get('t0', 0.0))
    events.sort(key=lambda e: e.get('t', 0.0))
    by_kind = {}
    for s in spans:
        k = by_kind.setdefault(s.get('kind', '?'),
                               {'spans': 0, 'total_s': 0.0})
        k['spans'] += 1
        k['total_s'] += max(s['t1'] - s['t0'], 0.0)
    steps = step_table(spans)
    step_ms = sorted((s['t1'] - s['t0']) * 1e3 for s in spans
                     if s.get('name') == 'jitted_step')
    chaos_events = [e for e in events if e.get('kind') == 'chaos']
    report = {
        'outdir': outdir,
        'ranks': sorted({m.get('rank', 0) for m in metas}
                        | {s.get('rank', 0) for s in spans}),
        'n_spans': len(spans),
        'n_events': len(events),
        'n_unparseable_lines': bad,
        'kinds': {k: {'spans': v['spans'],
                      'total_ms': round(v['total_s'] * 1e3, 3)}
                  for k, v in sorted(by_kind.items())},
        'steps': steps,
        'step_time_ms': ({} if not step_ms else {
            'count': len(step_ms),
            'p50': round(_percentile(step_ms, 0.50), 3),
            'p99': round(_percentile(step_ms, 0.99), 3),
            'mean': round(sum(step_ms) / len(step_ms), 3)}),
        'overlap': overlap_stats(spans),
        'chaos_events': [
            {'t': e['t'], 'rank': e.get('rank', 0),
             'name': e.get('name')} for e in chaos_events],
        'metrics': aggregate_metrics(rank_metrics),
    }
    report['serve'] = serve_summary(report['metrics'])
    report['requests'] = request_summary(spans + events)
    report['pipeline'] = pipeline_summary(events)
    report['input_bound'] = input_bound_stats(steps)
    return report


# ---------------------------------------------------------------------
# rendering + export

def render_text(report, max_steps=24):
    lines = ['telemetry session: %s' % report['outdir'],
             'ranks: %s   spans: %d   events: %d'
             % (report['ranks'], report['n_spans'],
                report['n_events'])]
    for kind, agg in report['kinds'].items():
        lines.append('  %-18s %6d spans  %10.3f ms total'
                     % (kind, agg['spans'], agg['total_ms']))
    if report['steps']:
        lines.append('step timeline (first %d of %d rows):'
                     % (min(max_steps, len(report['steps'])),
                        len(report['steps'])))
        hdr = ('  %6s %4s' % ('iter', 'rank')
               + ''.join(' %16s' % p for p in STEP_PHASES))
        lines.append(hdr)
        for row in report['steps'][:max_steps]:
            cells = ''.join(
                ' %13.3f ms' % row[p + '_ms']
                if p + '_ms' in row else ' %16s' % '-'
                for p in STEP_PHASES)
            lines.append('  %6d %4d%s' % (row['iteration'],
                                          row['rank'], cells))
    st = report.get('step_time_ms') or {}
    if st:
        lines.append('jitted step: %d samples, p50 %.3f ms, '
                     'p99 %.3f ms' % (st['count'], st['p50'],
                                      st['p99']))
    ib = report.get('input_bound')
    if ib is not None:
        if ib['input_bound']:
            lines.append(
                'INPUT-BOUND: rank %d host_batch_prep p50 %.3f ms >= '
                'jitted_step p50 %.3f ms (%.0f%% of the step) -- the '
                'input pipeline, not the device, paces this run; '
                'scale decode workers/prefetch '
                '(docs/data_pipeline.md)'
                % (ib['rank'], ib['host_batch_prep_p50_ms'],
                   ib['jitted_step_p50_ms'],
                   ib['input_fraction'] * 100))
        else:
            lines.append(
                'input: host_batch_prep p50 %.3f ms vs jitted_step '
                'p50 %.3f ms (rank %d, %.0f%% of the step) -- not '
                'input-bound'
                % (ib['host_batch_prep_p50_ms'],
                   ib['jitted_step_p50_ms'], ib['rank'],
                   ib['input_fraction'] * 100))
    ov = report['overlap']
    if ov['overlap_fraction'] is None:
        lines.append('overlap: no collective spans in capture')
    else:
        lines.append(
            'overlap fraction: %.3f  (collective %.3f ms total, '
            '%.3f ms exposed, %.3f ms hidden behind compute)'
            % (ov['overlap_fraction'], ov['total_collective_s'] * 1e3,
               ov['exposed_collective_s'] * 1e3,
               ov['hidden_collective_s'] * 1e3))
        for key, agg in sorted((ov.get('per_axis') or {}).items()):
            frac = agg.get('overlap_fraction')
            lines.append(
                '  axis %-12s %4d spans  %10.3f ms total  '
                '%10.3f ms exposed  overlap %s'
                % (key, agg['spans'],
                   agg['total_collective_s'] * 1e3,
                   agg['exposed_collective_s'] * 1e3,
                   '-' if frac is None else '%.3f' % frac))
    for row in report.get('pipeline') or ():
        # the pipe-axis row of the per-axis story: the schedule's
        # collectives live inside the jit (trace marks, not spans),
        # so its cost is the static bubble, reported per stage
        lines.append(
            'pipeline [%s] %d stage(s) x %d micro-batch(es) over '
            "axis '%s': bubble fraction %.3f per stage "
            '(%s ticks/step; shrink it with more micro-batches)'
            % (row['schedule'], row['n_stages'], row['n_micro'],
               row['axis'], row['bubble_fraction'],
               row.get('total_ticks')))
    serve = report.get('serve')
    if serve:
        lat = serve.get('latency_ms') or {}
        lines.append(
            'serving: %.0f requests in %.0f batches, %.0f shed'
            % (serve['requests'], serve['batches'], serve['shed'])
            + ('; latency p50 %.3f ms p99 %.3f ms'
               % (lat['p50'], lat['p99'])
               if lat.get('p50') is not None else '')
            + ('; pad waste %.1f%%' % (serve['pad_waste_mean'] * 100)
               if serve.get('pad_waste_mean') is not None else ''))
        if serve.get('shed_reasons'):
            lines.append('  shed reasons: ' + ', '.join(
                '%s=%.0f' % (k, v) for k, v
                in sorted(serve['shed_reasons'].items())))
        gen = serve.get('generate')
        if gen:
            ttft = gen.get('ttft_ms') or {}
            itl = gen.get('intertoken_ms') or {}
            lines.append(
                'generation: %.0f tokens / %.0f decode steps'
                % (gen['tokens'], gen['decode_steps'])
                + ('  %.0f tok/s' % gen['tokens_per_s']
                   if gen.get('tokens_per_s') else '')
                + ('; TTFT p50 %.3f ms p99 %.3f ms'
                   % (ttft['p50'], ttft['p99'])
                   if ttft.get('p50') is not None else '')
                + ('; inter-token p50 %.3f ms p99 %.3f ms'
                   % (itl['p50'], itl['p99'])
                   if itl.get('p50') is not None else ''))
    reqs = report.get('requests')
    if reqs:
        e2e = reqs.get('e2e_ms') or {}
        lines.append(
            'request traces: %d (%d completed, %d shed, %d in flight)'
            % (reqs['count'], reqs['completed'], reqs['shed'],
               reqs['in_flight'])
            + ('; e2e p50 %.3f ms p99 %.3f ms'
               % (e2e['p50'], e2e['p99'])
               if e2e.get('p50') is not None else ''))
        worst = reqs.get('worst')
        if worst:
            lines.append(
                '  worst request %s: e2e %.3f ms = %s  '
                '(%d decode ticks; stage sum %.3f ms)'
                % (worst['request_id'], worst['e2e_ms'],
                   ' + '.join(
                       '%s %.3f' % (k, worst['stage_ms'][k])
                       for k in (tuple(REQUEST_STAGES)
                                 + tuple(sorted(
                                     set(worst['stage_ms'])
                                     - set(REQUEST_STAGES))))
                       if k in worst['stage_ms']),
                   worst['n_decode'], worst['stage_sum_ms']))
    if report['chaos_events']:
        lines.append('chaos events in timeline: %d (%s)'
                     % (len(report['chaos_events']),
                        ', '.join(sorted({e['name'] for e in
                                          report['chaos_events']}))))
    for name, snap in report['metrics'].items():
        if snap.get('type') == 'histogram':
            summ = snap.get('summary') or {}
            if summ:
                lines.append(
                    '  metric %-28s n=%-6d p50=%.6g p99=%.6g'
                    % (name, summ['count'], summ['p50'], summ['p99']))
        else:
            lines.append('  metric %-28s %s=%s'
                         % (name, snap.get('type'), snap.get('value')))
    return '\n'.join(lines)


#: one label pair with the exposition-format escaping contract: label
#: values may contain ONLY escaped backslash/quote/newline sequences
#: (``\\``, ``\"``, ``\n``) -- a raw quote or backslash truncates or
#: mangles the sample at scrape time
_PROM_LABEL = r'[a-zA-Z_][a-zA-Z0-9_]*="(?:\\[\\"n]|[^"\\])*"'
_PROM_LINE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(\{(?:%(l)s)(?:,(?:%(l)s))*,?\})? '
    r'[-+]?([0-9]*\.?[0-9]+([eE][-+]?[0-9]+)?|[Nn]a[Nn]|[Ii]nf)$'
    % {'l': _PROM_LABEL})
_PROM_COMMENT = re.compile(
    r'^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]*( .*)?$')


def validate_prometheus(text):
    """Offending lines of a Prometheus text exposition (empty list =
    valid).  Deliberately strict: the CI smoke leg treats ANY
    malformed sample line as a failure -- including a label value
    with an unescaped quote/backslash, which the old looser pattern
    (any non-brace run) waved through."""
    bad = []
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith('#'):
            if (line.startswith(('# HELP', '# TYPE'))
                    and not _PROM_COMMENT.match(line)):
                bad.append(line)
            continue
        if not _PROM_LINE.match(line):
            bad.append(line)
    return bad


def export(outdir, report=None):
    """Write the merged artifacts next to the per-rank logs:
    ``merged_report.json``, ``metrics.json`` (aggregated) and
    ``metrics.prom`` (Prometheus text).  Returns the report."""
    report = report or build_report(outdir)
    with open(os.path.join(outdir, 'merged_report.json'), 'w') as f:
        json.dump(report, f, indent=1)
    with open(os.path.join(outdir, 'metrics.json'), 'w') as f:
        json.dump(report['metrics'], f, indent=1)
    prom = snapshot_to_prometheus(report['metrics'])
    with open(os.path.join(outdir, 'metrics.prom'), 'w') as f:
        f.write(prom)
    return report
