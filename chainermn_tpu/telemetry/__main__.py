"""``python -m chainermn_tpu.telemetry``: merge, report and diagnose
a telemetry capture.

``report DIR`` merges every rank's ``events-rank*.jsonl`` +
``metrics-rank*.json`` under ``DIR`` into one step timeline, prints
it with the overlap fraction, and writes the merged artifacts
(``merged_report.json``, aggregated ``metrics.json``,
``metrics.prom``) back into ``DIR``.

``doctor DIR`` runs the cross-rank diagnosis
(:mod:`chainermn_tpu.telemetry.diagnosis`): collective skew
attribution + chronic-lateness scores, MAD-based straggler/anomaly
flags, and the flight-record + heartbeat crash post-mortem (dead
rank, its last completed collective seq, where survivors were
blocked).  Writes ``doctor_report.json`` into ``DIR``.

Exit codes (both subcommands): 0 on a non-empty capture, 2 when the
directory holds no telemetry at all (CI smoke legs fail loudly on an
accidentally-disabled capture); ``report`` additionally exits 1 on a
malformed Prometheus export (never expected; guards the exporter).
A missing or unknown subcommand prints usage and exits 2 -- CI
misuse must never look like success.
"""

import argparse
import sys


def _build_parser():
    parser = argparse.ArgumentParser(
        prog='python -m chainermn_tpu.telemetry',
        description='merge per-rank telemetry logs into a step '
                    'timeline with overlap fraction and metrics '
                    'exports, or diagnose a multi-rank capture')
    sub = parser.add_subparsers(dest='cmd')
    rep = sub.add_parser('report', help='merge + report one session '
                                        'directory')
    rep.add_argument('outdir', help='telemetry session directory '
                                    '(the CHAINERMN_TPU_TELEMETRY '
                                    'value of the run)')
    rep.add_argument('--json', action='store_true',
                     help='print the merged report as JSON instead '
                          'of text')
    rep.add_argument('--max-steps', '--steps', type=int, default=24,
                     dest='max_steps', metavar='N',
                     help='max step-timeline rows to print '
                          '(default: %(default)s)')
    rep.add_argument('--no-export', action='store_true',
                     help='print only; do not write merged_report/'
                          'metrics.json/metrics.prom into the '
                          'session dir')
    doc = sub.add_parser('doctor', help='cross-rank diagnosis: '
                                        'collective skew, stragglers, '
                                        'crash post-mortem')
    doc.add_argument('outdir', help='telemetry session directory')
    doc.add_argument('--json', action='store_true',
                     help='print the diagnosis as JSON instead of '
                          'text')
    doc.add_argument('--liveness', action='append', default=[],
                     metavar='DIR',
                     help='extra heartbeat directory to consult '
                          '(repeatable; liveness dirs recorded in '
                          'the capture are found automatically)')
    doc.add_argument('--no-export', action='store_true',
                     help='print only; do not write '
                          'doctor_report.json into the session dir')
    return parser


def _cmd_report(args):
    from chainermn_tpu.telemetry import report as report_mod
    from chainermn_tpu.telemetry.recorder import snapshot_to_prometheus

    report = report_mod.build_report(args.outdir)
    if not args.no_export:
        report_mod.export(args.outdir, report)
    if args.json:
        import json
        print(json.dumps(report, indent=1))
    else:
        print(report_mod.render_text(report,
                                     max_steps=args.max_steps))
    if (report['n_spans'] + report['n_events'] == 0
            and not report.get('serve')):
        # a serving capture may legitimately hold only serve_*
        # metrics (the engine's in-memory window exports histograms,
        # no event log) -- that is a real capture, not an empty one
        print('telemetry: EMPTY capture under %s (was '
              'CHAINERMN_TPU_TELEMETRY set, and did the run flush?)'
              % args.outdir, file=sys.stderr)
        return 2
    bad = report_mod.validate_prometheus(
        snapshot_to_prometheus(report['metrics']))
    if bad:
        print('telemetry: malformed Prometheus line(s): %r' % bad[:5],
              file=sys.stderr)
        return 1
    return 0


def _cmd_doctor(args):
    from chainermn_tpu.telemetry import diagnosis

    diag = diagnosis.diagnose(args.outdir,
                              liveness_dirs=args.liveness)
    if not args.no_export:
        diagnosis.export(args.outdir, diag)
    if args.json:
        import json
        print(json.dumps(diag, indent=1, default=repr))
    else:
        print(diagnosis.render_doctor_text(diag))
    if (diag['n_spans'] + diag['n_events']
            + diag['n_flight_records'] == 0
            and not diag.get('serve')):
        # serve-metrics-only captures are non-empty (see _cmd_report)
        print('telemetry doctor: EMPTY capture under %s (was '
              'CHAINERMN_TPU_TELEMETRY set, and did the run flush?)'
              % args.outdir, file=sys.stderr)
        return 2
    return 0


def main(argv=None):
    parser = _build_parser()
    try:
        args = parser.parse_args(argv)
    except SystemExit as e:
        # argparse already printed usage + error; normalize the exit
        # status to a nonzero return so programmatic callers (and CI
        # pipelines capturing $?) see failure, never a traceback
        return e.code if e.code else 0
    if args.cmd is None:
        parser.print_usage(sys.stderr)
        print('%s: error: a subcommand is required (report | doctor)'
              % parser.prog, file=sys.stderr)
        return 2
    if args.cmd == 'report':
        return _cmd_report(args)
    return _cmd_doctor(args)


if __name__ == '__main__':
    sys.exit(main())
