"""``python -m chainermn_tpu.telemetry``: merge, report, diagnose
and SLO-judge a telemetry capture.

``report DIR`` merges every rank's ``events-rank*.jsonl`` +
``metrics-rank*.json`` under ``DIR`` into one step timeline, prints
it with the overlap fraction (plus the per-request trace summary and
the worst request's stage decomposition when the capture holds
``kind='request'`` records), and writes the merged artifacts
(``merged_report.json``, aggregated ``metrics.json``,
``metrics.prom``) back into ``DIR``.  ``--request ID`` prints ONE
request's reconstructed stage-by-stage timeline instead.

``doctor DIR`` runs the cross-rank diagnosis
(:mod:`chainermn_tpu.telemetry.diagnosis`): collective skew
attribution + chronic-lateness scores, MAD-based straggler/anomaly
flags, and the flight-record + heartbeat crash post-mortem (dead
rank, its last completed collective seq, where survivors were
blocked).  Writes ``doctor_report.json`` into ``DIR``.

``slo DIR`` replays the capture's request traces through the
sliding-window SLO monitor (:mod:`chainermn_tpu.telemetry.slo`) and
prints the multi-window burn-rate verdict (``ok``/``warn``/
``breach`` per SLO + overall) as of the capture's last instant --
deterministic, and byte-identical to what the live monitor's
``slo_snapshot.json`` would have said then.  Writes
``slo_report.json`` into ``DIR``; targets are overridable per
invocation (``--ttft-ms`` etc.).

``goodput OUT`` joins a supervisor run's ledger with every attempt's
telemetry capture (:mod:`chainermn_tpu.telemetry.goodput`) and
decomposes the wall clock into useful-step / bubble / exposed-
collective / checkpoint / input-bound / restart-downtime / other --
disjoint buckets that sum to the wall exactly -- then prints
``goodput_fraction`` and writes ``goodput_report.json`` into the run
dir.  ``--floor F`` makes it a CI gate (exit 1 below the floor).

Exit codes (all subcommands): 0 on a non-empty capture, 2 when the
directory holds no telemetry at all (CI smoke legs fail loudly on an
accidentally-disabled capture); ``report`` additionally exits 1 on a
malformed Prometheus export (never expected; guards the exporter)
and on an unknown ``--request`` id; ``goodput`` exits 1 below its
``--floor``.  A missing or unknown subcommand prints usage and exits
2 -- CI misuse must never look like success.
"""

import argparse
import sys


def _build_parser():
    parser = argparse.ArgumentParser(
        prog='python -m chainermn_tpu.telemetry',
        description='merge per-rank telemetry logs into a step '
                    'timeline with overlap fraction and metrics '
                    'exports, or diagnose a multi-rank capture')
    sub = parser.add_subparsers(dest='cmd')
    rep = sub.add_parser('report', help='merge + report one session '
                                        'directory')
    rep.add_argument('outdir', help='telemetry session directory '
                                    '(the CHAINERMN_TPU_TELEMETRY '
                                    'value of the run)')
    rep.add_argument('--json', action='store_true',
                     help='print the merged report as JSON instead '
                          'of text')
    rep.add_argument('--max-steps', '--steps', type=int, default=24,
                     dest='max_steps', metavar='N',
                     help='max step-timeline rows to print '
                          '(default: %(default)s)')
    rep.add_argument('--no-export', action='store_true',
                     help='print only; do not write merged_report/'
                          'metrics.json/metrics.prom into the '
                          'session dir')
    rep.add_argument('--request', metavar='ID', default=None,
                     help='print ONE request\'s reconstructed '
                          'stage-by-stage timeline (a request_id '
                          'from the capture, e.g. r42) instead of '
                          'the merged report')
    doc = sub.add_parser('doctor', help='cross-rank diagnosis: '
                                        'collective skew, stragglers, '
                                        'crash post-mortem')
    doc.add_argument('outdir', help='telemetry session directory')
    doc.add_argument('--json', action='store_true',
                     help='print the diagnosis as JSON instead of '
                          'text')
    doc.add_argument('--liveness', action='append', default=[],
                     metavar='DIR',
                     help='extra heartbeat directory to consult '
                          '(repeatable; liveness dirs recorded in '
                          'the capture are found automatically)')
    doc.add_argument('--no-export', action='store_true',
                     help='print only; do not write '
                          'doctor_report.json into the session dir')
    good = sub.add_parser(
        'goodput', help='decompose a run\'s wall clock into useful-'
                        'step / bubble / exposed-collective / '
                        'checkpoint / input-bound / restart-downtime '
                        'and print the goodput fraction')
    good.add_argument('outdir',
                      help='supervisor out dir (supervisor_ledger.'
                           'jsonl + telemetry/a* attempt captures) '
                           'or one telemetry session directory')
    good.add_argument('--json', action='store_true',
                      help='print the goodput report as JSON instead '
                           'of text')
    good.add_argument('--no-export', action='store_true',
                      help='print only; do not write '
                           'goodput_report.json into the run dir')
    good.add_argument('--floor', type=float, default=None,
                      metavar='F',
                      help='exit 1 when goodput_fraction < F (CI '
                           'chaos legs pin their floor here)')
    slo = sub.add_parser('slo', help='sliding-window SLO verdict '
                                     '(ok/warn/breach) over the '
                                     'capture\'s request traces')
    slo.add_argument('outdir', help='telemetry session directory')
    slo.add_argument('--json', action='store_true',
                     help='print the verdict as JSON instead of text')
    slo.add_argument('--no-export', action='store_true',
                     help='print only; do not write slo_report.json '
                          'into the session dir')
    slo.add_argument('--ttft-ms', type=float, default=1000.0,
                     metavar='MS',
                     help='TTFT latency target (default: '
                          '%(default)s ms)')
    slo.add_argument('--intertoken-ms', type=float, default=250.0,
                     metavar='MS',
                     help='inter-token latency target (default: '
                          '%(default)s ms)')
    slo.add_argument('--objective', type=float, default=0.99,
                     help='good-event fraction for the latency SLOs '
                          '(default: %(default)s)')
    slo.add_argument('--shed-fraction', type=float, default=0.05,
                     help='shed-fraction budget (default: '
                          '%(default)s)')
    slo.add_argument('--occupancy', type=float, default=0.98,
                     help='slot-occupancy warn ceiling (default: '
                          '%(default)s)')
    slo.add_argument('--tokens-per-s', type=float, default=None,
                     help='optional minimum generated tokens/s '
                          '(omitted: no throughput SLO)')
    slo.add_argument('--fast-window', type=float, default=None,
                     metavar='S', help='fast burn window, seconds '
                                       '(default: 30)')
    slo.add_argument('--slow-window', type=float, default=None,
                     metavar='S', help='slow burn window, seconds '
                                       '(default: 150)')
    return parser


def _cmd_report(args):
    from chainermn_tpu.telemetry import report as report_mod
    from chainermn_tpu.telemetry.recorder import snapshot_to_prometheus

    if getattr(args, 'request', None):
        _metas, spans, events, _bad = report_mod.load_rank_logs(
            args.outdir)
        traces = report_mod.request_traces(spans + events)
        trace = traces.get(str(args.request))
        if trace is None:
            print('telemetry: no request %r in %s (known: %s)'
                  % (args.request, args.outdir,
                     ', '.join(sorted(traces)[:12]) or 'none'),
                  file=sys.stderr)
            return 1
        if args.json:
            import json
            print(json.dumps(trace, indent=1, default=repr))
        else:
            print(report_mod.render_request_text(trace))
        return 0

    report = report_mod.build_report(args.outdir)
    if not args.no_export:
        report_mod.export(args.outdir, report)
    if args.json:
        import json
        print(json.dumps(report, indent=1))
    else:
        print(report_mod.render_text(report,
                                     max_steps=args.max_steps))
    if (report['n_spans'] + report['n_events'] == 0
            and not report.get('serve')):
        # a serving capture may legitimately hold only serve_*
        # metrics (the engine's in-memory window exports histograms,
        # no event log) -- that is a real capture, not an empty one
        print('telemetry: EMPTY capture under %s (was '
              'CHAINERMN_TPU_TELEMETRY set, and did the run flush?)'
              % args.outdir, file=sys.stderr)
        return 2
    bad = report_mod.validate_prometheus(
        snapshot_to_prometheus(report['metrics']))
    if bad:
        print('telemetry: malformed Prometheus line(s): %r' % bad[:5],
              file=sys.stderr)
        return 1
    return 0


def _cmd_doctor(args):
    from chainermn_tpu.telemetry import diagnosis

    diag = diagnosis.diagnose(args.outdir,
                              liveness_dirs=args.liveness)
    if not args.no_export:
        diagnosis.export(args.outdir, diag)
    if args.json:
        import json
        print(json.dumps(diag, indent=1, default=repr))
    else:
        print(diagnosis.render_doctor_text(diag))
    if (diag['n_spans'] + diag['n_events']
            + diag['n_flight_records'] == 0
            and not diag.get('serve')):
        # serve-metrics-only captures are non-empty (see _cmd_report)
        print('telemetry doctor: EMPTY capture under %s (was '
              'CHAINERMN_TPU_TELEMETRY set, and did the run flush?)'
              % args.outdir, file=sys.stderr)
        return 2
    return 0


def _cmd_goodput(args):
    from chainermn_tpu.telemetry import goodput as goodput_mod

    gp = goodput_mod.build_goodput(args.outdir)
    if gp.get('wall_s') is not None and not args.no_export:
        goodput_mod.export(args.outdir, gp)
    if args.json:
        import json
        print(json.dumps(gp, indent=1))
    else:
        print(goodput_mod.render_text(gp))
    if gp.get('wall_s') is None:
        print('telemetry goodput: EMPTY capture under %s (was '
              'CHAINERMN_TPU_TELEMETRY set, and did the run flush?)'
              % args.outdir, file=sys.stderr)
        return 2
    if (args.floor is not None
            and gp['goodput_fraction'] < args.floor):
        print('telemetry goodput: fraction %.4f BELOW floor %.4f'
              % (gp['goodput_fraction'], args.floor),
              file=sys.stderr)
        return 1
    return 0


def _cmd_slo(args):
    from chainermn_tpu.telemetry import slo as slo_mod

    windows = {}
    if args.fast_window is not None:
        windows['fast_window_s'] = args.fast_window
    if args.slow_window is not None:
        windows['slow_window_s'] = args.slow_window
    slos = slo_mod.default_slos(
        ttft_s=args.ttft_ms / 1e3,
        intertoken_s=args.intertoken_ms / 1e3,
        objective=args.objective,
        max_shed_fraction=args.shed_fraction,
        max_occupancy=args.occupancy,
        min_tokens_per_s=args.tokens_per_s, **windows)
    result = slo_mod.evaluate_capture(args.outdir, slos=slos)
    if not args.no_export:
        slo_mod.export(args.outdir, result)
    if args.json:
        import json
        print(json.dumps(result, indent=1, default=repr))
    else:
        print(slo_mod.render_slo_text(result))
    if result['n_request_records'] == 0:
        print('telemetry slo: no request traces or serve spans under '
              '%s (was CHAINERMN_TPU_TELEMETRY set during the serve '
              'window, and did the run flush?)' % args.outdir,
              file=sys.stderr)
        return 2
    return 0


def main(argv=None):
    parser = _build_parser()
    try:
        args = parser.parse_args(argv)
    except SystemExit as e:
        # argparse already printed usage + error; normalize the exit
        # status to a nonzero return so programmatic callers (and CI
        # pipelines capturing $?) see failure, never a traceback
        return e.code if e.code else 0
    if args.cmd is None:
        parser.print_usage(sys.stderr)
        print('%s: error: a subcommand is required (report | doctor '
              '| slo | goodput)' % parser.prog, file=sys.stderr)
        return 2
    import os
    if not os.path.isdir(args.outdir):
        # a missing capture directory is the empty-capture case, not
        # a traceback: every subcommand would otherwise crash trying
        # to write its export next to logs that do not exist
        print('telemetry %s: no session directory at %s (was '
              'CHAINERMN_TPU_TELEMETRY set, and did the run flush?)'
              % (args.cmd, args.outdir), file=sys.stderr)
        return 2
    if args.cmd == 'report':
        return _cmd_report(args)
    if args.cmd == 'slo':
        return _cmd_slo(args)
    if args.cmd == 'goodput':
        return _cmd_goodput(args)
    return _cmd_doctor(args)


if __name__ == '__main__':
    sys.exit(main())
