"""``python -m chainermn_tpu.telemetry``: merge and report a
telemetry capture.

``report DIR`` merges every rank's ``events-rank*.jsonl`` +
``metrics-rank*.json`` under ``DIR`` into one step timeline, prints
it with the overlap fraction, and writes the merged artifacts
(``merged_report.json``, aggregated ``metrics.json``,
``metrics.prom``) back into ``DIR``.  Exit codes: 0 on a non-empty
timeline, 2 when the directory holds no telemetry events (so CI
smoke legs fail loudly on an accidentally-disabled capture), 1 on a
malformed Prometheus export (never expected; guards the exporter).
"""

import argparse
import sys


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog='python -m chainermn_tpu.telemetry',
        description='merge per-rank telemetry logs into a step '
                    'timeline with overlap fraction and metrics '
                    'exports')
    sub = parser.add_subparsers(dest='cmd', required=True)
    rep = sub.add_parser('report', help='merge + report one session '
                                        'directory')
    rep.add_argument('outdir', help='telemetry session directory '
                                    '(the CHAINERMN_TPU_TELEMETRY '
                                    'value of the run)')
    rep.add_argument('--json', action='store_true',
                     help='print the merged report as JSON instead '
                          'of text')
    rep.add_argument('--steps', type=int, default=24,
                     help='max step-timeline rows to print')
    rep.add_argument('--no-export', action='store_true',
                     help='print only; do not write merged_report/'
                          'metrics.json/metrics.prom into the '
                          'session dir')
    args = parser.parse_args(argv)

    from chainermn_tpu.telemetry import report as report_mod
    from chainermn_tpu.telemetry.recorder import snapshot_to_prometheus

    report = report_mod.build_report(args.outdir)
    if not args.no_export:
        report_mod.export(args.outdir, report)
    if args.json:
        import json
        print(json.dumps(report, indent=1))
    else:
        print(report_mod.render_text(report, max_steps=args.steps))
    if report['n_spans'] + report['n_events'] == 0:
        print('telemetry: EMPTY capture under %s (was '
              'CHAINERMN_TPU_TELEMETRY set, and did the run flush?)'
              % args.outdir, file=sys.stderr)
        return 2
    bad = report_mod.validate_prometheus(
        snapshot_to_prometheus(report['metrics']))
    if bad:
        print('telemetry: malformed Prometheus line(s): %r' % bad[:5],
              file=sys.stderr)
        return 1
    return 0


if __name__ == '__main__':
    sys.exit(main())
