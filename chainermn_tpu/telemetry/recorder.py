"""Span/event recorder and metrics registry for runtime telemetry.

Design constraints (mirroring :mod:`chainermn_tpu.utils.chaos`, the
other env-activated runtime layer):

- **Zero cost when off.**  Nothing in this module runs on a
  telemetry-free hot path; call sites guard on the package-level
  ``telemetry._active is None`` (one attribute load + identity check)
  or go through :func:`chainermn_tpu.telemetry.span`, whose off path
  returns a preallocated no-op context.
- **Monotonic spans, wall-aligned at record time.**  Durations come
  from ``time.perf_counter()`` (immune to NTP steps); every recorded
  timestamp is expressed on the wall clock via a per-recorder anchor
  pair captured at construction, so per-rank logs from one machine
  (the CPU multi-controller harness) merge into one timeline without
  post-hoc skew fitting.
- **Optional device-sync fences.**  A span wrapping device work
  measures DISPATCH unless the telemetry session requests fences
  (``CHAINERMN_TPU_TELEMETRY_SYNC=1``): then ``span.sync(out)``
  blocks on the device values before the span closes and the span is
  tagged ``synced=True``.  Fences serialize the device -- they are a
  measurement mode, not a default.

Event-log schema (JSONL, one file per rank, first line is ``meta``)::

    {"type": "meta", "rank": 0, "pid": 123, "wall0": ..., "argv": ...}
    {"type": "span", "name": "jitted_step", "kind": "compute",
     "t0": <wall s>, "t1": <wall s>, "rank": 0, ...attrs}
    {"type": "event", "name": "chaos:drop_send", "kind": "chaos",
     "t": <wall s>, "rank": 0, ...attrs}

``kind`` is the timeline vocabulary the overlap computation consumes:
``compute`` (the jitted step), ``collective`` (eager collectives /
bounded rendezvous), ``p2p`` (eager object channel), ``host`` (batch
collation), ``h2d`` (host-to-device placement), ``checkpoint``,
``chaos``, and ``collective_trace`` (trace-time collective-issue
marks -- they fire once per compilation, not per step).
"""

import collections
import contextlib
import json
import os
import sys
import threading
import time

#: histogram sample retention cap -- long trainings must not grow
#: memory without bound; percentile accuracy over the newest samples
#: is what the exporters need
MAX_SAMPLES = 65536
#: event-log retention cap per rank (a week-long run with telemetry
#: left on must not OOM the host; the newest window wins)
MAX_EVENTS = 1 << 20
#: flight-recorder ring size -- the last N records a crash dump
#: preserves (`Recorder.dump_flight`); small on purpose: the flight
#: record is the black box read AFTER a death, not the full log
FLIGHT_RING = 256


def _percentile(sorted_vals, q):
    """Nearest-rank percentile of an ascending list (the convention
    ``StepTimer.summary`` always used)."""
    if not sorted_vals:
        return None
    n = len(sorted_vals)
    return sorted_vals[min(n - 1, int(n * q))]


class Counter:
    """Monotonically increasing count (Prometheus ``counter``)."""

    kind = 'counter'

    def __init__(self, name, help=''):
        self.name = name
        self.help = help
        self.value = 0.0

    def inc(self, n=1.0):
        self.value += n

    def snapshot(self):
        snap = {'type': 'counter', 'value': self.value}
        if self.help:
            snap['help'] = self.help
        return snap


class Gauge:
    """Last-written value (Prometheus ``gauge``)."""

    kind = 'gauge'

    def __init__(self, name, help=''):
        self.name = name
        self.help = help
        self.value = None

    def set(self, v):
        self.value = float(v)

    def snapshot(self):
        snap = {'type': 'gauge', 'value': self.value}
        if self.help:
            snap['help'] = self.help
        return snap


class Histogram:
    """Sample-retaining distribution with p50/p99 summaries.

    Retains raw samples (newest :data:`MAX_SAMPLES`) so per-rank
    snapshots can be MERGED exactly -- aggregated percentiles are
    recomputed from the union of samples, not averaged from per-rank
    percentiles (which would be wrong for skewed step times).
    """

    kind = 'histogram'

    def __init__(self, name, help=''):
        self.name = name
        self.help = help
        self.samples = []
        self.count = 0
        self.total = 0.0

    def observe(self, v):
        v = float(v)
        self.count += 1
        self.total += v
        self.samples.append(v)
        if len(self.samples) > MAX_SAMPLES:
            del self.samples[:len(self.samples) - MAX_SAMPLES]

    def summary(self):
        s = sorted(self.samples)
        if not s:
            return {'count': 0, 'sum': 0.0}
        return {
            'count': self.count,
            'sum': self.total,
            'min': s[0],
            'max': s[-1],
            'mean': sum(s) / len(s),
            'p50': _percentile(s, 0.50),
            'p90': _percentile(s, 0.90),
            'p99': _percentile(s, 0.99),
        }

    def snapshot(self):
        snap = {'type': 'histogram', 'count': self.count,
                'sum': self.total, 'samples': list(self.samples),
                'summary': self.summary()}
        if self.help:
            snap['help'] = self.help
        return snap


class Registry:
    """Named metrics, one instance per recorder (plus standalone use
    by :class:`~chainermn_tpu.utils.profiling.StepTimer` when
    telemetry is off)."""

    def __init__(self):
        self._metrics = {}
        self._lock = threading.Lock()

    def _get(self, cls, name, help):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help)
            elif not isinstance(m, cls):
                raise TypeError(
                    'metric %r already registered as %s, requested %s'
                    % (name, type(m).__name__, cls.__name__))
            return m

    def counter(self, name, help=''):
        return self._get(Counter, name, help)

    def gauge(self, name, help=''):
        return self._get(Gauge, name, help)

    def histogram(self, name, help=''):
        return self._get(Histogram, name, help)

    def names(self):
        return sorted(self._metrics)

    def snapshot(self):
        return {name: m.snapshot()
                for name, m in sorted(self._metrics.items())}

    def to_prometheus(self, prefix='chainermn_tpu_'):
        """Prometheus text exposition (0.0.4).  Histograms export as
        summaries: ``<name>{quantile="0.5"}``, ``_count``, ``_sum``.
        """
        return snapshot_to_prometheus(self.snapshot(), prefix=prefix)


def _prom_name(prefix, name):
    out = []
    for ch in prefix + name:
        out.append(ch if (ch.isalnum() and ch.isascii()) or ch in '_:'
                   else '_')
    head = out[0] if out else '_'
    if not (head.isalpha() or head in '_:'):
        out.insert(0, '_')
    return ''.join(out)


def escape_label_value(value):
    """Prometheus label-value escaping (text exposition 0.0.4):
    backslash, double-quote and newline must be escaped or the scrape
    silently truncates/mangles the sample."""
    return (str(value).replace('\\', r'\\').replace('"', r'\"')
            .replace('\n', r'\n'))


def escape_help(text):
    """``# HELP`` line escaping: backslash and newline only (quotes
    are legal in help text)."""
    return str(text).replace('\\', r'\\').replace('\n', r'\n')


def _labels_text(labels):
    if not labels:
        return ''
    return '{%s}' % ','.join(
        '%s="%s"' % (k, escape_label_value(v))
        for k, v in sorted(labels.items()))


def snapshot_to_prometheus(snapshot, prefix='chainermn_tpu_'):
    """Render a (possibly merged) registry snapshot as Prometheus
    text.  Shared by the live registry and the offline aggregator in
    :mod:`chainermn_tpu.telemetry.report`.

    Emits ``# HELP`` (escaped) alongside ``# TYPE`` when the metric
    carries help text, and escapes every label value (``\\``, ``"``,
    newline) -- a snapshot's optional ``labels`` dict is rendered on
    counter/gauge sample lines."""
    lines = []
    for name, snap in sorted(snapshot.items()):
        pname = _prom_name(prefix, name)
        kind = snap.get('type')
        help_text = snap.get('help')
        if kind in ('counter', 'gauge'):
            v = snap.get('value')
            if v is None:
                continue
            if help_text:
                lines.append('# HELP %s %s'
                             % (pname, escape_help(help_text)))
            lines.append('# TYPE %s %s' % (pname, kind))
            lines.append('%s%s %s' % (pname,
                                      _labels_text(snap.get('labels')),
                                      repr(float(v))))
        elif kind == 'histogram':
            summ = snap.get('summary') or {}
            if help_text:
                lines.append('# HELP %s %s'
                             % (pname, escape_help(help_text)))
            lines.append('# TYPE %s summary' % pname)
            for q in ('p50', 'p90', 'p99'):
                if summ.get(q) is not None:
                    lines.append('%s{quantile="0.%s"} %s'
                                 % (pname, q[1:], repr(summ[q])))
            lines.append('%s_count %s'
                         % (pname, repr(float(snap.get('count', 0)))))
            lines.append('%s_sum %s'
                         % (pname, repr(float(snap.get('sum', 0.0)))))
    return '\n'.join(lines) + '\n' if lines else ''


class _SpanHandle:
    """What ``with recorder.span(...) as sp`` yields: lets the caller
    attach attributes discovered mid-span and request the device-sync
    fence."""

    __slots__ = ('_recorder', 'attrs', 'synced')

    def __init__(self, recorder, attrs):
        self._recorder = recorder
        self.attrs = attrs
        self.synced = False

    def set(self, **attrs):
        self.attrs.update(attrs)

    def sync(self, value):
        """Block on device values before the span closes -- only when
        the telemetry session requested fences; otherwise a no-op, so
        call sites need no conditional."""
        if self._recorder.sync_fences and value is not None:
            import jax
            jax.block_until_ready(value)
            self.synced = True
        return value


class _NullSpan:
    """Preallocated no-op context for the disabled path."""

    __slots__ = ()
    attrs = None
    synced = False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        pass

    def sync(self, value):
        return value


NULL_SPAN = _NullSpan()


class Recorder:
    """One process's telemetry session: spans, events, metrics, and
    the per-rank JSONL/JSON flush."""

    def __init__(self, outdir=None, sync_fences=False,
                 flight_ring=FLIGHT_RING):
        self.outdir = outdir
        self.sync_fences = bool(sync_fences)
        self.registry = Registry()
        self.events = []
        self._lock = threading.Lock()
        # wall-clock anchor: every recorded time is
        # wall0 + (perf_counter() - mono0)
        self._mono0 = time.perf_counter()
        self._wall0 = time.time()
        self._flushed_upto = 0
        self._meta_written = False
        # flight recorder: the last N records, cheap to maintain and
        # small enough to dump atomically from a dying process
        self._flight = collections.deque(maxlen=flight_ring)
        # spans currently OPEN (entered, not yet exited) -- the dump
        # includes them so "where was this rank blocked" is answerable
        # even though unclosed spans never reach the event log
        self._open_spans = {}
        # newest closed collective span (and p2p separately) -- the
        # "last completed collective seq" a post-mortem names
        self._last_collective = None
        self._last_p2p = None
        #: liveness directory handed off by
        #: ``CommunicatorBase.enable_peer_liveness`` so the doctor can
        #: find the heartbeat files that pair with this capture
        self.liveness_dir = None
        self.flight_dumps = 0
        #: streaming record consumers (the live SLO monitor,
        #: :class:`chainermn_tpu.telemetry.slo.SLOMonitor`): called
        #: with every appended record OUTSIDE the recorder lock.  The
        #: empty-list check is the only hot-path cost when nothing is
        #: attached -- and none of this runs at all when telemetry is
        #: off (the zero-cost-off contract lives at the call sites).
        self._listeners = []
        #: named zero-arg callables whose return value is embedded in
        #: every flight dump -- components register LIVE state tables
        #: here (the generation engine's in-flight request table), so
        #: a crash mid-generation names which requests died where
        self.flight_sources = {}

    # -- clock ---------------------------------------------------------
    def now(self):
        return self._wall0 + (time.perf_counter() - self._mono0)

    # -- recording -----------------------------------------------------
    def _append(self, rec):
        with self._lock:
            self.events.append(rec)
            self._flight.append(rec)
            kind = rec.get('kind')
            if kind == 'collective':
                self._last_collective = rec
            elif kind == 'p2p':
                self._last_p2p = rec
            if len(self.events) > MAX_EVENTS:
                # drop the oldest UNFLUSHED window is wrong -- flushed
                # records are already on disk, so trim from the front
                # and move the flush cursor with it
                drop = len(self.events) - MAX_EVENTS
                del self.events[:drop]
                self._flushed_upto = max(0, self._flushed_upto - drop)
        if self._listeners:
            # outside the lock: a listener that re-enters the recorder
            # (or blocks) must not deadlock or stall span close paths
            for fn in list(self._listeners):
                try:
                    fn(rec)
                except Exception:
                    pass  # a broken consumer never breaks recording

    def add_listener(self, fn):
        """Register a streaming record consumer (called with every
        appended span/event record, after it is recorded)."""
        if fn not in self._listeners:
            self._listeners.append(fn)

    def remove_listener(self, fn):
        try:
            self._listeners.remove(fn)
        except ValueError:
            pass

    @contextlib.contextmanager
    def span(self, name, kind='generic', **attrs):
        handle = _SpanHandle(self, attrs)
        t0 = self.now()
        # `attrs` is the handle's LIVE dict: attributes set mid-span
        # (sp.set(...)) are visible in a flight dump of the open span.
        # Lock-free on purpose (id-keyed dict set/del are GIL-atomic):
        # this sits on the enabled hot path the <2% overhead pin
        # bounds; dump_flight tolerates a transiently-inconsistent
        # view
        self._open_spans[id(handle)] = {'name': name, 'kind': kind,
                                        't0': t0, 'attrs': attrs}
        try:
            yield handle
        finally:
            self._open_spans.pop(id(handle), None)
            rec = {'type': 'span', 'name': name, 'kind': kind,
                   't0': t0, 't1': self.now()}
            if handle.synced:
                rec['synced'] = True
            if handle.attrs:
                rec.update(handle.attrs)
            self._append(rec)

    def event(self, name, kind='event', **attrs):
        rec = {'type': 'event', 'name': name, 'kind': kind,
               't': self.now()}
        if attrs:
            rec.update(attrs)
        self._append(rec)

    def child_span(self, request_id, name, t0, t1=None, kind='request',
                   **attrs):
        """Record one already-timed child span of a request trace --
        the per-request tracing primitive the serving path uses.

        Cheaper than :meth:`span` on purpose (one dict + append, no
        context manager, no open-span registry entry): the decode
        scheduler records one of these per live slot per tick.  The
        caller supplies ``t0`` (and optionally ``t1``) on THIS
        recorder's clock (:meth:`now`), which is what lets stage spans
        tile a request's timeline exactly -- each stage starts where
        the previous one ended, so the per-stage budgets telescope to
        the end-to-end latency with no gaps to fabricate."""
        rec = {'type': 'span', 'name': name, 'kind': kind,
               'request_id': request_id, 't0': t0,
               't1': self.now() if t1 is None else t1}
        if attrs:
            rec.update(attrs)
        self._append(rec)

    # -- flush ---------------------------------------------------------
    def _rank(self):
        try:
            import jax
            return int(jax.process_index())
        except Exception:
            return 0

    def flush(self, outdir=None, blocking=True):
        """Append unwritten events to ``events-rank<N>.jsonl`` and
        rewrite ``metrics-rank<N>.json`` under the session directory.
        Idempotent and incremental; safe to call repeatedly (the
        enable path registers it atexit).

        ``blocking=False`` is the signal-handler mode: CPython runs
        handlers between bytecodes of the interrupted thread, so if
        that thread holds ``_lock`` (it is taken on every span/event
        close), a blocking acquire here would self-deadlock.  When the
        lock is unavailable the flush is SKIPPED (returns None) rather
        than risking a duplicate window; the next boundary flush picks
        the pending events up."""
        outdir = outdir or self.outdir
        if outdir is None:
            return None
        os.makedirs(outdir, exist_ok=True)
        rank = self._rank()
        epath = os.path.join(outdir, 'events-rank%d.jsonl' % rank)
        if not self._lock.acquire(blocking=blocking):
            return None
        try:
            pending = self.events[self._flushed_upto:]
            self._flushed_upto = len(self.events)
        finally:
            self._lock.release()
        with open(epath, 'a') as f:
            if not self._meta_written:
                f.write(json.dumps({
                    'type': 'meta', 'rank': rank, 'pid': os.getpid(),
                    'wall0': self._wall0,
                    'sync_fences': self.sync_fences,
                    'argv': list(sys.argv)}) + '\n')
                self._meta_written = True
            for rec in pending:
                f.write(json.dumps(dict(rec, rank=rank)) + '\n')
        mpath = os.path.join(outdir, 'metrics-rank%d.json' % rank)
        tmp = mpath + '.tmp'
        with open(tmp, 'w') as f:
            json.dump({'rank': rank,
                       'metrics': self.registry.snapshot()}, f)
        os.replace(tmp, mpath)
        return epath

    def dump_flight(self, reason, outdir=None, blocking=True, **attrs):
        """Crash-safe black-box dump: atomically (tmp + rename, with
        the serializers' write-complete sentinel convention) write
        ``flight-rank<N>.json`` holding the last :data:`FLIGHT_RING`
        records, every OPEN span (where this rank is blocked right
        now), the newest completed collective/p2p span, and the
        caller's ``reason``/attrs.  The event log is flushed first so
        the JSONL tail is as current as the flight record.

        Called from the places a process dies or detects death: chaos
        kill sites before ``os._exit``, the typed-failure
        constructors (``ChannelTimeout`` / ``PeerDeadError`` /
        ``CheckpointCorruptError``), and the preemption SIGTERM hook.
        Latest dump wins (one file per rank); ``n_dumps`` counts how
        many this process wrote.  Best-effort by contract: returns
        the path or None, never raises.

        ``blocking=False`` is REQUIRED from signal handlers: the
        recorder lock is non-reentrant and taken by the interrupted
        thread on every span close, so blocking on it from a handler
        self-deadlocks the process.  When the lock cannot be acquired
        the dump degrades -- the incremental flush is skipped and the
        ring is snapshotted lock-free (consistent when the holder is
        the interrupted frame of this same thread; a cross-thread
        mid-mutation copy is retried, then dropped) -- and the record
        carries ``degraded: true``."""
        outdir = outdir or self.outdir
        if outdir is None:
            return None
        try:
            try:
                self.flush(outdir, blocking=blocking)
            except Exception:
                pass  # the flight record must still be attempted
            rank = self._rank()
            locked = self._lock.acquire(blocking=blocking)
            try:
                ring = []
                for _ in range(3):
                    try:
                        ring = list(self._flight)
                        break
                    except RuntimeError:
                        # deque mutated mid-copy: only possible on the
                        # lock-free path with a concurrent appender
                        continue
                last_coll = (dict(self._last_collective)
                             if self._last_collective else None)
                last_p2p = (dict(self._last_p2p)
                            if self._last_p2p else None)
            finally:
                if locked:
                    self._lock.release()
            open_spans = [
                dict({k: v for k, v in rec.items()
                      if k != 'attrs'}, **(rec.get('attrs') or {}))
                for rec in list(self._open_spans.values())]
            self.flight_dumps += 1
            record = {
                'rank': rank,
                'pid': os.getpid(),
                'reason': reason,
                't': self.now(),
                'wall0': self._wall0,
                'n_dumps': self.flight_dumps,
                'liveness_dir': self.liveness_dir,
                'last_collective': last_coll,
                'last_p2p': last_p2p,
                'open_spans': open_spans,
                'ring': ring,
            }
            if attrs:
                record['attrs'] = attrs
            # live state tables registered by components (the
            # generation engine's in-flight request table): a crash
            # mid-generation then names which requests died where.
            # Each source is best-effort -- a racing mutation on the
            # dying process must not void the black box
            for name, fn in list(self.flight_sources.items()):
                try:
                    record[name] = fn()
                except Exception:
                    continue
            if not locked:
                record['degraded'] = True  # lock-free snapshot
            record['complete'] = True  # write-complete sentinel
            path = os.path.join(outdir, 'flight-rank%d.json' % rank)
            tmp = path + '.tmp.%d' % os.getpid()
            with open(tmp, 'w') as f:
                # default=repr: an exotic attr value must not void the
                # whole black box
                json.dump(record, f, default=repr)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            return path
        except Exception:
            return None  # a failing dump must never mask the fault
