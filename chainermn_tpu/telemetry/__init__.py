"""Unified runtime telemetry: per-step event timeline, collective
spans, metrics export (ROADMAP item 5's evidence layer).

The reference stack has no observability subsystem at all; here the
runtime narrates itself.  A low-overhead per-process recorder
(:mod:`chainermn_tpu.telemetry.recorder`) is threaded through the
layers that matter -- communicator eager collectives and the object
p2p channel (``communicators/base.py``), step phases in both updaters
(host batch prep / H2D / jitted step / metrics sync), checkpoint
write/verify/resume (``training/recovery.py``), and chaos fault
injections (``utils/chaos.py``) -- so a fault and its latency
consequences correlate in ONE timeline.  On top: a metrics registry
(counters / gauges / histograms with p50/p99), per-rank JSONL event
logs, an aggregated ``metrics.json``, and a Prometheus text exporter;
``python -m chainermn_tpu.telemetry report`` merges per-rank logs
into a step timeline and computes the **overlap fraction** (collective
time hidden behind compute vs exposed) -- the dynamic twin of the
static shardlint rule SL009 -- and ``... telemetry doctor`` runs the
cross-rank diagnosis (:mod:`chainermn_tpu.telemetry.diagnosis`):
collective skew attribution, straggler naming with the lagging
phase, and the crash post-mortem from the crash-safe flight recorder
(:func:`dump_flight` / ``flight-rank*.json``) merged with
peer-liveness heartbeats.  See ``docs/observability.md``.

Activation (exactly the chaos discipline -- zero cost when off)::

    CHAINERMN_TPU_TELEMETRY=/path/to/outdir python train.py
    # optional: device-sync fences (spans measure completion, not
    # dispatch; serializes the device -- a measurement mode)
    CHAINERMN_TPU_TELEMETRY_SYNC=1

or programmatically::

    from chainermn_tpu import telemetry
    rec = telemetry.enable('/tmp/tele')   # or enable() for in-memory
    ...
    rec.flush()                           # also registered atexit

Hot call sites guard on ``telemetry._active is not None`` (one
attribute load + identity check); :func:`span`/:func:`event` are
additionally safe to call unconditionally -- disabled, they cost one
function call and return a preallocated no-op context.
"""

import os

from chainermn_tpu.telemetry.recorder import (  # noqa: F401
    Counter, FLIGHT_RING, Gauge, Histogram, NULL_SPAN, Recorder,
    Registry, escape_help, escape_label_value, snapshot_to_prometheus)

ENV_VAR = 'CHAINERMN_TPU_TELEMETRY'
ENV_SYNC = 'CHAINERMN_TPU_TELEMETRY_SYNC'

_active = None
_env_checked = False


def active():
    """The installed :class:`Recorder`, or None."""
    return _active


def enabled():
    return _active is not None


def enable(outdir=None, sync_fences=None):
    """Install a recorder (idempotent per process: re-enabling with a
    different outdir re-points the existing recorder's flush so spans
    recorded before ``enable`` are not lost)."""
    global _active
    if sync_fences is None:
        sync_fences = os.environ.get(ENV_SYNC, '') not in ('', '0')
    if _active is None:
        _active = Recorder(outdir=outdir, sync_fences=sync_fences)
        if outdir is not None:
            import atexit
            atexit.register(_flush_at_exit)
    elif outdir is not None and _active.outdir is None:
        _active.outdir = outdir
        import atexit
        atexit.register(_flush_at_exit)
    return _active


def disable():
    """Uninstall (testing hook; does NOT flush)."""
    global _active, _env_checked
    _active, _env_checked = None, False


def _flush_at_exit():
    rec = _active
    if rec is not None and rec.outdir is not None:
        try:
            rec.flush()
        except Exception:
            pass  # interpreter teardown: never mask the real exit


def maybe_enable_from_env(env_var=ENV_VAR):
    """Install a recorder from ``CHAINERMN_TPU_TELEMETRY`` once per
    process (no-op when unset or already checked).  The value is the
    session output directory; the literal ``1`` enables an in-memory
    recorder (programmatic flush only)."""
    global _env_checked
    if _active is not None or _env_checked:
        return _active
    _env_checked = True
    value = os.environ.get(env_var)
    if not value:
        return None
    return enable(outdir=None if value == '1' else value)


def span(name, kind='generic', **attrs):
    """Context manager timing the enclosed block into the active
    recorder; the disabled path returns a no-op singleton."""
    rec = _active
    if rec is None:
        return NULL_SPAN
    return rec.span(name, kind=kind, **attrs)


def event(name, kind='event', **attrs):
    """Record a point-in-time event (no-op when disabled)."""
    rec = _active
    if rec is not None:
        rec.event(name, kind=kind, **attrs)


def request_stage(request_id, name, t0, t1=None, **attrs):
    """Record one completed stage of a per-request trace
    (``kind='request'`` span via :meth:`Recorder.child_span`); no-op
    when disabled.  The serving path threads a request's lifecycle
    through these -- ``queue_wait`` -> ``bucket_pack`` -> ``prefill``
    -> per-tick ``decode`` (or ``execute`` on the batch path) -- with
    each stage's ``t0`` equal to the previous stage's ``t1``, so
    ``telemetry report`` reconstructs a gap-free timeline whose stage
    budgets sum to the end-to-end latency."""
    rec = _active
    if rec is not None:
        rec.child_span(request_id, name, t0, t1, **attrs)


def request_event(request_id, name, **attrs):
    """Record a terminal request event (``complete`` / ``shed`` /
    ``error``) as a ``kind='request'`` event; no-op when disabled."""
    rec = _active
    if rec is not None:
        rec.event(name, kind='request', request_id=request_id, **attrs)


def registry():
    """The active recorder's metrics registry, or None."""
    rec = _active
    return rec.registry if rec is not None else None


def flush(outdir=None):
    rec = _active
    return rec.flush(outdir) if rec is not None else None


def dump_flight(reason, outdir=None, blocking=True, **attrs):
    """Write the crash-safe flight record (last-N-records ring, open
    spans, last completed collective) for this rank -- see
    :meth:`Recorder.dump_flight`.  Signal handlers MUST pass
    ``blocking=False`` (non-reentrant recorder lock).  No-op (None)
    when telemetry is disabled or the session is in-memory; never
    raises."""
    rec = _active
    if rec is None:
        return None
    return rec.dump_flight(reason, outdir=outdir, blocking=blocking,
                           **attrs)
