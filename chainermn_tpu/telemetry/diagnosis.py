"""Cross-rank diagnosis: collective skew attribution, straggler
detection, and post-mortem crash analysis from flight records.

PR 6 gave the raw substrate (per-rank span/event JSONL, merged step
timelines, overlap fractions); this module INTERPRETS multi-rank
captures.  The stack's collectives are synchronous (PAPER.md L2/L4):
one chronically late rank stalls the whole pod, so the single
highest-leverage observability question is *which rank, which phase*.
Three analyses answer it:

- **Collective skew attribution** (:func:`collective_skew`).  Eager
  collective spans carry a per-stream ``seq`` attribute (recorded by
  ``communicators/base.py``), so the same rendezvous is pairable
  across ranks by ``(name, tag, seq)``.  A rendezvous collective
  *exits* on every rank at (nearly) the same true instant -- the last
  arrival releases everyone -- so per-rank clock offset is estimated
  as the median deviation of a rank's exit times from the per-group
  mean (:func:`estimate_clock_offsets`); the spans are wall-aligned
  at record time but wall clocks drift.  After offset correction,
  per-group *arrival* (``t0``) spread is genuine waiting: per
  collective we report the skew and the latest rank, and per rank a
  chronic-lateness score -- "rank 2 arrives 18 ms late to 94% of
  allreduces" is machine-produced, with the lagging phase attributed
  by comparing the late rank's per-span-name median durations against
  its peers' (:func:`attribute_phase`): the phase that GREW on the
  late rank is the cause; its collective spans shrink (it waits
  least), so they never win the attribution.

- **Straggler / anomaly detection** (:func:`find_stragglers`,
  :func:`step_anomalies`).  Chronic cross-rank comparison uses
  median-vs-peer-median excess (robust at the 2-3 rank counts the CI
  runs, where cross-rank MAD degenerates); within-run anomalies use
  MAD-based modified z-scores (:func:`robust_outliers`) over the raw
  per-step samples -- step time, each step phase, exposed-collective
  time -- each flagged row attributed to the phase that grew.

- **Crash analysis** (:func:`crash_analysis`).  Merges the crash-safe
  flight records (``flight-rank*.json``, written atomically by
  :meth:`~chainermn_tpu.telemetry.recorder.Recorder.dump_flight` from
  chaos kill sites before ``os._exit``, from the typed-failure
  constructors in :mod:`chainermn_tpu.utils.failure`, and from the
  preemption SIGTERM hook) with the peer-liveness heartbeat files
  (``heartbeat-*.json``; the directory is handed off by
  ``enable_peer_liveness``) to name the dead/stalled rank, its last
  completed collective seq, and the open span each surviving rank was
  blocked in when it detected the death.

:func:`diagnose` runs all three and renders one verdict;
``python -m chainermn_tpu.telemetry doctor DIR`` is the CLI.  See
``docs/observability.md`` ("Diagnosing stragglers and crashes").
"""

import glob
import json
import os

from chainermn_tpu.telemetry.report import (
    SERVE_PHASES, STEP_PHASES, exposed_time, input_bound_stats,
    load_rank_logs, load_rank_metrics, aggregate_metrics,
    merge_intervals, request_summary, serve_summary, step_table,
    _percentile)

#: phases the within-run anomaly scan pools samples for: the training
#: step phases plus the serve-batch phases (``serve_execute`` spans
#: carry ``iteration`` = batch index, so a latency-cliff batch is
#: attributable exactly like a slow training step)
ANOMALY_PHASES = STEP_PHASES + SERVE_PHASES

#: eager collectives whose EXIT is a rendezvous (every rank leaves
#: when the last arrives) -- the clock-offset anchors.  The eager
#: ``broadcast_data`` span is a local replicate, not a rendezvous, so
#: it contributes to skew pairing only, never to offset estimation.
RENDEZVOUS_COLLECTIVES = ('barrier', 'allreduce_obj')

#: a rank is chronically late when it is the latest arrival in at
#: least this fraction of paired collectives ...
CHRONIC_LATE_FRACTION = 0.5
#: ... by at least this much on average (ms) -- below it, "latest" is
#: scheduler noise, not a straggler
MIN_LATE_MS = 2.0

#: cross-rank straggler flag: median step/phase time exceeding the
#: peer median by this fraction AND by MIN_EXCESS_MS
STRAGGLER_EXCESS_FRAC = 0.2
STRAGGLER_MIN_EXCESS_MS = 2.0

#: modified z-score cutoff for MAD-based within-run outliers (the
#: conventional 3.5 of Iglewicz & Hoaglin)
MAD_Z = 3.5


def _median(vals):
    s = sorted(vals)
    n = len(s)
    if not n:
        return None
    return (s[n // 2] if n % 2 else (s[n // 2 - 1] + s[n // 2]) / 2.0)


def mad(samples):
    """``(median, median-absolute-deviation)`` of a sample list."""
    med = _median(samples)
    if med is None:
        return None, None
    return med, _median([abs(v - med) for v in samples])


def effective_mad(samples):
    """``(median, effective deviation)``: the MAD, or the mean
    absolute deviation when the MAD collapses (over half the samples
    identical -- a lone spike in an otherwise flat series zeroes the
    MAD but cannot zero the mean absolute deviation).  ``(median,
    None)`` when no usable deviation exists (constant or empty data);
    every z-score computed against :func:`robust_outliers`' flags
    MUST use this deviation, not the raw MAD."""
    med, m = mad(samples)
    if med is None:
        return None, None
    if not m or m < 1e-9 * max(abs(med), 1.0):
        m = sum(abs(v - med) for v in samples) / len(samples)
        if not m or m < 1e-9 * max(abs(med), 1.0):
            return med, None
    return med, m


def robust_outliers(samples, z=MAD_Z, min_dev=0.0):
    """Indices of MAD-based outliers (modified z-score > ``z``,
    slow side only -- a suspiciously FAST step is not a straggler
    signal).  Degenerate inputs flag nothing (absence of evidence,
    not fabricated flags): < 4 samples, MAD 0 on constant data, or a
    MAD that is pure floating-point noise relative to the median (the
    classic near-constant-series pitfall where nanoscale jitter earns
    astronomical z-scores); near-constant-with-a-spike series fall
    back to the mean absolute deviation (:func:`effective_mad`).
    ``min_dev`` additionally requires the deviation itself to be
    material in the samples' own unit."""
    if len(samples) < 4:
        return []
    med, m = effective_mad(samples)
    if med is None or m is None:
        return []
    return [i for i, v in enumerate(samples)
            if 0.6745 * (v - med) / m > z and (v - med) > min_dev]


# ---------------------------------------------------------------------
# collective pairing + clock offsets + skew

def pair_collectives(spans):
    """Group ``kind='collective'`` spans carrying a ``seq`` by
    ``(name, tag, seq)`` into ``{key: {rank: span}}`` -- the same
    rendezvous seen from every rank.  Spans without a seq (pre-PR-8
    captures) are unpairable and skipped."""
    groups = {}
    for s in spans:
        if s.get('kind') != 'collective' or 'seq' not in s:
            continue
        key = (s.get('name'), s.get('tag'), int(s['seq']))
        groups.setdefault(key, {})[int(s.get('rank', 0))] = s
    return groups


def protocol_divergence(spans, exclude_ranks=()):
    """Replay per-rank eager collective ``seq`` streams from a
    capture through the cross-rank checker core
    (:func:`chainermn_tpu.analysis.commcheck.verify_streams`) -- the
    DYNAMIC twin of shardlint's SL013: the static rule feeds the same
    core simulated streams, this replay feeds it recorded spans, so
    the two verdicts cannot drift apart.

    Streams are each rank's ``kind='collective'`` spans carrying a
    ``seq`` (the PR 8 pairing stamps), in ``t0`` order.
    ``exclude_ranks`` removes ranks already explained by crash
    analysis: a dead rank's stream ends early by DEATH, which is the
    crash verdict's finding, not a protocol divergence.  Returns
    ``None`` when the surviving streams agree (or fewer than two
    ranks recorded collectives), else the checker's divergence dict
    (first divergent position, each rank's op and surrounding ops).
    """
    excl = {int(r) for r in exclude_ranks}
    by_rank = {}
    for s in spans:
        if s.get('kind') != 'collective' or 'seq' not in s:
            continue
        r = int(s.get('rank', 0))
        if r in excl:
            continue
        by_rank.setdefault(r, []).append(s)
    if len(by_rank) < 2:
        return None
    streams = {}
    for r, recs in by_rank.items():
        recs.sort(key=lambda s: float(s.get('t0', 0.0)))
        streams[r] = [{'op': s.get('name'), 'kind': 'collective',
                       'tag': s.get('tag'), 'seq': int(s['seq'])}
                      for s in recs]
    from chainermn_tpu.analysis import commcheck
    return commcheck.verify_streams(streams)


def estimate_clock_offsets(groups, ranks=None):
    """Per-rank wall-clock offset (seconds; subtract from a rank's
    timestamps to land on the common clock), estimated from paired
    RENDEZVOUS exits: within one group every rank's ``t1`` is the
    same true instant, so a rank's deviation from the group mean is
    its offset plus noise; the median over groups is robust to the
    odd late release.  Ranks without paired exits get 0.0."""
    devs = {}
    for (name, _tag, _seq), by_rank in groups.items():
        if name not in RENDEZVOUS_COLLECTIVES or len(by_rank) < 2:
            continue
        t1s = {r: s['t1'] for r, s in by_rank.items()}
        center = sum(t1s.values()) / len(t1s)
        for r, t in t1s.items():
            devs.setdefault(r, []).append(t - center)
    offsets = {r: _median(ds) for r, ds in devs.items()}
    for r in (ranks or ()):
        offsets.setdefault(r, 0.0)
    return offsets


def collective_skew(spans, offsets=None, max_worst=8):
    """Arrival-skew attribution over paired collective spans.

    Returns ``None`` when no collective pairs exist (single-rank
    capture, or spans predating seq tagging); else a dict with

    - ``paired``: number of cross-rank-paired collectives,
    - ``clock_offsets_ms``: the per-rank offsets used,
    - ``skew_ms``: p50/p99/max of per-collective arrival spread
      (first arrival to last, offset-corrected),
    - ``worst``: the ``max_worst`` widest collectives
      (name/tag/seq/skew_ms/late_rank),
    - ``per_rank``: chronic-lateness score per rank --
      ``late_fraction`` (how often this rank arrived last, among
      collectives with real spread), ``mean_late_ms`` /
      ``p99_late_ms`` (its arrival lag behind the first rank),
      ``chronic`` (both thresholds crossed).
    """
    groups = pair_collectives(spans)
    ranks = sorted({r for g in groups.values() for r in g})
    if offsets is None:
        offsets = estimate_clock_offsets(groups, ranks)
    rows = []
    lateness = {r: [] for r in ranks}
    late_counts = {r: 0 for r in ranks}
    judged = 0
    for (name, tag, seq), by_rank in sorted(groups.items(),
                                            key=lambda kv: str(kv[0])):
        if len(by_rank) < 2:
            continue
        arrivals = {r: s['t0'] - (offsets.get(r) or 0.0)
                    for r, s in by_rank.items()}
        first = min(arrivals.values())
        late_rank = max(arrivals, key=lambda r: arrivals[r])
        skew_ms = (arrivals[late_rank] - first) * 1e3
        for r, a in arrivals.items():
            lateness[r].append((a - first) * 1e3)
        judged += 1
        if skew_ms > MIN_LATE_MS:
            late_counts[late_rank] += 1
        rows.append({'name': name, 'tag': tag, 'seq': seq,
                     'skew_ms': round(skew_ms, 3),
                     'late_rank': late_rank})
    if not judged:
        return None
    skews = sorted(r['skew_ms'] for r in rows)
    meaningful = sum(1 for r in rows if r['skew_ms'] > MIN_LATE_MS)
    per_rank = {}
    for r in ranks:
        lats = lateness[r]
        frac = (late_counts[r] / meaningful) if meaningful else 0.0
        mean_late = (sum(lats) / len(lats)) if lats else 0.0
        per_rank[r] = {
            'late_fraction': round(frac, 4),
            'mean_late_ms': round(mean_late, 3),
            'p99_late_ms': round(_percentile(sorted(lats), 0.99), 3)
            if lats else None,
            'chronic': (frac >= CHRONIC_LATE_FRACTION
                        and mean_late >= MIN_LATE_MS),
        }
    return {
        'paired': judged,
        'clock_offsets_ms': {r: round((offsets.get(r) or 0.0) * 1e3, 3)
                             for r in ranks},
        'skew_ms': {
            'p50': round(_percentile(skews, 0.50), 3),
            'p99': round(_percentile(skews, 0.99), 3),
            'max': round(skews[-1], 3),
        },
        'worst': sorted(rows, key=lambda r: -r['skew_ms'])[:max_worst],
        'per_rank': per_rank,
    }


# ---------------------------------------------------------------------
# phase attribution + stragglers

def _durations_by_name(spans, rank):
    out = {}
    for s in spans:
        if int(s.get('rank', 0)) != rank:
            continue
        out.setdefault(s.get('name'), []).append(
            (s['t1'] - s['t0']) * 1e3)
    return out


def attribute_phase(spans, rank):
    """``(phase, delta_ms)``: the span name whose median duration on
    ``rank`` most exceeds the median of its peers' medians -- the
    phase that GREW on the suspect rank.  A late rank's own
    collective spans SHRINK (it waits least), so they lose this argmax
    by construction; the winner is the causal phase (host_batch_prep,
    send_obj, ...).  ``(None, 0.0)`` when nothing grew."""
    ranks = sorted({int(s.get('rank', 0)) for s in spans})
    mine = _durations_by_name(spans, rank)
    others = {r: _durations_by_name(spans, r)
              for r in ranks if r != rank}
    best, best_delta = None, 0.0
    for name, durs in mine.items():
        peer_meds = [
            _median(o[name]) for o in others.values() if o.get(name)]
        if not peer_meds:
            continue
        delta = _median(durs) - _median(peer_meds)
        if delta > best_delta:
            best, best_delta = name, delta
    return best, round(best_delta, 3)


def exposed_by_rank(spans):
    """Per-rank exposed-collective time (ms): collective span time
    with no same-rank compute span running -- the straggler-visible
    half of the overlap accounting in ``report.overlap_stats``."""
    ranks = sorted({int(s.get('rank', 0)) for s in spans})
    out = {}
    for rank in ranks:
        comp = merge_intervals(
            [(s['t0'], s['t1']) for s in spans
             if int(s.get('rank', 0)) == rank
             and s.get('kind') == 'compute'])
        coll = [(s['t0'], s['t1']) for s in spans
                if int(s.get('rank', 0)) == rank
                and s.get('kind') == 'collective']
        out[rank] = round(sum(exposed_time(c, comp) for c in
                              merge_intervals(coll)) * 1e3, 3)
    return out


def _excess_vs_peers(per_rank_values):
    """``{rank: (excess_ms, excess_frac)}`` of each rank's value over
    the median of its peers' values (cross-rank comparison that stays
    meaningful at 2-3 ranks, where cross-rank MAD degenerates)."""
    out = {}
    for rank, v in per_rank_values.items():
        peers = [w for r, w in per_rank_values.items() if r != rank]
        base = _median(peers)
        if base is None or v is None:
            continue
        excess = v - base
        out[rank] = (excess, excess / base if base > 0 else float('inf')
                     if excess > 0 else 0.0)
    return out


def find_stragglers(spans, skew=None):
    """Straggler candidates, most damning evidence first.

    Evidence tiers, each consulted only when the stronger one is
    silent: (1) chronic lateness to paired collectives -- the direct
    synchronous-stall signal; when it names ranks, the weaker tiers
    are SKIPPED, because the victims of a chronic straggler show
    inflated collective waits that would read as false positives;
    (2) step-time median excess over peers; (3) exposed-collective
    DEFICIT -- in a synchronous pod everyone waits for the straggler,
    so the rank whose exposed-collective time is far BELOW its peers'
    (it arrives last and waits least) is the one stalling them.  Each
    candidate carries the attributed phase from
    :func:`attribute_phase`."""
    out = []
    if skew:
        for rank, st in sorted(skew['per_rank'].items()):
            if not st['chronic']:
                continue
            phase, delta = attribute_phase(spans, rank)
            out.append({
                'rank': rank, 'evidence': 'chronic_collective_lateness',
                'late_fraction': st['late_fraction'],
                'mean_late_ms': st['mean_late_ms'],
                'phase': phase, 'phase_delta_ms': delta,
            })
    if out:
        return out
    step_meds = {}
    for s in spans:
        if s.get('name') == 'jitted_step':
            step_meds.setdefault(int(s.get('rank', 0)), []).append(
                (s['t1'] - s['t0']) * 1e3)
    med_by_rank = {r: _median(v) for r, v in step_meds.items()
                   if len(v) >= 2}
    for rank, (excess, frac) in sorted(
            _excess_vs_peers(med_by_rank).items()):
        if (frac > STRAGGLER_EXCESS_FRAC
                and excess > STRAGGLER_MIN_EXCESS_MS):
            phase, delta = attribute_phase(spans, rank)
            out.append({
                'rank': rank, 'evidence': 'step_time_excess',
                'excess_ms': round(excess, 3),
                'excess_fraction': round(frac, 4),
                'phase': phase, 'phase_delta_ms': delta,
            })
    if out:
        return out
    for rank, (excess, frac) in sorted(
            _excess_vs_peers(exposed_by_rank(spans)).items()):
        deficit = -excess
        if (frac < -STRAGGLER_EXCESS_FRAC
                and deficit > STRAGGLER_MIN_EXCESS_MS):
            phase, delta = attribute_phase(spans, rank)
            out.append({
                'rank': rank, 'evidence': 'exposed_collective_deficit',
                'deficit_ms': round(deficit, 3),
                'deficit_fraction': round(-frac, 4),
                'phase': phase, 'phase_delta_ms': delta,
            })
    return out


def step_anomalies(spans, z=MAD_Z, max_rows=16):
    """Within-run MAD outliers over the raw per-step samples: for
    step time and each step phase, pool every (rank, iteration)
    duration, flag modified z-scores above ``z``, and attribute each
    flagged step to the phase that grew.  Sorted by severity.

    The FIRST step of each (phase, rank) series is excluded: it is
    compile/warmup (a 20x iteration-0 ``jitted_step`` is XLA doing
    its job), and flagging it in every capture would teach operators
    to ignore the column."""
    samples = {}  # phase -> [(value_ms, rank, iteration)]
    first_it = {}  # (phase, rank) -> smallest iteration seen
    for s in spans:
        name = s.get('name')
        if name not in ANOMALY_PHASES or 'iteration' not in s:
            continue
        rank, it = int(s.get('rank', 0)), int(s['iteration'])
        cur = first_it.get((name, rank))
        if cur is None or it < cur:
            first_it[(name, rank)] = it
        samples.setdefault(name, []).append(
            ((s['t1'] - s['t0']) * 1e3, rank, it))
    for name, vals in samples.items():
        samples[name] = [v for v in vals
                         if v[2] != first_it[(name, v[1])]]
    rows = []
    for phase, vals in samples.items():
        series = [v[0] for v in vals]
        # effective_mad, not mad: when the MAD collapses (flat series
        # with a lone spike) robust_outliers flags against the mean-
        # absolute-deviation fallback, and the z reported here must
        # use that same deviation or divide by zero
        med, m = effective_mad(series)
        # min_dev: an anomalous step must ALSO be materially slow
        # (>= MIN_LATE_MS) -- sub-millisecond jitter is scheduler
        # noise however many z-scores it spans
        for i in robust_outliers(series, z, min_dev=MIN_LATE_MS):
            v, rank, it = vals[i]
            rows.append({
                'phase': phase, 'rank': rank, 'iteration': it,
                'value_ms': round(v, 3), 'median_ms': round(med, 3),
                'z': round(0.6745 * (v - med) / m, 2),
            })
    rows.sort(key=lambda r: -r['z'])
    return rows[:max_rows]


# ---------------------------------------------------------------------
# flight records + heartbeats -> crash analysis

def load_flight_records(outdir):
    """``{rank: record}`` from every complete ``flight-rank*.json``
    under a session directory; torn or sentinel-less files are
    skipped (a crash mid-dump must not poison the post-mortem)."""
    out = {}
    for path in sorted(glob.glob(
            os.path.join(outdir, 'flight-rank*.json'))):
        try:
            with open(path) as f:
                rec = json.load(f)
        except (ValueError, OSError):
            continue
        if not rec.get('complete'):
            continue
        out[int(rec.get('rank', 0))] = rec
    return out


def load_heartbeats(dirs):
    """``{process_index: beat}`` from ``heartbeat-*.json`` files in
    each directory (newest wins on duplicates)."""
    out = {}
    for d in dirs:
        if not d:
            continue
        for path in sorted(glob.glob(
                os.path.join(d, 'heartbeat-*.json'))):
            try:
                with open(path) as f:
                    beat = json.load(f)
            except (ValueError, OSError):
                continue
            pi = beat.get('process_index')
            if pi is None:
                continue
            pi = int(pi)
            if (pi not in out
                    or beat.get('time', 0) > out[pi].get('time', 0)):
                out[pi] = dict(beat, path=path)
    return out


def _last_collective_from_events(spans, rank):
    best = None
    for s in spans:
        if (int(s.get('rank', 0)) == rank
                and s.get('kind') == 'collective'):
            if best is None or s['t1'] > best['t1']:
                best = s
    return best


def crash_analysis(outdir, metas, spans, events, flights,
                   liveness_dirs=(), stall_timeout=None):
    """Post-mortem death/blocked verdicts from flight records +
    heartbeats.

    A rank is DEAD when (a) its flight record's reason is a chaos
    kill site or a preemption signal, (b) a surviving rank's typed
    ``PeerDeadError`` flight record accuses it, or (c) its heartbeat
    froze ``stall_timeout`` earlier than the newest heartbeat in the
    directory (relative age: the doctor runs after everything exited,
    so absolute age means nothing).  Each dead rank is reported with
    its last completed collective (name + seq), preferring its own
    flight record (written BEFORE ``os._exit``) over its event log.
    Survivors' flight records contribute their open spans -- where
    each one was blocked when it detected the death."""
    dirs = list(liveness_dirs)
    timeout = stall_timeout
    for e in events:
        if e.get('name') == 'liveness_enabled':
            if e.get('dir'):
                dirs.append(e['dir'])
            if timeout is None and e.get('stall_timeout'):
                timeout = float(e['stall_timeout'])
    for rec in flights.values():
        if rec.get('liveness_dir'):
            dirs.append(rec['liveness_dir'])
    # liveness dirs are often given relative to the run's cwd; also
    # try them under the capture dir so the doctor works from anywhere
    cand = []
    for d in dict.fromkeys(dirs):
        cand.append(d)
        if not os.path.isabs(d):
            cand.append(os.path.join(outdir, d))
    cand.append(outdir)
    beats = load_heartbeats(dict.fromkeys(cand))
    timeout = 5.0 if timeout is None else timeout

    ranks = sorted({int(m.get('rank', 0)) for m in metas}
                   | set(flights) | set(beats))
    dead = {}  # rank -> [reasons]

    def accuse(rank, why):
        dead.setdefault(rank, []).append(why)

    preempted = set()
    for rank, rec in flights.items():
        reason = str(rec.get('reason') or '')
        if reason.startswith(('chaos:kill', 'chaos:ckpt_kill')):
            accuse(rank, 'flight record: %s' % reason)
        elif reason == 'sigterm':
            # a SIGTERM flight followed by a completed checkpoint
            # span is a CLEAN preemption-evacuation; only a SIGTERM
            # with no checkpoint after it reads as a death (the
            # scheduler's SIGKILL follow-up won)
            evacuated = any(
                s.get('name') == 'checkpoint_write'
                and int(s.get('rank', 0)) == rank
                and s['t1'] >= rec.get('t', 0)
                for s in spans)
            if evacuated:
                preempted.add(rank)
            else:
                accuse(rank, 'flight record: preemption signal with '
                       'no checkpoint after it')
        elif reason == 'PeerDeadError':
            attrs = rec.get('attrs') or {}
            peer = attrs.get('process_index')
            if peer is not None:
                accuse(int(peer),
                       'rank %d raised PeerDeadError naming it' % rank)
    if len(beats) >= 2:
        newest = max(b.get('time', 0) for b in beats.values())
        for rank, b in beats.items():
            if newest - b.get('time', 0) > timeout:
                accuse(rank, 'heartbeat froze %.1fs before the newest'
                       % (newest - b.get('time', 0)))

    # fired chaos injections per rank, from the event timeline: the
    # flight record keeps only the LAST dump's reason (a later typed
    # or sigterm dump overwrites a chaos one -- e.g. hang_step then
    # the escalation SIGTERM), so the injection history must come
    # from the events, which are append-only
    chaos_events = {}
    for e in events:
        name = str(e.get('name') or '')
        if e.get('kind') == 'chaos' or name.startswith('chaos:'):
            chaos_events.setdefault(
                int(e.get('rank', 0)), []).append(name)

    # an accused rank may have left no meta/flight/beat of its own
    # (killed before its first flush); it still belongs in the verdict
    ranks = sorted(set(ranks) | set(dead) | set(chaos_events))
    per_rank = {}
    for rank in ranks:
        rec = flights.get(rank)
        state = ('dead' if rank in dead
                 else 'preempted' if rank in preempted else 'alive')
        info = {'state': state, 'why': dead.get(rank, [])}
        if rank in chaos_events:
            info['chaos_events'] = chaos_events[rank]
        beat = beats.get(rank)
        if beat is not None:
            info['last_heartbeat_iteration'] = beat.get('iteration')
        if rec is not None:
            info['flight_reason'] = rec.get('reason')
            last = (rec.get('last_collective')
                    or _last_collective_from_events(spans, rank))
            if last is not None:
                info['last_collective'] = {
                    'name': last.get('name'), 'seq': last.get('seq'),
                    'tag': last.get('tag')}
            if rec.get('last_p2p'):
                lp = rec['last_p2p']
                info['last_p2p'] = {
                    'name': lp.get('name'), 'seq': lp.get('seq'),
                    'dest': lp.get('dest'), 'source': lp.get('source')}
            blocked = [s for s in (rec.get('open_spans') or [])
                       if s.get('kind') in ('collective', 'p2p')]
            if blocked:
                info['blocked_in'] = blocked
        elif rank in dead:
            last = _last_collective_from_events(spans, rank)
            if last is not None:
                info['last_collective'] = {
                    'name': last.get('name'), 'seq': last.get('seq'),
                    'tag': last.get('tag')}
        per_rank[rank] = info
    return {
        'dead_ranks': sorted(dead),
        'per_rank': per_rank,
        'heartbeat_dirs': [d for d in dict.fromkeys(cand)
                           if glob.glob(os.path.join(
                               d, 'heartbeat-*.json'))],
        'stall_timeout_s': timeout,
    }


# ---------------------------------------------------------------------
# the doctor

def diagnose(outdir, liveness_dirs=(), z=MAD_Z):
    """The full cross-rank diagnosis of one capture directory: skew
    attribution + straggler flags + step anomalies + crash analysis,
    under a single machine-readable ``verdict``."""
    metas, spans, events, bad = load_rank_logs(outdir)
    flights = load_flight_records(outdir)
    # serve recognition: a forward-only serving capture may hold ONLY
    # metrics (the bench's in-memory window exports histograms, no
    # event log) -- the serve summary is computed from the metrics
    # files so such a capture is diagnosable, not "empty"
    serve = serve_summary(aggregate_metrics(load_rank_metrics(outdir)))
    requests = request_summary(spans + events)
    skew = collective_skew(spans)
    stragglers = find_stragglers(spans, skew)
    anomalies = step_anomalies(spans, z=z)
    crash = crash_analysis(outdir, metas, spans, events, flights,
                           liveness_dirs=liveness_dirs)
    ranks = sorted({int(m.get('rank', 0)) for m in metas}
                   | {int(s.get('rank', 0)) for s in spans}
                   | set(flights))
    dead = crash['dead_ranks']
    straggler = stragglers[0] if stragglers else None
    # typed-failure black boxes (a timeout/corruption that did not
    # kill anyone still deserves the operator's eye)
    typed_flights = {
        r: rec.get('reason') for r, rec in sorted(flights.items())
        if rec.get('reason') in ('ChannelTimeout', 'PeerDeadError',
                                 'CheckpointCorruptError')}
    # protocol replay: did every (surviving) rank issue the same
    # collectives in the same order?  Dead ranks are excluded -- a
    # stream truncated by death is the crash verdict's finding.
    protocol = protocol_divergence(spans, exclude_ranks=dead)
    healthy = (not dead and not straggler and not anomalies
               and not typed_flights and protocol is None)
    summary = []
    for r in dead:
        info = crash['per_rank'][r]
        line = 'rank %d is DEAD (%s)' % (r, '; '.join(info['why']))
        last = info.get('last_collective')
        if last:
            line += ', last completed collective %s seq %s' % (
                last.get('name'), last.get('seq'))
        summary.append(line)
    for r, info in sorted(crash['per_rank'].items()):
        for b in info.get('blocked_in', []):
            summary.append(
                'rank %d was blocked in %s(%s)' % (
                    r, b.get('name'),
                    ', '.join('%s=%s' % (k, v)
                              for k, v in sorted(b.items())
                              if k not in ('name', 'kind', 't0'))))
    if straggler is not None:
        if straggler['evidence'] == 'chronic_collective_lateness':
            summary.append(
                'rank %d arrives %.1f ms late to %.0f%% of paired '
                'collectives (phase: %s)'
                % (straggler['rank'], straggler['mean_late_ms'],
                   straggler['late_fraction'] * 100,
                   straggler['phase'] or 'unattributed'))
        else:
            ms = straggler.get('excess_ms',
                               straggler.get('deficit_ms', 0.0))
            summary.append(
                'rank %d is a straggler: %s %.1f ms vs peers '
                '(phase: %s)'
                % (straggler['rank'], straggler['evidence'], ms,
                   straggler['phase'] or 'unattributed'))
    for r, reason in typed_flights.items():
        if r not in dead:
            summary.append('rank %d hit a typed failure: %s (see its '
                           'flight record)' % (r, reason))
    if protocol is not None:
        summary.append('protocol divergence at %s'
                       % protocol['summary'])
        for r, info in sorted(protocol['ranks'].items()):
            summary.append(
                'rank %s ops around position %d: %s'
                % (r, protocol['position'],
                   ' '.join(info['context']) or '(stream ended)'))
    if anomalies and not straggler:
        a = anomalies[0]
        summary.append(
            '%d anomalous step(s); worst: iteration %d rank %d '
            '%s %.1f ms (median %.1f ms, z=%.1f)'
            % (len(anomalies), a['iteration'], a['rank'], a['phase'],
               a['value_ms'], a['median_ms'], a['z']))
    if serve:
        lat = serve.get('latency_ms') or {}
        summary.append(
            'serving capture: %.0f requests / %.0f batches, %.0f shed'
            % (serve['requests'], serve['batches'], serve['shed'])
            + ('; latency p50 %.3f ms p99 %.3f ms'
               % (lat['p50'], lat['p99'])
               if lat.get('p50') is not None else ''))
        gen = serve.get('generate')
        if gen:
            ttft = gen.get('ttft_ms') or {}
            itl = gen.get('intertoken_ms') or {}
            line = ('decode capture: %.0f tokens over %.0f decode '
                    'steps' % (gen['tokens'], gen['decode_steps']))
            if gen.get('tokens_per_s'):
                line += ' (%.0f tok/s)' % gen['tokens_per_s']
            if ttft.get('p50') is not None:
                line += ('; TTFT p50 %.3f ms p99 %.3f ms'
                         % (ttft['p50'], ttft['p99']))
            if itl.get('p50') is not None:
                line += ('; inter-token p50 %.3f ms p99 %.3f ms'
                         % (itl['p50'], itl['p99']))
            summary.append(line)
        if serve.get('shed_reasons'):
            summary.append('shed reasons: ' + ', '.join(
                '%s=%.0f' % (k, v) for k, v
                in sorted(serve['shed_reasons'].items())))
    if requests and requests.get('worst'):
        worst = requests['worst']
        summary.append(
            'worst traced request %s: e2e %.3f ms (%s)'
            % (worst['request_id'], worst['e2e_ms'],
               ', '.join('%s %.3f' % (k, v) for k, v
                         in worst['stage_ms'].items())))
    input_bound = input_bound_stats(step_table(spans))
    if input_bound is not None and input_bound['input_bound']:
        # the input twin of the straggler-phase attribution: the
        # dominating phase is host-side batch prep, so the fix is
        # loader capacity (workers/prefetch), not the device
        summary.append(
            'input-bound: rank %d host_batch_prep p50 %.1f ms >= '
            'jitted_step p50 %.1f ms (%.0f%% of the step) -- scale '
            'the streaming loader (n_workers/prefetch), the device '
            'is idle waiting on data'
            % (input_bound['rank'],
               input_bound['host_batch_prep_p50_ms'],
               input_bound['jitted_step_p50_ms'],
               input_bound['input_fraction'] * 100))
    if healthy:
        summary.append('no cross-rank skew, stragglers, anomalies or '
                       'deaths detected')
    return {
        'outdir': outdir,
        'ranks': ranks,
        'n_spans': len(spans),
        'n_events': len(events),
        'n_flight_records': len(flights),
        'serve': serve,
        'requests': requests,
        'n_unparseable_lines': bad,
        'collective_skew': skew,
        'stragglers': stragglers,
        'step_anomalies': anomalies,
        'input_bound': input_bound,
        'crash': crash,
        'protocol_divergence': protocol,
        'verdict': {
            'healthy': healthy,
            'dead_ranks': dead,
            'protocol_divergence': protocol,
            'straggler_rank': (None if straggler is None
                               else straggler['rank']),
            'straggler_phase': (None if straggler is None
                                else straggler['phase']),
            'summary': summary,
        },
    }


def quick_verdict(outdir, liveness_dirs=()):
    """Library-callable doctor: the full :func:`diagnose` dict for a
    capture directory, or ``None`` when there is nothing to diagnose
    (missing directory, or a capture with no spans, events or flight
    records).  NEVER raises -- this is the supervisor's cross-check
    path, and a torn capture from a freshly killed pod must degrade
    to "no doctor opinion", not crash the component whose whole job
    is surviving that death."""
    try:
        if not os.path.isdir(outdir):
            return None
        diag = diagnose(outdir, liveness_dirs=liveness_dirs)
        if not (diag['n_spans'] or diag['n_events']
                or diag['n_flight_records'] or diag['serve']):
            return None
        return diag
    except Exception:
        return None


def skew_summary(spans):
    """The two bench-row fields (``collective_skew_p99_ms`` /
    ``straggler_rank``) from a span list -- honest Nones on
    single-rank or unpaired captures."""
    skew = collective_skew(spans)
    stragglers = find_stragglers(spans, skew)
    return {
        'collective_skew_p99_ms': (None if skew is None
                                   else skew['skew_ms']['p99']),
        'straggler_rank': (stragglers[0]['rank'] if stragglers
                           else None),
    }


def render_doctor_text(diag):
    lines = ['telemetry doctor: %s' % diag['outdir'],
             'ranks: %s   spans: %d   events: %d   flight records: %d'
             % (diag['ranks'], diag['n_spans'], diag['n_events'],
                diag['n_flight_records'])]
    skew = diag['collective_skew']
    if skew is None:
        lines.append('collective skew: no paired collective spans '
                     '(single rank, or capture predates seq tagging)')
    else:
        lines.append(
            'collective skew over %d paired collectives: p50 %.3f ms  '
            'p99 %.3f ms  max %.3f ms'
            % (skew['paired'], skew['skew_ms']['p50'],
               skew['skew_ms']['p99'], skew['skew_ms']['max']))
        for r, st in sorted(skew['per_rank'].items()):
            lines.append(
                '  rank %d: latest in %5.1f%% of collectives, mean '
                'lateness %8.3f ms%s'
                % (r, st['late_fraction'] * 100, st['mean_late_ms'],
                   '  [CHRONIC]' if st['chronic'] else ''))
        for row in skew['worst'][:4]:
            lines.append(
                '  widest: %s seq %s  skew %.3f ms  (rank %d last)'
                % (row['name'], row['seq'], row['skew_ms'],
                   row['late_rank']))
    serve = diag.get('serve')
    if serve:
        lat = serve.get('latency_ms') or {}
        lines.append(
            'serving: %.0f requests / %.0f batches, %.0f shed%s'
            % (serve['requests'], serve['batches'], serve['shed'],
               '  (latency p50 %.3f ms  p99 %.3f ms)'
               % (lat['p50'], lat['p99'])
               if lat.get('p50') is not None else ''))
    for s in diag['stragglers']:
        lines.append('straggler: rank %d (%s, phase: %s)'
                     % (s['rank'], s['evidence'],
                        s['phase'] or 'unattributed'))
    for a in diag['step_anomalies'][:6]:
        lines.append(
            'anomaly: iteration %d rank %d %s %.3f ms (median %.3f, '
            'z=%.1f)' % (a['iteration'], a['rank'], a['phase'],
                         a['value_ms'], a['median_ms'], a['z']))
    crash = diag['crash']
    for r in crash['dead_ranks']:
        info = crash['per_rank'][r]
        lines.append('dead: rank %d -- %s' % (r, '; '.join(info['why'])))
        if info.get('last_collective'):
            last = info['last_collective']
            lines.append('  last completed collective: %s seq %s'
                         % (last.get('name'), last.get('seq')))
    for r, info in sorted(crash['per_rank'].items()):
        for b in info.get('blocked_in', []):
            lines.append('blocked: rank %d in %s (%s)' % (
                r, b.get('name'),
                ', '.join('%s=%s' % (k, v) for k, v in sorted(b.items())
                          if k not in ('name', 'kind', 't0'))))
    protocol = diag.get('protocol_divergence')
    if protocol is not None:
        lines.append('protocol divergence: first divergent position '
                     '%d (%s)' % (protocol['position'],
                                  protocol['kind']))
        for r, info in sorted(protocol['ranks'].items()):
            lines.append('  rank %s: %s   around: %s'
                         % (r, info['op'] or '<stream ended>',
                            ' '.join(info['context'])
                            or '(stream ended)'))
    lines.append('verdict: %s' % ('HEALTHY' if diag['verdict']['healthy']
                                  else 'UNHEALTHY'))
    for s in diag['verdict']['summary']:
        lines.append('  - %s' % s)
    return '\n'.join(lines)


def export(outdir, diag=None, liveness_dirs=()):
    """Write ``doctor_report.json`` next to the per-rank logs and
    return the diagnosis."""
    diag = diag or diagnose(outdir, liveness_dirs=liveness_dirs)
    path = os.path.join(outdir, 'doctor_report.json')
    tmp = path + '.tmp'
    with open(tmp, 'w') as f:
        json.dump(diag, f, indent=1, default=repr)
    os.replace(tmp, path)
    return diag
