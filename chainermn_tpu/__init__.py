"""ChainerMN-TPU: a TPU-native distributed deep-learning framework.

A from-scratch rebuild of the capability surface of ChainerMN (the
multi-node distributed-training extension for Chainer; reference public
API at ``chainermn/__init__.py:1-10``) designed for TPUs: SPMD over
``jax.sharding.Mesh``, XLA collectives over ICI/DCN, ``shard_map``/``pjit``
for parallelism, and Pallas kernels for hot ops.

Public API (parity with the reference's five entry points, plus the
TPU-native extras):

- :func:`create_communicator` -- mesh-backed communicator factory
- :func:`scatter_dataset` -- per-process dataset partitioning
- :class:`MultiNodeChainList` -- model-parallel stage container
- :func:`create_multi_node_evaluator` -- cross-replica metric averaging
- :func:`create_multi_node_optimizer` -- gradient-allreduce optimizer wrapper
"""

from chainermn_tpu.utils import jax_compat as _jax_compat

_jax_compat.ensure()

from chainermn_tpu.communicators import create_communicator  # noqa
from chainermn_tpu.communicators.base import CommunicatorBase  # noqa
from chainermn_tpu.dataset import scatter_dataset  # noqa
from chainermn_tpu.datasets import create_empty_dataset  # noqa
from chainermn_tpu.link import MultiNodeChainList  # noqa
from chainermn_tpu.multi_node_evaluator import create_multi_node_evaluator  # noqa
from chainermn_tpu.multi_node_optimizer import create_multi_node_optimizer  # noqa
from chainermn_tpu import precision  # noqa
from chainermn_tpu.precision import Policy  # noqa
from chainermn_tpu import telemetry  # noqa
from chainermn_tpu import utils  # noqa

__version__ = '0.1.0'
