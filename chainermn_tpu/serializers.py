"""Checkpoint serialization.

The reference delegates checkpoint/resume entirely to Chainer's npz
serializers (``--resume`` -> ``chainer.serializers.load_npz``,
``train_mnist.py:44-45,117-118``).  Parity surface: :func:`save_npz` /
:func:`load_npz` over arbitrary pytrees.  TPU-plus surface:
:func:`save_checkpoint` / :func:`restore_checkpoint` via orbax, which
writes sharded arrays per host (the genuine gap SURVEY.md 5 flags:
rank-aware snapshots the reference never had).
"""

import os

import jax
import numpy as np


def _flatten_with_names(tree):
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    out = {}
    for path, leaf in flat:
        key = '/'.join(str(getattr(p, 'key', getattr(p, 'idx', p)))
                       for p in path) or '_root'
        out[key] = np.asarray(leaf)
    return out, treedef


_WIDTH_EQUIV = {2: np.uint16, 1: np.uint8, 4: np.uint32}


def _to_native(arr):
    """numpy-native view of an array; ml_dtypes (bfloat16, fp8, ...)
    are stored as same-width unsigned ints with the dtype name carried
    in the key."""
    if arr.dtype.kind in 'fiubc':
        return arr, None
    equiv = _WIDTH_EQUIV[arr.dtype.itemsize]
    return arr.view(equiv), arr.dtype.name


def save_npz(path, tree):
    """Write a pytree to ``path``(.npz), keys = tree paths."""
    arrays, _ = _flatten_with_names(tree)
    stored = {}
    for key, arr in arrays.items():
        native, dtype_name = _to_native(arr)
        stored[key if dtype_name is None
               else key + '::' + dtype_name] = native
    if not path.endswith('.npz'):
        path = path + '.npz'
    with open(path, 'wb') as f:
        np.savez(f, **stored)
    return path


def load_npz(path, template):
    """Read arrays saved by :func:`save_npz` back into ``template``'s
    structure (dtypes/shapes validated leaf-by-leaf)."""
    if not path.endswith('.npz') and not os.path.exists(path):
        path = path + '.npz'
    with np.load(path) as data:
        by_key = {}
        for stored_key in data.files:
            key, _, dtype_name = stored_key.partition('::')
            arr = data[stored_key]
            if dtype_name:
                import ml_dtypes
                arr = arr.view(np.dtype(getattr(ml_dtypes, dtype_name)))
            by_key[key] = arr
        arrays, treedef = _flatten_with_names(template)
        leaves = []
        for key, tmpl in arrays.items():
            if key not in by_key:
                raise KeyError('checkpoint missing %r' % key)
            arr = by_key[key]
            if tuple(arr.shape) != tuple(tmpl.shape):
                raise ValueError('shape mismatch for %r: %r vs %r'
                                 % (key, arr.shape, tmpl.shape))
            leaves.append(arr.astype(tmpl.dtype))
        return jax.tree_util.tree_unflatten(treedef, leaves)


def updater_state(updater):
    """The canonical snapshot pytree of a live updater: params,
    optimizer state, iteration/epoch counters, plus -- when present --
    BatchNorm/model state, the pipeline's replicated prologue/epilogue
    params (``extra``) and the mixed-precision loss-scale state
    (``scale_state``, so a resumed f16 run continues at its adapted
    scale instead of re-warming from the initial one).  Single source
    of truth shared by ``extensions.snapshot()``, NanGuard's
    divergence forensics and the preemption checkpoint
    (:mod:`chainermn_tpu.training.recovery`)."""
    state = {
        'params': updater.params,
        'opt_state': updater.opt_state,
        'iteration': updater.iteration,
        'epoch': updater.epoch,
    }
    if getattr(updater, 'model_state', None) is not None:
        state['model_state'] = updater.model_state
    if getattr(updater, 'extra', None) is not None:
        state['extra'] = updater.extra
    if getattr(updater, 'scale_state', None) is not None:
        state['scale_state'] = updater.scale_state
    return state


def resume_updater(path, updater, comm=None):
    """Restore a snapshot written by ``extensions.snapshot()`` into a
    live updater: params, optimizer state, BatchNorm/model state,
    loss-scale state, and the iteration/epoch counters (so stop
    triggers and log filenames continue rather than restart).

    Every restored leaf is placed with the LIVE updater leaf's own
    sharding, so whatever layout the updater established at
    construction is preserved: replicated (``StandardUpdater``),
    mesh-sharded optimizer state (``zero=True``), stage-sharded
    pipeline params (``PipelineUpdater``).  The loaded host arrays
    never alias device buffers, so donation stays safe.  ``comm`` is
    accepted for backward compatibility and unused."""
    template = dict(updater_state(updater), iteration=0, epoch=0)
    try:
        state = load_npz(path, template)
    except KeyError:
        if 'scale_state' not in template:
            raise
        # checkpoints written before loss-scale state was snapshot
        # (or by a non-policy run) restore everything else; the live
        # scale state is kept as-is
        template.pop('scale_state')
        state = load_npz(path, template)

    def place(new_tree, cur_tree):
        return jax.tree_util.tree_map(
            lambda new, cur: (jax.device_put(new, cur.sharding)
                              if isinstance(cur, jax.Array) else new),
            new_tree, cur_tree)

    updater.params = place(state['params'], updater.params)
    updater.opt_state = place(state['opt_state'], updater.opt_state)
    if 'model_state' in template:
        updater.model_state = place(state['model_state'],
                                    updater.model_state)
    if 'extra' in template:
        updater.extra = place(state['extra'], updater.extra)
    if 'scale_state' in state:
        updater.scale_state = place(state['scale_state'],
                                    updater.scale_state)
    updater.iteration = int(state['iteration'])
    it = updater.iterator
    if hasattr(it, 'restore_epoch'):
        it.restore_epoch(int(state['epoch']))
    elif hasattr(it, 'epoch'):
        it.epoch = int(state['epoch'])
    return state


_async_ckptr = None


def save_checkpoint(directory, tree, step=0, async_=False):
    """Sharded checkpoint via orbax (each host writes its shards).

    ``async_=True`` returns as soon as the device arrays are snapshot
    to host memory and writes to disk on a background thread --
    training resumes immediately instead of stalling on filesystem
    I/O.  A subsequent async save (or :func:`wait_checkpoints`) joins
    the previous write first, so at most one write is in flight and
    ordering is preserved.
    """
    import orbax.checkpoint as ocp
    directory = os.path.abspath(directory)
    path = os.path.join(directory, str(step))
    if async_:
        global _async_ckptr
        if _async_ckptr is None:
            import atexit
            _async_ckptr = ocp.AsyncCheckpointer(
                ocp.PyTreeCheckpointHandler())
            atexit.register(wait_checkpoints)
        _async_ckptr.save(path, tree, force=True)
        return directory
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(path, tree, force=True)
    return directory


def wait_checkpoints():
    """Block until any in-flight async checkpoint write has committed
    (call before reading a just-saved step or at shutdown; the atexit
    hook does the latter automatically)."""
    if _async_ckptr is not None:
        _async_ckptr.wait_until_finished()


def restore_checkpoint(directory, template, step=0):
    wait_checkpoints()  # never read a step whose write is in flight
    import orbax.checkpoint as ocp
    ckptr = ocp.PyTreeCheckpointer()
    return ckptr.restore(os.path.join(os.path.abspath(directory),
                                      str(step)), item=template)
