"""Checkpoint serialization and the checkpoint trust layer.

The reference delegates checkpoint/resume entirely to Chainer's npz
serializers (``--resume`` -> ``chainer.serializers.load_npz``,
``train_mnist.py:44-45,117-118``).  Parity surface: :func:`save_npz` /
:func:`load_npz` over arbitrary pytrees.  TPU-plus surface:
:func:`save_checkpoint` / :func:`restore_checkpoint` via orbax, which
writes sharded arrays per host (the genuine gap SURVEY.md 5 flags:
rank-aware snapshots the reference never had).

On top of both sits an integrity layer (SURVEY 5's elastic-resume
gap): every snapshot carries a **topology-tagged manifest** -- world
size, device count, mesh shape, per-leaf shape/dtype/crc32 and a
write-complete sentinel -- npz writes are **atomic** (tmp + rename,
so a crash mid-write never leaves a torn file under the final name),
:func:`verify_checkpoint` probes a snapshot without restoring it, and
every integrity failure raises the typed
:class:`~chainermn_tpu.utils.failure.CheckpointCorruptError` naming
the offending leaf instead of a bare ``KeyError`` /
``zipfile.BadZipFile`` deep inside npz internals.
:func:`resume_updater` is **elastic**: a checkpoint written at N
processes restores at M -- ZeRO-1 optimizer partitions are regathered
and re-split (:func:`chainermn_tpu.parallel.zero.reshard_stacked_state`),
replicated state is re-placed through
``placement.multihost_device_put``, and the iterator's epoch position
is re-expressed at the new shard size.  See
``docs/fault_tolerance.md``.
"""

import json
import os
import zlib

import jax
import numpy as np

from chainermn_tpu.utils import chaos as _chaos
from chainermn_tpu.utils import failure as _failure

#: Reserved npz key holding the JSON manifest (uint8 bytes); user
#: trees must not use it as a top-level leaf name.
MANIFEST_KEY = '__manifest__'

MANIFEST_FORMAT = 1


def _flatten_with_names(tree):
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    out = {}
    for path, leaf in flat:
        key = '/'.join(str(getattr(p, 'key', getattr(p, 'idx', p)))
                       for p in path) or '_root'
        out[key] = np.asarray(leaf)
    return out, treedef


def _flatten_spec(tree):
    """Like :func:`_flatten_with_names` but WITHOUT materializing
    leaves on the host -- safe for templates whose arrays are sharded
    across processes (only ``.shape``/``.dtype`` are read)."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    out = {}
    for path, leaf in flat:
        key = '/'.join(str(getattr(p, 'key', getattr(p, 'idx', p)))
                       for p in path) or '_root'
        out[key] = leaf
    return out, treedef


def _leaf_shape(leaf):
    return tuple(getattr(leaf, 'shape', np.shape(leaf)))


def _leaf_dtype(leaf):
    dt = getattr(leaf, 'dtype', None)
    return np.dtype(dt) if dt is not None else np.asarray(leaf).dtype


_WIDTH_EQUIV = {2: np.uint16, 1: np.uint8, 4: np.uint32}


def _to_native(arr):
    """numpy-native view of an array; ml_dtypes (bfloat16, fp8, ...)
    are stored as same-width unsigned ints with the dtype name carried
    in the key."""
    if arr.dtype.kind in 'fiubc':
        return arr, None
    equiv = _WIDTH_EQUIV[arr.dtype.itemsize]
    return arr.view(equiv), arr.dtype.name


def _corrupt(message, path, leaf, kind):
    return _failure.CheckpointCorruptError(
        '%s [snapshot %s]' % (message, path), path=path, leaf=leaf,
        kind=kind)


def _manifest(leaves, mesh_shape=None):
    return {
        'format': MANIFEST_FORMAT,
        'complete': True,
        'world_size': jax.process_count(),
        'device_count': jax.device_count(),
        'mesh_shape': (dict(mesh_shape) if mesh_shape is not None
                       else None),
        'leaves': leaves,
    }


def save_npz(path, tree, mesh_shape=None):
    """Write a pytree to ``path``(.npz), keys = tree paths.

    The file additionally carries a topology-tagged manifest under
    :data:`MANIFEST_KEY` -- world size, device count, ``mesh_shape``
    (pass ``dict(comm.mesh.shape)`` to record it), per-leaf
    shape/dtype/crc32 and the write-complete sentinel -- and is
    written ATOMICALLY (temp file + ``os.replace``), so a crash
    mid-write can never leave a torn snapshot under the final name.
    """
    arrays, _ = _flatten_with_names(tree)
    stored, leaves = {}, {}
    for key, arr in arrays.items():
        native, dtype_name = _to_native(arr)
        stored[key if dtype_name is None
               else key + '::' + dtype_name] = native
        leaves[key] = {
            'shape': list(arr.shape),
            'dtype': str(arr.dtype),
            'crc32': zlib.crc32(
                np.ascontiguousarray(native).tobytes()),
        }
    blob = json.dumps(_manifest(leaves, mesh_shape)).encode()
    stored[MANIFEST_KEY] = np.frombuffer(blob, np.uint8)
    if not path.endswith('.npz'):
        path = path + '.npz'
    tmp = path + '.tmp'
    with open(tmp, 'wb') as f:
        np.savez(f, **stored)
        f.flush()
        os.fsync(f.fileno())
    if _chaos._active is not None:  # ckpt_kill: crash mid-write
        _chaos.on_checkpoint_write(tmp)
    os.replace(tmp, path)
    if _chaos._active is not None:  # ckpt_truncate / ckpt_flip
        _chaos.corrupt_checkpoint(path)
    return path


def read_npz(path, verify=True):
    """Read a :func:`save_npz` file into ``({key: array}, manifest)``.

    ``manifest`` is ``None`` for legacy (pre-manifest) files.  Every
    integrity failure -- zero-byte/truncated/unreadable file, a leaf
    the manifest lists but the archive lacks, a per-leaf crc32
    mismatch (bit rot) -- raises the typed
    :class:`~chainermn_tpu.utils.failure.CheckpointCorruptError`.  A
    MISSING file raises ``OSError`` unchanged: absence is a lookup
    problem, not corruption.
    """
    if not path.endswith('.npz') and not os.path.exists(path):
        path = path + '.npz'
    if os.path.getsize(path) == 0:
        raise _corrupt('zero-byte snapshot', path, None, 'unreadable')
    by_key, crcs, manifest = {}, {}, None
    try:
        with np.load(path) as data:
            if MANIFEST_KEY in data.files:
                manifest = json.loads(bytes(data[MANIFEST_KEY]))
            for stored_key in data.files:
                if stored_key == MANIFEST_KEY:
                    continue
                key, _, dtype_name = stored_key.partition('::')
                arr = data[stored_key]
                if verify and manifest is not None:
                    crcs[key] = zlib.crc32(
                        np.ascontiguousarray(arr).tobytes())
                if dtype_name:
                    import ml_dtypes
                    arr = arr.view(
                        np.dtype(getattr(ml_dtypes, dtype_name)))
                by_key[key] = arr
    except _failure.CheckpointCorruptError:
        raise
    except Exception as e:
        raise _corrupt('unreadable snapshot (%s: %s)'
                       % (type(e).__name__, e), path, None,
                       'unreadable')
    if verify and manifest is not None:
        for key, meta in manifest.get('leaves', {}).items():
            if key not in by_key:
                raise _corrupt(
                    'manifest lists leaf %r but the archive lacks it'
                    % key, path, key, 'missing')
            if 'crc32' in meta and crcs.get(key) != meta['crc32']:
                raise _corrupt(
                    'crc32 mismatch for leaf %r (bit rot or torn '
                    'write)' % key, path, key, 'crc')
    return by_key, manifest


def _fetch_tree(by_key, template, prefix, path, strict_shapes=True,
                optional=False):
    """Assemble ``template``'s structure from flat ``by_key`` arrays
    under ``prefix``, with typed per-leaf shape/dtype validation.
    ``strict_shapes=False`` admits shape mismatches (the elastic ZeRO
    path reshards them afterwards); dtype is always strict.
    ``optional=True`` returns None when the subtree is absent."""
    spec, treedef = _flatten_spec(template)
    leaves = []
    for key, tmpl in spec.items():
        if not prefix:
            fkey = key
        else:
            fkey = prefix if key == '_root' else prefix + '/' + key
        if fkey not in by_key:
            if optional:
                return None
            raise _corrupt('checkpoint is missing leaf %r' % fkey,
                           path, fkey, 'missing')
        arr = by_key[fkey]
        tshape = _leaf_shape(tmpl)
        if strict_shapes and tuple(arr.shape) != tshape:
            raise _corrupt(
                'shape mismatch for %r: snapshot %r vs template %r'
                % (fkey, tuple(arr.shape), tshape), path, fkey,
                'shape')
        tdtype = _leaf_dtype(tmpl)
        if np.dtype(arr.dtype) != tdtype:
            raise _corrupt(
                'dtype mismatch for %r: snapshot %s vs template %s'
                % (fkey, arr.dtype, tdtype), path, fkey, 'dtype')
        leaves.append(arr)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def load_npz(path, template):
    """Read arrays saved by :func:`save_npz` back into ``template``'s
    structure.  Shapes and dtypes are validated leaf-by-leaf against
    the template; any mismatch -- like any file-level corruption --
    raises the typed
    :class:`~chainermn_tpu.utils.failure.CheckpointCorruptError`
    naming the offending leaf path."""
    by_key, _ = read_npz(path)
    return _fetch_tree(by_key, template, '', path)


def checkpoint_complete(path):
    """Cheap validity probe (no array data read): True iff ``path``
    is a snapshot whose write COMMITTED -- a non-empty npz carrying
    the manifest sentinel, or an orbax step dir whose manifest
    sidecar exists and is complete.  A crash mid-write fails this
    (tmp+rename means the final npz name never exists; the orbax
    sidecar is written only after the save commits), so
    ``latest_snapshot`` can never select a torn or half-written file
    -- even outside elastic mode."""
    try:
        if os.path.isdir(path):
            d, step = os.path.split(os.path.abspath(path))
            m = read_orbax_manifest(d, step)
            return bool(m and m.get('complete'))
        p = path
        if not p.endswith('.npz') and not os.path.exists(p):
            p = p + '.npz'
        if os.path.getsize(p) == 0:
            return False
        with np.load(p) as data:
            if MANIFEST_KEY not in data.files:
                return False
            m = json.loads(bytes(data[MANIFEST_KEY]))
            return bool(m.get('complete'))
    except Exception:
        return False


def verify_checkpoint(path, template=None):
    """Full integrity probe WITHOUT restoring; returns the manifest.

    npz: the file must unzip, carry a complete manifest, and every
    manifest leaf must match its stored crc32 (bit-rot detection);
    with ``template``, per-leaf shape/dtype are checked too.  orbax
    step dirs: the manifest sidecar must exist and be complete
    (per-shard content is orbax's own job at restore time); with
    ``template``, leaf specs are checked against the manifest.  Any
    failure raises the typed
    :class:`~chainermn_tpu.utils.failure.CheckpointCorruptError`.
    """
    if os.path.isdir(path):
        d, step = os.path.split(os.path.abspath(path))
        manifest = read_orbax_manifest(d, step)
        if not (manifest and manifest.get('complete')):
            raise _corrupt(
                'missing or incomplete manifest sidecar (torn or '
                'legacy orbax snapshot)', path, None, 'incomplete')
        if template is not None:
            _check_template(manifest, template, path)
        return manifest
    by_key, manifest = read_npz(path)  # crc-checked
    if not (manifest and manifest.get('complete')):
        raise _corrupt(
            'no write-complete manifest sentinel (legacy or torn '
            'snapshot)', path, None, 'incomplete')
    if template is not None:
        _fetch_tree(by_key, template, '', path)
    return manifest


def _check_template(manifest, template, path):
    spec, _ = _flatten_spec(template)
    leaves = manifest.get('leaves', {})
    for key, tmpl in spec.items():
        meta = leaves.get(key)
        if meta is None:
            raise _corrupt('checkpoint is missing leaf %r' % key,
                           path, key, 'missing')
        if list(meta.get('shape', [])) != list(_leaf_shape(tmpl)):
            raise _corrupt(
                'shape mismatch for %r: snapshot %r vs template %r'
                % (key, meta.get('shape'), list(_leaf_shape(tmpl))),
                path, key, 'shape')
        if meta.get('dtype') != str(_leaf_dtype(tmpl)):
            raise _corrupt(
                'dtype mismatch for %r: snapshot %s vs template %s'
                % (key, meta.get('dtype'), _leaf_dtype(tmpl)),
                path, key, 'dtype')


def updater_state(updater):
    """The canonical snapshot pytree of a live updater: params,
    optimizer state, iteration/epoch counters, the fractional
    ``epoch_detail`` (so an ELASTIC resume can re-express the
    in-epoch position at a different shard size), plus -- when
    present -- BatchNorm/model state, the pipeline's replicated
    prologue/epilogue params (``extra``) and the mixed-precision
    loss-scale state (``scale_state``, so a resumed f16 run continues
    at its adapted scale instead of re-warming from the initial one).
    Single source of truth shared by ``extensions.snapshot()``,
    NanGuard's divergence forensics and the preemption checkpoint
    (:mod:`chainermn_tpu.training.recovery`)."""
    state = {
        'params': updater.params,
        'opt_state': updater.opt_state,
        'iteration': updater.iteration,
        'epoch': updater.epoch,
        'epoch_detail': float(getattr(updater, 'epoch_detail', 0.0)),
    }
    # streaming-loader cursor (chainermn_tpu.data): the EXACT global
    # stream position, so an N->M elastic resume replays the
    # remaining sample sequence with no repeats and no drops --
    # epoch_detail alone only lands "nearby" after rounding
    cursor = getattr(getattr(updater, 'iterator', None),
                     'stream_cursor', None)
    if cursor is not None:
        state['stream_cursor'] = int(cursor)
    if getattr(updater, 'model_state', None) is not None:
        state['model_state'] = updater.model_state
    if getattr(updater, 'extra', None) is not None:
        state['extra'] = updater.extra
    if getattr(updater, 'scale_state', None) is not None:
        state['scale_state'] = updater.scale_state
    return state


def gather_replicated(tree, mesh):
    """Make every leaf of ``tree`` fully replicated -- a complete
    copy on every process -- via ONE compiled all-gather program, so
    the npz writer can ``np.asarray`` state that lives sharded across
    processes (ZeRO-1 optimizer partitions above all).  COLLECTIVE:
    every process in ``mesh`` must call this with the same tree.
    Leaves that are already addressable or replicated pass through
    untouched; a tree with none others returns as-is (zero cost in
    single-controller runs)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    flat, treedef = jax.tree_util.tree_flatten(tree)
    idx = [i for i, x in enumerate(flat)
           if isinstance(x, jax.Array)
           and not (x.is_fully_addressable or x.is_fully_replicated)]
    if not idx:
        return tree
    repl = NamedSharding(mesh, P())
    gathered = jax.jit(lambda xs: xs, out_shardings=repl)(
        [flat[i] for i in idx])
    jax.block_until_ready(gathered)
    for i, g in zip(idx, gathered):
        flat[i] = g
    return jax.tree_util.tree_unflatten(treedef, flat)


def restore_counters(updater, iteration, epoch=0, epoch_detail=None,
                     stream_cursor=None):
    """Restore the step counter and the iterator's epoch position.

    Elastic rules, most-exact first: when the snapshot carries a
    ``stream_cursor`` and the iterator supports ``restore_cursor``
    (the streaming loader), the EXACT global stream position is
    restored -- the cursor is topology-free, so an N->M resume
    replays the identical remaining sample sequence; else when
    ``epoch_detail`` is available and the iterator supports
    ``restore_position``, the GLOBAL fraction of the epoch consumed
    is preserved -- re-expressed at the CURRENT topology's shard
    length (``dataset.epoch_position``); otherwise the integer epoch
    is restored as before."""
    updater.iteration = int(iteration)
    it = getattr(updater, 'iterator', None)
    if it is None:
        return
    if stream_cursor is not None and hasattr(it, 'restore_cursor'):
        base = (int(float(epoch_detail)) if epoch_detail is not None
                else int(epoch))
        it.restore_cursor(base, int(stream_cursor))
    elif epoch_detail is not None and hasattr(it, 'restore_position'):
        it.restore_position(float(epoch_detail))
    elif hasattr(it, 'restore_epoch'):
        it.restore_epoch(int(epoch))
    elif hasattr(it, 'epoch'):
        it.epoch = int(epoch)


def _maybe_reshard_opt(saved, live_opt, updater, elastic, path):
    """``(state, resharded)``: pass the saved optimizer state through
    -- or, when leaf shapes differ and the updater runs ZeRO-1 with
    ``elastic`` on, regather+re-split the stacked partitions to the
    live mesh size (``zero.reshard_stacked_state``)."""
    mismatch = []

    def chk(s, t):
        if tuple(np.shape(s)) != _leaf_shape(t):
            mismatch.append((tuple(np.shape(s)), _leaf_shape(t)))
        return s

    jax.tree_util.tree_map(chk, saved, live_opt)
    if not mismatch:
        return saved, False
    if not (elastic and getattr(updater, '_zero', False)):
        raise _corrupt(
            'optimizer-state shape mismatch (snapshot %r vs live %r) '
            'and no elastic ZeRO-1 reshard applies -- the snapshot '
            'was written under a different topology'
            % mismatch[0], path, 'opt_state', 'shape')
    from chainermn_tpu.parallel import zero as zero_mod
    return zero_mod.reshard_stacked_state(saved, live_opt), True


def _restore_state(updater, by_key, manifest, path, elastic=True,
                   require_manifest=False):
    """Shared restore core of :func:`resume_updater` /
    :func:`restore_updater_from_tree`: fetch every component with
    typed validation, reshard ZeRO state on topology change, place
    with the LIVE updater leaf's own sharding via the multihost-safe
    path, restore counters.  Fetches everything BEFORE assigning
    anything, so a corrupt leaf never leaves the updater
    half-restored."""
    from chainermn_tpu.training.placement import multihost_device_put

    if require_manifest and not (manifest
                                 and manifest.get('complete')):
        raise _corrupt(
            'no write-complete manifest sentinel (legacy or torn '
            'snapshot)', path, None, 'incomplete')
    live = updater_state(updater)

    params = _fetch_tree(by_key, live['params'], 'params', path)
    opt = _fetch_tree(by_key, live['opt_state'], 'opt_state', path,
                      strict_shapes=False)
    opt, resharded = _maybe_reshard_opt(opt, live['opt_state'],
                                        updater, elastic, path)
    subtrees = {}
    for name in ('model_state', 'extra'):
        if live.get(name) is not None:
            subtrees[name] = _fetch_tree(by_key, live[name], name,
                                         path)
    scale = None
    if live.get('scale_state') is not None:
        # optional for backward compatibility: checkpoints written
        # before loss-scale state was snapshot (or by a non-policy
        # run) restore everything else; the live scale is kept as-is
        scale = _fetch_tree(by_key, live['scale_state'],
                            'scale_state', path, optional=True)
    if 'iteration' not in by_key:
        raise _corrupt('checkpoint is missing leaf %r' % 'iteration',
                       path, 'iteration', 'missing')

    def place(new_tree, cur_tree):
        return jax.tree_util.tree_map(
            lambda new, cur: (multihost_device_put(new, cur.sharding)
                              if isinstance(cur, jax.Array) else new),
            new_tree, cur_tree)

    updater.params = place(params, updater.params)
    updater.opt_state = place(opt, updater.opt_state)
    for name, sub in subtrees.items():
        setattr(updater, name, place(sub, getattr(updater, name)))
    if scale is not None:
        updater.scale_state = place(scale, updater.scale_state)
    detail = by_key.get('epoch_detail')
    cursor = by_key.get('stream_cursor')
    restore_counters(updater, by_key['iteration'],
                     by_key.get('epoch', 0),
                     None if detail is None else float(detail),
                     None if cursor is None else int(cursor))
    return {'iteration': updater.iteration, 'resharded': resharded,
            'manifest': manifest}


def resume_updater(path, updater, comm=None, elastic=True,
                   require_manifest=False):
    """Restore a snapshot written by ``extensions.snapshot()`` /
    :class:`~chainermn_tpu.training.recovery.PreemptionHandler` into
    a live updater: params, optimizer state, BatchNorm/model state,
    loss-scale state, and the iteration/epoch counters (so stop
    triggers and log filenames continue rather than restart).

    Every restored leaf is placed with the LIVE updater leaf's own
    sharding through the multihost-safe
    ``placement.multihost_device_put`` path, so whatever layout the
    updater established at construction is preserved: replicated
    (``StandardUpdater``), mesh-sharded optimizer state
    (``zero=True``), stage-sharded pipeline params
    (``PipelineUpdater``).  The loaded host arrays never alias device
    buffers, so donation stays safe.

    ELASTIC (default): when the snapshot was written under a
    different topology -- its stacked ZeRO-1 optimizer-state shapes
    disagree with the live mesh -- the partitions are regathered and
    re-split N->M on the host
    (:func:`chainermn_tpu.parallel.zero.reshard_stacked_state`) and
    the iterator's epoch position is re-expressed at the new shard
    size (``epoch_detail`` + ``restore_position``).
    ``elastic=False`` turns any such mismatch into the typed
    :class:`~chainermn_tpu.utils.failure.CheckpointCorruptError`.

    ``require_manifest=True`` (used by ``auto_resume``) additionally
    rejects snapshots without the write-complete manifest sentinel.
    ``comm`` is accepted for backward compatibility and unused.
    Returns ``{'iteration', 'resharded', 'manifest'}``."""
    del comm
    by_key, manifest = read_npz(path)
    return _restore_state(updater, by_key, manifest, path,
                          elastic=elastic,
                          require_manifest=require_manifest)


def restore_updater_from_tree(updater, state, manifest=None,
                              elastic=True, path=None):
    """Restore a live updater from an in-memory snapshot pytree whose
    leaves are HOST arrays (e.g. a raw orbax restore) -- same typed
    validation, elastic ZeRO reshard, multihost placement and counter
    semantics as :func:`resume_updater`."""
    by_key, _ = _flatten_with_names(state)
    return _restore_state(updater, by_key, manifest,
                          path or '<in-memory tree>', elastic=elastic)


_async_ckptr = None
_pending_manifests = []


def _orbax_manifest_path(directory, step):
    return os.path.join(os.path.abspath(directory),
                        '%s.manifest.json' % step)


def _write_orbax_manifest(directory, step, manifest):
    if jax.process_index() != 0:
        return
    mpath = _orbax_manifest_path(directory, step)
    tmp = mpath + '.tmp'
    with open(tmp, 'w') as f:
        json.dump(manifest, f)
    os.replace(tmp, mpath)


def read_orbax_manifest(directory, step):
    """The manifest sidecar of an orbax step (written by process 0
    AFTER the collective save commits -- it doubles as the
    write-complete sentinel), or ``None`` for legacy/torn steps."""
    try:
        with open(_orbax_manifest_path(directory, step)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _tree_manifest(tree, mesh_shape=None):
    spec, _ = _flatten_spec(tree)
    leaves = {key: {'shape': list(_leaf_shape(leaf)),
                    'dtype': str(_leaf_dtype(leaf))}
              for key, leaf in spec.items()}
    return _manifest(leaves, mesh_shape)


def save_checkpoint(directory, tree, step=0, async_=False,
                    mesh_shape=None):
    """Sharded checkpoint via orbax (each host writes its shards).

    ``async_=True`` returns as soon as the device arrays are snapshot
    to host memory and writes to disk on a background thread --
    training resumes immediately instead of stalling on filesystem
    I/O.  A subsequent async save (or :func:`wait_checkpoints`) joins
    the previous write first, so at most one write is in flight and
    ordering is preserved.

    Process 0 additionally writes a topology-tagged manifest sidecar
    (``<step>.manifest.json`` next to the step dir -- per-leaf
    shape/dtype, world size, device count, ``mesh_shape``) AFTER the
    write commits; it is the write-complete sentinel
    ``latest_snapshot``/``verify_checkpoint`` require, so a job
    killed mid-save can never be selected as a resume point.  For
    async saves the sidecar is deferred to the join point.
    """
    import orbax.checkpoint as ocp
    directory = os.path.abspath(directory)
    path = os.path.join(directory, str(step))
    manifest = _tree_manifest(tree, mesh_shape)
    if async_:
        global _async_ckptr
        if _async_ckptr is None:
            import atexit
            _async_ckptr = ocp.AsyncCheckpointer(
                ocp.PyTreeCheckpointHandler())
            atexit.register(wait_checkpoints)
        _async_ckptr.save(path, tree, force=True)
        _pending_manifests.append((directory, step, manifest))
        return directory
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(path, tree, force=True)
    _write_orbax_manifest(directory, step, manifest)
    return directory


def wait_checkpoints():
    """Block until any in-flight async checkpoint write has committed
    (call before reading a just-saved step or at shutdown; the atexit
    hook does the latter automatically), then write the deferred
    manifest sidecars -- the sentinel only ever describes data that
    is really on disk."""
    if _async_ckptr is not None:
        _async_ckptr.wait_until_finished()
    while _pending_manifests:
        directory, step, manifest = _pending_manifests.pop(0)
        _write_orbax_manifest(directory, step, manifest)


def restore_checkpoint(directory, template, step=0):
    """Restore an orbax step into ``template``'s structure (pass
    ``template=None`` for a raw restore to host numpy arrays -- the
    elastic path reads a checkpoint written under a DIFFERENT
    topology this way).  Unreadable/torn steps raise the typed
    :class:`~chainermn_tpu.utils.failure.CheckpointCorruptError`."""
    wait_checkpoints()  # never read a step whose write is in flight
    import orbax.checkpoint as ocp
    ckptr = ocp.PyTreeCheckpointer()
    path = os.path.join(os.path.abspath(directory), str(step))
    try:
        if template is None:
            return ckptr.restore(path)
        return ckptr.restore(path, item=template)
    except _failure.CheckpointCorruptError:
        raise
    except Exception as e:
        raise _corrupt('unreadable orbax snapshot (%s: %s)'
                       % (type(e).__name__, e), path, None,
                       'unreadable')
