"""Reduce-scatter / allgather allreduce (reference
``two_dimensional_communicator.py``).

The reference's multi-NIC strategy: NCCL ``reduce_scatter`` within the
node, per-shard inter-node allreduce so *every* GPU drives its own NIC,
then NCCL ``allgather`` (``:41-55``).  TPU mapping: scatter over the
full flattened mesh so each device owns ``1/size`` of the buffer, a
two-axis psum having been folded into the scatter+gather pair:

    psum_scatter(inter+intra) -> all_gather(inter+intra)

This is the canonical bidirectional-ring decomposition XLA uses for
large allreduces; keeping it as an explicitly staged strategy lets the
benchmark harness compare it against the single-collective ``xla``
flagship (reference keeps the same choice surface,
``communicators/__init__.py:12-20``).
"""

from jax import lax

from chainermn_tpu.communicators import memory_utility
from chainermn_tpu.communicators.base import CommunicatorBase
from chainermn_tpu.communicators.mesh_utility import AXES


class TwoDimensionalCommunicator(CommunicatorBase):

    def _allreduce_impl(self, grads):
        def reduce_buf(buf):
            buf, n = memory_utility.pad_to_multiple(buf, self.size)
            shard = lax.psum_scatter(buf, AXES, scatter_dimension=0,
                                     tiled=True)
            shard = shard / self.size
            return lax.all_gather(shard, AXES, axis=0, tiled=True)[:n]

        return memory_utility.fused_reduce(grads, reduce_buf)
