"""Host-staged hierarchical allreduce (reference
``non_cuda_aware_communicator.py``).

The reference exists because some MPI builds cannot read GPU pointers:
inter-node traffic is staged through pinned host memory (``:49-73``).
The TPU analogue of "stage across the slow link on the host" is forcing
the DCN leg of the reduction through a transfer-friendly dtype: the
intra (ICI) reduction runs at full precision, the inter (DCN) leg is
cast to float32 (or kept if already lower) so links with no native
wide-type support behave deterministically.  Functionally it is the
hierarchical strategy with an explicit DCN staging dtype.
"""

import jax.numpy as jnp
from jax import lax

from chainermn_tpu.communicators import memory_utility
from chainermn_tpu.communicators.base import CommunicatorBase
from chainermn_tpu.communicators.mesh_utility import AXIS_INTER, AXIS_INTRA


class NonCudaAwareCommunicator(CommunicatorBase):

    inter_dtype = jnp.float32

    def _allreduce_impl(self, grads):
        def reduce_buf(buf):
            buf, n = memory_utility.pad_to_multiple(buf, self.intra_size)
            shard = lax.psum_scatter(buf, AXIS_INTRA, scatter_dimension=0,
                                     tiled=True)
            # Stage the DCN leg at <= float32: narrow wide dtypes, never
            # widen (widening would double DCN bytes, the opposite of
            # what host staging is for).
            stage_dt = self.inter_dtype
            narrow = jnp.dtype(shard.dtype).itemsize > jnp.dtype(
                stage_dt).itemsize
            staged = shard.astype(stage_dt) if narrow else shard
            staged = lax.psum(staged, AXIS_INTER)
            shard = staged.astype(shard.dtype)
            buf = lax.all_gather(shard, AXIS_INTRA, axis=0, tiled=True)
            return buf[:n] / self.size

        return memory_utility.fused_reduce(grads, reduce_buf)
