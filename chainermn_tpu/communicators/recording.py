"""Recording fake communicator for cross-rank protocol verification.

:class:`RecordingCommunicator` mirrors the EAGER protocol surface of
:class:`~chainermn_tpu.communicators.base.CommunicatorBase`
(``barrier`` / ``allreduce_obj`` / ``broadcast_data`` / ``send_obj`` /
``recv_obj``) but performs NO communication: every call appends one
``(op, kind, peer/axes, tag, seq)`` record to ``self.records``,
stamped with exactly the sequence-number discipline of the real
implementation --

* ``barrier``: 1-based per-tag epoch counter (``_barrier_epochs``),
* ``allreduce_obj`` / ``broadcast_data``: 0-based per-(name, tag)
  eager-collective counter (``_next_eager_seq``),
* ``send_obj`` / ``recv_obj``: 0-based per-(peer, tag, channel) stream
  cursors, and the SAME KV key format
  (``chainermn_tpu/p2p/<channel>/<src>/<dest>/<tag>/<seq>``) the real
  channel publishes under, so the matcher in
  :mod:`chainermn_tpu.analysis.commcheck` reasons about real wire keys
  (including the rebuilt-communicator seq-0 collision hazard the
  ``_p2p_channel`` docstring warns about).

:func:`simulate_protocol` drives one protocol function once per
simulated rank and hands the per-rank record streams to
``commcheck.verify_streams`` / ``commcheck.match_p2p`` -- the SL013 /
SL014 static twins of the run-time channel.
"""

P2P_KEY_FMT = 'chainermn_tpu/p2p/%s/%d/%d/%d/%d'


class RecordingCommunicator:
    """A fake eager communicator that logs instead of communicating.

    Args:
      rank: the simulated process index this instance plays.
      size: the simulated process count (world size).
      channel: p2p channel namespace (the real communicator derives it
        from the mesh fingerprint; any stable string works here).
      records: optionally share another instance's record list -- used
        by :meth:`rebuilt` to model a communicator rebuilt over the
        same mesh (same channel, FRESH seq counters: the documented
        key-collision hazard).
    """

    def __init__(self, rank, size, channel='sim', records=None):
        self.rank = int(rank)
        self.size = int(size)
        self.channel = channel
        self.records = records if records is not None else []
        self._eager_coll_seq = {}
        self._barrier_epochs = {}
        self._send_seq = {}
        self._recv_seq = {}

    # introspection parity with CommunicatorBase
    @property
    def intra_rank(self):
        return self.rank

    def rebuilt(self):
        """A fresh communicator over the SAME channel with reset seq
        counters -- the rebuild-mid-conversation hazard
        (``base.py _p2p_channel`` docstring): its first ``send_obj``
        reuses an already-published key."""
        return RecordingCommunicator(self.rank, self.size,
                                     channel=self.channel,
                                     records=self.records)

    def _rec(self, **kw):
        kw['rank'] = self.rank
        self.records.append(kw)
        return kw

    def _next_eager_seq(self, name, tag=None):
        seqs = self._eager_coll_seq
        key = (name, tag)
        n = seqs.get(key, 0)
        seqs[key] = n + 1
        return n

    # -- eager collectives ---------------------------------------------
    def barrier(self, timeout=60.0, tag='barrier'):
        if self.size == 1:
            return
        n = self._barrier_epochs[tag] = (
            self._barrier_epochs.get(tag, 0) + 1)
        self._rec(op='barrier', kind='collective', tag=tag, seq=n)

    def allreduce_obj(self, value, op='mean', timeout=None):
        if self.size == 1:
            return value
        if timeout is not None:
            self.barrier(timeout=timeout, tag='allreduce_obj')
        self._rec(op='allreduce_obj', kind='collective', tag=None,
                  seq=self._next_eager_seq('allreduce_obj'), detail=op)
        return value

    def broadcast_data(self, params, root=0):
        # eager multihost broadcast: a local replicate on every
        # process (base.py broadcast_data) -- recorded for the stream
        # comparison but NOT a blocking rendezvous for the matcher
        self._rec(op='broadcast_data', kind='collective', tag=None,
                  seq=self._next_eager_seq('broadcast_data'),
                  detail=root)
        return params

    # -- eager p2p ------------------------------------------------------
    def send_obj(self, obj, dest, tag=0, channel=None, timeout=30.0):
        dest = int(dest)
        channel = channel if channel is not None else self.channel
        stream = (dest, tag, channel)
        seq = self._send_seq.get(stream, 0)
        self._rec(op='send_obj', kind='p2p', peer=dest, tag=tag,
                  seq=seq, channel=channel,
                  key=P2P_KEY_FMT % (channel, self.rank, dest, tag,
                                     seq))
        self._send_seq[stream] = seq + 1

    def recv_obj(self, source, tag=0, timeout=120.0, channel=None):
        source = int(source)
        channel = channel if channel is not None else self.channel
        stream = (source, tag, channel)
        seq = self._recv_seq.get(stream, 0)
        self._rec(op='recv_obj', kind='p2p', peer=source, tag=tag,
                  seq=seq, channel=channel,
                  key=P2P_KEY_FMT % (channel, source, self.rank, tag,
                                     seq))
        self._recv_seq[stream] = seq + 1
        return None


def simulate_protocol(protocol, world_size, channel='sim'):
    """``{rank: [record, ...]}`` from running ``protocol(comm)`` once
    per simulated rank of a ``world_size`` fleet.

    Each rank gets a fresh :class:`RecordingCommunicator`; the
    protocol function sees the usual eager surface (``comm.rank`` /
    ``comm.size`` / ``comm.barrier`` / ...), so REAL protocol code can
    be pointed at it unchanged.  A Python branch on ``comm.rank`` that
    adds or reorders a collective shows up as diverging streams --
    exactly what ``commcheck.verify_streams`` flags as SL013.
    """
    streams = {}
    for rank in range(world_size):
        comm = RecordingCommunicator(rank, world_size, channel=channel)
        protocol(comm)
        streams[rank] = comm.records
    return streams
