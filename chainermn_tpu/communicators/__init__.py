"""Communicator factory.

Rebuild of ``chainermn/communicators/__init__.py:1-73``: the same
name->strategy dispatch surface, mapped to mesh/collective layouts
instead of MPI/NCCL stacks.

Selection guide (parity with the reference's table at
``communicators/__init__.py:12-20``):

============== ========== ===========================================
Name           Mesh       Use case
============== ========== ===========================================
xla            2-D        flagship: let XLA lower the fused allreduce
                          (recommended; no reference equivalent)
hierarchical   2-D        explicit ICI reduce-scatter -> DCN psum ->
                          ICI all-gather (reference default)
two_dimensional 2-D       full-mesh reduce-scatter/all-gather
flat           2-D        one fused collective, no staging
naive          2-D        per-parameter pmean; CPU testing
single_node    1 host     ICI-only; asserts inter_size == 1
non_cuda_aware 2-D        hierarchical with f32-staged DCN leg
dummy          any        no communication; fusion-overhead probe
bucketed       2-D        ~25MB fused chunks in backward order: lets
                          XLA overlap collectives with the backward
                          pass (no reference equivalent)
============== ========== ===========================================
"""

from chainermn_tpu.communicators.base import CommunicatorBase  # noqa
from chainermn_tpu.communicators.bucketed_communicator import (
    BucketedCommunicator)
from chainermn_tpu.communicators.dummy_communicator import DummyCommunicator
from chainermn_tpu.communicators.flat_communicator import FlatCommunicator
from chainermn_tpu.communicators.hierarchical_communicator import (
    HierarchicalCommunicator)
from chainermn_tpu.communicators.naive_communicator import NaiveCommunicator
from chainermn_tpu.communicators.recording import (  # noqa
    RecordingCommunicator, simulate_protocol)
from chainermn_tpu.communicators.non_cuda_aware_communicator import (
    NonCudaAwareCommunicator)
from chainermn_tpu.communicators.single_node_communicator import (
    SingleNodeCommunicator)
from chainermn_tpu.communicators.two_dimensional_communicator import (
    TwoDimensionalCommunicator)
from chainermn_tpu.communicators.xla_communicator import XlaCommunicator

_COMMUNICATORS = {
    'naive': NaiveCommunicator,
    'flat': FlatCommunicator,
    'hierarchical': HierarchicalCommunicator,
    'two_dimensional': TwoDimensionalCommunicator,
    'single_node': SingleNodeCommunicator,
    'non_cuda_aware': NonCudaAwareCommunicator,
    'dummy': DummyCommunicator,
    'xla': XlaCommunicator,
    'bucketed': BucketedCommunicator,
}


def create_communicator(communicator_name='xla', mesh=None, mesh_shape=None,
                        devices=None, **kwargs):
    """Create a communicator by strategy name.

    Parity with ``chainermn.create_communicator(name, mpi_comm)``
    (reference ``communicators/__init__.py:22-34``); ``mesh``/
    ``mesh_shape``/``devices`` replace the ``mpi_comm`` argument (the
    default -- discover all global devices -- replaces
    ``MPI.COMM_WORLD``).  Extra keyword arguments pass through to the
    strategy (e.g. ``bucket_mb`` for ``'bucketed'``, or
    ``reduce_dtype='bfloat16'`` -- accepted by EVERY strategy -- to
    run gradient reductions in a narrower dtype; see
    ``CommunicatorBase.__init__`` and ``docs/mixed_precision.md``).
    """
    try:
        cls = _COMMUNICATORS[communicator_name]
    except KeyError:
        raise ValueError(
            'Unrecognized communicator: %r (choose from %s)'
            % (communicator_name, ', '.join(sorted(_COMMUNICATORS))))
    return cls(mesh=mesh, mesh_shape=mesh_shape, devices=devices,
               **kwargs)
