"""Intra-node-only allreduce (reference ``single_node_communicator.py``).

The reference is pure-NCCL and asserts it runs on one node
(``single_node_communicator.py:13-15``).  Ours reduces over the ICI
(``intra``) axis only and asserts ``inter_size == 1`` at construction,
exactly mirroring that contract.
"""

from jax import lax

from chainermn_tpu.communicators import memory_utility
from chainermn_tpu.communicators.base import CommunicatorBase
from chainermn_tpu.communicators.mesh_utility import AXIS_INTRA


class SingleNodeCommunicator(CommunicatorBase):

    reduction_axes = (AXIS_INTRA,)

    def __init__(self, mesh=None, mesh_shape=None, devices=None,
                 reduce_dtype=None):
        super().__init__(mesh, mesh_shape, devices,
                         reduce_dtype=reduce_dtype)
        if self.inter_size != 1:
            raise ValueError(
                'SingleNodeCommunicator requires inter_size == 1 '
                '(got %d); use hierarchical/xla for multi-host meshes'
                % self.inter_size)

    def _allreduce_impl(self, grads):
        return memory_utility.fused_reduce(
            grads, lambda buf: lax.pmean(buf, AXIS_INTRA))
