"""Bucketed allreduce: fused chunks sized for compute/comm overlap.

TPU-native extension beyond the reference's strategy set (its closest
relatives are ``flat`` -- one giant buffer, reference
``flat_communicator.py:19-39`` -- and ``naive`` -- one collective per
leaf).  Both extremes lose overlap: a single flat buffer cannot start
reducing until EVERY gradient of the backward pass exists, while
per-leaf collectives drown small tensors in per-collective latency.

The modern middle ground (the bucketing every DDP-style framework
converged on): pack leaves in backward-completion order -- the model's
reversed leaf order, since backprop produces last-layer gradients
first -- into ~``bucket_mb`` fused buffers, one ``pmean`` per bucket.
Inside the single jitted train step XLA sees each bucket's psum depend
only on that bucket's gradients, so its latency-hiding scheduler can
launch the first buckets' collectives while the backward pass is still
computing earlier layers' gradients, and overlap buckets with one
another on the ICI.

Buckets group by dtype first (mixed-precision models must not share a
buffer across dtypes), then split at the size threshold.
"""

import jax
import jax.numpy as jnp
from jax import lax

from chainermn_tpu.communicators import memory_utility
from chainermn_tpu.communicators.base import CommunicatorBase
from chainermn_tpu.communicators.mesh_utility import AXES


class BucketedCommunicator(CommunicatorBase):

    def __init__(self, mesh=None, mesh_shape=None, devices=None,
                 bucket_mb=25.0, reduce_dtype=None):
        super().__init__(mesh, mesh_shape, devices,
                         reduce_dtype=reduce_dtype)
        if bucket_mb <= 0:
            raise ValueError('bucket_mb must be positive')
        self.bucket_bytes = int(bucket_mb * 1e6)

    def plan_buckets(self, leaves):
        """Partition leaf indices into fused buckets: backward-
        completion order (reversed leaf order approximates "last layer
        first", letting early buckets close early), one OPEN bucket
        per dtype -- interleaved mixed-precision leaf orders (bf16
        weights alternating with f32 norm scales) must still fuse into
        big buckets, not flush on every dtype flip -- split at
        ``bucket_bytes``."""
        buckets = []       # list of lists of leaf indices
        open_buckets = {}  # dtype -> (indices, bytes)
        for i in reversed(range(len(leaves))):
            leaf = leaves[i]
            dt = jnp.dtype(leaf.dtype)
            nbytes = leaf.size * dt.itemsize
            cur, cur_bytes = open_buckets.get(dt, ([], 0))
            if cur and cur_bytes + nbytes > self.bucket_bytes:
                buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append(i)
            open_buckets[dt] = (cur, cur_bytes + nbytes)
        for cur, _ in open_buckets.values():
            if cur:
                buckets.append(cur)
        return buckets

    def _allreduce_impl(self, grads):
        if not jax.tree_util.tree_leaves(grads):
            return grads
        return memory_utility.fused_reduce(
            grads, lambda buf: lax.pmean(buf, AXES),
            plan=self.plan_buckets)
