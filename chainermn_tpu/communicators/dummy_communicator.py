"""No-communication communicator (reference ``dummy_communicator.py``).

Runs the full pack/unpack path but performs no collective, so measured
step time isolates fusion overhead from communication -- the same
measurement purpose as the reference (``dummy_communicator.py:8-12``),
and like the reference it does not produce correct training results on
more than one device.
"""

from chainermn_tpu.communicators import memory_utility
from chainermn_tpu.communicators.base import CommunicatorBase


class DummyCommunicator(CommunicatorBase):

    reduction_axes = ()

    def _allreduce_impl(self, grads):
        return memory_utility.fused_reduce(grads, lambda buf: buf)
