"""Two-level ICI-then-DCN allreduce (reference default,
``hierarchical_communicator.py``).

The reference reduces within each node over NCCL, allreduces across node
roots over MPI, then broadcasts within nodes (``:37-53``).  The TPU
mapping: reduce-scatter + regather staged so the *intra* (ICI) axis
carries the bulk of the traffic and the *inter* (DCN) axis moves only
the already-reduced values once:

    psum_scatter(intra) -> psum(inter) -> all_gather(intra)

Each device ships ``1/intra_size`` of the buffer over DCN -- the same
bandwidth shape as the reference's node-root chunking
(``hierarchical_communicator.py:27-29``), but with the inter-node
traffic spread over every device's DCN link instead of one root.
"""

from jax import lax

from chainermn_tpu.communicators import memory_utility
from chainermn_tpu.communicators.base import CommunicatorBase
from chainermn_tpu.communicators.mesh_utility import AXIS_INTER, AXIS_INTRA


class HierarchicalCommunicator(CommunicatorBase):

    def _allreduce_impl(self, grads):
        def reduce_buf(buf):
            buf, n = memory_utility.pad_to_multiple(buf, self.intra_size)
            shard = lax.psum_scatter(buf, AXIS_INTRA, scatter_dimension=0,
                                     tiled=True)
            shard = lax.psum(shard, AXIS_INTER)
            buf = lax.all_gather(shard, AXIS_INTRA, axis=0, tiled=True)
            return buf[:n] / self.size

        return memory_utility.fused_reduce(grads, reduce_buf)
