"""Fused single-collective allreduce (reference ``flat_communicator.py``).

The reference packs every gradient into ONE contiguous device buffer and
performs a single CUDA-aware MPI ``Allreduce`` over it
(``flat_communicator.py:19-39``).  Ours keeps that exact shape: all
leaves are promoted to one common dtype and fused into a single buffer
for a single ``pmean`` -- one collective total, maximal fusion, at the
cost of upcasting narrow dtypes in mixed-precision models.  (Contrast
``xla``, which fuses per dtype: no upcast, one collective per dtype.)
Original dtypes are restored on unpack.
"""

import jax
import jax.numpy as jnp
from jax import lax

from chainermn_tpu.communicators import memory_utility
from chainermn_tpu.communicators.base import CommunicatorBase
from chainermn_tpu.communicators.mesh_utility import AXES


class FlatCommunicator(CommunicatorBase):

    def _allreduce_impl(self, grads):
        leaves = jax.tree_util.tree_leaves(grads)
        if not leaves:
            return grads
        common = leaves[0].dtype
        for leaf in leaves[1:]:
            common = jnp.promote_types(common, leaf.dtype)
        buf, schema = memory_utility.pack_params(grads, dtype=common)
        buf = lax.pmean(buf, AXES)
        return memory_utility.unpack_params(buf, schema)
