"""Fused single-collective allreduce (reference ``flat_communicator.py``).

The reference packs every gradient into one contiguous device buffer and
performs a single CUDA-aware MPI ``Allreduce`` over it
(``flat_communicator.py:19-39``).  Here the fusion is a traced
concatenate (:mod:`memory_utility`) followed by one flat ``pmean`` over
the whole mesh -- one large collective instead of many small ones,
which amortizes ICI latency for many-parameter models (the reference's
"tensor fusion stress" case, VGG-16).
"""

from jax import lax

from chainermn_tpu.communicators import memory_utility
from chainermn_tpu.communicators.base import CommunicatorBase
from chainermn_tpu.communicators.mesh_utility import AXES


class FlatCommunicator(CommunicatorBase):

    def _allreduce_impl(self, grads):
        return memory_utility.fused_reduce(
            grads, lambda buf: lax.pmean(buf, AXES))
