"""Flagship XLA communicator -- the ``north_star`` backend.

One fused ``pmean`` over the whole mesh, no manual staging: XLA's
topology-aware collective lowering picks the algorithm (bidirectional
rings on ICI, hierarchical over DCN) per buffer size and mesh shape.
This is the strategy the reference could not have -- its hand-rolled
hierarchy (``hierarchical_communicator.py``) exists precisely because
MPI+NCCL cannot see the whole topology at once; XLA can.

Unfused per-leaf reduction is still avoided: gradients are packed into
one buffer per dtype so small parameters ride one collective.
"""

from jax import lax

from chainermn_tpu.communicators import memory_utility
from chainermn_tpu.communicators.base import CommunicatorBase
from chainermn_tpu.communicators.mesh_utility import AXES


class XlaCommunicator(CommunicatorBase):

    def _allreduce_impl(self, grads):
        return memory_utility.fused_reduce(
            grads, lambda buf: lax.pmean(buf, AXES))
