"""Gradient tensor fusion.

TPU-native rebuild of ``chainermn/communicators/_memory_utility.py``.
The reference maintains raw CUDA buffers (``DeviceMemory``,
``HostPinnedMemory``) and loops over parameters every iteration to
pack/unpack them into one contiguous region (``:77-92``) so a single
collective covers the whole model.

Under XLA the packing itself is a traced op (one fused concatenate, no
per-iteration Python loop at run time) and buffer lifetime is owned by
the compiler, so there is no allocator class to manage.  What remains
is the *schema*: a deterministic flatten/unflatten of a pytree into one
1-D buffer per dtype, with the reference's sorted-parameter-order
determinism (``hierarchical_communicator.py:24``) provided by pytree
ordering.
"""

import jax
import jax.numpy as jnp


class PackSchema:
    """Shapes/dtypes/offsets for a fused flat buffer of a pytree."""

    def __init__(self, tree):
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        self.treedef = treedef
        self.shapes = [l.shape for l in leaves]
        self.dtypes = [l.dtype for l in leaves]
        self.sizes = []
        for sh in self.shapes:
            n = 1
            for d in sh:
                n *= int(d)
            self.sizes.append(n)
        self.total = sum(self.sizes)


def pack_params(tree, dtype=None):
    """Fuse a pytree into one flat buffer (+ schema to invert).

    Parity: ``pack_params`` (``_memory_utility.py:77-83``) -- but it is
    a pure function XLA fuses into the surrounding graph rather than a
    stream of device memcpys.
    """
    schema = PackSchema(tree)
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.zeros((0,), dtype or jnp.float32), schema
    buf_dtype = dtype or leaves[0].dtype
    flat = jnp.concatenate([l.ravel().astype(buf_dtype) for l in leaves])
    return flat, schema


def unpack_params(buf, schema):
    """Invert :func:`pack_params` (reference ``_memory_utility.py:86-92``)."""
    leaves = []
    offset = 0
    for shape, dt, n in zip(schema.shapes, schema.dtypes, schema.sizes):
        leaves.append(buf[offset:offset + n].reshape(shape).astype(dt))
        offset += n
    return jax.tree_util.tree_unflatten(schema.treedef, leaves)


def pad_to_multiple(buf, multiple):
    """Pad a flat buffer so collective-scatter shards divide evenly."""
    n = buf.shape[0]
    rem = (-n) % multiple
    if rem:
        buf = jnp.concatenate([buf, jnp.zeros((rem,), buf.dtype)])
    return buf, n


def plan_by_dtype(leaves):
    """Default fusion plan: one group per dtype (mixed-precision models
    must not be flattened into one buffer -- casting bf16/f32 together
    corrupts gradients)."""
    by_dtype = {}
    for i, leaf in enumerate(leaves):
        by_dtype.setdefault(jnp.dtype(leaf.dtype), []).append(i)
    return [idxs for _, idxs in sorted(by_dtype.items(),
                                       key=lambda kv: kv[0].name)]


def fused_reduce(tree, reduce_buf, plan=plan_by_dtype):
    """Apply ``reduce_buf(flat_buffer) -> flat_buffer`` to a pytree,
    one fused buffer per group of ``plan(leaves) -> [[leaf_idx, ...]]``.

    The default plan groups per dtype, so the collective count is
    O(#dtypes), not O(#params); strategies with other fusion policies
    (e.g. the bucketed communicator's size-capped backward-order
    groups) pass their own plan and share this pack/reduce/unpack
    path.
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    out = [None] * len(leaves)
    for idxs in plan(leaves):
        buf, schema = pack_params([leaves[i] for i in idxs])
        buf = reduce_buf(buf)
        for i, leaf in zip(idxs, unpack_params(buf, schema)):
            out[i] = leaf
    return jax.tree_util.tree_unflatten(treedef, out)
