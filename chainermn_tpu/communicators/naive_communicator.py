"""Per-parameter allreduce (reference ``naive_communicator.py``).

The reference issues one in-place MPI ``Allreduce`` per parameter and
divides by world size afterwards (``naive_communicator.py:16-20``).  The
TPU analogue is a per-leaf ``pmean`` over the full mesh -- XLA emits one
collective per leaf, no fusion.  Like the reference, this is the
baseline/CPU-friendly strategy and the fusion-free control for
benchmarking.
"""

from chainermn_tpu.communicators.base import CommunicatorBase


class NaiveCommunicator(CommunicatorBase):

    def _allreduce_impl(self, grads):
        return self.allreduce(grads, op='mean')
