"""Topology discovery and device-mesh construction.

TPU-native replacement for the reference's hostname-based rank discovery
(``chainermn/communicators/_communication_utility.py:7-40`` groups MPI
ranks by ``MPI.Get_processor_name()`` into (intra_rank, inter_rank)).

On TPU the two-level topology is intrinsic: devices within one host /
slice talk over ICI, hosts talk over DCN.  We therefore build a 2-D
``jax.sharding.Mesh`` with axes ``('inter', 'intra')``:

- ``intra`` -- devices that share a process (>= ICI locality), the
  analogue of the reference's intra-node NCCL group,
- ``inter`` -- across processes (DCN), the analogue of the reference's
  inter-node MPI group.

No launcher is involved: JAX's runtime enumerates global devices, so the
all-gather/scatter handshake the reference performs at
``_communication_utility.py:16-40`` is unnecessary.
"""

import collections
import math

import jax
import numpy as np
from jax.sharding import Mesh

#: Mesh axis that maps to DCN (across hosts) -- reference "inter_rank".
AXIS_INTER = 'inter'
#: Mesh axis that maps to ICI (within a host/slice) -- reference "intra_rank".
AXIS_INTRA = 'intra'
#: Both axes, in majorness order; data parallelism spans the product.
AXES = (AXIS_INTER, AXIS_INTRA)


def sorted_devices(devices=None):
    """Global devices in deterministic (process_index, id) order."""
    if devices is None:
        devices = jax.devices()
    return sorted(devices, key=lambda d: (d.process_index, d.id))


def detect_topology(devices=None):
    """Return ``(inter_size, intra_size)`` discovered from the device set.

    Mirrors the information computed by ``init_ranks``
    (``_communication_utility.py:7-40``) -- but from the JAX runtime's
    process/device table instead of an MPI hostname gather.
    """
    devices = sorted_devices(devices)
    per_process = collections.Counter(d.process_index for d in devices)
    sizes = set(per_process.values())
    if len(sizes) != 1:
        # Ragged hosts cannot form a rectangular mesh; collapse to 1-D.
        return (1, len(devices))
    intra = sizes.pop()
    return (len(per_process), intra)


def build_mesh(devices=None, mesh_shape=None):
    """Build the 2-D ``(inter, intra)`` mesh.

    ``mesh_shape`` overrides discovery, letting tests emulate a
    multi-host topology on a single process (the analogue of the
    reference testing multi-node code with ``mpiexec -n 3`` on one CPU
    host, ``.travis.yml:55``).
    """
    devices = sorted_devices(devices)
    n = len(devices)
    if mesh_shape is None:
        mesh_shape = detect_topology(devices)
    inter, intra = mesh_shape
    if inter == -1:
        inter = n // intra
    if intra == -1:
        intra = n // inter
    if inter * intra != n:
        raise ValueError(
            'mesh_shape %r does not cover %d devices' % ((inter, intra), n))
    arr = np.asarray(devices, dtype=object).reshape(inter, intra)
    return Mesh(arr, AXES)


def factorized_mesh(devices=None, intra_size=None):
    """Mesh with a chosen intra size (defaults to detected topology)."""
    devices = sorted_devices(devices)
    if intra_size is None:
        return build_mesh(devices)
    return build_mesh(devices, (-1, intra_size))


def balanced_2d(n):
    """Near-square (inter, intra) factorization of ``n`` for tests."""
    intra = int(math.sqrt(n))
    while n % intra:
        intra -= 1
    return (n // intra, intra)
