"""Topology discovery and device-mesh construction.

TPU-native replacement for the reference's hostname-based rank discovery
(``chainermn/communicators/_communication_utility.py:7-40`` groups MPI
ranks by ``MPI.Get_processor_name()`` into (intra_rank, inter_rank)).

On TPU the two-level topology is intrinsic: chips within one SLICE talk
over ICI (even when several host processes feed the slice), slices talk
over DCN.  We therefore build a 2-D ``jax.sharding.Mesh`` with axes
``('inter', 'intra')``:

- ``intra`` -- one ICI domain: all chips of a slice when the runtime
  exposes ``slice_index``, else the chips of one process (CPU
  fallback); the analogue of the reference's intra-node NCCL group,
- ``inter`` -- across ICI domains (DCN), the analogue of the
  reference's inter-node MPI group.

No launcher is involved: JAX's runtime enumerates global devices, so the
all-gather/scatter handshake the reference performs at
``_communication_utility.py:16-40`` is unnecessary.
"""

import collections
import math

import jax
import numpy as np
from jax.sharding import Mesh

#: Mesh axis that maps to DCN (across slices) -- reference "inter_rank".
AXIS_INTER = 'inter'
#: Mesh axis that maps to ICI (within one slice, possibly spanning
#: several host processes) -- reference "intra_rank".
AXIS_INTRA = 'intra'
#: Both axes, in majorness order; data parallelism spans the product.
AXES = (AXIS_INTER, AXIS_INTRA)


def _ici_domain(d):
    """The device's ICI domain id, or ``None`` when the runtime does
    not expose one.

    On multi-slice TPU deployments every chip carries a
    ``slice_index``: ICI spans ALL chips of a slice -- including chips
    owned by different host processes -- and DCN only separates
    slices.  The process boundary is therefore the WRONG locality
    proxy there (a v5e-64 is 16 processes but ONE ICI domain).
    """
    return getattr(d, 'slice_index', None)


def sorted_devices(devices=None):
    """Global devices in deterministic (slice, process, id) order, so
    a ``reshape(inter, intra)`` groups each ICI domain contiguously.

    The slice key participates only when EVERY device reports one --
    the same all-or-nothing rule as :func:`detect_topology`, so the
    ordering and the (inter, intra) factorization always agree on what
    a row of the mesh means (partial metadata must not let one stray
    ``slice_index`` interleave devices of different processes).
    """
    if devices is None:
        devices = jax.devices()
    use_slice = bool(devices) and all(
        _ici_domain(d) is not None for d in devices)

    def key(d):
        s = _ici_domain(d) if use_slice else 0
        return (s, d.process_index, d.id)

    return sorted(devices, key=key)


def detect_topology(devices=None):
    """Return ``(inter_size, intra_size)`` discovered from the device set.

    Mirrors the information computed by ``init_ranks``
    (``_communication_utility.py:7-40``) -- but from hardware locality
    metadata instead of an MPI hostname gather:

    1. When every device reports a ``slice_index`` (TPU), the slice IS
       the ICI domain: ``intra`` = chips per slice (across however many
       host processes feed it), ``inter`` = number of slices (DCN).
    2. Otherwise (CPU / backends without slice metadata) fall back to
       the process boundary as the locality proxy.

    Either way a ragged layout (domains of unequal size) collapses to a
    1-D ``(1, n)`` mesh, since it cannot tile a rectangle.
    """
    devices = sorted_devices(devices)
    if not devices:
        return (1, 0)
    slice_ids = [_ici_domain(d) for d in devices]
    if all(s is not None for s in slice_ids):
        groups = collections.Counter(slice_ids)
    else:
        groups = collections.Counter(d.process_index for d in devices)
    sizes = set(groups.values())
    if len(sizes) != 1:
        # Ragged domains cannot form a rectangular mesh; collapse to 1-D.
        return (1, len(devices))
    return (len(groups), sizes.pop())


def build_mesh(devices=None, mesh_shape=None):
    """Build the 2-D ``(inter, intra)`` mesh.

    ``mesh_shape`` overrides discovery, letting tests emulate a
    multi-host topology on a single process (the analogue of the
    reference testing multi-node code with ``mpiexec -n 3`` on one CPU
    host, ``.travis.yml:55``).
    """
    devices = sorted_devices(devices)
    n = len(devices)
    if mesh_shape is None:
        mesh_shape = detect_topology(devices)
    inter, intra = mesh_shape
    if inter == -1:
        inter = n // intra
    if intra == -1:
        intra = n // inter
    if inter * intra != n:
        raise ValueError(
            'mesh_shape %r does not cover %d devices' % ((inter, intra), n))
    arr = np.asarray(  # noqa: shardlint - eager driver-level
        devices, dtype=object).reshape(inter, intra)
    return Mesh(arr, AXES)


def factorized_mesh(devices=None, intra_size=None):
    """Mesh with a chosen intra size (defaults to detected topology)."""
    devices = sorted_devices(devices)
    if intra_size is None:
        return build_mesh(devices)
    return build_mesh(devices, (-1, intra_size))


def balanced_2d(n):
    """Near-square (inter, intra) factorization of ``n`` for tests."""
    intra = int(math.sqrt(n))
    while n % intra:
        intra -= 1
    return (n // intra, intra)


def divisor_leq(n, k):
    """The largest divisor of ``n`` that is ``<= k`` (>= 1).

    The graceful-degradation rule shared by
    :class:`chainermn_tpu.parallel.MeshPlan`: a requested axis width
    that does not divide the device count clamps DOWN to one that
    does, so a plan written for a pod still builds on a laptop --
    ``divisor_leq(1, k) == 1`` (the (1, 1) mesh), ``divisor_leq(n, n)
    == n`` (the (1, n) mesh), ``divisor_leq(7, 2) == 1`` (prime
    counts degrade to pure data parallelism)."""
    if n < 1:
        raise ValueError('need at least one device, got %d' % n)
    k = max(1, min(int(k), n))
    while n % k:
        k -= 1
    return k


def divisors_leq(n, ks):
    """:func:`divisor_leq` extended to N requested axis widths: each
    requested ``k`` clamps (in the GIVEN priority order) to the
    largest divisor of the devices still unclaimed, so the product of
    the effective widths always divides ``n`` and the leading (data)
    axis absorbs the remainder.

    This is the 3-D graceful-degradation rule of
    ``MeshPlan.create(tp=..., pp=...)``: ``ks=(tp, pp)`` -- tensor
    parallelism has placement priority (it rides the tightest ICI
    neighbors), the pipeline axis clamps within what remains, and
    degenerate counts degrade SHAPE-ONLY -- 1 device -> ``(1, 1)``
    effective widths (the (1, 1, 1) mesh), ``tp * pp > n`` clamps
    both down, a prime remainder degrades the later axis to 1
    (``divisors_leq(6, (2, 2)) == (2, 1)``: 3 devices left, no even
    divisor).  Axis NAMES never change with the shape."""
    if n < 1:
        raise ValueError('need at least one device, got %d' % n)
    remaining = n
    out = []
    for k in ks:
        eff = divisor_leq(remaining, k)
        out.append(eff)
        remaining //= eff
    return tuple(out)
